"""Named scenarios + the runner.

A scenario is a function ``(sim: Sim) -> duration`` that schedules a
workload and a fault timeline on the sim's engine, returning how long
(in virtual seconds) to run before the heal-and-converge epilogue.  The
runner wraps it with clock installation, the finish sequence (heal all
faults, restart everything, grace period, convergence checks), and
report assembly.

Every scenario exercises at least three distinct fault classes from the
taxonomy in ``faults``; the randomized ``random-fuzz`` scenario draws
its entire fault timeline from the seed.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..obs import planes as planes_mod
from ..obs.flightrec import flightrec
from ..obs.journey import journeys
from ..obs.sampler import Sampler
from ..obs.trace import tracer
from ..utils.sampling import poisson as _poisson
from .cluster import Sim
from .faults import NetConfig


@dataclass
class SimReport:
    scenario: str
    seed: int
    duration: float
    events: int
    trace_hash: str
    ok: bool
    violations: List[str] = field(default_factory=list)
    stats: dict = field(default_factory=dict)
    trace: List[str] = field(default_factory=list)   # when keep_trace
    # Chrome trace-event JSON of the control plane under virtual time
    # (obs.tracer spans); byte-identical for a given (scenario, seed)
    obs_trace: str = ""
    obs_trace_sha256: str = ""   # computed once in __post_init__
    # flight-recorder post-mortem, written automatically when the run
    # ends with invariant violations; sha is a pure function of the
    # seed (virtual timestamps, delta-based samples)
    flightrec_path: str = ""
    flightrec_sha256: str = ""
    # per-task journey ledger (obs/journey.py) captured at scenario
    # exit: milestones ride replicated stamps, so the dump — and its
    # sha — is a pure function of (scenario, seed), leader crashes
    # included (stitched across members, asserted in tests/test_obs.py)
    journeys_dump: dict = field(default_factory=dict)
    journeys_sha256: str = ""

    def __post_init__(self) -> None:
        if self.obs_trace and not self.obs_trace_sha256:
            self.obs_trace_sha256 = hashlib.sha256(
                self.obs_trace.encode()).hexdigest()

    def to_dict(self) -> dict:
        out = {
            "scenario": self.scenario, "seed": self.seed,
            "duration_virtual_s": self.duration, "events": self.events,
            "trace_hash": self.trace_hash,
            "obs_trace_sha256": self.obs_trace_sha256, "ok": self.ok,
            "violations": self.violations, "stats": self.stats,
        }
        if self.flightrec_path:
            out["flightrec_path"] = self.flightrec_path
            out["flightrec_sha256"] = self.flightrec_sha256
        if self.journeys_sha256:
            out["journeys_sha256"] = self.journeys_sha256
        return out


# --------------------------------------------------------------- scenarios

def _partition_churn(sim: Sim) -> float:
    """The acceptance scenario: a 3-manager/5-agent cluster through
    partitions, message loss, leader churn, and agent crash/partition
    faults — four distinct fault classes on one seeded timeline."""
    eng = sim.engine
    sim.start_raft_workload(interval=0.4)
    sim.cp.create_tasks(12)
    rng = eng.fork_rng()
    mids = [m.id for m in sim.managers]

    def churn():
        if sim.finishing:
            return False
        # random two-way split (sometimes isolating the leader)
        lone = rng.choice(mids)
        sim.net.split([lone], [m for m in mids if m != lone])
        eng.after(2.5, "heal split", sim.net.heal_all)
        return None

    eng.every(6.0, "partition churn", churn, phase=5.0)

    # message-loss burst mid-run
    def drops_on():
        sim.net.config.drop_p = 0.15
        eng.log("fault drop-burst on")

    def drops_off():
        sim.net.config.drop_p = 0.0
        eng.log("fault drop-burst off")

    eng.at(eng.clock.start + 20.0, "drop burst on", drops_on)
    eng.at(eng.clock.start + 30.0, "drop burst off", drops_off)

    # forced leader churn
    eng.at(eng.clock.start + 14.0, "stepdown", sim.stepdown_leader)
    eng.at(eng.clock.start + 40.0, "stepdown", sim.stepdown_leader)

    # agent faults: crash/restart one, partition another
    a0, a1 = sim.cp.agents[0], sim.cp.agents[1]
    eng.at(eng.clock.start + 12.0, "agent crash", a0.crash)
    eng.at(eng.clock.start + 32.0, "agent restart", a0.restart)
    eng.at(eng.clock.start + 25.0, "agent partition",
           lambda: a1.partition(True))
    eng.at(eng.clock.start + 45.0, "agent heal",
           lambda: a1.partition(False))
    eng.at(eng.clock.start + 35.0, "more tasks",
           lambda: sim.cp.create_tasks(6))
    return 55.0


def _crash_leader_mid_commit(sim: Sim) -> float:
    """Propose a burst at the leader and crash it in the same virtual
    instant — entries are on its WAL and (partially) on the wire but
    unacked.  The cluster must elect a successor without losing any
    entry it committed, and the rejoining ex-leader must converge."""
    eng = sim.engine
    sim.start_raft_workload(interval=0.5)
    sim.cp.create_tasks(8)

    def strike():
        m = sim.leader()
        if m is None:
            eng.after(1.0, "await leader", strike)
            return
        for i in range(20):
            sim.propose(f"burst-{i:03d}".encode())
        m.crash()                       # before any ack round-trips
        eng.after(6.0, "restart ex-leader", m.restart)

    eng.at(eng.clock.start + 5.0, "crash leader mid-commit", strike)

    # second strike against the successor (WAL intact — crash-with-
    # truncation is a durability bug the checkers are REQUIRED to flag,
    # exercised separately in tests)
    def strike2():
        m = sim.leader()
        if m is not None:
            for i in range(10):
                sim.propose(f"burst2-{i:03d}".encode())
            m.crash()
            eng.after(5.0, "restart ex-leader", m.restart)

    eng.at(eng.clock.start + 16.0, "crash successor mid-commit", strike2)
    eng.at(eng.clock.start + 10.0, "agent crash",
           sim.cp.agents[2].crash)
    eng.at(eng.clock.start + 20.0, "agent restart",
           sim.cp.agents[2].restart)
    return 28.0


def _crash_restart_churn(sim: Sim) -> float:
    """Rolling crash/restart of managers (never losing quorum for
    long); every restart rebuilds from the WAL and the ledger checker
    verifies the re-applied committed prefix byte-for-byte."""
    eng = sim.engine
    sim.start_raft_workload(interval=0.3)
    sim.cp.create_tasks(10)
    rng = eng.fork_rng()

    def churn():
        if sim.finishing:
            return False
        alive = [m for m in sim.managers if m.alive]
        if len(alive) <= 2:     # keep a quorum candidate pool
            return None
        victim = rng.choice(alive)
        victim.crash()
        eng.after(3.0, f"restart {victim.id}", victim.restart)
        return None

    eng.every(7.0, "crash churn", churn, phase=4.0)
    # agents churn too
    a = sim.cp.agents
    eng.at(eng.clock.start + 9.0, "agent crash", a[3].crash)
    eng.at(eng.clock.start + 18.0, "agent restart", a[3].restart)
    eng.at(eng.clock.start + 22.0, "more tasks",
           lambda: sim.cp.create_tasks(5))
    return 45.0


def _clock_skew(sim: Sim) -> float:
    """Timing faults: slow the leader's tick rate (its heartbeats
    arrive late -> followers may elect; pre-vote keeps this from
    cascading into term explosions), and slow one agent's heartbeat
    cadence past the TTL so the dispatcher marks it DOWN."""
    eng = sim.engine
    sim.start_raft_workload(interval=0.5)
    sim.cp.create_tasks(10)

    def skew_leader():
        m = sim.leader()
        if m is None:
            eng.after(1.0, "await leader", skew_leader)
            return
        m.tick_scale = 3.0
        eng.log(f"fault clock-skew {m.id} x3")
        eng.after(12.0, "unskew", lambda: setattr(m, "tick_scale", 1.0))

    eng.at(eng.clock.start + 8.0, "skew leader", skew_leader)
    agent = sim.cp.agents[4]

    def skew_agent():
        agent.rate_scale = 8.0      # heartbeats now slower than the TTL
        eng.log(f"fault clock-skew agent {agent.node_id} x8")

    eng.at(eng.clock.start + 15.0, "skew agent", skew_agent)
    eng.at(eng.clock.start + 32.0, "unskew agent",
           lambda: setattr(agent, "rate_scale", 1.0))
    eng.at(eng.clock.start + 20.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 28.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))
    return 40.0


def _agent_storm(sim: Sim) -> float:
    """Control-plane stress: task failure storms + agent churn while the
    consensus layer rides steady message jitter."""
    eng = sim.engine
    sim.start_raft_workload(interval=0.5)
    sim.cp.create_tasks(20)

    def storm_on():
        for a in sim.cp.agents:
            a.fail_p = 0.08
        eng.log("fault task-failure-storm on")

    def storm_off():
        for a in sim.cp.agents:
            a.fail_p = 0.0
        eng.log("fault task-failure-storm off")

    eng.at(eng.clock.start + 8.0, "storm on", storm_on)
    eng.at(eng.clock.start + 25.0, "storm off", storm_off)
    rng = eng.fork_rng()

    def agent_churn():
        if sim.finishing:
            return False
        up = [a for a in sim.cp.agents if a.alive]
        if len(up) > 2:
            victim = rng.choice(up)
            victim.crash()
            # outlive the heartbeat TTL (period 2s x grace 3) so the
            # dispatcher's expiry -> DOWN -> reschedule path runs
            eng.after(8.0, "agent restart", victim.restart)
        return None

    eng.every(6.0, "agent churn", agent_churn, phase=10.0)
    eng.at(eng.clock.start + 30.0, "more tasks",
           lambda: sim.cp.create_tasks(8))
    return 42.0


def _random_fuzz(sim: Sim) -> float:
    """The fuzzer's scenario: the entire fault timeline is drawn from
    the seed.  Constraints keep the run inside raft's fault model
    (crashes keep WALs intact and leave at least two members up so
    elections stay possible; durability bugs are injected only by the
    dedicated checker-detection test)."""
    eng = sim.engine
    rng = eng.fork_rng()
    sim.start_raft_workload(interval=0.3 + rng.random() * 0.4)
    sim.cp.create_tasks(rng.randrange(6, 16))
    duration = 30.0

    t = 3.0
    while t < duration - 4.0:
        op = rng.choice([
            "split", "isolate", "heal", "crash", "crash",
            "stepdown", "drop_burst", "agent_crash", "agent_partition",
            "skew", "tasks"])
        at = eng.clock.start + t

        if op == "split":
            def do_split():
                if sim.finishing:
                    return
                mids = [m.id for m in sim.managers]
                lone = rng.choice(mids)
                sim.net.split([lone], [m for m in mids if m != lone])
            eng.at(at, "fuzz split", do_split)
        elif op == "isolate":
            mid = rng.choice([m.id for m in sim.managers])
            eng.at(at, "fuzz isolate",
                   lambda mid=mid: sim.net.isolate(mid))
        elif op == "heal":
            eng.at(at, "fuzz heal", sim.net.heal_all)
        elif op == "crash":
            def do_crash():
                if sim.finishing:
                    return
                alive = [m for m in sim.managers if m.alive]
                if len(alive) <= 2:
                    return
                victim = rng.choice(alive)
                victim.crash()
                eng.after(2.0 + rng.random() * 4.0,
                          f"fuzz restart {victim.id}", victim.restart)
            eng.at(at, "fuzz crash", do_crash)
        elif op == "stepdown":
            eng.at(at, "fuzz stepdown", sim.stepdown_leader)
        elif op == "drop_burst":
            p = 0.05 + rng.random() * 0.2
            eng.at(at, "fuzz drops on",
                   lambda p=p: setattr(sim.net.config, "drop_p", p))
            eng.at(at + 2.0 + rng.random() * 4.0, "fuzz drops off",
                   lambda: setattr(sim.net.config, "drop_p", 0.0))
        elif op == "agent_crash":
            def do_acrash():
                up = [a for a in sim.cp.agents if a.alive]
                if len(up) > 1:
                    victim = rng.choice(up)
                    victim.crash()
                    eng.after(2.0 + rng.random() * 5.0,
                              "fuzz agent restart", victim.restart)
            eng.at(at, "fuzz agent crash", do_acrash)
        elif op == "agent_partition":
            agent = rng.choice(sim.cp.agents)
            eng.at(at, "fuzz agent partition",
                   lambda a=agent: a.partition(True))
            eng.at(at + 3.0 + rng.random() * 5.0, "fuzz agent heal",
                   lambda a=agent: a.partition(False))
        elif op == "skew":
            m = rng.choice(sim.managers)
            scale = 1.5 + rng.random() * 2.0
            eng.at(at, "fuzz skew",
                   lambda m=m, s=scale: setattr(m, "tick_scale", s))
            eng.at(at + 5.0, "fuzz unskew",
                   lambda m=m: setattr(m, "tick_scale", 1.0))
        elif op == "tasks":
            n = rng.randrange(2, 8)
            eng.at(at, "fuzz tasks", lambda n=n: sim.cp.create_tasks(n))
        t += 1.0 + rng.random() * 2.5
    return duration


def _pipelined_commit_churn(sim: Sim) -> float:
    """Chunk-pipelined scheduler commits through the sim consensus layer
    under leader crash: a raft-attached store (SimRaftProposer) commits
    a device-planned task group as many small pipelined block chunks,
    and the leader is crashed while later chunks are still in flight.

    Asserted (as violations when broken):
      * the clean pipelined tick commits every task;
      * NO chunk commits to the store after the leadership-loss instant
        — in-flight device plans must fail, roll back, and requeue;
      * committed + requeued always accounts for every task (none lost);
      * after a new leader emerges, a re-tick places the remainder;
      * the committed-entry ledger invariant (RaftInvariants) holds for
        the chunk-pipelined proposals interleaved with the background
        raft workload — checked continuously by the shared checkers.
    """
    eng = sim.engine
    sim.start_raft_workload(interval=0.6)
    sim.cp.create_tasks(6)   # keep the standard control plane busy too

    # top-level pumping: wait_proposal advances virtual time itself, so
    # this scenario DRIVES its workload inline instead of scheduling it
    # (the engine loop is not re-entrant from inside an event)
    while sim.leader() is None and eng.clock.elapsed() < 30.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    if sim.leader() is None:
        sim.violations.record("pipelined-commit",
                              "no ready leader within 30s")
        return eng.clock.elapsed() + 5.0

    from ..models import (
        Annotations, Node, NodeDescription, NodeSpec, NodeState,
        NodeStatus, ReplicatedService, Resources, Service, ServiceMode,
        ServiceSpec, Task, TaskSpec, TaskState, TaskStatus, Version,
    )
    from ..models.types import now
    from ..ops import TPUPlanner
    from ..scheduler import Scheduler
    from ..state.store import MemoryStore
    from .cluster import SimRaftProposer

    proposer = SimRaftProposer(sim)
    store = MemoryStore(proposer=proposer)
    store.pipeline_depth = 4            # chunk-pipelined proposals
    store.BLOCK_PROPOSAL_MAX_ITEMS = 8  # many small chunks per group

    def mk_nodes(tx):
        for i in range(16):
            tx.create(Node(
                id=f"pn{i:02d}",
                spec=NodeSpec(annotations=Annotations(name=f"pn{i:02d}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"pn{i:02d}",
                    resources=Resources(nano_cpus=8 * 10 ** 9,
                                        memory_bytes=32 << 30))))

    store.update(mk_nodes)
    svc = Service(
        id="svc-pipe",
        spec=ServiceSpec(annotations=Annotations(name="pipe"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=96),
                         task=TaskSpec()),
        spec_version=Version(index=1))
    store.update(lambda tx: tx.create(svc))

    def mk_tasks(base):
        def cb(tx):
            for i in range(48):
                tx.create(Task(
                    id=f"pt{base + i:03d}", service_id=svc.id,
                    slot=base + i + 1,
                    desired_state=TaskState.RUNNING, spec=svc.spec.task,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        store.update(cb)

    def count_assigned():
        return sum(1 for t in store.view(lambda tx: tx.find(Task))
                   if t.node_id
                   and t.status.state >= TaskState.ASSIGNED)

    planner = TPUPlanner()
    planner.enable_small_group_routing = False
    # scheduler-level depth 1: its committer thread would break the
    # sim's single-threaded determinism; the store-level chunk pipeline
    # (window 4 above) is what this scenario exercises
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    store.view(sched._setup_tasks_list)

    # ---- phase 1: clean pipelined tick, every chunk rides consensus
    mk_tasks(0)
    sched._resync()
    sched.tick()
    assigned1 = count_assigned()
    if assigned1 != 48:
        sim.violations.record(
            "pipelined-commit",
            f"clean pipelined tick committed {assigned1}/48")

    # ---- phase 2: crash the leader while chunks are in flight.  The
    # strike is keyed off the pipeline itself (after the 2nd chunk's
    # commit is acked, with up to window-1 later chunks still riding
    # consensus), not off wall/virtual timing — deterministic per seed
    # and guaranteed to land mid-pipeline.
    mk_tasks(48)
    sched._resync()
    at_crash: Dict[str, int] = {}
    acked = {"n": 0}

    def strike():
        m = sim.leader()
        if m is None:
            return
        at_crash["assigned"] = count_assigned()
        eng.log(f"fault crash {m.id} mid-pipeline")
        m.crash()
        eng.after(8.0, "restart ex-leader", m.restart)

    orig_wait = proposer.wait_proposal

    def wait_then_strike(waiter):
        orig_wait(waiter)
        acked["n"] += 1
        if acked["n"] == 2:
            strike()

    proposer.wait_proposal = wait_then_strike
    try:
        sched.tick()
    finally:
        proposer.wait_proposal = orig_wait
    assigned2 = count_assigned()
    requeued = len(sched.unassigned_tasks)
    flightrec.note(f"pipelined-commit phase2: assigned={assigned2} "
                   f"at_crash={at_crash.get('assigned')} "
                   f"requeued={requeued}")
    if "assigned" not in at_crash:
        sim.violations.record("pipelined-commit",
                              "leader crash fault never fired")
    elif assigned2 > at_crash["assigned"]:
        sim.violations.record(
            "pipeline-commit-after-leadership-loss",
            f"{assigned2 - at_crash['assigned']} tasks committed after "
            f"the leadership-loss instant (in-flight chunks must fail)")
    if assigned2 - 48 + requeued != 48:
        sim.violations.record(
            "pipelined-commit",
            f"task accounting broken after churn: committed "
            f"{assigned2 - 48} + requeued {requeued} != 48")

    # ---- phase 3: a successor leader acks the re-placed remainder
    while sim.leader() is None and eng.clock.elapsed() < 90.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    if sim.leader() is None:
        sim.violations.record("pipelined-commit",
                              "no successor leader within 90s")
    else:
        sched._resync()
        sched.tick()
        assigned3 = count_assigned()
        if assigned3 != 96:
            sim.violations.record(
                "pipelined-commit",
                f"re-tick after churn placed {assigned3}/96")
    return eng.clock.elapsed() + 3.0


def _fused_differential_churn(sim: Sim) -> float:
    """Differential: the FUSED many-service planner must place exactly
    what the per-service planner places, per seed, under churn.

    Two standalone stores ride the sim consensus (unbound
    SimRaftProposers) while the raft-attached control plane churns in
    the background: scheduler F plans with the fused path, scheduler P
    with ``fused_enabled=False``.  Identical workloads and faults are
    applied to both in lockstep and placements are compared after every
    phase — any divergence is a violation.  Phases cover the degraded
    routes too: host fallback (node.ip constraint group in every tick),
    task-failure down-weighting, node drains, a PlannerBreaker trip
    (both planners host-route, then half-open probe after the virtual
    cooldown), and a leadership stepdown (commit failure -> rollback ->
    requeue -> converge on the successor).
    """
    eng = sim.engine
    sim.start_raft_workload(interval=0.8)
    sim.cp.create_tasks(6)   # background control-plane traffic

    while sim.leader() is None and eng.clock.elapsed() < 30.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    if sim.leader() is None:
        sim.violations.record("fused-differential",
                              "no ready leader within 30s")
        return eng.clock.elapsed() + 5.0

    from ..models import (
        Annotations, Node, NodeAvailability, NodeDescription, NodeSpec,
        NodeState, NodeStatus, Placement, PlacementPreference,
        ReplicatedService, Resources, ResourceRequirements, Service,
        ServiceMode, ServiceSpec, SpreadOver, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from ..models.types import now
    from ..ops import TPUPlanner
    from ..scheduler import Scheduler
    from ..state.store import MemoryStore
    from .cluster import SimRaftProposer

    res = ResourceRequirements(
        reservations=Resources(nano_cpus=10 ** 8, memory_bytes=64 << 20))
    svc_specs = {
        "fa": TaskSpec(resources=res),
        "fb": TaskSpec(resources=res),
        "fc": TaskSpec(placement=Placement(preferences=[
            PlacementPreference(spread=SpreadOver(
                spread_descriptor="node.labels.rack"))]),
            resources=res),
        # node.ip constraints stay on the host oracle: the fused run
        # breaks around this group every tick (host-fallback parity)
        "fd": TaskSpec(placement=Placement(
            constraints=["node.ip!=10.0.0.9"])),
    }

    def build_store():
        store = MemoryStore(proposer=SimRaftProposer(sim))
        def mk(tx):
            for i in range(12):
                tx.create(Node(
                    id=f"dn{i:02d}",
                    spec=NodeSpec(annotations=Annotations(
                        name=f"dn{i:02d}",
                        labels={"rack": f"r{i % 3}"})),
                    status=NodeStatus(state=NodeState.READY),
                    description=NodeDescription(
                        hostname=f"dn{i:02d}",
                        resources=Resources(nano_cpus=8 * 10 ** 9,
                                            memory_bytes=32 << 30))))
            for sid, spec in svc_specs.items():
                tx.create(Service(
                    id=sid,
                    spec=ServiceSpec(annotations=Annotations(name=sid),
                                     mode=ServiceMode.REPLICATED,
                                     replicated=ReplicatedService(
                                         replicas=0),
                                     task=spec),
                    spec_version=Version(index=1)))
        store.update(mk)
        return store

    seqs = {sid: 0 for sid in svc_specs}

    def add_tasks(store, sid, n, base):
        spec = svc_specs[sid]
        def cb(tx):
            for i in range(n):
                tx.create(Task(
                    id=f"{sid}-{base + i:04d}", service_id=sid,
                    slot=base + i + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        store.update(cb)

    stores, scheds, planners = [], [], []
    for fused in (True, False):
        store = build_store()
        planner = TPUPlanner()
        planner.enable_small_group_routing = False
        planner.fused_enabled = fused
        sched = Scheduler(store, batch_planner=planner,
                          pipeline_depth=1)
        store.view(sched._setup_tasks_list)
        stores.append(store)
        scheds.append(sched)
        planners.append(planner)

    def snap(store):
        # placement claim only: ids, nodes, states.  Timestamps differ
        # by construction (the two ticks run seconds apart in virtual
        # time) and are not part of the equivalence being asserted.
        return sorted((t.id, t.node_id, int(t.status.state))
                      for t in store.view(lambda tx: tx.find(Task)))

    def both(fn):
        for store in stores:
            fn(store)

    def tick_and_compare(phase):
        for sched in scheds:
            sched._resync()
            sched.tick()
        a, b = snap(stores[0]), snap(stores[1])
        if a != b:
            diff = [(x, y) for x, y in zip(a, b) if x != y][:5]
            sim.violations.record(
                "fused-differential",
                f"{phase}: fused placements diverged from per-service "
                f"(first diffs: {diff})")

    # ---- phase 1: clean multi-service tick
    for sid, n in (("fa", 40), ("fb", 24), ("fc", 18), ("fd", 8)):
        both(lambda s, sid=sid, n=n: add_tasks(s, sid, n, seqs[sid]))
        seqs[sid] += {"fa": 40, "fb": 24, "fc": 18, "fd": 8}[sid]
    tick_and_compare("clean-tick")
    if planners[0].stats.get("groups_fused", 0) < 2:
        sim.violations.record(
            "fused-differential",
            "fused path never engaged on the fused-side scheduler "
            f"(stats {planners[0].stats})")
    if planners[1].stats.get("groups_fused", 0):
        sim.violations.record(
            "fused-differential",
            "per-service side took the fused path; differential is void")

    # ---- phase 2: task failures (down-weighted scoring) + scale-up
    def fail_tasks(store):
        victims = [t for t in store.view(lambda tx: tx.find(Task))
                   if t.service_id == "fa" and t.node_id][:6]
        def cb(tx):
            for v in victims:
                cur = tx.get(Task, v.id)
                if cur is None:
                    continue
                cur = cur.copy()
                cur.status = TaskStatus(state=TaskState.FAILED,
                                        timestamp=now(),
                                        message="sim fault")
                tx.update(cur)
        store.update(cb)
    both(fail_tasks)
    eng.run_until(eng.clock.elapsed() + 1.0)
    both(lambda s: add_tasks(s, "fa", 20, seqs["fa"]))
    seqs["fa"] += 20
    tick_and_compare("failure-churn")

    # ---- phase 3: drain nodes, then place more work around them
    def drain(store):
        def cb(tx):
            for nid in ("dn00", "dn05"):
                cur = tx.get(Node, nid).copy()
                cur.spec.availability = NodeAvailability.DRAIN
                tx.update(cur)
        store.update(cb)
    both(drain)
    both(lambda s: add_tasks(s, "fb", 16, seqs["fb"]))
    both(lambda s: add_tasks(s, "fc", 12, seqs["fc"]))
    seqs["fb"] += 16
    seqs["fc"] += 12
    tick_and_compare("drain-churn")

    # ---- phase 4: breaker trip — BOTH planners degrade to the host
    # oracle, then half-open probe after the virtual cooldown
    for planner in planners:
        for _ in range(planner.breaker.threshold):
            planner.breaker.record_failure()
    both(lambda s: add_tasks(s, "fa", 12, seqs["fa"]))
    seqs["fa"] += 12
    tick_and_compare("breaker-open")
    if not planners[0].stats.get("groups_breaker_to_host"):
        sim.violations.record(
            "fused-differential",
            "breaker-open tick did not host-route (degraded differential "
            "not exercised)")
    eng.run_until(eng.clock.elapsed()
                  + planners[0].breaker.base_cooldown + 1.0)
    both(lambda s: add_tasks(s, "fb", 12, seqs["fb"]))
    seqs["fb"] += 12
    tick_and_compare("breaker-probe")

    # ---- phase 5: leadership stepdown mid-workload — both sides fail
    # their commits, roll back, requeue, and converge on the successor
    both(lambda s: add_tasks(s, "fa", 10, seqs["fa"]))
    seqs["fa"] += 10
    sim.stepdown_leader()
    tick_and_compare("stepdown-requeue")
    while sim.leader() is None and eng.clock.elapsed() < 90.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    if sim.leader() is None:
        sim.violations.record("fused-differential",
                              "no successor leader within 90s")
    else:
        tick_and_compare("post-stepdown-converge")
        pending = len(scheds[0].unassigned_tasks)
        if pending:
            sim.violations.record(
                "fused-differential",
                f"{pending} tasks still unplaced after the successor "
                "re-tick")
    return eng.clock.elapsed() + 3.0


_fused_differential_churn.raft_cp = True


def _steady_state_churn(sim: Sim) -> float:
    """Differential: the STREAMING scheduler (device-resident node
    state, dirty-row incremental refreshes — ops/streaming.py) must
    place exactly what a forced full-replan scheduler places, per seed,
    under sustained Poisson churn.

    Twin stores ride the sim consensus through epoch-reporting
    ``SimRaftProposer``s while the raft-attached control plane churns
    in the background: scheduler S refreshes resident columns from the
    delta tracker (the real watch feed, pumped between ticks exactly
    like the production event loop), scheduler F runs with
    ``streaming_enabled=False`` — the ``SWARM_STREAMING_PLANNER=0``
    posture, O(cluster) rebuild every tick.  Identical workloads and
    faults apply to both in lockstep; any placement divergence is an
    ``incremental-equals-full-replan`` violation.  Phases cover every
    row of the streaming fallback matrix: steady arrivals/exits/
    failures (incremental ticks, the common case), node availability
    churn + a spread service (dirty node rows, resident leaf columns),
    a host-routed constraint group every tick (hook-marked host
    mutations), node add + node REMOVE (append vs forced-full), and a
    leader stepdown (epoch change -> the successor-reign resync that
    rebuilds resident state before trusting it — the
    ``streaming-resync x scheduler`` coverage cell)."""
    eng = sim.engine
    rng = eng.fork_rng()
    sim.start_raft_workload(interval=0.8)
    sim.cp.create_tasks(4)   # background control-plane traffic

    while sim.leader() is None and eng.clock.elapsed() < 30.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    if sim.leader() is None:
        sim.violations.record("incremental-equals-full-replan",
                              "no ready leader within 30s")
        return eng.clock.elapsed() + 5.0

    from ..models import (
        Annotations, Node, NodeAvailability, NodeDescription, NodeSpec,
        NodeState, NodeStatus, Placement, PlacementPreference,
        ReplicatedService, Resources, ResourceRequirements, Service,
        ServiceMode, ServiceSpec, SpreadOver, Task, TaskSpec, TaskState,
        TaskStatus, Version,
    )
    from ..models.types import now
    from ..ops import TPUPlanner
    from ..scheduler import Scheduler
    from ..state.events import Event, EventSnapshotRestore
    from ..state.store import MemoryStore
    from .cluster import SimRaftProposer

    class _EpochedProposer(SimRaftProposer):
        """Unbound proposer that still reports a fencing epoch (the
        current leader's) so the twin schedulers' tick pinning — and
        the streaming plane's resync-on-handoff — see reigns change."""

        @property
        def leadership_epoch(self):
            m = self.sim.leader()
            return m.core.leadership_epoch if m is not None else None

    res = ResourceRequirements(
        reservations=Resources(nano_cpus=10 ** 8, memory_bytes=64 << 20))
    svc_specs = {
        "ga": TaskSpec(resources=res),
        "gb": TaskSpec(resources=res),
        # spread service: exercises the resident leaf columns
        "gc": TaskSpec(placement=Placement(preferences=[
            PlacementPreference(spread=SpreadOver(
                spread_descriptor="node.labels.rack"))]),
            resources=res),
        # node.ip constraints stay on the host oracle: every tick ends
        # with hook-marked host-path mirror mutations the dirty set
        # must absorb (NOT a full rebuild)
        "gd": TaskSpec(placement=Placement(
            constraints=["node.ip!=10.0.0.9"])),
    }

    def mk_node(tx, i: int):
        tx.create(Node(
            id=f"sn{i:02d}",
            spec=NodeSpec(annotations=Annotations(
                name=f"sn{i:02d}", labels={"rack": f"r{i % 3}"})),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname=f"sn{i:02d}",
                resources=Resources(nano_cpus=8 * 10 ** 9,
                                    memory_bytes=32 << 30))))

    def build_store():
        store = MemoryStore(proposer=_EpochedProposer(sim))

        def mk(tx):
            for i in range(14):
                mk_node(tx, i)
            for sid, spec in svc_specs.items():
                tx.create(Service(
                    id=sid,
                    spec=ServiceSpec(annotations=Annotations(name=sid),
                                     mode=ServiceMode.REPLICATED,
                                     replicated=ReplicatedService(
                                         replicas=0),
                                     task=spec),
                    spec_version=Version(index=1)))
        store.update(mk)
        return store

    seqs = {sid: 0 for sid in svc_specs}

    def add_tasks(store, sid, n):
        spec = svc_specs[sid]
        base = seqs[sid]

        def cb(tx):
            for i in range(n):
                tx.create(Task(
                    id=f"{sid}-{base + i:04d}", service_id=sid,
                    slot=base + i + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
        store.update(cb)

    stores, scheds, planners, subs = [], [], [], []
    for streaming in (True, False):
        store = build_store()
        planner = TPUPlanner()
        planner.enable_small_group_routing = False
        planner.streaming_enabled = streaming
        sched = Scheduler(store, batch_planner=planner,
                          pipeline_depth=1)
        _, sub = store.view_and_watch(
            lambda tx, s=sched: s._setup_tasks_list(tx),
            accepts_blocks=True)
        stores.append(store)
        scheds.append(sched)
        planners.append(planner)
        subs.append(sub)

    def pump(i):
        """Drain the store watch into the scheduler exactly as its
        production event loop would — this IS the streaming delta feed
        (blocks are the scheduler's own commits and are skipped, like
        the run() loop skips them)."""
        sched, sub = scheds[i], subs[i]
        while True:
            ev = sub.poll()
            if ev is None:
                return
            if isinstance(ev, EventSnapshotRestore):
                sched._resync()
            elif isinstance(ev, Event):
                sched._handle_event(ev)

    def snap(store):
        return sorted((t.id, t.node_id, int(t.status.state))
                      for t in store.view(lambda tx: tx.find(Task)))

    def both(fn):
        for store in stores:
            fn(store)

    violated = {"n": 0}

    def tick_and_compare(phase):
        for i in range(2):
            pump(i)
            scheds[i].tick()
        a, b = snap(stores[0]), snap(stores[1])
        if a != b and violated["n"] < 3:   # first divergences only
            violated["n"] += 1
            diff = [(x, y) for x, y in zip(a, b) if x != y][:5]
            sim.violations.record(
                "incremental-equals-full-replan",
                f"{phase}: streaming placements diverged from "
                f"full-replan (first diffs: {diff})")

    def fail_some(store, sid, k):
        victims = sorted(
            (t for t in store.view(lambda tx: tx.find(Task))
             if t.service_id == sid and t.node_id
             and t.status.state == TaskState.ASSIGNED), key=lambda t: t.id
        )[:k]

        def cb(tx):
            for v in victims:
                cur = tx.get(Task, v.id)
                if cur is None:
                    continue
                cur = cur.copy()
                cur.status = TaskStatus(state=TaskState.FAILED,
                                        timestamp=now(),
                                        message="sim churn exit")
                tx.update(cur)
        store.update(cb)

    # ---- phase 1: seed the steady state
    for sid, n in (("ga", 16), ("gb", 12), ("gc", 10), ("gd", 4)):
        both(lambda s, sid=sid, n=n: add_tasks(s, sid, n))
        seqs[sid] += n
    tick_and_compare("seed")

    # ---- phase 2: sustained Poisson churn — arrivals, exits/failures,
    # node availability flips.  This is the tick shape the streaming
    # plane exists for: every refresh must be incremental.
    for w in range(12):
        for sid, lam in (("ga", 1.6), ("gb", 1.2), ("gc", 0.9),
                         ("gd", 0.5)):
            n = _poisson(rng, lam)
            if n:
                both(lambda s, sid=sid, n=n: add_tasks(s, sid, n))
                seqs[sid] += n
        exits = _poisson(rng, 1.1)
        if exits:
            sid = ("ga", "gb", "gc")[w % 3]
            both(lambda s, sid=sid, k=exits: fail_some(s, sid, k))
        if w % 4 == 2:
            flip = f"sn{rng.randrange(14):02d}"

            def avail(store, nid=flip, drain=(w % 8 == 2)):
                def cb(tx):
                    cur = tx.get(Node, nid)
                    if cur is None:
                        return
                    cur = cur.copy()
                    cur.spec.availability = (
                        NodeAvailability.DRAIN if drain
                        else NodeAvailability.ACTIVE)
                    tx.update(cur)
                store.update(cb)
            both(avail)
        eng.run_until(eng.clock.elapsed() + 0.7)
        tick_and_compare(f"churn-w{w}")

    st_stats = planners[0].streaming_snapshot()
    if st_stats["incremental_ticks"] < 8:
        sim.violations.record(
            "incremental-equals-full-replan",
            "streaming side barely ran incrementally "
            f"({st_stats}) — the differential is void")
    if planners[1].streaming_snapshot()["enabled"]:
        sim.violations.record(
            "incremental-equals-full-replan",
            "full-replan side had streaming enabled; differential void")

    # ---- phase 3: membership churn — a node joins (append row), a
    # node leaves (forced full rebuild; row order shifted)
    def add_node(store):
        store.update(lambda tx: mk_node(tx, 14))
    both(add_node)
    both(lambda s: add_tasks(s, "ga", 6))
    seqs["ga"] += 6
    tick_and_compare("node-join")

    def del_node(store):
        def cb(tx):
            cur = tx.get(Node, "sn03")
            if cur is not None:
                tx.delete(Node, "sn03")
        store.update(cb)
    both(del_node)
    both(lambda s: add_tasks(s, "gb", 6))
    seqs["gb"] += 6
    tick_and_compare("node-leave")

    # ---- phase 4: leader stepdown mid-churn — commits fail, roll
    # back, requeue; the successor reign's first refresh must RESYNC
    # the resident state (epoch change), not trust pre-handoff rows
    both(lambda s: add_tasks(s, "ga", 5))
    seqs["ga"] += 5
    pre_resyncs = planners[0].streaming_snapshot()["resyncs"]
    sim.stepdown_leader()
    tick_and_compare("stepdown-requeue")
    while sim.leader() is None and eng.clock.elapsed() < 90.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    if sim.leader() is None:
        sim.violations.record("incremental-equals-full-replan",
                              "no successor leader within 90s")
        return eng.clock.elapsed() + 3.0
    tick_and_compare("post-stepdown-converge")
    post_resyncs = planners[0].streaming_snapshot()["resyncs"]
    if post_resyncs > pre_resyncs:
        # coverage cell (scripts/chaos_sweep.py REQUIRED_CELLS): a
        # leader handoff ACTUALLY rebuilt resident state this run
        eng.log("fault streaming-resync scheduler")
    else:
        sim.violations.record(
            "incremental-equals-full-replan",
            "leader handoff did not resync the resident state "
            f"(resyncs {pre_resyncs} -> {post_resyncs})")

    # ---- phase 5: converged steady state again
    both(lambda s: add_tasks(s, "gb", 4))
    seqs["gb"] += 4
    eng.run_until(eng.clock.elapsed() + 0.7)
    tick_and_compare("post-handoff-churn")
    pending = len(scheds[0].unassigned_tasks)
    if pending:
        sim.violations.record(
            "incremental-equals-full-replan",
            f"{pending} schedulable tasks still unplaced after the "
            "post-handoff re-tick")
    return eng.clock.elapsed() + 3.0


_steady_state_churn.raft_cp = True


# ------------------------------------------------- failover scenarios
#
# These run the RAFT-ATTACHED control plane (Sim(raft_cp=True)): every
# member holds a replicated store, the real scheduler / dispatcher /
# allocator / restart supervisor / orchestrators run on the leader only,
# and leadership hand-off is exercised under faults.  The shared
# checkers run throughout, plus control-loops-only-on-leader,
# no-stale-epoch-commit (epoch fencing), and the failover-replacement
# end-state check in Sim.finish.


def _device_planner():
    """Planner factory for the failover scenarios: the device path with
    small-group routing off, so every group's assignments commit as
    chunk-pipelined columnar block proposals (the pipelined commit the
    partition scenario strikes mid-flight)."""
    from ..ops import TPUPlanner
    p = TPUPlanner()
    p.enable_small_group_routing = False
    return p


def _arm_leader_strike(sim: Sim, fire) -> None:
    """Wrap the ACTIVE leader's member-bound proposer so ``fire(member)``
    triggers deterministically from inside the control plane's own
    consensus traffic (not off wall/virtual timing).  ``fire`` returns
    True once the strike happened; arming then stops."""
    eng = sim.engine
    state = {"fired": False}

    def arm():
        if sim.finishing or state["fired"]:
            return False
        mc = sim.cp.active
        if mc is None:
            return None
        proposer = sim.cp.proposers[mc.member.id]
        if getattr(proposer, "_strike_armed", False):
            return None
        proposer._strike_armed = True
        orig_wait = proposer.wait_proposal

        def wait_then_strike(waiter):
            orig_wait(waiter)
            if not state["fired"] and not sim.finishing \
                    and fire(proposer, mc.member):
                state["fired"] = True
        proposer.wait_proposal = wait_then_strike
        return None

    eng.every(0.5, "arm leader strike", arm, phase=0.25)


def _mk_leader_crash_mid_tick(depth: int) -> Callable[[Sim], float]:
    def scenario(sim: Sim) -> float:
        eng = sim.engine
        cp = sim.cp
        cp.store_pipeline_depth = depth
        cp.block_proposal_max_items = 4
        cp.planner_factory = _device_planner
        sim.start_raft_workload(interval=0.6)
        cp.create_tasks(12)

        def fire(proposer, member) -> bool:
            # strike only once real control traffic is flowing: past the
            # bootstrap + scale + task-creation commits, i.e. inside a
            # scheduling/status tick of the attached leader
            if proposer.stats["committed"] < 6:
                return False
            eng.log(f"fault crash {member.id} mid-tick")
            member.crash()
            eng.after(6.0, "restart ex-leader", member.restart)
            return True

        _arm_leader_strike(sim, fire)
        # agent churn rides along so the successor re-learns sessions
        a = sim.cp.agents
        eng.at(eng.clock.start + 16.0, "agent crash", a[1].crash)
        eng.at(eng.clock.start + 24.0, "agent restart", a[1].restart)
        eng.at(eng.clock.start + 20.0, "more tasks",
               lambda: cp.create_tasks(6))
        return 40.0
    scenario.raft_cp = True
    return scenario


def _mk_partition_pipelined_commit(depth: int) -> Callable[[Sim], float]:
    def scenario(sim: Sim) -> float:
        eng = sim.engine
        cp = sim.cp
        cp.store_pipeline_depth = depth
        cp.block_proposal_max_items = 4
        cp.planner_factory = _device_planner
        sim.start_raft_workload(interval=0.7)
        cp.create_tasks(16)
        state = {"armed_async": False}

        def fire(proposer, member) -> bool:
            if state["armed_async"]:
                return False
            if proposer.stats["committed"] < 4:
                return False
            # from here on, the moment the chunk-pipelined window holds
            # 2+ in-flight proposals, cut the leader off mid-commit
            state["armed_async"] = True
            orig_async = proposer.propose_async

            # at depth 1 chunks ride strictly serially, so one in-flight
            # proposal IS the mid-commit window; deeper pipelines strike
            # with the window actually filled
            window_needed = 2 if depth > 1 else 1

            def async_then_partition(actions, commit_cb=None, epoch=None):
                w = orig_async(actions, commit_cb, epoch=epoch)
                if len(proposer._pending) >= window_needed \
                        and member.alive:
                    eng.log(f"fault partition {member.id} mid-pipelined-"
                            f"commit (window={len(proposer._pending)})")
                    sim.net.isolate(member.id)
                    eng.after(10.0, "heal partition",
                              lambda: sim.net.rejoin(member.id))
                    proposer.propose_async = orig_async
                return w
            proposer.propose_async = async_then_partition
            return True

        _arm_leader_strike(sim, fire)
        eng.at(eng.clock.start + 24.0, "more tasks",
               lambda: cp.create_tasks(6))
        return 45.0
    scenario.raft_cp = True
    return scenario


def _failover_churn_rollout(sim: Sim) -> float:
    """Scale rollout (up, down, up) under leader churn, agent churn and
    a task-failure storm: the restart supervisor and orchestrators must
    keep the replica count converging across two leadership hand-offs
    with no lost or duplicated restarts.  A replicated JOB rides along
    (jobs orchestrator live in the raft-attached control plane): its
    ``total_completions`` must all land despite the leader hand-offs —
    job iterations survive failover via the replicated store."""
    eng = sim.engine
    cp = sim.cp
    sim.start_raft_workload(interval=0.8)
    cp.create_tasks(10)
    # jobs under churn: 6 completions through a max_concurrent=2 window,
    # spanning both leadership hand-offs below
    eng.at(eng.clock.start + 6.0, "job under churn",
           lambda: cp.run_job("job-churn", total=6, max_concurrent=2))
    cp.expect_job_complete("job-churn", 6)
    eng.at(eng.clock.start + 10.0, "scale up", lambda: cp.scale(16))
    eng.at(eng.clock.start + 20.0, "scale down", lambda: cp.scale(6))
    eng.at(eng.clock.start + 28.0, "scale up again",
           lambda: cp.scale(12))

    eng.at(eng.clock.start + 14.0, "stepdown", sim.stepdown_leader)

    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 24.0, "crash leader", crash_leader)

    def storm_on():
        for a in cp.agents:
            a.fail_p = 0.05
        eng.log("fault task-failure-storm on")

    def storm_off():
        for a in cp.agents:
            a.fail_p = 0.0
        eng.log("fault task-failure-storm off")
    eng.at(eng.clock.start + 8.0, "storm on", storm_on)
    eng.at(eng.clock.start + 30.0, "storm off", storm_off)
    a = cp.agents
    eng.at(eng.clock.start + 12.0, "agent crash", a[2].crash)
    eng.at(eng.clock.start + 26.0, "agent restart", a[2].restart)
    return 45.0


_failover_churn_rollout.raft_cp = True


def _preemption_storm(sim: Sim) -> float:
    """Priority bands arriving under node churn and leader stepdown:
    three replicated bands (priority 0 / 5 / 10) with per-task cpu
    reservations contend for 5 workers x 4 slots.  Two node deaths
    shrink capacity to 12 slots just as the higher bands arrive, so the
    mid and high bands are infeasible without evicting the low band —
    the scheduler's preemption pass (device victim kernel behind the
    breaker seam, host oracle on fallback) must place them, the
    orchestrators must requeue the evicted slots, and after heal the
    whole workload (20 tasks) fits again.  Judged by the preemption
    invariants (no-priority-inversion, no-preempt-equal-or-higher,
    preemption-thrash-bound, preempted-tasks-requeue) plus the
    preemptions-observed coverage check."""
    eng = sim.engine
    cp = sim.cp
    cp.planner_factory = _device_planner    # device victim selection
    cp.expect_preemptions = True
    sim.start_raft_workload(interval=0.8)

    CPU = 2 * 10 ** 9    # 4 slots per 8-cpu worker
    eng.at(eng.clock.start + 6.0, "band lo",
           lambda: cp.add_service("svc-lo", 12, priority=0,
                                  nano_cpus=CPU))
    # node churn: two workers die while the higher bands arrive
    a = cp.agents
    eng.at(eng.clock.start + 20.0, "node death w0", a[0].crash)
    eng.at(eng.clock.start + 24.0, "node death w1", a[1].crash)
    eng.at(eng.clock.start + 22.0, "band mid",
           lambda: cp.add_service("svc-mid", 4, priority=5,
                                  nano_cpus=CPU))

    def high_band():
        # the burst the coverage matrix requires: the high band lands on
        # a shrunken cluster and must preempt its way in
        eng.log("fault preempt-burst scheduler")
        cp.add_service("svc-hi", 4, priority=10, nano_cpus=CPU)
    eng.at(eng.clock.start + 30.0, "band high (preempt burst)",
           high_band)

    eng.at(eng.clock.start + 34.0, "stepdown mid-storm",
           sim.stepdown_leader)
    eng.at(eng.clock.start + 40.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 46.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))
    eng.at(eng.clock.start + 50.0, "node return w0", a[0].restart)
    eng.at(eng.clock.start + 54.0, "node return w1", a[1].restart)
    return 80.0


_preemption_storm.raft_cp = True


def _tenant_storm(sim: Sim) -> float:
    """Autoscaler + multi-tenant QoS soak (ISSUE 12): a quota'd
    low-band tenant's autoscaled service bursts (the scenario drives
    its load signal up 16x) while a high-band tenant's service must
    keep its pending->assigned p99 — judged by the derived cross-band
    bound, not a constant.  The burst rides agent churn, a drop burst,
    and a leader crash landing MID-SCALE-UP (the successor's
    supervisor resumes the policy from the replicated
    ``autoscale_status``, still inside bounds and rate).  Quotas clamp
    the burst at admission — host filter AND device quota-mask column
    (the planner factory is the device path) — so the low tenant's
    committed usage can never exceed its quota and the high band never
    waits on capacity the burst stole.  Load removal at the end must
    converge replicas back to min.  Judged by quota-never-exceeded,
    autoscale-within-bounds-and-rate, no-cross-band-p99-violation and
    autoscale-converges on top of the shared checkers."""
    from ..models.specs import AutoscaleConfig
    from ..models.types import TenantQuota
    eng = sim.engine
    cp = sim.cp
    cp.planner_factory = _device_planner    # quota mask on device
    CPU = 2 * 10 ** 9    # 4 slots per 8-cpu worker (5 workers = 40 cpu)
    eng.at(eng.clock.start + 4.0, "tenants",
           lambda: cp.configure_tenants({
               # t-lo: 12 cpu = 6 tasks — far below the burst's ask
               "t-lo": TenantQuota(nano_cpus=12 * 10 ** 9),
               # t-hi: room for the whole high band
               "t-hi": TenantQuota(nano_cpus=24 * 10 ** 9)}))
    eng.at(eng.clock.start + 6.0, "burst service",
           lambda: cp.add_service(
               "svc-burst", 2, priority=0, nano_cpus=CPU,
               tenant="t-lo",
               autoscale=AutoscaleConfig(
                   min_replicas=2, max_replicas=16,
                   target_utilization=1.0, scale_up_step=4,
                   scale_down_step=6, stabilization_window=3.0)))
    eng.at(eng.clock.start + 8.0, "high band",
           lambda: cp.add_service("svc-hi", 4, priority=10,
                                  nano_cpus=CPU, tenant="t-hi"))
    # steady pre-burst load so the policy has a signal either way
    eng.at(eng.clock.start + 10.0, "baseline load",
           lambda: cp.set_load("svc-burst", 2.0))

    def burst():
        # the injected fault: a 16x tenant burst into the scheduler
        eng.log("fault autoscale-burst scheduler")
        cp.set_load("svc-burst", 32.0)
    eng.at(eng.clock.start + 14.0, "tenant burst", burst)
    # the burst's scale-up wants 16 replicas; quota admits 6 — clamps
    # MUST be observed (coverage cell quota-clamp x scheduler), and the
    # committed replica count must still reach the spec maximum across
    # the leader crash below
    cp.expect_autoscale("svc-burst", at_least=10, by=48.0)
    cp.expect_band_p99(5, 14.0, 58.0)

    # agent churn AWAY from the leader outage (its TTL-driven
    # re-placement must ride a live leader)
    a = cp.agents
    eng.at(eng.clock.start + 18.0, "agent crash", a[3].crash)
    eng.at(eng.clock.start + 36.0, "agent restart", a[3].restart)

    # leader crash mid-scale-up: the supervisor is between steps of the
    # burst ramp — the successor resumes from the replicated status
    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 26.0, "crash leader mid-scale-up",
           crash_leader)

    eng.at(eng.clock.start + 44.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 50.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))

    # load removed: the policy must walk replicas back to min and stay
    eng.at(eng.clock.start + 58.0, "load removed",
           lambda: cp.set_load("svc-burst", 0.0))
    cp.expect_autoscale_converge("svc-burst", to=2, by=95.0)
    return 85.0


_tenant_storm.raft_cp = True


def _gang_deadlock(sim: Sim) -> float:
    """Gang scheduling under contention (ISSUE 16): two all-or-nothing
    gangs of 8 tasks each land on a cluster shrunk to 12 slots — each
    gang fits alone, both together do not.  Partial placement would
    livelock them (each holding half the capacity, neither complete);
    atomic admission plus the deterministic (-priority, age, key)
    admission order must place one gang in a single commit and defer
    the other INTACT.  A leader stepdown mid-contention rebuilds the
    deferral bookkeeping on the successor, an agent crash evicts part
    of the placed gang (its replacements must re-place without
    demanding a whole new gang — placed-live members count toward
    min_size), and when the dead workers return the deferred gang must
    place in full.  Judged by the gang-atomicity invariant (no commit
    ever assigns a strict subset of a unit) plus end-state convergence
    of BOTH gangs."""
    eng = sim.engine
    cp = sim.cp
    cp.planner_factory = _device_planner    # gang_fit on device
    sim.start_raft_workload(interval=0.8)

    CPU = 2 * 10 ** 9    # 4 slots per 8-cpu worker
    a = cp.agents
    # shrink to 3 workers x 4 slots = 12 before the gangs arrive
    eng.at(eng.clock.start + 6.0, "node death w0", a[0].crash)
    eng.at(eng.clock.start + 7.0, "node death w1", a[1].crash)

    def gangs():
        # the injected fault: two half-placeable gangs race for 12
        # slots — the deadlock gang scheduling exists to break
        eng.log("fault gang-deadlock scheduler")
        cp.add_service("svc-gang-a", 8, gang_min=8, nano_cpus=CPU)
        cp.add_service("svc-gang-b", 8, gang_min=8, nano_cpus=CPU)
    eng.at(eng.clock.start + 10.0, "two contending gangs", gangs)

    # leader churn mid-contention: the deferred unit's age/blocked
    # bookkeeping is leader-local and must rebuild on the successor
    eng.at(eng.clock.start + 20.0, "stepdown mid-contention",
           sim.stepdown_leader)
    # agent churn under the placed gang: its replacements re-place
    # against placed-live min_size accounting, still atomically
    eng.at(eng.clock.start + 26.0, "agent crash w2", a[2].crash)
    eng.at(eng.clock.start + 34.0, "agent return w2", a[2].restart)
    eng.at(eng.clock.start + 38.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 44.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))
    # capacity returns: the deferred gang must now place in full
    eng.at(eng.clock.start + 48.0, "node return w0", a[0].restart)
    eng.at(eng.clock.start + 52.0, "node return w1", a[1].restart)
    cp.expect_service_running("svc-gang-a", 8)
    cp.expect_service_running("svc-gang-b", 8)
    return 80.0


_gang_deadlock.raft_cp = True


def _pipeline_chaos(sim: Sim) -> float:
    """Pipeline DAG rollout under churn (ISSUE 16): a 3-deep workflow
    (stage-a -> stage-b -> {stage-c, stage-d}) where the supervisor
    must release each stage only once its upstream is fully running —
    across a leader crash landing between releases (verdicts are
    replicated on the Service rows, so the successor resumes them).
    stage-b is poisoned from the start: its tasks die on startup, so it
    accumulates failure observations past the poison threshold and the
    supervisor must HALT both downstream stages — stage-c freezes
    (halt), stage-d scales to zero (rollback policy) — while stage-b
    itself stays released and churns restarts until the global heal.
    Judged by the pipeline-order invariant (no downstream task RUNNING
    before its upstream ever ran) plus the end-state verdicts."""
    eng = sim.engine
    cp = sim.cp
    sim.start_raft_workload(interval=0.8)

    def poison():
        # the injected fault: the mid stage is poisoned — every task
        # dies on startup until the end-of-scenario heal
        eng.log("fault pipeline-stage orchestrator")
        cp.poison_services.add("svc-stage-b")
    eng.at(eng.clock.start + 4.0, "poison mid stage", poison)

    eng.at(eng.clock.start + 6.0, "stage a",
           lambda: cp.add_service("svc-stage-a", 4))
    eng.at(eng.clock.start + 8.0, "stage b",
           lambda: cp.add_service("svc-stage-b", 4,
                                  depends_on=["svc-stage-a"]))
    eng.at(eng.clock.start + 10.0, "stages c+d", lambda: (
        cp.add_service("svc-stage-c", 3, depends_on=["svc-stage-b"],
                       on_upstream_failure="halt"),
        cp.add_service("svc-stage-d", 3, depends_on=["svc-stage-b"],
                       on_upstream_failure="rollback")))

    # leader crash between stage releases: the successor's supervisor
    # resumes from the replicated pipeline_status verdicts
    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 14.0, "crash leader mid-rollout",
           crash_leader)
    eng.at(eng.clock.start + 30.0, "stepdown", sim.stepdown_leader)
    eng.at(eng.clock.start + 36.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 42.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))

    cp.expect_service_running("svc-stage-a", 4,
                              label="pipeline-converges")
    # released before the poison verdicts land downstream; churns
    # restarts until the heal clears the poison, then converges
    cp.expect_pipeline_state("svc-stage-b", "released")
    cp.expect_pipeline_state("svc-stage-c", "halted")
    cp.expect_pipeline_state("svc-stage-d", "halted")
    return 75.0


_pipeline_chaos.raft_cp = True


# ----------------------------------------- follower-served read plane
#
# ISSUE 11: the consumer plane (watch streams, agent sessions,
# linearizable control-API reads) is served from FOLLOWER members'
# replicated stores — raft read-index/lease reads underneath — and must
# survive leader loss.  Judged by follower-reads-never-uncommitted,
# lease-read-safe-under-skew and watch-resume-no-gap-no-dup on top of
# the shared checkers.


def _follower_read_failover(sim: Sim) -> float:
    """Watchers + agent sessions pinned to followers while the leader
    crashes, a partition strands an ex-leader whose expired lease must
    refuse to serve, and a clock-skew fault forces lease reads to
    auto-degrade to read-index rounds.  Watch streams must lose nothing
    across member hops (resume-token continuity), agent sessions must
    fail over to different members, and no read may ever be stale."""
    from ..manager.watchapi import WatchRequest
    from ..models import Task
    from ..state.raft.node import ReadUnavailable
    eng = sim.engine
    cp = sim.cp
    cp.enable_follower_reads()
    sim.start_raft_workload(interval=0.8)
    cp.create_tasks(10)
    # one broad watcher, one using the per-kind field filters (the
    # member-agnostic filter path): both judged for continuity
    cp.add_watchers(1)
    cp.add_watchers(1, request=WatchRequest(kinds=[Task],
                                            service_ids=["svc-sim"]))
    cp.start_read_probes(interval=1.5)

    # leader crash mid-run: sessions + streams hop to survivors
    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 14.0, "crash leader", crash_leader)

    # agent churn rides along (session re-resolution under backoff)
    a = cp.agents
    eng.at(eng.clock.start + 18.0, "agent crash", a[1].crash)
    eng.at(eng.clock.start + 27.0, "agent restart", a[1].restart)

    # crash the member a watcher is pinned to: its stream MUST resume on
    # a different member from its token — the continuity checker judges
    # the hop gap-free and dup-free
    def crash_watch_member():
        w = cp.watchers[0]
        m = w.member
        if m is None or not m.alive:
            return
        m.crash()
        eng.after(6.0, "restart watch member", m.restart)
    eng.at(eng.clock.start + 21.0, "crash watcher member",
           crash_watch_member)

    # partition the (new) leader and, mid-partition, make the stranded
    # ex-leader TRY to serve a linearizable read: its lease is expired
    # and its read-index round cannot reach a quorum — the read must
    # come back unavailable (or fresh after heal), never stale
    state: Dict[str, object] = {}

    def cut_leader():
        m = sim.leader()
        if m is None:
            return
        state["ex"] = m
        sim.net.isolate(m.id)
        eng.after(8.0, "heal ex-leader partition",
                  lambda: sim.net.rejoin(m.id))
    eng.at(eng.clock.start + 26.0, "partition leader", cut_leader)

    def stale_probe():
        m = state.get("ex")
        if m is None or not m.alive or m.store is None:
            return
        eng.log("fault stale-read-probe read-plane")
        try:
            cp.linearizable_read(m, lambda tx: len(tx.find(Task)),
                                 timeout=4.0)
            # success means the barrier confirmed FRESH data (e.g. the
            # partition healed under it) — the invariants judge safety
        except ReadUnavailable:
            cp.read_stats["stale_probe_refused"] += 1
    eng.at(eng.clock.start + 28.5, "stale-read probe", stale_probe)

    # clock-skew fault: lease reads must auto-disable (degrade to
    # read-index) for its whole duration
    def skew_on():
        lead = sim.leader()
        victim = next((m for m in sim.managers
                       if m.alive and m is not lead), sim.managers[0])
        state["skewed"] = victim
        victim.tick_scale = 2.0
        eng.log(f"fault clock-skew {victim.id} x2")

    def skew_off():
        victim = state.get("skewed")
        if victim is not None:
            victim.tick_scale = 1.0
            eng.log(f"fault clock-skew {victim.id} off")
    eng.at(eng.clock.start + 38.0, "skew member", skew_on)
    eng.at(eng.clock.start + 46.0, "unskew member", skew_off)

    eng.at(eng.clock.start + 40.0, "more tasks",
           lambda: cp.create_tasks(6))
    return 55.0


_follower_read_failover.raft_cp = True


def _read_storm_degraded(sim: Sim) -> float:
    """Continuous linearizable read load against follower members while
    the leadership churns (stepdowns, a crash, a drop burst): every
    probe must eventually serve — degraded to read-index latency during
    gaps, NEVER an error, never stale — and the follower-pinned watch
    streams must stay continuous throughout."""
    eng = sim.engine
    cp = sim.cp
    cp.enable_follower_reads()
    cp.expect_reads_never_fail = True
    sim.start_raft_workload(interval=0.8)
    cp.create_tasks(12)
    cp.add_watchers(2)
    eng.log("fault read-storm read-plane")
    cp.start_read_probes(interval=1.0, timeout=25.0)

    # rolling leader churn under the storm
    for t in (10.0, 18.0, 34.0):
        eng.at(eng.clock.start + t, "stepdown", sim.stepdown_leader)

    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 24.0, "crash leader", crash_leader)

    eng.at(eng.clock.start + 30.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 36.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))
    eng.at(eng.clock.start + 20.0, "more tasks",
           lambda: cp.create_tasks(8))
    return 48.0


_read_storm_degraded.raft_cp = True


# ------------------------------------------- million-swarm harness
#
# ISSUE 20: overload-safe serving at fleet scale.  A MuxAgentFleet
# multiplexes an env-scalable session count over one driver timer and
# one RPC budget; the dispatcher runs with its overload-protection
# bounds ON (session cap, adaptive heartbeat stretch, bounded status
# buffer, counted assignment-set compaction) and the scenario drives a
# full task fan-out through a leader crash, a follower-plane member
# crash, and a drop burst.  Judged by the shared checkers plus
# overload-sheds-are-counted-and-recovered and
# heartbeat-liveness-under-stretch.


def _million_swarm(sim: Sim) -> float:
    """Full fan-out at fleet scale under overload bounds: the status
    storm right after the fan-out overruns the bounded per-plane update
    buffer (admission sheds — every one counted and recovered), the
    session count runs past the stretch threshold (adaptive heartbeat
    stretching — no premature expiry allowed), and the usual chaos
    (leader crash, follower-plane crash, drop burst, fleet-agent churn)
    rides on top.  Sessions/tasks scale via
    ``SWARM_MILLION_SWARM_SESSIONS`` / ``SWARM_MILLION_SWARM_TASKS``
    (defaults sized for the sweep; crank them for soak runs — the
    event budget scales along)."""
    from .cluster import MuxAgentFleet
    eng = sim.engine
    cp = sim.cp
    sessions = int(os.environ.get("SWARM_MILLION_SWARM_SESSIONS", "64"))
    fanout = int(os.environ.get("SWARM_MILLION_SWARM_TASKS", "150"))
    eng.max_events = max(eng.max_events, sessions * 50_000)
    cp.enable_follower_reads()
    # overload-protection bounds, applied to every dispatcher the plane
    # builds (leader + follower read planes).  The update-buffer bound
    # sits well under the fan-out's per-window status arrivals, so the
    # storm MUST shed; the session cap sits above the fleet, so steady
    # registration stays admitted (register-path sheds are pinned by
    # unit tests instead — a scenario-level cap would just park part of
    # the fleet forever).
    cp.dispatcher_overrides = {
        "max_sessions": sessions + cp.n_agents + 8,
        "hb_stretch_start": max(4, sessions // 8),
        "hb_stretch_max": 4.0,
        "max_pending_updates": max(12, fanout // 12),
        "max_terminal_tasks": max(64, fanout),
    }
    # generous tick budget: the deadline plumbing runs live (virtual
    # now() advances through each group's consensus commit) without
    # starving convergence in the common case
    cp.tick_budget_s = 1.5
    fleet = MuxAgentFleet(cp, sessions, interval=1.0,
                          driver_interval=0.25,
                          rpc_budget=max(64, sessions // 2))
    sim.start_raft_workload(interval=0.8)
    cp.create_tasks(12)

    # the fan-out: one burst to the full task count — the status storm
    # in the following windows is the overload the plane must absorb
    def fan_out():
        eng.log("fault fan-out-burst dispatcher")
        cp.scale(fanout)
    eng.at(eng.clock.start + 8.0, "full fan-out", fan_out)

    # leader crash AT full fan-out: the successor re-learns the fleet
    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 14.0, "crash leader at fan-out",
           crash_leader)

    # follower-plane failover: kill a member SERVING sessions — its
    # shard re-registers across the survivors (jitter-spread, not a
    # thundering herd)
    def crash_follower():
        lead = sim.leader()
        victim = next((m for m in sim.managers
                       if m.alive and m is not lead), None)
        if victim is None:
            return
        victim.crash()
        eng.after(8.0, "restart follower", victim.restart)
    eng.at(eng.clock.start + 26.0, "crash follower plane",
           crash_follower)

    eng.at(eng.clock.start + 34.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.12))
    eng.at(eng.clock.start + 40.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))

    # fleet-agent churn: a slice of sessions dies and returns
    for i, (t_down, t_up) in enumerate(((20.0, 36.0), (30.0, 44.0))):
        a = fleet.agents[i * 7]
        eng.at(eng.clock.start + t_down, "fleet agent crash", a.crash)
        eng.at(eng.clock.start + t_up, "fleet agent restart", a.restart)
    return 60.0


_million_swarm.raft_cp = True


# ----------------------------------------------- rolling-update scenarios
#
# The UpdateSupervisor is live inside the raft-attached control plane
# (threadless drive mode): these scenarios run REAL spec rollouts —
# parallelism, per-batch delay, monitor window, failure pause/rollback —
# under partitions, crashes and churn, with convergence and version
# invariants on top of the shared checkers (UpdateInvariants,
# expect_update, placement quality).


def _update_cfg(action, parallelism=3, delay=0.2, monitor=1.5,
                ratio=0.2):
    from ..models.types import UpdateConfig
    return UpdateConfig(parallelism=parallelism, delay=delay,
                        monitor=monitor, max_failure_ratio=ratio,
                        failure_action=action)


def _rolling_upgrade_chaos(sim: Sim) -> float:
    """Rolling spec updates under chaos: a good rollout rides a leader
    stepdown + leader partition (the in-flight rollout hands off to the
    successor), a poisoned rollout triggers automatic rollback, and a
    second poisoned rollout pauses at the failure threshold — each leg
    bounded by update-convergence invariants, with agent churn and a
    drop burst along the way."""
    from ..models.types import UpdateFailureAction, UpdateState
    eng = sim.engine
    cp = sim.cp
    sim.start_raft_workload(interval=0.8)
    cp.scale(6)
    cp.placement_quality_bound = 4.0

    # leg 1: good rollout, CONTINUE action, leader churn mid-rollout
    def leg1():
        v = cp.rollout("img:2", update=_update_cfg(
            UpdateFailureAction.CONTINUE))
        cp.expect_update(v, (UpdateState.COMPLETED,), 55.0)
    eng.at(eng.clock.start + 8.0, "rollout good", leg1)
    eng.at(eng.clock.start + 11.0, "stepdown mid-rollout",
           sim.stepdown_leader)

    def partition_leader():
        m = sim.leader()
        if m is None:
            return
        sim.net.isolate(m.id)
        eng.after(4.0, "heal leader partition",
                  lambda: sim.net.rejoin(m.id))
    eng.at(eng.clock.start + 16.0, "partition leader mid-rollout",
           partition_leader)

    # leg 2: poisoned rollout -> automatic rollback restores the old spec
    def leg2():
        v = cp.rollout("img:bad-rb", poison=True, update=_update_cfg(
            UpdateFailureAction.ROLLBACK))
        cp.expect_update(v, (UpdateState.ROLLBACK_COMPLETED,), 100.0)
    eng.at(eng.clock.start + 45.0, "rollout poisoned (rollback)", leg2)

    # leg 3: poisoned rollout with PAUSE -> halts at the threshold
    def leg3():
        v = cp.rollout("img:bad-pause", poison=True, update=_update_cfg(
            UpdateFailureAction.PAUSE))
        cp.expect_update(v, (UpdateState.PAUSED,), 110.0)
    eng.at(eng.clock.start + 80.0, "rollout poisoned (pause)", leg3)

    # background churn
    a = cp.agents
    eng.at(eng.clock.start + 20.0, "agent crash", a[2].crash)
    eng.at(eng.clock.start + 30.0, "agent restart", a[2].restart)
    eng.at(eng.clock.start + 50.0, "drop burst",
           lambda: setattr(sim.net.config, "drop_p", 0.1))
    eng.at(eng.clock.start + 58.0, "drop off",
           lambda: setattr(sim.net.config, "drop_p", 0.0))
    eng.at(eng.clock.start + 62.0, "agent partition",
           lambda: a[4].partition(True))
    eng.at(eng.clock.start + 72.0, "agent heal",
           lambda: a[4].partition(False))
    return 100.0


_rolling_upgrade_chaos.raft_cp = True


def _cascading_failure_rebalance(sim: Sim) -> float:
    """Sequential node deaths during a rebalance: a scale-up lands while
    nodes die one after another (heartbeat TTL -> DOWN -> restart
    supervisor re-places), a leader crash rides the cascade, and the
    post-convergence placement must still be balanced (quality bound),
    not just complete."""
    eng = sim.engine
    cp = sim.cp
    sim.start_raft_workload(interval=0.8)
    cp.scale(6)
    cp.placement_quality_bound = 3.5

    eng.at(eng.clock.start + 8.0, "scale up (rebalance)",
           lambda: cp.scale(14))
    a = cp.agents
    # the cascade: one death every ~6s while the scale-up places
    eng.at(eng.clock.start + 10.0, "node death w0", a[0].crash)
    eng.at(eng.clock.start + 16.0, "node death w1", a[1].crash)
    eng.at(eng.clock.start + 22.0, "node death w2", a[2].crash)
    eng.at(eng.clock.start + 21.0, "node return w0", a[0].restart)
    eng.at(eng.clock.start + 28.0, "node return w1", a[1].restart)
    eng.at(eng.clock.start + 34.0, "node return w2", a[2].restart)

    def crash_leader():
        m = sim.leader()
        if m is None:
            return
        m.crash()
        eng.after(6.0, "restart ex-leader", m.restart)
    eng.at(eng.clock.start + 18.0, "crash leader mid-cascade",
           crash_leader)

    eng.at(eng.clock.start + 26.0, "scale down", lambda: cp.scale(10))
    eng.at(eng.clock.start + 32.0, "scale up again",
           lambda: cp.scale(16))
    return 48.0


_cascading_failure_rebalance.raft_cp = True


def _long_soak(sim: Sim) -> float:
    """Long-horizon virtual-time soak: repeated rollouts (every third
    one poisoned and rolled back) over continuous mixed churn — agent
    crash/partition cycles, manager crash/restart, leader stepdowns,
    partitions, drop bursts, scale oscillation.  Default duration is
    ``SWARM_SOAK_VIRTUAL_SECONDS`` (1200 = 20 virtual minutes; crank it
    for multi-day soaks — the event budget scales with it).  Every good
    rollout must converge within its bound and the end placement must
    meet the quality bound."""
    from ..models.types import UpdateFailureAction, UpdateState
    eng = sim.engine
    cp = sim.cp
    duration = float(os.environ.get("SWARM_SOAK_VIRTUAL_SECONDS", "1200"))
    sim.engine.max_events = max(sim.engine.max_events,
                                int(duration) * 2000)
    sim.start_raft_workload(interval=0.9)
    cp.scale(8)
    cp.placement_quality_bound = 4.0
    rng = eng.fork_rng()
    counter = {"n": 0}

    def rollout_cycle():
        if sim.finishing:
            return False
        if eng.clock.elapsed() > duration - 120.0:
            return False   # last rollout must fit its convergence bound
        counter["n"] += 1
        n = counter["n"]
        if n % 3 == 0:
            v = cp.rollout(f"img:bad-{n}", poison=True,
                           update=_update_cfg(
                               UpdateFailureAction.ROLLBACK))
            cp.expect_update(v, (UpdateState.ROLLBACK_COMPLETED,),
                             eng.clock.elapsed() + 110.0)
        else:
            v = cp.rollout(f"img:{n}", update=_update_cfg(
                UpdateFailureAction.CONTINUE))
            cp.expect_update(v, (UpdateState.COMPLETED,),
                             eng.clock.elapsed() + 110.0)
        return None
    eng.every(120.0, "soak rollout", rollout_cycle, phase=15.0)

    def agent_churn():
        if sim.finishing:
            return False
        up = [a for a in cp.agents if a.alive]
        if len(up) > 3:
            victim = rng.choice(up)
            victim.crash()
            eng.after(10.0 + rng.random() * 10.0, "soak agent restart",
                      victim.restart)
        return None
    eng.every(45.0, "soak agent churn", agent_churn, phase=25.0)

    def manager_churn():
        if sim.finishing:
            return False
        alive = [m for m in sim.managers if m.alive]
        if len(alive) <= 2:
            return None
        victim = rng.choice(alive)
        victim.crash()
        eng.after(5.0 + rng.random() * 5.0,
                  f"soak restart {victim.id}", victim.restart)
        return None
    eng.every(140.0, "soak manager churn", manager_churn, phase=70.0)

    def partition_cycle():
        if sim.finishing:
            return False
        mids = [m.id for m in sim.managers]
        lone = rng.choice(mids)
        sim.net.split([lone], [m for m in mids if m != lone])
        eng.after(4.0 + rng.random() * 4.0, "soak heal", sim.net.heal_all)
        return None
    eng.every(90.0, "soak partition", partition_cycle, phase=40.0)

    def stepdown():
        if sim.finishing:
            return False
        sim.stepdown_leader()
        return None
    eng.every(200.0, "soak stepdown", stepdown, phase=100.0)

    def drop_burst():
        if sim.finishing:
            return False
        sim.net.config.drop_p = 0.05 + rng.random() * 0.1
        eng.after(3.0 + rng.random() * 4.0, "soak drops off",
                  lambda: setattr(sim.net.config, "drop_p", 0.0))
        return None
    eng.every(150.0, "soak drop burst", drop_burst, phase=60.0)

    def scale_wobble():
        if sim.finishing:
            return False
        cp.scale(6 + (counter["n"] % 3) * 2)
        return None
    eng.every(160.0, "soak scale wobble", scale_wobble, phase=130.0)
    return duration


_long_soak.raft_cp = True


def _raft_cp_variant(fn: Callable[[Sim], float],
                     base: str) -> Callable[[Sim], float]:
    """Route a legacy standalone-control-plane scenario through the
    raft-attached control plane: same fault timeline, but the real
    scheduler/dispatcher/orchestrators/updater run on the elected
    leader's replicated store, under the failover invariants too."""
    def scenario(sim: Sim) -> float:
        return fn(sim)
    scenario.raft_cp = True
    scenario.__doc__ = (f"'{base}' driven through the raft-attached "
                        "control plane (Sim(raft_cp=True)): "
                        + (fn.__doc__ or "").strip())
    return scenario


SCENARIOS: Dict[str, Callable[[Sim], float]] = {
    "partition-churn": _partition_churn,
    "crash-leader-mid-commit": _crash_leader_mid_commit,
    "crash-restart-churn": _crash_restart_churn,
    "clock-skew": _clock_skew,
    "agent-storm": _agent_storm,
    "pipelined-commit-churn": _pipelined_commit_churn,
    "fused-differential-churn": _fused_differential_churn,
    # streaming scheduler: incremental vs full-replan twin differential
    "steady-state-churn": _steady_state_churn,
    "random-fuzz": _random_fuzz,
    # failover suite (raft-attached control plane); depth = store-level
    # chunk-pipelined proposal window
    "leader-crash-mid-tick": _mk_leader_crash_mid_tick(2),
    "leader-crash-mid-tick-d1": _mk_leader_crash_mid_tick(1),
    "partition-pipelined-commit": _mk_partition_pipelined_commit(2),
    "partition-pipelined-commit-d1": _mk_partition_pipelined_commit(1),
    "failover-churn-rollout": _failover_churn_rollout,
    # priority & preemption (device victim kernel + host oracle)
    "preemption-storm": _preemption_storm,
    # autoscaler + multi-tenant QoS (quota mask column + control loop)
    "tenant-storm": _tenant_storm,
    # gang scheduling & pipeline workflows (atomic admission, DAG gate)
    "gang-deadlock": _gang_deadlock,
    "pipeline-chaos": _pipeline_chaos,
    # follower-served read plane (read-index/lease reads, resume tokens)
    "follower-read-failover": _follower_read_failover,
    "read-storm-degraded": _read_storm_degraded,
    # overload plane + mux-fleet harness (ISSUE 20)
    "million-swarm": _million_swarm,
    # rolling-update suite (real UpdateSupervisor, threadless drive)
    "rolling-upgrade-chaos": _rolling_upgrade_chaos,
    "cascading-failure-rebalance": _cascading_failure_rebalance,
    "long-soak": _long_soak,
    # legacy scenarios routed through the raft-attached control plane
    "partition-churn-rcp": _raft_cp_variant(_partition_churn,
                                            "partition-churn"),
    "crash-restart-churn-rcp": _raft_cp_variant(_crash_restart_churn,
                                                "crash-restart-churn"),
    "agent-storm-rcp": _raft_cp_variant(_agent_storm, "agent-storm"),
}

#: the failover sweep scripts/chaos_sweep.py seed-sweeps by default
FAILOVER_SCENARIOS = (
    "leader-crash-mid-tick", "leader-crash-mid-tick-d1",
    "partition-pipelined-commit", "partition-pipelined-commit-d1",
    "failover-churn-rollout",
)

#: rolling-update chaos suite (ISSUE 8)
UPDATE_SCENARIOS = (
    "rolling-upgrade-chaos", "cascading-failure-rebalance", "long-soak",
)

#: priority & preemption suite (ISSUE 10)
PREEMPT_SCENARIOS = ("preemption-storm",)

#: autoscaler + multi-tenant QoS suite (ISSUE 12)
QOS_SCENARIOS = ("tenant-storm",)

#: gang scheduling & pipeline workflows suite (ISSUE 16)
GANG_SCENARIOS = ("gang-deadlock", "pipeline-chaos")

#: follower-served read plane (ISSUE 11)
READ_SCENARIOS = ("follower-read-failover", "read-storm-degraded")

#: streaming scheduler differential (ISSUE 14)
STREAMING_SCENARIOS = ("steady-state-churn",)

#: overload plane + million-swarm harness (ISSUE 20)
OVERLOAD_SCENARIOS = ("million-swarm",)

#: legacy fault timelines re-driven through Sim(raft_cp=True)
LEGACY_RCP_SCENARIOS = (
    "partition-churn-rcp", "crash-restart-churn-rcp", "agent-storm-rcp",
)

#: scenarios the seed-rotating fuzzers (``python -m swarmkit_tpu.sim
#: --fuzz`` without --scenario, and chaos_sweep --suite fuzz) draw from.
#: Every registry entry must be here or in FUZZ_EXCLUDED with a reason —
#: tests/test_update_chaos.py enforces the parity, so a new scenario
#: cannot silently lag fuzz coverage.
FUZZ_EXCLUDED: Dict[str, str] = {
    "long-soak": "minutes of virtual time per run; swept by the "
                 "dedicated slow soak test, not per-seed rotation",
    "million-swarm": "heavyweight mux-fleet fan-out (an order of "
                     "magnitude more events per run than the rotation "
                     "scenarios); swept by its own chaos_sweep suite "
                     "and the dedicated determinism test instead",
}
FUZZ_POOL: tuple = tuple(
    sorted(n for n in SCENARIOS if n not in FUZZ_EXCLUDED))


# ------------------------------------------------------------------ runner

def run_scenario(name: str, seed: int, n_managers: int = 3,
                 n_agents: int = 5, grace: float = 20.0,
                 keep_trace: bool = False,
                 flightrec_dir: Optional[str] = None) -> SimReport:
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}")
    sim = Sim(seed, n_managers=n_managers, n_agents=n_agents,
              net_config=NetConfig(),
              raft_cp=getattr(fn, "raft_cp", False))
    with sim:
        # record control-plane spans under the virtual clock: epoch and
        # every timestamp are virtual, span ids are a counter, and the
        # sim is single-threaded — the exported JSON is a pure function
        # of (scenario, seed).  The shared tracer's prior recording
        # state (an embedding process may be tracing) is saved and
        # restored around the scenario.  Constraint: other threads must
        # not RECORD spans while the scenario runs (their wall-clock
        # spans would land in the sim buffer and break byte-identity) —
        # run sims from the CLI or tests, not inside a live traced
        # manager process.
        saved = tracer.save_state()
        fr_saved = flightrec.save_state()
        pl_saved = planes_mod.save_state()
        j_saved = journeys.save_state()
        tracer.reset()
        tracer.enable()
        # the black box records continuously under virtual time:
        # spans (tracer sink), store events, raft transitions, and
        # periodic metric samples (deltas, so concurrent-process
        # history cannot leak into the capture).  A violating or
        # crashing run dumps it as a post-mortem whose sha is a pure
        # function of the seed.
        flightrec.reset(deterministic=True)
        flightrec.enabled = True
        # journeys at full sample under the virtual clock: every member
        # mints milestones from replicated stamps via the recorder's
        # store taps, so the ledger stitches across leader crashes and
        # its bytes are seed-pure (JOURNEY_CAP bounds memory)
        planes_mod.reset()
        journeys.reset(sample_rate=1.0)
        journeys.enabled = True
        flightrec.journey_sink = journeys.handle_event
        # raft-attached mode taps every member's replicated store (the
        # leader's commits and the followers' replayed applies both land
        # in the black box); standalone taps the one control-plane store.
        # A store rebuilt by a crash-restart is not re-tapped — the
        # post-mortem keeps the pre-crash view, the WAL has the rest.
        fr_stores = [m.store for m in sim.managers
                     if m.store is not None] or [sim.cp.store]
        for s in fr_stores:
            flightrec.watch_store(s)
        sampler = Sampler(deterministic=True)

        def _sample():
            if sim.cp.stopped:
                return False
            flightrec.poll_store()
            sampler.sample()
            return None

        sim.engine.every(5.0, "flightrec sample", _sample)
        fr_path = fr_sha = ""
        crashed = False
        try:
            sim.engine.log(f"scenario {name} seed {seed}")
            duration = fn(sim)
            sim.run(duration)
            sim.finish(grace=grace)
            stats = sim.stats()
        except BaseException as e:
            crashed = True
            flightrec.note(f"scenario crashed: {type(e).__name__}: {e}")
            raise
        finally:
            tracer.disable()
            obs_trace = tracer.to_json()
            # fold any store events still buffered into the ledger
            # before capturing it (the dump below reads it too)
            flightrec.poll_store()
            j_dump = journeys.dump()
            j_sha = hashlib.sha256(journeys.dump_bytes()).hexdigest()
            if crashed or sim.violations.items:
                fr_path, fr_sha = _dump_flightrec(name, seed,
                                                  flightrec_dir)
            flightrec.enabled = False
            journeys.enabled = False
            for s in fr_stores:                     # only the sim's taps
                flightrec.unwatch_store(s)
            flightrec.restore_state(fr_saved)
            journeys.restore_state(j_saved)
            planes_mod.restore_state(pl_saved)
            tracer.restore_state(saved)
    return SimReport(
        scenario=name, seed=seed, duration=duration + grace,
        events=sim.engine.events_run, trace_hash=sim.engine.trace_hash(),
        ok=not sim.violations.items,
        violations=list(sim.violations.items), stats=stats,
        trace=list(sim.engine.trace) if keep_trace else [],
        obs_trace=obs_trace, flightrec_path=fr_path,
        flightrec_sha256=fr_sha, journeys_dump=j_dump,
        journeys_sha256=j_sha)


def _dump_flightrec(name: str, seed: int,
                    flightrec_dir: Optional[str]) -> tuple:
    """Write the post-mortem (``flightrec_<scenario>_seed<N>.json``) in
    ``flightrec_dir`` (default: $SWARM_SIM_FLIGHTREC_DIR, else cwd)."""
    d = flightrec_dir or os.environ.get("SWARM_SIM_FLIGHTREC_DIR") or "."
    path = os.path.join(d, f"flightrec_{name}_seed{seed}.json")
    try:
        sha = flightrec.dump(path)
    except OSError:
        return "", ""
    return path, sha
