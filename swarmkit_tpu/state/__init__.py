from .events import (
    Event, EventCommit, EventSnapshotRestore, EventTaskBlock, match, any_of,
)
from .store import (
    All, AlreadyExists, Batch, By, ByCustom, ByDesiredState, ByIDPrefix,
    ByKind, ByMembership, ByName, ByNamePrefix, ByNode, ByReferencedConfig,
    ByReferencedNetwork, ByReferencedSecret, ByRole, ByService, BySlot,
    ByTaskState, ByVolumeGroup, MemoryStore, NameConflict, NotFound, Or,
    Proposer, ReadTx, SequenceConflict, StoreAction, StoreError,
    TaskBlockAction, Where, WriteTx, MAX_CHANGES_PER_TX,
)
from .watch import Closed, Queue, Subscription
