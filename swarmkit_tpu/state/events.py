"""Store change events + matcher combinators.

The reference generates a typed event per (object kind × action) with
per-field "checks" (api/*.pb.go EventCreateTask etc.).  Here one generic
``Event`` carries (action, object, old_object) and matchers are plain
predicate builders — equally expressive, no codegen.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple, Type

from ..utils.metrics import registry as _metrics

# cached Timer reference (Registry.reset() resets it in place): total
# wall time spent synthesizing per-task events out of coalesced blocks —
# the watch fan-out cost the bench reports as ``fanout_s``
_FANOUT_TIMER = _metrics.timer("swarm_watch_fanout_latency")


@dataclass(frozen=True)
class Event:
    action: str              # "create" | "update" | "delete"
    obj: Any                 # the (new) object; for delete, the deleted object
    old: Any = None          # previous version on update, else None
    # store version this change committed at (the watch resume token).
    # 0 = unstamped: create/update events fall back to the object's own
    # meta.version.index; the store stamps deletes explicitly (a delete
    # burns a version index the payload cannot carry).
    version: int = 0

    @property
    def collection(self) -> str:
        return self.obj.collection


def event_version(ev: Event) -> int:
    """The change's store version — the resume token a watch client hands
    back to continue exactly after this event, on ANY member's replicated
    store (version stamping is part of the replicated state, so tokens
    survive reconnecting to a different member)."""
    if ev.version:
        return ev.version
    meta = getattr(ev.obj, "meta", None)
    return meta.version.index if meta is not None else 0


class EventTaskBlock:
    """One coalesced event for a columnar scheduler block commit.

    Carries the block arrays (pre-assignment tasks, node ids, version
    base, status columns); ``expand_events()`` lazily synthesizes the
    equivalent per-task update Events ONCE, shared across every
    subscriber — the watch queue expands it for subscribers that have
    not opted into block delivery (``accepts_blocks``), so existing
    consumers observe exactly the per-task stream the per-object commit
    path would have produced.  No reference counterpart: the reference
    publishes one event per task (state/store/memory.go publish); the
    block form is what lets the TPU scheduler's array-shaped commits
    stay legal with live watchers.
    """

    __slots__ = ("olds", "node_ids", "base_version", "state", "message",
                 "ts", "_events", "_per_node")

    def __init__(self, olds, node_ids, base_version, state, message, ts):
        self.olds = olds
        self.node_ids = node_ids
        self.base_version = base_version
        self.state = state
        self.message = message
        self.ts = ts
        self._events = None
        self._per_node = None

    def expand_events(self):
        """Synthesized per-task Events (cached; thread-safe because the
        build is idempotent and the final assignment is atomic).  One
        native pass when the commit plane's hot path is available
        (hotpath.c fanout_expand); the list comprehension below is the
        fallback and its differential oracle.  Runs on CONSUMER threads
        only — never under the store locks (swarmlint lock-discipline
        bans fanout_expand under them)."""
        events = self._events
        if events is None:
            from .. import native
            from .store import _materialize_task
            base = self.base_version
            state, message, ts = self.state, self.message, self.ts
            hp = native.get_commit()
            with _FANOUT_TIMER.time():
                if hp is not None:
                    from ..models.types import TaskState, TaskStatus
                    status = TaskStatus(state=TaskState(state),
                                        timestamp=ts, message=message)
                    events = hp.fanout_expand(self.olds, self.node_ids,
                                              base, ts, status, Event)
                else:
                    events = [
                        Event("update",
                              _materialize_task(old, nid, base + 1 + i,
                                                ts, state, message),
                              old)
                        for i, (old, nid) in enumerate(zip(self.olds,
                                                           self.node_ids))
                    ]
            self._events = events
        return events

    def per_node(self):
        """node_id -> [(old_task, version), ...] grouping (cached,
        shared).  Block-aware per-node consumers (dispatcher sessions)
        use this for an O(1) membership probe instead of filtering the
        synthesized per-task stream — with S agent sessions that turns
        O(tasks x S) predicate work into O(tasks + S).  Native pass when
        available (hotpath.c per_node_group); the loop below is the
        oracle."""
        grouped = self._per_node
        if grouped is None:
            from .. import native
            base = self.base_version
            hp = native.get_commit()
            if hp is not None:
                with _FANOUT_TIMER.time():
                    grouped = hp.per_node_group(self.olds, self.node_ids,
                                                base)
            else:
                grouped = {}
                for i, (old, nid) in enumerate(zip(self.olds,
                                                   self.node_ids)):
                    lst = grouped.get(nid)
                    if lst is None:
                        lst = grouped[nid] = []
                    lst.append((old, base + 1 + i))
            self._per_node = grouped
        return grouped

    def __len__(self) -> int:
        return len(self.olds)


@dataclass(frozen=True)
class EventCommit:
    """Published once per committed transaction — drives debounced loops
    (reference: state/store/memory.go publishes state.EventCommit)."""

    version: int = 0


@dataclass(frozen=True)
class EventSnapshotRestore:
    """Published after a full store restore; watchers must resync."""


Pred = Callable[[Any], bool]


def is_event(ev: Any) -> bool:
    return isinstance(ev, Event)


def match(kind: Optional[Type] = None, actions: Tuple[str, ...] = (),
          where: Optional[Pred] = None) -> Pred:
    """Build an event predicate: object kind, action set, and object filter.

    ``where`` is applied to the new object (or the deleted one).
    """

    def pred(ev: Any) -> bool:
        if not isinstance(ev, Event):
            return False
        if kind is not None and not isinstance(ev.obj, kind):
            return False
        if actions and ev.action not in actions:
            return False
        if where is not None and not where(ev.obj):
            return False
        return True

    return pred


def any_of(*preds: Pred) -> Pred:
    def pred(ev: Any) -> bool:
        return any(p(ev) for p in preds)
    return pred


def commit_or(pred: Pred) -> Pred:
    """Match commit events plus whatever ``pred`` matches."""

    def p(ev: Any) -> bool:
        return isinstance(ev, EventCommit) or pred(ev)
    return p
