"""Store change events + matcher combinators.

The reference generates a typed event per (object kind × action) with
per-field "checks" (api/*.pb.go EventCreateTask etc.).  Here one generic
``Event`` carries (action, object, old_object) and matchers are plain
predicate builders — equally expressive, no codegen.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class Event:
    action: str              # "create" | "update" | "delete"
    obj: Any                 # the (new) object; for delete, the deleted object
    old: Any = None          # previous version on update, else None

    @property
    def collection(self) -> str:
        return self.obj.collection


@dataclass(frozen=True)
class EventCommit:
    """Published once per committed transaction — drives debounced loops
    (reference: state/store/memory.go publishes state.EventCommit)."""

    version: int = 0


@dataclass(frozen=True)
class EventSnapshotRestore:
    """Published after a full store restore; watchers must resync."""


Pred = Callable[[Any], bool]


def is_event(ev: Any) -> bool:
    return isinstance(ev, Event)


def match(kind: Optional[Type] = None, actions: Tuple[str, ...] = (),
          where: Optional[Pred] = None) -> Pred:
    """Build an event predicate: object kind, action set, and object filter.

    ``where`` is applied to the new object (or the deleted one).
    """

    def pred(ev: Any) -> bool:
        if not isinstance(ev, Event):
            return False
        if kind is not None and not isinstance(ev.obj, kind):
            return False
        if actions and ev.action not in actions:
            return False
        if where is not None and not where(ev.obj):
            return False
        return True

    return pred


def any_of(*preds: Pred) -> Pred:
    def pred(ev: Any) -> bool:
        return any(p(ev) for p in preds)
    return pred


def commit_or(pred: Pred) -> Pred:
    """Match commit events plus whatever ``pred`` matches."""

    def p(ev: Any) -> bool:
        return isinstance(ev, EventCommit) or pred(ev)
    return p
