from .core import RaftCore
from .node import NotLeader, ProposalDropped, RaftNode
from .storage import DecryptionError, Encoder, KeyEncoder, RaftLogger
from .transport import LocalNetwork

__all__ = ["DecryptionError", "Encoder", "KeyEncoder",
           "LocalNetwork", "NotLeader",
           "ProposalDropped", "RaftCore", "RaftLogger", "RaftNode"]
