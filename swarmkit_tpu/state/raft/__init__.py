from .core import RaftCore
from .node import NotLeader, ProposalDropped, RaftNode
from .storage import Encoder, RaftLogger
from .transport import LocalNetwork

__all__ = ["Encoder", "LocalNetwork", "NotLeader", "ProposalDropped",
           "RaftCore", "RaftLogger", "RaftNode"]
