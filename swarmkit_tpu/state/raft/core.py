"""Raft consensus core: a sans-IO state machine.

Reference behavior: manager/state/raft/raft.go wraps etcd-raft; this module
implements the same consensus protocol (leader election with randomized
timeouts, log replication, commit by majority match, snapshot install,
leader no-op entry on election) as a pure state machine — no threads, no
clocks, no sockets.  The driver (node.py) feeds it ``tick()`` and
``step(msg)`` and drains ``ready()``:

    rd = core.ready()
    1. persist rd.hard_state and rd.entries (WAL) BEFORE sending
    2. send rd.messages
    3. apply rd.committed to the application state machine
    4. core.advance(rd)

This ordering gives raft's durability guarantee: nothing is sent or applied
before it is on stable storage (raft.go:540's Ready loop does the same).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...models.types import now as _now

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

ENTRY_NORMAL = 0
ENTRY_NOOP = 1
ENTRY_CONF = 2   # data = JSON {"op": "add"|"remove", "id": member id,
                 #                "addr": optional [host, port]}

# leadership epochs are minted at term * stride: the headroom between
# consecutive terms absorbs every fence bump a reign can accumulate
# (deposal + explicit handler fences), so an epoch minted under any
# later term — in particular, after a crash-restart — is strictly
# greater than every epoch the earlier term could have reached
EPOCH_TERM_STRIDE = 1 << 20


@dataclass
class Entry:
    term: int
    index: int
    data: bytes = b""
    type: int = ENTRY_NORMAL


@dataclass
class HardState:
    """Must be persisted before acting on a Ready (raft thesis §3.8)."""

    term: int = 0
    voted_for: str = ""
    commit: int = 0


@dataclass
class Snapshot:
    index: int = 0
    term: int = 0
    data: bytes = b""
    # the peer set as of `index`: conf entries before the snapshot are
    # compacted away, so membership must travel with it (etcd ConfState)
    peers: List[str] = field(default_factory=list)
    # transport addresses learned through conf entries: every member must
    # be able to dial every other even if it never served their join RPC
    # (the reference stores member addrs in the raft member list itself,
    # membership/cluster.go)
    peer_addrs: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    # managers' remote-API addresses: distributed to agents via heartbeat
    # responses so they can fail over (reference: session Message.Managers)
    api_addrs: Dict[str, Tuple[str, int]] = field(default_factory=dict)


@dataclass
class Message:
    type: str            # vote / vote_resp / app / app_resp / snap
                         # / read_index / read_index_resp
    term: int
    src: str
    dst: str
    # vote
    last_log_index: int = 0
    last_log_term: int = 0
    granted: bool = False
    # append
    prev_index: int = 0
    prev_term: int = 0
    entries: List[Entry] = field(default_factory=list)
    commit: int = 0
    success: bool = False
    match_index: int = 0
    # snapshot
    snapshot: Optional[Snapshot] = None
    # read-index protocol (etcd-raft MsgReadIndex/MsgReadIndexResp):
    # heartbeat rounds carry a context id that successful followers echo
    # so the leader can count a quorum for the reads pinned to the round
    read_ctx: int = 0
    # requester-minted id for one read-barrier request, echoed in the resp
    read_seq: int = 0
    # the leader's confirmed commit index for that request
    read_index: int = 0
    # the resp was served off the leader lease (no quorum round)
    lease_read: bool = False


@dataclass
class Ready:
    hard_state: Optional[HardState]
    entries: List[Entry]             # new entries to persist
    messages: List[Message]          # send after persisting
    committed: List[Entry]           # apply to the state machine
    snapshot: Optional[Snapshot]     # received snapshot to persist+restore


class RaftCore:
    """One member's consensus state (pure; deterministic given inputs)."""

    def __init__(self, node_id: str, peers: Sequence[str],
                 election_tick: int = 10, heartbeat_tick: int = 1,
                 rng: Optional[random.Random] = None,
                 prevote: bool = True):
        self.id = node_id
        self.peers = set(peers) | {node_id}
        # pre-vote (raft thesis §9.6, etcd-raft PreVote): before a real
        # campaign, probe a majority with a WOULD-you-vote round that
        # mutates no state — a partitioned rejoiner keeps timing out its
        # pre-vote instead of bumping its term, so it cannot depose a
        # healthy leader when the partition heals
        self.prevote = prevote
        self._in_prevote = False
        self._prevotes: Dict[str, bool] = {}
        self.peer_addrs: Dict[str, Tuple[str, int]] = {}
        self.api_addrs: Dict[str, Tuple[str, int]] = {}
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self._rng = rng or random.Random()

        self.term = 0
        self.voted_for = ""
        self.role = FOLLOWER
        self.leader_id = ""
        # leadership-epoch fencing token (Chubby sequencer / ZooKeeper
        # zxid-epoch style): minted strictly monotonically on every
        # transition INTO leadership and bumped again the moment
        # leadership is lost (or explicitly fenced), so a proposal
        # stamped with the epoch it was created under can be rejected at
        # the proposer's fence points even if its in-flight role checks
        # race a re-election.  Epochs live at term * EPOCH_TERM_STRIDE
        # plus a per-term fence count: a new election's term strictly
        # exceeds every persisted term, so post-restart epochs are
        # strictly above every pre-crash epoch (however many fences
        # inflated it, up to the stride) WITHOUT persisting the counter
        # itself — a stale pin can never collide across a restart.
        self.leadership_epoch = 0
        # observability tap: called as (member_id, role, term) on every
        # role transition.  The core stays sans-IO — embedders (RaftNode,
        # the sim's SimManager) point this at the flight recorder; the
        # callback must be non-throwing and side-effect-free w.r.t.
        # consensus state.
        self.on_transition: Optional[Callable[[str, str, int], None]] = None

        # log[0] corresponds to index snap_index+1
        self.log: List[Entry] = []
        self.snap_index = 0
        self.snap_term = 0
        self.commit_index = 0
        self.applied_index = 0

        self._elapsed = 0
        self._stepdown_ticks = 0
        self._timeout = self._rand_timeout()
        self._votes: Dict[str, bool] = {}
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        # index of the no-op appended at election: the leader is not ready
        # for proposals until it is applied (all prior-term entries are then
        # in the state machine — swarmkit's signalledLeadership gate)
        self.noop_index = 0

        self._msgs: List[Message] = []
        self._persisted_index = 0    # highest entry index known persisted
        self._hs_dirty = False
        self._pending_snapshot: Optional[Snapshot] = None
        # ---- read-index / lease state (etcd-raft readOnly + lease read).
        # The core stays sans-IO for consensus; the lease alone reads
        # wall/virtual time through the models.types.now() seam, because
        # a lease IS a clock claim ("no one can have been elected yet").
        #: seconds a quorum-acked heartbeat round extends the lease; the
        #: embedder sets this to (election_tick * tick_seconds) — it MUST
        #: stay below the minimum election timeout or the lease argument
        #: is void.  None disables the lease fast path (read-index only).
        self.lease_duration: Optional[float] = None
        #: fraction shaved off the lease for clock drift between members
        #: (the reference design's clock-drift safety margin)
        self.lease_drift_margin = 0.2
        #: embedder veto: when set and returning False, lease reads are
        #: refused (the sim wires this to "a clock-skew fault is active",
        #: which voids the lease math — election timers no longer run at
        #: spec rate).  None = no veto (production default).
        self.lease_gate: Optional[Callable[[], bool]] = None
        #: the clock the lease window is measured on.  Defaults to the
        #: models.types.now() seam (virtual under the sim — lease math
        #: must be a pure function of the seed there); production
        #: embedders (RaftNode) override it with a MONOTONIC clock: a
        #: backward wall-clock step (NTP) must shrink the lease to
        #: nothing, never extend it past the election timeout.
        self.lease_clock: Callable[[], float] = _now
        self._lease_expiry = 0.0
        #: local read-barrier results: read_seq -> (index, ok, lease)
        self.read_results: Dict[int, Tuple[int, bool, bool]] = {}
        #: called (read_seq, index, ok, lease) whenever a local read
        #: resolves — the driver completes its blocked readers here
        self.on_read_ready: Optional[
            Callable[[int, int, bool, bool], None]] = None
        #: plain tallies; embedders export them as metrics
        self.read_stats = {"lease_served": 0, "read_index_served": 0,
                           "lease_refused_gate": 0, "read_failed": 0}
        self._read_seq = 0           # local request ids (this member)
        self._read_ctx = 0           # heartbeat-round context (leader)
        self._read_acks: Dict[int, set] = {}
        self._hb_sent_at: Dict[int, float] = {}
        # (ctx, requester, read_seq, index): reads pinned to a round
        self._pending_reads: List[Tuple[int, str, int, int]] = []
        # check-quorum: a leader that cannot reach a majority steps down so
        # its blocked proposals fail fast (etcd-raft CheckQuorum behavior)
        self._quorum_elapsed = 0
        self._recent_active: set = set()
        # set once a committed conf change removes this member: the node
        # stops ticking/voting so it cannot disrupt the remaining cluster
        self.removed = False
        # single-conf-change-at-a-time guard (etcd pendingConfIndex)
        self.pending_conf_index = 0

    # ------------------------------------------------------------- log utils

    def last_index(self) -> int:
        return self.snap_index + len(self.log)

    def _term_at(self, index: int) -> Optional[int]:
        if index == 0:
            return 0
        if index == self.snap_index:
            return self.snap_term
        if index < self.snap_index or index > self.last_index():
            return None
        return self.log[index - self.snap_index - 1].term

    def _entry_at(self, index: int) -> Entry:
        return self.log[index - self.snap_index - 1]

    def entries_from(self, index: int) -> List[Entry]:
        if index <= self.snap_index:
            return []
        return self.log[index - self.snap_index - 1:]

    def _rand_timeout(self) -> int:
        return self.election_tick + self._rng.randrange(self.election_tick)

    def step_down(self) -> None:
        """Leader voluntarily abdicates (demotion path).  Its own
        campaigns are suppressed for a bounded window so another peer wins
        the next election instead of this node flapping straight back into
        leadership (it usually has the most up-to-date log); the bound
        keeps a lone up-to-date survivor able to recover leadership if no
        other peer can win (reference: raft.go:1134 TransferLeadership
        targets a peer for the same reason)."""
        if self.role == LEADER:
            self._become_follower(self.term)
        self._stepdown_ticks = 10 * self.election_tick

    # --------------------------------------------------------------- loading

    def load(self, hard_state: HardState, entries: List[Entry],
             snapshot: Optional[Snapshot]) -> None:
        """Restore persisted state on restart (before any tick/step)."""
        if snapshot is not None:
            self.snap_index = snapshot.index
            self.snap_term = snapshot.term
            self.commit_index = snapshot.index
            self.applied_index = snapshot.index
            if snapshot.peers:
                self.peers = set(snapshot.peers)
            if snapshot.peer_addrs:
                self.peer_addrs = {k: tuple(v)
                                   for k, v in snapshot.peer_addrs.items()}
            if snapshot.api_addrs:
                self.api_addrs = {k: tuple(v)
                                  for k, v in snapshot.api_addrs.items()}
        self.term = hard_state.term
        self.voted_for = hard_state.voted_for
        # epoch floor: any election after this restart runs at a term —
        # and hence an epoch stride — above everything minted before
        # the crash
        self.leadership_epoch = max(self.leadership_epoch,
                                    hard_state.term * EPOCH_TERM_STRIDE)
        self.commit_index = max(self.commit_index, hard_state.commit)
        self.log = [e for e in entries if e.index > self.snap_index]
        self._persisted_index = self.last_index()

    # ----------------------------------------------------------------- ticks

    def tick(self) -> None:
        if self.removed:
            return
        if self.role == LEADER:
            self._elapsed += 1
            if self._elapsed >= self.heartbeat_tick:
                self._elapsed = 0
                self._broadcast_append(heartbeat=True)
            self._quorum_elapsed += 1
            if self._quorum_elapsed >= 2 * self.election_tick:
                self._quorum_elapsed = 0
                active = len(self._recent_active | {self.id})
                self._recent_active = set()
                if active <= len(self.peers) // 2:
                    self._become_follower(self.term)
        else:
            if self._stepdown_ticks > 0:
                self._stepdown_ticks -= 1
                self._elapsed = 0
                return
            self._elapsed += 1
            if self._elapsed >= self._timeout:
                if self.prevote and len(self.peers) > 1:
                    self._prevote_campaign()
                else:
                    self._campaign()

    def _prevote_campaign(self) -> None:
        """Probe for electability without mutating term/vote state."""
        self._in_prevote = True
        self._prevotes = {self.id: True}
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        for peer in sorted(self.peers):
            if peer == self.id:
                continue
            self._msgs.append(Message(
                type="prevote", term=self.term + 1, src=self.id, dst=peer,
                last_log_index=self.last_index(),
                last_log_term=self._term_at(self.last_index()) or 0))

    def _campaign(self) -> None:
        self._become_candidate()
        if len(self.peers) == 1:
            self._become_leader()
            return
        for peer in sorted(self.peers):
            if peer == self.id:
                continue
            self._msgs.append(Message(
                type="vote", term=self.term, src=self.id, dst=peer,
                last_log_index=self.last_index(),
                last_log_term=self._term_at(self.last_index()) or 0))

    # ------------------------------------------------------------ transitions

    def fence_epoch(self) -> None:
        """Invalidate every proposal created under the current epoch.
        Called automatically on deposal; role-transition handlers (the
        Manager, the sim's control plane) call it explicitly so their
        stop-the-loops path and the fence can never disagree."""
        self.leadership_epoch += 1

    def _become_follower(self, term: int, leader: str = "") -> None:
        role_changed = self.role != FOLLOWER
        if self.role == LEADER:
            # deposed: fence the reign's epoch so in-flight proposals
            # created under it fail even if this member is re-elected
            # before they reach a fence point
            self.fence_epoch()
            self._fail_pending_reads()
        if term > self.term:
            self.term = term
            self.voted_for = ""
            self._hs_dirty = True
        self.role = FOLLOWER
        self.leader_id = leader
        self._in_prevote = False
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        # only genuine role changes reach the tap: this path also runs
        # for term bumps while already a follower (every higher-term
        # message), which would flood the bounded raft ring
        if role_changed and self.on_transition is not None:
            self.on_transition(self.id, self.role, self.term)

    def _become_candidate(self) -> None:
        self.term += 1
        self.voted_for = self.id
        self._hs_dirty = True
        self._in_prevote = False
        self.role = CANDIDATE
        self.leader_id = ""
        self._votes = {self.id: True}
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        if self.on_transition is not None:
            self.on_transition(self.id, self.role, self.term)

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_id = self.id
        # a lease is earned per reign: the first quorum-acked heartbeat
        # round under THIS term starts it (a carried-over expiry could
        # overlap the previous leader's)
        self._lease_expiry = 0.0
        self._read_acks.clear()
        self._hb_sent_at.clear()
        self._pending_reads.clear()
        # mint a fresh leadership epoch.  max(): strictly greater than
        # every epoch this process ever minted or fenced, and — because
        # an election's term strictly exceeds every persisted term, and
        # a reign's fence bumps never reach the next term's stride —
        # strictly greater than any epoch minted before a crash-restart.
        self.leadership_epoch = max(self.leadership_epoch + 1,
                                    self.term * EPOCH_TERM_STRIDE)
        self._elapsed = 0
        last = self.last_index()
        for peer in self.peers:
            self.next_index[peer] = last + 1
            self.match_index[peer] = 0
        self.match_index[self.id] = last
        # no-op entry commits prior-term entries (raft thesis §3.6.2; etcd
        # does the same on election)
        self._append(Entry(term=self.term, index=last + 1,
                           type=ENTRY_NOOP))
        self.noop_index = last + 1
        if self.on_transition is not None:
            self.on_transition(self.id, self.role, self.term)
        self._broadcast_append()

    @property
    def leader_ready(self) -> bool:
        """True once this leader may accept proposals: its election no-op
        (and hence everything before it) has been applied locally."""
        return self.role == LEADER and self.applied_index >= self.noop_index

    # -------------------------------------------------------------- proposal

    def propose(self, data: bytes) -> int:
        """Leader-only: append a new entry; returns its index."""
        assert self.role == LEADER, "propose on non-leader"
        index = self.last_index() + 1
        self._append(Entry(term=self.term, index=index, data=data))
        self._broadcast_append()
        return index

    def propose_conf_change(self, op: str, member_id: str,
                            addr: Optional[Tuple[str, int]] = None,
                            api_addr: Optional[Tuple[str, int]] = None
                            ) -> int:
        """Leader-only membership change (reference: raft.go Join :926 /
        Leave :1138 propose ConfChange entries).  Single-change-at-a-time
        semantics: a second change is refused until the first has been
        APPLIED (etcd pendingConfIndex)."""
        import json as _json
        assert self.role == LEADER, "conf change on non-leader"
        if self.pending_conf_index > self.applied_index:
            raise RuntimeError(
                "a membership change is already in flight")
        index = self.last_index() + 1
        self.pending_conf_index = index
        change = {"op": op, "id": member_id}
        if addr is not None:
            change["addr"] = list(addr)
        if api_addr is not None:
            change["api_addr"] = list(api_addr)
        self._append(Entry(term=self.term, index=index,
                           data=_json.dumps(change).encode(),
                           type=ENTRY_CONF))
        self._broadcast_append()
        return index

    def apply_conf_change(self, op: str, member_id: str,
                          addr: Optional[Tuple[str, int]] = None,
                          api_addr: Optional[Tuple[str, int]] = None
                          ) -> None:
        """Called by the driver when an ENTRY_CONF commits."""
        if op == "add":
            self.peers.add(member_id)
            if addr is not None:
                self.peer_addrs[member_id] = tuple(addr)
            if api_addr is not None:
                self.api_addrs[member_id] = tuple(api_addr)
            if self.role == LEADER and member_id not in self.next_index:
                self.next_index[member_id] = self.last_index() + 1
                self.match_index[member_id] = 0
        elif op == "remove":
            self.peers.discard(member_id)
            self.peer_addrs.pop(member_id, None)
            self.api_addrs.pop(member_id, None)
            self.next_index.pop(member_id, None)
            self.match_index.pop(member_id, None)
            if member_id == self.id:
                # we were removed: stop participating entirely
                self.removed = True
                self._become_follower(self.term)
            elif self.role == LEADER:
                self._maybe_commit()  # quorum shrank

    def _append(self, entry: Entry) -> None:
        self.log.append(entry)
        self.match_index[self.id] = self.last_index()
        if len(self.peers) == 1:
            self._maybe_commit()

    # ----------------------------------------------------- linearizable reads
    #
    # Read-index protocol (raft thesis §6.4, etcd-raft ReadIndex): a
    # linearizable read needs the CURRENT leader's commit index, proven
    # current by one heartbeat quorum round; the reader then waits until
    # its local applied index passes that commit index.  The leader-lease
    # fast path skips the round while the lease from the last
    # quorum-acked heartbeat is still valid (minus a clock-drift margin):
    # no other member can have won an election inside that window.

    def lease_valid(self) -> bool:
        """True while the leader lease covers a quorum-free read."""
        if (self.role != LEADER or self.lease_duration is None
                or not self.leader_ready):
            return False
        if len(self.peers) == 1:
            return True
        return self.lease_clock() < self._lease_expiry

    def request_read(self) -> Optional[int]:
        """Begin a read-barrier request on ANY member; returns a
        ``read_seq`` whose result lands in ``read_results`` (and fires
        ``on_read_ready``), or None when no leader is known to ask."""
        self._read_seq += 1
        seq = self._read_seq
        if self.role == LEADER:
            self._serve_read_index(self.id, seq)
            return seq
        if not self.leader_id:
            self._read_seq -= 1
            return None
        self._msgs.append(Message(
            type="read_index", term=self.term, src=self.id,
            dst=self.leader_id, read_seq=seq))
        return seq

    def _serve_read_index(self, requester: str, read_seq: int) -> None:
        """Leader side of one read request (local or remote)."""
        if self.role != LEADER or not self.leader_ready:
            self._read_reply(requester, read_seq, 0, ok=False)
            return
        index = self.commit_index
        if len(self.peers) == 1:
            self.read_stats["read_index_served"] += 1
            self._read_reply(requester, read_seq, index, ok=True)
            return
        if self.lease_duration is not None \
                and self.lease_gate is not None and not self.lease_gate():
            # the embedder vetoed the lease (clock-skew fault active):
            # fall through to the full quorum round
            self.read_stats["lease_refused_gate"] += 1
        elif self.lease_valid():
            self.read_stats["lease_served"] += 1
            self._read_reply(requester, read_seq, index, ok=True,
                             lease=True)
            return
        ctx = self._read_ctx + 1
        self._pending_reads.append((ctx, requester, read_seq, index))
        self._broadcast_append(heartbeat=True)

    def _read_reply(self, requester: str, read_seq: int, index: int,
                    ok: bool, lease: bool = False) -> None:
        if requester == self.id or not requester:
            self.read_results[read_seq] = (index, ok, lease)
            if self.on_read_ready is not None:
                self.on_read_ready(read_seq, index, ok, lease)
            return
        self._msgs.append(Message(
            type="read_index_resp", term=self.term, src=self.id,
            dst=requester, read_seq=read_seq, read_index=index,
            success=ok, lease_read=lease))

    def _confirm_read_ctx(self, ctx: int) -> None:
        """A heartbeat round got its quorum: renew the lease from the
        round's SEND time (conservative — followers reset their election
        timers no earlier than that) and resolve every read pinned to
        this or an earlier round."""
        sent = self._hb_sent_at.get(ctx)
        if sent is not None and self.lease_duration is not None:
            self._lease_expiry = max(
                self._lease_expiry,
                sent + self.lease_duration * (1.0 - self.lease_drift_margin))
        # prune BOTH maps through ctx — a round whose every echo was
        # lost never shows up in _read_acks, and its _hb_sent_at entry
        # would otherwise outlive the reign (leak on a lossy link)
        for c in [c for c in self._read_acks if c <= ctx]:
            del self._read_acks[c]
        for c in [c for c in self._hb_sent_at if c <= ctx]:
            del self._hb_sent_at[c]
        still = []
        for (c, requester, seq, index) in self._pending_reads:
            if c <= ctx:
                self.read_stats["read_index_served"] += 1
                self._read_reply(requester, seq, index, ok=True)
            else:
                still.append((c, requester, seq, index))
        self._pending_reads = still

    def _fail_pending_reads(self) -> None:
        pending, self._pending_reads = self._pending_reads, []
        for (_c, requester, seq, _index) in pending:
            self.read_stats["read_failed"] += 1
            self._read_reply(requester, seq, 0, ok=False)
        self._read_acks.clear()
        self._hb_sent_at.clear()

    # -------------------------------------------------------------- messages

    def step(self, m: Message) -> None:
        if self.removed:
            return
        if m.src != self.id and m.src not in self.peers:
            # not (or no longer) a member: ignore — a removed node's
            # campaigns must not depose live leaders
            return
        if self.role == LEADER and m.src in self.peers:
            self._recent_active.add(m.src)
        if m.type in ("prevote", "prevote_resp"):
            # pre-vote rounds carry a FUTURE term the sender has not
            # adopted; they must never make the receiver step down or
            # adjust its own term (etcd-raft: pre-vote messages are
            # exempt from the term-advance rule)
            if m.type == "prevote":
                self._on_prevote(m)
            else:
                self._on_prevote_resp(m)
            return
        if m.term > self.term:
            leader = m.src if m.type in ("app", "snap") else ""
            self._become_follower(m.term, leader)
        if m.type == "vote":
            self._on_vote(m)
        elif m.type == "vote_resp":
            self._on_vote_resp(m)
        elif m.type == "app":
            self._on_append(m)
        elif m.type == "app_resp":
            self._on_append_resp(m)
        elif m.type == "snap":
            self._on_snapshot(m)
        elif m.type == "read_index":
            if self.role == LEADER:
                self._serve_read_index(m.src, m.read_seq)
            else:
                # not the leader anymore: refuse so the requester retries
                # against whoever leads now
                self._msgs.append(Message(
                    type="read_index_resp", term=self.term, src=self.id,
                    dst=m.src, read_seq=m.read_seq, success=False))
        elif m.type == "read_index_resp":
            self._on_read_index_resp(m)

    def _on_read_index_resp(self, m: Message) -> None:
        if m.success and m.term < self.term:
            # a stale leader's grant must not complete a barrier minted
            # under a newer view of the cluster; failures always deliver
            # (they only trigger a retry)
            return
        if not m.success:
            self.read_stats["read_failed"] += 1
        self.read_results[m.read_seq] = (m.read_index, m.success,
                                         m.lease_read)
        if self.on_read_ready is not None:
            self.on_read_ready(m.read_seq, m.read_index, m.success,
                               m.lease_read)

    def _on_prevote(self, m: Message) -> None:
        """Answer a pre-vote probe; grants mutate NO local state.  Grant
        only when (a) the proposed term is ahead of ours, (b) the
        candidate's log is at least as up-to-date, and (c) our leader
        lease has lapsed — i.e. we have not heard from a live leader
        within an election timeout (leader stickiness, the property that
        stops a healed rejoiner from deposing a healthy leader)."""
        my_last = self.last_index()
        my_last_term = self._term_at(my_last) or 0
        up_to_date = (m.last_log_term, m.last_log_index) >= \
            (my_last_term, my_last)
        if self.role == LEADER:
            # a live leader never grants: check-quorum demotes it first
            # if it actually lost its majority
            lease_lapsed = False
        else:
            lease_lapsed = (self.leader_id == ""
                            or self._elapsed >= self.election_tick)
        grant = m.term > self.term and up_to_date and lease_lapsed
        self._msgs.append(Message(type="prevote_resp", term=m.term,
                                  src=self.id, dst=m.src, granted=grant))

    def _on_prevote_resp(self, m: Message) -> None:
        if not self._in_prevote or m.term != self.term + 1:
            return
        self._prevotes[m.src] = m.granted
        granted = sum(1 for g in self._prevotes.values() if g)
        if granted > len(self.peers) // 2:
            # a majority would vote for us: run the real election
            self._in_prevote = False
            self._campaign()
        elif len(self._prevotes) - granted > len(self.peers) // 2:
            # majority rejected: stand down without having disturbed
            # anyone's term; retry on the next timeout
            self._in_prevote = False

    def _on_vote(self, m: Message) -> None:
        if m.term < self.term:
            self._msgs.append(Message(type="vote_resp", term=self.term,
                                      src=self.id, dst=m.src, granted=False))
            return
        my_last = self.last_index()
        my_last_term = self._term_at(my_last) or 0
        up_to_date = (m.last_log_term, m.last_log_index) >= \
            (my_last_term, my_last)
        grant = (self.voted_for in ("", m.src)) and up_to_date
        if grant:
            self.voted_for = m.src
            self._hs_dirty = True
            self._elapsed = 0
        self._msgs.append(Message(type="vote_resp", term=self.term,
                                  src=self.id, dst=m.src, granted=grant))

    def _on_vote_resp(self, m: Message) -> None:
        if self.role != CANDIDATE or m.term < self.term:
            return
        self._votes[m.src] = m.granted
        granted = sum(1 for g in self._votes.values() if g)
        if granted > len(self.peers) // 2:
            self._become_leader()
        elif len(self._votes) - granted > len(self.peers) // 2:
            self._become_follower(self.term)

    def _on_append(self, m: Message) -> None:
        if m.term < self.term:
            self._msgs.append(Message(type="app_resp", term=self.term,
                                      src=self.id, dst=m.src, success=False))
            return
        if self.role != FOLLOWER and self.on_transition is not None:
            # only genuine role changes reach the tap — this runs on
            # every heartbeat, and a steady-state follower is not news
            self.on_transition(self.id, FOLLOWER, self.term)
        self.role = FOLLOWER
        self.leader_id = m.src
        self._elapsed = 0
        # a live leader cancels any pre-vote round in flight: a stale
        # grant arriving after this heartbeat must not start a real
        # campaign (etcd-raft clears pre-vote state on leader contact)
        self._in_prevote = False

        prev_term = self._term_at(m.prev_index)
        if prev_term is None or (m.prev_index > 0
                                 and prev_term != m.prev_term):
            # log mismatch: ask the leader to back up.  The read context
            # is still echoed — a mismatching follower has accepted this
            # leader for the term, which is all a read quorum needs.
            self._msgs.append(Message(
                type="app_resp", term=self.term, src=self.id, dst=m.src,
                success=False,
                match_index=min(m.prev_index - 1, self.last_index()),
                read_ctx=m.read_ctx))
            return
        # append, truncating conflicts
        for e in m.entries:
            existing = self._term_at(e.index)
            if existing is None or existing != e.term:
                if e.index <= self.last_index():
                    # conflict: truncate from here
                    del self.log[e.index - self.snap_index - 1:]
                    self._persisted_index = min(self._persisted_index,
                                                self.last_index())
                self.log.append(e)
        # commit may only advance over entries this append VERIFIED to
        # match the leader (up to prev_index + new entries) — never over
        # untruncated local tail entries (raft paper fig. 2: AppendEntries
        # step 5, "index of last new entry")
        last_new = m.prev_index + len(m.entries)
        if m.commit > self.commit_index:
            self.commit_index = min(m.commit, last_new)
            self._hs_dirty = True
        self._msgs.append(Message(
            type="app_resp", term=self.term, src=self.id, dst=m.src,
            success=True, match_index=max(last_new, self.commit_index),
            read_ctx=m.read_ctx))

    def _on_append_resp(self, m: Message) -> None:
        if self.role != LEADER or m.term < self.term:
            return
        if m.read_ctx:
            # read-quorum accounting: success is irrelevant — any echo at
            # our term is an acceptance of this leadership
            acks = self._read_acks.setdefault(m.read_ctx, set())
            acks.add(m.src)
            if len(acks | {self.id}) > len(self.peers) // 2:
                self._confirm_read_ctx(m.read_ctx)
        if m.success:
            self.match_index[m.src] = max(self.match_index.get(m.src, 0),
                                          m.match_index)
            self.next_index[m.src] = self.match_index[m.src] + 1
            self._maybe_commit()
            if self.next_index[m.src] <= self.last_index():
                # follower acked a heartbeat but is missing entries
                # (e.g. rejoined after a partition): repair now
                self._send_append(m.src)
        else:
            hint = m.match_index
            self.next_index[m.src] = max(1, min(
                hint + 1, self.next_index.get(m.src, 1) - 1))
            self._send_append(m.src)

    def _on_snapshot(self, m: Message) -> None:
        if m.term < self.term or m.snapshot is None:
            return
        if self.role != FOLLOWER and self.on_transition is not None:
            self.on_transition(self.id, FOLLOWER, self.term)
        self.role = FOLLOWER
        self.leader_id = m.src
        self._elapsed = 0
        self._in_prevote = False
        snap = m.snapshot
        if snap.index <= self.commit_index:
            # stale snapshot; report progress instead
            self._msgs.append(Message(
                type="app_resp", term=self.term, src=self.id, dst=m.src,
                success=True, match_index=self.commit_index))
            return
        self._pending_snapshot = snap
        self.snap_index = snap.index
        self.snap_term = snap.term
        if snap.peers:
            self.peers = set(snap.peers)
        self.log = []
        self.commit_index = snap.index
        self.applied_index = snap.index
        self._persisted_index = snap.index
        self._hs_dirty = True
        self._msgs.append(Message(
            type="app_resp", term=self.term, src=self.id, dst=m.src,
            success=True, match_index=snap.index))

    # ------------------------------------------------------------ replication

    def _maybe_commit(self) -> None:
        for n in range(self.last_index(), self.commit_index, -1):
            if (self._term_at(n) == self.term
                    and sum(1 for p in self.peers
                            if self.match_index.get(p, 0) >= n)
                    > len(self.peers) // 2):
                self.commit_index = n
                self._hs_dirty = True
                break

    def _broadcast_append(self, heartbeat: bool = False) -> None:
        # every broadcast round doubles as a leadership proof: it carries
        # a read-index context the followers echo on success, so the
        # quorum count confirms pending reads and renews the lease
        self._read_ctx += 1
        ctx = self._read_ctx
        self._hb_sent_at[ctx] = self.lease_clock()
        # sorted: message emission order must be a pure function of state,
        # not of str-hash-seeded set order, so the deterministic simulator
        # gets identical traces across processes
        for peer in sorted(self.peers):
            if peer != self.id:
                self._send_append(peer, heartbeat=heartbeat, ctx=ctx)

    def _send_append(self, peer: str, heartbeat: bool = False,
                     ctx: int = 0) -> None:
        next_i = self.next_index.get(peer, self.last_index() + 1)
        if next_i <= self.snap_index:
            # follower is behind our log start: needs a snapshot; the
            # driver fills in the snapshot data (we only know the index)
            self._msgs.append(Message(
                type="snap", term=self.term, src=self.id, dst=peer,
                snapshot=Snapshot(index=self.snap_index,
                                  term=self.snap_term)))
            return
        prev = next_i - 1
        entries = [] if heartbeat else self.entries_from(next_i)
        self._msgs.append(Message(
            type="app", term=self.term, src=self.id, dst=peer,
            prev_index=prev, prev_term=self._term_at(prev) or 0,
            entries=list(entries), commit=self.commit_index,
            read_ctx=ctx))

    # ----------------------------------------------------------------- ready

    def has_ready(self) -> bool:
        return bool(self._msgs or self._hs_dirty
                    or self._pending_snapshot is not None
                    or self.last_index() > self._persisted_index
                    or self.commit_index > self.applied_index)

    def ready(self) -> Ready:
        hs = None
        if self._hs_dirty:
            hs = HardState(term=self.term, voted_for=self.voted_for,
                           commit=self.commit_index)
        new_entries = self.entries_from(self._persisted_index + 1)
        # only committed entries that are also persisted locally are applied
        apply_upto = min(self.commit_index,
                         max(self._persisted_index, self.last_index()))
        committed = [self._entry_at(i)
                     for i in range(self.applied_index + 1, apply_upto + 1)
                     if self._term_at(i) is not None]
        msgs, self._msgs = self._msgs, []
        snap, self._pending_snapshot = self._pending_snapshot, None
        return Ready(hard_state=hs, entries=list(new_entries),
                     messages=msgs, committed=committed, snapshot=snap)

    def advance(self, rd: Ready) -> None:
        if rd.hard_state is not None:
            self._hs_dirty = False
        if rd.entries:
            self._persisted_index = max(self._persisted_index,
                                        rd.entries[-1].index)
        if rd.committed:
            self.applied_index = max(self.applied_index,
                                     rd.committed[-1].index)
        if rd.snapshot is not None:
            self.applied_index = max(self.applied_index, rd.snapshot.index)

    # ------------------------------------------------------------ compaction

    def compact(self, index: int, snapshot_term: Optional[int] = None) -> None:
        """Drop log entries up to ``index`` (inclusive); the driver has a
        durable snapshot at that index."""
        if index <= self.snap_index:
            return
        term = snapshot_term if snapshot_term is not None \
            else self._term_at(index)
        self.log = self.entries_from(index + 1)
        self.snap_index = index
        self.snap_term = term or 0
