"""RaftNode: drives the consensus core, persists through the logger, and
implements the store's Proposer seam.

Reference: manager/state/raft/raft.go (Node.Run Ready loop :540,
ProposeValue :1592 / processInternalRaftRequest :1785, processCommitted
:1890) and manager/state/proposer.go.

Wiring: every member owns a MemoryStore.  The leader's store proposes
change-lists here; ``propose`` blocks until the entry commits, then the
leader's store applies locally (MemoryStore.update's normal flow).
Followers apply committed entries via ``apply_store_actions`` — identical
bytes, identical version stamps, so all stores converge bit-for-bit.
Snapshots carry the full store (store.save_bytes) and are installed on
slow/new followers.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ...utils.metrics import registry as _metrics
from .. import serde
from ..store import MemoryStore, Proposer, StoreAction
from .core import (
    ENTRY_CONF, ENTRY_NOOP, Entry, HardState, LEADER, Message, RaftCore,
    Snapshot,
)
from .storage import RaftLogger

log = logging.getLogger("raft")

# cached Timer references (Registry.reset() resets these in place, so
# holding them is safe); apply runs once per committed entry
_APPLY_TIMER = _metrics.timer("swarm_raft_apply_latency")
_PROPOSE_TIMER = _metrics.timer("swarm_raft_propose_latency")
_READ_INDEX_TIMER = _metrics.timer("swarm_read_index_latency")


class NotLeader(Exception):
    """Proposal sent to a non-leader member."""


class ReadUnavailable(Exception):
    """A linearizable read barrier could not be confirmed in time (no
    reachable leader, or this member could not catch up to the barrier
    index).  Retry against another member — the data was never served
    stale."""


class StaleEpoch(NotLeader):
    """Proposal carries a leadership epoch that has been fenced."""


class ProposalDropped(Exception):
    """Leadership was lost before the proposal committed."""


@dataclass
class _Waiter:
    event: threading.Event
    term: int
    index: int
    ok: bool = False
    commit_cb: Optional[Callable[[], None]] = None
    t0: float = 0.0   # propose_async submit time (propose-latency timer)
    # leadership epoch the proposal was created under; checked against
    # the core's current epoch pre-WAL and at commit-callback delivery
    epoch: int = -1


class RaftNode(Proposer):
    """One consensus member (reference: raft.Node)."""

    TICK_INTERVAL = 0.02

    def __init__(self, node_id: str, peers: Sequence[str],
                 store: MemoryStore, logger: RaftLogger, transport,
                 snapshot_interval: int = 1000,
                 on_leadership: Optional[Callable[[bool], None]] = None,
                 force_new_cluster: bool = False,
                 tick_interval: Optional[float] = None):
        self.id = node_id
        self.store = store
        self.logger = logger
        self.transport = transport
        self.snapshot_interval = snapshot_interval
        self.on_leadership = on_leadership
        # injectable tick pacing (tests/simulation shrink it; the
        # deterministic simulator bypasses this thread entirely and
        # drives RaftCore ticks itself)
        self.tick_interval = (tick_interval if tick_interval is not None
                              else self.TICK_INTERVAL)
        self.core = RaftCore(node_id, peers)
        # black-box the role history: every transition (with term) lands
        # in the flight recorder's bounded ring for post-mortems
        from ...obs.flightrec import flightrec
        self.core.on_transition = flightrec.record_raft
        # leader-lease sizing: one election timeout of real time, margin
        # already shaved inside the core (lease_drift_margin).  The
        # lease window is measured on the MONOTONIC clock: a backward
        # wall-clock step (NTP) must never extend a lease past the
        # election timeout, it can only shorten it.
        self.core.lease_duration = \
            self.core.election_tick * (tick_interval if tick_interval
                                       is not None else self.TICK_INTERVAL)
        # monotonic by design, see above
        # swarmlint: disable=determinism-seam
        self.core.lease_clock = time.monotonic
        self.core.on_read_ready = self._on_read_ready

        self._inbox: "queue.Queue" = queue.Queue()
        # plane saturation probes (obs/planes.py): inbox depth is the
        # commit plane's queue, commit-applied lag is the apply plane's.
        # Pulled at roll time — the hot paths stay untouched; weakref so
        # a probe never pins a stopped node.  With co-resident nodes
        # (HA tests) the last-constructed node owns the probe;
        # production runs one node per process.
        import weakref
        from ...obs import planes as _planes
        _ref = weakref.ref(self)
        _planes.plane(_planes.RAFT).set_probe(
            lambda: ({"depth": float(_ref()._inbox.qsize())}
                     if _ref() is not None else {}))
        _planes.plane(_planes.RAFT_APPLY).set_probe(
            lambda: ({"depth": float(max(
                0, _ref().core.commit_index - _ref().core.applied_index))}
                if _ref() is not None else {}))
        self._waiters: Dict[int, _Waiter] = {}
        self._waiters_lock = threading.Lock()
        self._read_waiters: Dict[int, dict] = {}
        self._read_submitting = False   # raft-thread-only flag
        self._local_indices: set = set()
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._was_leader = False
        self._last_snap_applied = 0
        self.stats = {"applied": 0, "snapshots": 0,
                      "stale_epoch_rejects": 0}

        # boot from disk (reference: JoinAndStart -> BootstrapFromDisk)
        hs, entries, snapshot = logger.bootstrap()
        if snapshot is not None and snapshot.data:
            self.store.restore_bytes(snapshot.data)
            self._last_snap_applied = snapshot.index
        self.core.load(hs, entries, snapshot)
        # replay committed-but-unapplied log entries into the store
        for e in self.core.entries_from(self.core.applied_index + 1):
            if e.index > self.core.commit_index:
                break
            self._apply_entry(e, replay=True)
            self.core.applied_index = e.index

        if force_new_cluster:
            # quorum-loss recovery (reference: manager.go:99-101
            # --force-new-cluster): keep the replayed store state but
            # collapse membership to this node alone, then snapshot so a
            # later restart cannot resurrect the dead peers from old
            # conf entries
            log.warning("force-new-cluster: collapsing membership "
                        "%s -> {%s}", sorted(self.core.peers), node_id)
            self.core.peers = {node_id}
            self.core.peer_addrs = {
                k: v for k, v in self.core.peer_addrs.items()
                if k == node_id}
            self.core.api_addrs = {
                k: v for k, v in self.core.api_addrs.items()
                if k == node_id}
            self.core.removed = False
            # drop uncommitted tail entries: as a sole leader this node
            # would otherwise commit them next term, potentially
            # re-adding the dead peers via stale conf changes
            self.core.log = [e for e in self.core.log
                             if e.index <= self.core.commit_index]
            index = self.core.applied_index
            snap = Snapshot(
                index=index, term=self.core._term_at(index) or 0,
                data=self.store.save_bytes(),
                peers=sorted(self.core.peers),
                peer_addrs=dict(self.core.peer_addrs),
                api_addrs=dict(self.core.api_addrs))
            self.logger.save_snapshot(snap, index)
            # save_snapshot rewrites the WAL from DISK, which still
            # carries the dropped tail; force the on-disk log to match
            # the truncated in-memory one or a crash-before-next-append
            # restart would resurrect the stale conf entries
            from .core import HardState as _HS
            self.logger.rewrite(
                _HS(term=self.core.term, voted_for=self.core.voted_for,
                    commit=self.core.commit_index),
                self.core.log, keep_entries_from=index)
            self.core.compact(index, snap.term)

        self._sync_transport_from_core()
        transport.register(node_id, self._inbox.put)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run,
                                        name=f"raft-{self.id}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:   # never started: nothing to wait on
            self._done.wait(timeout=10)
        self.transport.unregister(self.id)
        self.logger.close()
        self._fail_waiters()

    @property
    def is_leader(self) -> bool:
        return self.core.role == LEADER

    @property
    def leadership_epoch(self) -> int:
        """Current fencing token (see RaftCore.leadership_epoch).  The
        store pins multi-proposal commits (chunked block commits, the
        scheduler's pipelined drafts) to the epoch read here so none of
        their chunks can land across a role change."""
        return self.core.leadership_epoch

    @property
    def leader_id(self) -> str:
        return self.core.leader_id

    def run(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    item = self._inbox.get(timeout=self.tick_interval)
                except queue.Empty:
                    item = None
                if item is None:
                    self.core.tick()
                elif isinstance(item, Message):
                    self.core.step(item)
                elif isinstance(item, tuple):   # local proposal/command
                    self._handle_proposal(*item)
                # drain any further queued items before processing ready
                while True:
                    try:
                        item = self._inbox.get_nowait()
                    except queue.Empty:
                        break
                    if isinstance(item, Message):
                        self.core.step(item)
                    elif isinstance(item, tuple):
                        self._handle_proposal(*item)

                if self._was_leader and self.core.role != LEADER:
                    # fail blocked proposers NOW, before applying anything:
                    # apply_store_actions/restore need the store update
                    # lock a blocked proposer may hold
                    self._fail_waiters()
                self._process_ready()
                self._leadership_change()
        finally:
            self._done.set()

    def _handle_proposal(self, *item) -> None:
        if item[0] == "stepdown":
            if self.core.role == LEADER:
                self.core.step_down()
            return
        if item[0] == "read":
            _, slot, ev = item
            # the flag marks the synchronous-resolution window: a
            # callback firing inside request_read must leave the result
            # in core.read_results for the pop below (raft thread only)
            self._read_submitting = True
            try:
                seq = self.core.request_read()
            finally:
                self._read_submitting = False
            if seq is None:
                # no known leader to ask; the caller backs off and retries
                slot["ok"] = False
                ev.set()
                return
            res = self.core.read_results.pop(seq, None)
            if res is not None:
                # resolved synchronously (lease / single-member fast path)
                slot["index"], slot["ok"], slot["lease"] = res
                ev.set()
            else:
                with self._waiters_lock:
                    self._read_waiters[seq] = (slot, ev)
            return
        if item[0] == "conf":
            _, op, member_id, addr, api_addr, waiter = item
            if not self.core.leader_ready:
                waiter.ok = False
                waiter.event.set()
                return
            try:
                index = self.core.propose_conf_change(op, member_id, addr,
                                                      api_addr)
            except RuntimeError:
                # a membership change is already in flight: fail this
                # waiter (callers retry); never let the error kill the
                # raft event loop
                waiter.ok = False
                waiter.event.set()
                return
            waiter.term = self.core.term
            waiter.index = index
            self._local_indices.add(index)
            with self._waiters_lock:
                self._waiters[index] = waiter
            return
        data, waiter = item
        # pre-WAL fence: this runs on the raft thread — the same thread
        # that applies role transitions — so the check cannot race a
        # deposal/re-election: a proposal created under a fenced epoch is
        # rejected HERE, before it is appended (and therefore before it
        # can ever be serialized into the WAL or replicated)
        if not self.core.leader_ready \
                or waiter.epoch != self.core.leadership_epoch:
            if self.core.role == LEADER \
                    and waiter.epoch != self.core.leadership_epoch:
                self.stats["stale_epoch_rejects"] += 1
                _metrics.counter("swarm_raft_stale_epoch_rejects")
                log.warning(
                    "pre-WAL fence: proposal epoch %d != current %d",
                    waiter.epoch, self.core.leadership_epoch)
            waiter.ok = False
            waiter.event.set()
            return
        index = self.core.propose(data)
        waiter.term = self.core.term
        waiter.index = index
        self._local_indices.add(index)
        with self._waiters_lock:
            self._waiters[index] = waiter

    def _process_ready(self) -> None:
        while self.core.has_ready():
            rd = self.core.ready()
            # 1. persist before anything else (the fsync batch: its
            # share of wall time is the raft plane's occupancy)
            _save_t0 = time.perf_counter()
            self.logger.save(rd.hard_state, rd.entries)
            from ...obs import planes as _planes
            _planes.plane(_planes.RAFT).note_busy(
                time.perf_counter() - _save_t0)
            if rd.snapshot is not None and rd.snapshot.data:
                self.logger.save_snapshot(rd.snapshot, rd.snapshot.index)
                self.store.restore_bytes(rd.snapshot.data)
                self._last_snap_applied = rd.snapshot.index
                self.stats["snapshots"] += 1
                self._sync_transport_from_core()
            # 2. send messages (attach snapshot payloads)
            for m in rd.messages:
                if m.type == "snap" and m.snapshot is not None \
                        and not m.snapshot.data:
                    try:
                        snap = self.logger.load_snapshot()
                    except OSError:
                        # transient read error (load_snapshot propagates
                        # I/O errors rather than quarantining): skip this
                        # send — the follower's next rejection retries it
                        log.exception("snapshot read failed; send skipped")
                        continue
                    if snap is None:
                        continue
                    m.snapshot = snap
                self.transport.send(m)
            # 3. apply committed entries
            for e in rd.committed:
                self._apply_entry(e)
            self.core.advance(rd)
            if rd.committed:
                self._maybe_snapshot()

    # -------------------------------------------------------------- applying

    def _sync_transport_peer(self, op: str, member_id: str, addr) -> None:
        """Keep the transport's dialing table in lockstep with replicated
        membership, so every member can reach every other after leader
        failures and restarts (addresses arrive via conf entries and
        snapshots, not just via whoever served the join RPC)."""
        if member_id == self.id:
            return
        if op == "add" and addr and hasattr(self.transport, "set_peer"):
            self.transport.set_peer(member_id, tuple(addr))
        elif op == "remove" and hasattr(self.transport, "remove_peer"):
            self.transport.remove_peer(member_id)

    def _sync_transport_from_core(self) -> None:
        if hasattr(self.transport, "set_peer"):
            for nid, addr in self.core.peer_addrs.items():
                if nid != self.id:
                    self.transport.set_peer(nid, tuple(addr))

    def _apply_entry(self, e: Entry, replay: bool = False) -> None:
        if e.type == ENTRY_CONF:
            import json as _json
            try:
                change = _json.loads(e.data)
                addr = change.get("addr")
                api_addr = change.get("api_addr")
                self.core.apply_conf_change(
                    change["op"], change["id"],
                    tuple(addr) if addr else None,
                    tuple(api_addr) if api_addr else None)
                self._sync_transport_peer(change["op"], change["id"], addr)
                log.info("membership change applied: %s %s",
                         change["op"], change["id"])
            except Exception:
                log.exception("applying conf change failed")
            with self._waiters_lock:
                waiter = self._waiters.pop(e.index, None)
            self._local_indices.discard(e.index)
            if waiter is not None and not replay:
                waiter.ok = True
                waiter.event.set()
            return
        if e.type == ENTRY_NOOP or not e.data:
            return
        self.stats["applied"] += 1
        _metrics.counter("swarm_raft_entries_applied")
        _apply_t0 = time.perf_counter()
        local = e.index in self._local_indices
        if local:
            self._local_indices.discard(e.index)
        if local and not replay:
            # run the proposing store's commit callback *here*, in the
            # apply path, before appliedIndex advances — snapshots taken at
            # this index must include this entry's changes (reference:
            # wait.trigger runs the commit cb inside processEntry,
            # raft.go:1917)
            with self._waiters_lock:
                waiter = self._waiters.pop(e.index, None)
            if waiter is not None \
                    and waiter.epoch != self.core.leadership_epoch:
                # commit-callback fence: the entry committed, but the
                # reign that created it is over (fenced epoch).  The
                # proposer must observe failure — its commit callback
                # (store-side success path) must NOT run — while the
                # store still converges by applying the entry below
                # exactly like a follower would.
                self.stats["stale_epoch_rejects"] += 1
                _metrics.counter("swarm_raft_stale_epoch_rejects")
                log.warning(
                    "commit fence: entry %d epoch %d != current %d",
                    e.index, waiter.epoch, self.core.leadership_epoch)
                waiter.ok = False
                waiter.event.set()
                waiter = None
            if waiter is not None:
                ok = True
                if waiter.commit_cb is not None:
                    try:
                        waiter.commit_cb()
                    except Exception:
                        # contract: on failure propose must raise — never
                        # report success for an uncommitted local tx
                        log.exception("local commit callback failed")
                        ok = False
                waiter.ok = ok
                waiter.event.set()
                _dt = time.perf_counter() - _apply_t0
                _APPLY_TIMER.observe(_dt)
                from ...obs import planes as _planes
                _planes.plane(_planes.RAFT_APPLY).note_busy(_dt)
                return
            # the waiter was cancelled (leadership churn) but the entry
            # committed anyway: apply it like a remote entry so this store
            # does not diverge from the cluster (reference: processEntry's
            # no-wait branch, raft.go:1907)
        try:
            actions = serde.entry_to_actions(e.data)
            self.store.apply_store_actions(actions)
        except Exception:
            log.exception("applying raft entry %d failed", e.index)
        _dt = time.perf_counter() - _apply_t0
        _APPLY_TIMER.observe(_dt)
        from ...obs import planes as _planes
        _planes.plane(_planes.RAFT_APPLY).note_busy(_dt)

    def _maybe_snapshot(self) -> None:
        """reference: raft.go:781 needsSnapshot + doSnapshot."""
        if self.core.applied_index - self.core.snap_index \
                < self.snapshot_interval:
            return
        index = self.core.applied_index
        snap = Snapshot(index=index, term=self.core._term_at(index) or 0,
                        data=self.store.save_bytes(),
                        peers=sorted(self.core.peers),
                        peer_addrs=dict(self.core.peer_addrs),
                        api_addrs=dict(self.core.api_addrs))
        self.logger.save_snapshot(snap, index)
        self.core.compact(index, snap.term)
        self.stats["snapshots"] += 1

    def _leadership_change(self) -> None:
        leader = self.core.role == LEADER
        if leader != self._was_leader:
            self._was_leader = leader
            if not leader:
                self._fail_waiters()
            if self.on_leadership is not None:
                try:
                    self.on_leadership(leader)
                except Exception:
                    log.exception("leadership callback failed")

    def _fail_waiters(self) -> None:
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, {}
            read_waiters, self._read_waiters = self._read_waiters, {}
        for w in waiters.values():
            w.ok = False
            w.event.set()
        for slot, ev in read_waiters.values():
            slot["ok"] = False
            ev.set()

    # ---------------------------------------------------- linearizable reads

    def _on_read_ready(self, seq: int, index: int, ok: bool,
                       lease: bool) -> None:
        """Core callback (raft thread): a read-barrier request resolved."""
        with self._waiters_lock:
            w = self._read_waiters.pop(seq, None)
        if w is None:
            if not self._read_submitting:
                # nobody is waiting (the reader timed out or a
                # leadership change failed its waiter) and this is not
                # the synchronous-resolution window: drop the orphaned
                # result or it leaks for the process lifetime
                self.core.read_results.pop(seq, None)
            # else: resolved synchronously inside request_read — the
            # inbox handler reads it straight out of core.read_results
            return
        self.core.read_results.pop(seq, None)
        slot, ev = w
        slot["index"], slot["ok"], slot["lease"] = index, ok, lease
        ev.set()

    def read_barrier(self, timeout: float = 10.0) -> int:
        """Linearizable read barrier (raft thesis §6.4): returns once this
        member's applied state includes everything committed cluster-wide
        at the moment of the call — served off the leader lease when
        valid, a read-index heartbeat quorum round otherwise.  Callable
        on ANY member; followers ask the leader for the confirmed commit
        index and wait until their applied index passes it.  Raises
        ReadUnavailable when no leader confirms within ``timeout`` —
        degraded, never stale.  MUST NOT be called while holding the
        store's locks (swarmlint lock-discipline enforces this)."""
        t0 = time.perf_counter()
        deadline = t0 + timeout
        slot: dict = {}
        while True:
            slot = {}
            ev = threading.Event()
            self._inbox.put(("read", slot, ev))
            ev.wait(timeout=max(0.001, deadline - time.perf_counter()))
            if ev.is_set() and slot.get("ok"):
                break
            if time.perf_counter() >= deadline:
                _metrics.counter('swarm_lease_reads{result="unavailable"}')
                raise ReadUnavailable(
                    f"{self.id}: no leader confirmed a read barrier "
                    f"within {timeout:.1f}s")
            # refused (leaderless gap / churn): brief backoff, retry
            self._stop.wait(timeout=0.01)
        index = slot["index"]
        while self.core.applied_index < index:
            if time.perf_counter() >= deadline:
                _metrics.counter('swarm_lease_reads{result="lagging"}')
                raise ReadUnavailable(
                    f"{self.id}: applied index {self.core.applied_index} "
                    f"never reached the barrier {index}")
            self._stop.wait(timeout=0.002)
        _READ_INDEX_TIMER.observe(time.perf_counter() - t0)
        _metrics.counter('swarm_lease_reads{result="lease"}'
                         if slot.get("lease")
                         else 'swarm_lease_reads{result="read_index"}')
        # one consistent meaning everywhere: "was the last read served
        # off a lease" — on a follower the LEADER's lease answers its
        # read_index request, and the resp's lease flag carries that
        _metrics.gauge("swarm_lease_enabled",
                       1.0 if slot.get("lease") else 0.0)
        return index

    # ------------------------------------------------------------ membership

    def _propose_conf(self, op: str, member_id: str, addr=None,
                      api_addr=None) -> None:
        if not self.core.leader_ready:
            raise NotLeader(f"{self.id} is not a ready leader")
        waiter = _Waiter(event=threading.Event(), term=self.core.term,
                        index=0)
        self._inbox.put(("conf", op, member_id, addr, api_addr, waiter))
        waiter.event.wait(timeout=10)
        if not waiter.ok:
            raise ProposalDropped("membership change dropped")

    def step_down(self) -> None:
        """Voluntarily relinquish leadership (used before self-demotion;
        reference: raft.go:1225 TransferLeadership)."""
        self._inbox.put(("stepdown",))

    def add_member(self, member_id: str, addr=None,
                   api_addr=None) -> None:
        """Leader-side join (reference: raft.go:926 Join).  ``addr`` is
        the member's raft transport address and ``api_addr`` its remote
        API address; both replicate with the conf entry so every member
        can dial the newcomer and agents can fail over to it."""
        self._propose_conf("add", member_id, addr, api_addr)

    def remove_member(self, member_id: str) -> None:
        """Leader-side leave/demote (reference: raft.go:1138 Leave)."""
        self._propose_conf("remove", member_id)

    # -------------------------------------------------------------- proposer

    def propose_async(self, actions: Sequence[StoreAction],
                      commit_cb=None, epoch: Optional[int] = None
                      ) -> _Waiter:
        """Submit a proposal without waiting for consensus: serialize on
        the caller's thread, enqueue to the raft loop, return the waiter.
        Proposals submitted from one thread are appended to the log (and
        therefore committed and applied) in submission order — the
        ordering guarantee the store's chunk-pipelined block commits rely
        on.  Pair every returned waiter with ``wait_proposal``: the
        commit callback runs in the apply path regardless, but success or
        failure is only observable through the wait.

        ``epoch`` pins the proposal to a leadership epoch captured
        earlier (``leadership_epoch``): a multi-proposal commit passes
        the epoch it started under so no chunk can be created — let
        alone land — after a role change.  A stale pin is rejected here,
        before serialization; None stamps the current epoch."""
        if self.core.role != LEADER:
            raise NotLeader(f"{self.id} is not the leader")
        cur = self.core.leadership_epoch
        if epoch is None:
            epoch = cur
        elif epoch != cur:
            # pre-serialization fence: the reign this commit belongs to
            # is already over
            self.stats["stale_epoch_rejects"] += 1
            _metrics.counter("swarm_raft_stale_epoch_rejects")
            raise StaleEpoch(
                f"{self.id}: proposal epoch {epoch} fenced "
                f"(current {cur})")
        t0 = time.perf_counter()
        # columnar block commits serialize to the compact binary form
        # (decoded natively on every member); other change lists keep
        # the JSON form — one shared grammar (serde.entry_to_actions)
        data = serde.actions_to_entry_data(actions)
        waiter = _Waiter(event=threading.Event(), term=self.core.term,
                         index=0, commit_cb=commit_cb, t0=t0,
                         epoch=epoch)
        self._inbox.put((data, waiter))
        return waiter

    def wait_proposal(self, waiter: _Waiter) -> None:
        """Block until a ``propose_async`` proposal commits (commit_cb
        already ran in the apply path) or fails; raises ProposalDropped
        on leadership loss (no internal timeout by design,
        design/raft.md:215)."""
        waiter.event.wait()
        # serialize -> consensus round -> apply-path commit, end to end
        _PROPOSE_TIMER.observe(time.perf_counter() - waiter.t0)
        if not waiter.ok:
            raise ProposalDropped(
                "raft proposal dropped (leadership change)")

    def propose(self, actions: Sequence[StoreAction],
                commit_cb=None, epoch: Optional[int] = None) -> None:
        """Block until the change list is committed by consensus and
        ``commit_cb`` ran in the apply path (reference: raft.go:1592
        ProposeValue)."""
        self.wait_proposal(self.propose_async(actions, commit_cb,
                                              epoch=epoch))
