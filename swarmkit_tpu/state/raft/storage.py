"""Raft persistence: write-ahead log + snapshot files.

Reference: manager/state/raft/storage/ (EncryptedRaftLogger over etcd
wal/snap).  Layout under a state directory:

    wal.jsonl       — append-only records: hardstate / entry lines
    snapshot        — latest snapshot (index, term, payload)
    snapshot.tmp    — atomic-replace staging

Records are serde JSON lines; an ``Encoder`` seam (encode/decode bytes)
slots in at-rest encryption (reference: manager/encryption) without
touching the log logic.  On restart ``bootstrap()`` loads the snapshot,
replays the WAL, and returns (hard_state, entries, snapshot) for
RaftCore.load.  The WAL is truncated to post-snapshot entries whenever a
new snapshot is saved (KeepOldSnapshots=0 semantics).
"""

from __future__ import annotations

import base64
import hashlib
import hmac as _hmac
import json
import logging
import os
import secrets
import threading
import zlib
from typing import List, Optional, Tuple

log = logging.getLogger("raft.storage")

from .core import Entry, HardState, Snapshot


class Encoder:
    """At-rest encryption seam (reference: manager/encryption)."""

    def encode(self, data: bytes) -> bytes:
        return data

    def decode(self, data: bytes) -> bytes:
        return data


class DecryptionError(Exception):
    """Sealed state could not be authenticated: wrong key or tampering.
    Must fail closed — never be mistaken for an empty/torn log."""


class KeyEncoder(Encoder):
    """At-rest encryption of WAL records and snapshots under a data
    encryption key (reference: manager/encryption NACLSecretbox around
    the raft DEK, storage.go EncryptedRaftLogger).  Stdlib-only: a
    per-record random nonce keys an SHA256-counter XOR stream, sealed
    with an HMAC-SHA256 tag (encrypt-then-MAC); the same DEK derivation
    stand-in KeyReadWriter uses for node keys."""

    MAGIC = b"ENCR1:"

    def __init__(self, dek: bytes, allow_plaintext: bool = False,
                 fallback: "Optional[KeyEncoder]" = None):
        if not dek:
            raise ValueError("a non-empty data encryption key is required")
        self._enc_key = hashlib.sha256(b"enc" + dek).digest()
        self._mac_key = hashlib.sha256(b"mac" + dek).digest()
        # migration-only escape hatch: replaying a WAL written before
        # encryption was enabled.  Steady-state decode fails closed —
        # otherwise an attacker with state-dir write access could inject
        # unauthenticated plaintext records that replay as raft state.
        self.allow_plaintext = allow_plaintext
        # decode-only second key: a crash mid-re-key (CA rotation) can
        # leave snapshot/WAL/state-file under a mix of old and new keys;
        # the reference's RotateEncryptionKey likewise decrypts with
        # old-or-new until the snapshot barrier converges
        self.fallback = fallback

    def _stream(self, data: bytes, nonce: bytes) -> bytes:
        out = bytearray()
        counter = 0
        while len(out) < len(data):
            out.extend(hashlib.sha256(
                self._enc_key + nonce
                + counter.to_bytes(8, "big")).digest())
            counter += 1
        return bytes(a ^ b for a, b in zip(data, out[: len(data)]))

    def encode(self, data: bytes) -> bytes:
        nonce = secrets.token_bytes(16)
        body = nonce + self._stream(data, nonce)
        tag = _hmac.new(self._mac_key, body, hashlib.sha256).digest()
        return self.MAGIC + tag + body

    def decode(self, data: bytes) -> bytes:
        if not data.startswith(self.MAGIC):
            if self.allow_plaintext:
                # pre-encryption WAL migration replay, explicitly opted in
                return data
            raise DecryptionError(
                "unencrypted record in an encrypted raft log (pass "
                "allow_plaintext=True only for a one-time migration "
                "replay of a pre-encryption state dir)")
        tag, body = data[6:38], data[38:]
        want = _hmac.new(self._mac_key, body, hashlib.sha256).digest()
        if not _hmac.compare_digest(tag, want):
            if self.fallback is not None:
                return self.fallback.decode(data)
            raise DecryptionError(
                "raft log record failed authentication (wrong key or "
                "corrupted state)")
        nonce, payload = body[:16], body[16:]
        return self._stream(payload, nonce)


class RaftLogger:
    def __init__(self, state_dir: str, encoder: Optional[Encoder] = None,
                 fsync: bool = False):
        self.state_dir = state_dir
        self.encoder = encoder or Encoder()
        self.fsync = fsync
        # serializes appends vs snapshot/re-key rewrites: rotate_encoder
        # runs on reconciler/adoption threads while raft saves on its own
        self._mu = threading.RLock()
        os.makedirs(state_dir, exist_ok=True)
        self._wal_path = os.path.join(state_dir, "wal.jsonl")
        self._snap_path = os.path.join(state_dir, "snapshot")
        self._wal = None

    # ---------------------------------------------------------------- write

    def _open_wal(self, mode: str = "ab"):
        if self._wal is None:
            self._wal = open(self._wal_path, mode)
        return self._wal

    @staticmethod
    def _record_crc(record: dict) -> int:
        """CRC32 over the canonical (sorted-key) serialization of the
        record WITHOUT its crc field — integrity of the decoded content,
        so a bit flip that survives base64/JSON/decryption parsing (e.g.
        inside an entry's data payload) is still caught on replay.  The
        load path re-canonicalizes before checking, so the write path is
        free to append the crc after the canonical body."""
        body = {k: v for k, v in record.items() if k != "crc"}
        return zlib.crc32(json.dumps(body, sort_keys=True,
                                     separators=(",", ":")).encode())

    def _write_record(self, record: dict) -> None:
        # serialize the crc-less body exactly once: the checksum covers
        # these canonical bytes, and the crc field is appended textually
        # (JSON key order is irrelevant to the loader, which
        # re-canonicalizes via _record_crc before verifying)
        record.pop("crc", None)
        body = json.dumps(record, sort_keys=True, separators=(",", ":"))
        crc = zlib.crc32(body.encode())
        data = (body[:-1] + ',"crc":' + str(crc) + "}").encode()
        payload = base64.b64encode(self.encoder.encode(data))
        wal = self._open_wal()
        wal.write(payload + b"\n")
        wal.flush()
        if self.fsync:
            os.fsync(wal.fileno())

    def save(self, hard_state: Optional[HardState],
             entries: List[Entry]) -> None:
        """Persist a Ready's durable parts; called before sending/applying
        (reference: raft.go:540 saveToStorage)."""
        with self._mu:
            self._save_locked(hard_state, entries)

    def _save_locked(self, hard_state, entries) -> None:
        if hard_state is not None:
            self._write_record({
                "t": "hs", "term": hard_state.term,
                "vote": hard_state.voted_for, "commit": hard_state.commit})
        for e in entries:
            self._write_record({
                "t": "ent", "term": e.term, "index": e.index,
                "type": e.type,
                "data": base64.b64encode(e.data).decode("ascii")})

    def _write_snapshot_file(self, snapshot: Snapshot) -> None:
        tmp = self._snap_path + ".tmp"
        encoded = self.encoder.encode(snapshot.data)
        record = json.dumps({
            "index": snapshot.index, "term": snapshot.term,
            # integrity hash of the STORED (encoded) body, verified
            # before decode on load: corruption quarantines the snapshot
            # instead of restoring a damaged store.  Hashing the
            # ciphertext — never the plaintext — keeps the cleartext
            # envelope from becoming a content-confirmation oracle under
            # encryption-at-rest.
            "data_sha256": hashlib.sha256(encoded).hexdigest(),
            "peers": list(snapshot.peers),
            "peer_addrs": {k: list(v)
                           for k, v in snapshot.peer_addrs.items()},
            "api_addrs": {k: list(v)
                          for k, v in snapshot.api_addrs.items()},
            # the same encoded bytes the hash covers (the encoder is
            # nonce-randomized: encoding twice would break the pairing)
            "data": base64.b64encode(encoded).decode("ascii"),
        }, sort_keys=True).encode()
        with open(tmp, "wb") as f:
            f.write(record)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)

    def _rewrite_wal(self, hs: Optional[HardState], entries: List[Entry],
                     keep_entries_from: int) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        wal_tmp = self._wal_path + ".tmp"
        with open(wal_tmp, "wb") as f:
            self._wal = f
            if hs is not None:
                self._write_record({"t": "hs", "term": hs.term,
                                    "vote": hs.voted_for,
                                    "commit": hs.commit})
            for e in entries:
                if e.index > keep_entries_from:
                    self._write_record({
                        "t": "ent", "term": e.term, "index": e.index,
                        "type": e.type,
                        "data": base64.b64encode(e.data).decode("ascii")})
            self._wal = None
        os.replace(wal_tmp, self._wal_path)

    def save_snapshot(self, snapshot: Snapshot,
                      keep_entries_from: int) -> None:
        """Atomically persist a snapshot and truncate the WAL to entries
        after ``keep_entries_from`` (reference: storage.go:198)."""
        with self._mu:
            self._write_snapshot_file(snapshot)
            # rewrite the WAL without pre-snapshot entries
            hs, entries, _ = self._load_wal()
            self._rewrite_wal(hs, entries, keep_entries_from)

    def rewrite(self, hard_state: Optional[HardState],
                entries: List[Entry], keep_entries_from: int = 0) -> None:
        """Replace the on-disk WAL with exactly these records (used by
        force-new-cluster to drop a stale uncommitted tail that the
        snapshot rewrite would otherwise preserve)."""
        with self._mu:
            self._rewrite_wal(hard_state, entries, keep_entries_from)

    def rotate_encoder(self, new_encoder: Encoder) -> None:
        """Re-encrypt all persisted raft state under a new key: decode
        with the old encoder, swap, rewrite snapshot + WAL (reference:
        storage.go:175 RotateEncryptionKey + its snapshot barrier)."""
        with self._mu:
            hs, entries, _ = self._load_wal()   # decoded with the OLD key
            snapshot = self.load_snapshot()
            self.encoder = new_encoder
            if snapshot is not None:
                self._write_snapshot_file(snapshot)
            self._rewrite_wal(hs, entries, keep_entries_from=0)

    # ----------------------------------------------------------------- read

    def _load_wal(self) -> Tuple[Optional[HardState], List[Entry], int]:
        hs: Optional[HardState] = None
        entries: List[Entry] = []
        if not os.path.exists(self._wal_path):
            return hs, entries, 0
        count = 0
        with open(self._wal_path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    data = self.encoder.decode(base64.b64decode(line))
                    rec = json.loads(data)
                except DecryptionError:
                    raise   # wrong key must not look like an empty log
                except Exception:
                    break  # torn tail record: stop replay here
                crc = rec.get("crc")
                if crc is not None and crc != self._record_crc(rec):
                    # corrupt record (bit flip that survived parsing):
                    # everything from here on is untrustworthy — stop
                    # replay exactly like a torn tail.  Records without
                    # a crc are legacy (pre-CRC WALs) and replay as-is.
                    log.error("WAL record %d failed CRC32; truncating "
                              "replay here", count + 1)
                    break
                count += 1
                if rec["t"] == "hs":
                    hs = HardState(term=rec["term"], voted_for=rec["vote"],
                                   commit=rec["commit"])
                elif rec["t"] == "ent":
                    e = Entry(term=rec["term"], index=rec["index"],
                              type=rec.get("type", 0),
                              data=base64.b64decode(rec["data"]))
                    # later records override earlier ones (truncation)
                    while entries and entries[-1].index >= e.index:
                        entries.pop()
                    entries.append(e)
        return hs, entries, count

    def read_wal(self):
        """Public read of the WAL: (hard_state | None, entries) — used by
        rafttool and diagnostics."""
        hs, entries, _ = self._load_wal()
        return hs, entries

    def _quarantine_snapshot(self, reason: str) -> None:
        """Move the corrupt snapshot aside (``snapshot.corrupt``) so
        bootstrap falls back to WAL-only replay instead of restoring a
        damaged store — and the evidence survives for forensics."""
        corrupt = self._snap_path + ".corrupt"
        try:
            os.replace(self._snap_path, corrupt)
            log.error("snapshot quarantined to %s (%s); bootstrap will "
                      "replay the WAL only", corrupt, reason)
        except OSError:
            log.exception("quarantining corrupt snapshot failed")

    def load_snapshot(self) -> Optional[Snapshot]:
        if not os.path.exists(self._snap_path):
            return None
        # transient I/O errors (EIO, permissions) must NOT look like
        # corruption: quarantining a healthy snapshot on a flaky read
        # would permanently degrade bootstrap to the post-compaction WAL
        # tail — let OSError propagate to the caller instead
        with open(self._snap_path, "rb") as f:
            raw = f.read()
        try:
            rec = json.loads(raw)
            body = base64.b64decode(rec["data"])
        except Exception:
            self._quarantine_snapshot("unparseable")
            return None
        want = rec.get("data_sha256")
        if want is not None and \
                hashlib.sha256(body).hexdigest() != want:
            # stored-body hash mismatch, checked BEFORE decryption
            # (absent hash = legacy snapshot)
            self._quarantine_snapshot("body hash mismatch")
            return None
        try:
            return Snapshot(
                index=rec["index"], term=rec["term"],
                peers=list(rec.get("peers", [])),
                peer_addrs={k: tuple(v) for k, v in
                            rec.get("peer_addrs", {}).items()},
                api_addrs={k: tuple(v) for k, v in
                           rec.get("api_addrs", {}).items()},
                data=self.encoder.decode(body))
        except DecryptionError:
            raise   # wrong key/tampering must not read as "no snapshot"
        except Exception:
            self._quarantine_snapshot("unparseable")
            return None

    def bootstrap(self) -> Tuple[HardState, List[Entry],
                                 Optional[Snapshot]]:
        """reference: storage.go:51 BootstrapFromDisk."""
        snapshot = self.load_snapshot()
        hs, entries, _ = self._load_wal()
        if snapshot is not None:
            entries = [e for e in entries if e.index > snapshot.index]
        return hs or HardState(), entries, snapshot

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None
