"""Raft message transport.

Reference: manager/state/raft/transport/ (per-peer gRPC streams).  The
in-process implementation routes messages between nodes in one process and
supports pausing/partitioning links — the test capability the reference
gets from its WrappedListener (testutils.go:31).  A network transport
implements the same two-method surface.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Set, Tuple

from .core import Message


class LocalNetwork:
    """Message router for in-process clusters, with fault injection."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[str, Callable[[Message], None]] = {}
        self._paused: Set[str] = set()
        self._cut: Set[Tuple[str, str]] = set()

    def register(self, node_id: str,
                 handler: Callable[[Message], None]) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    # ------------------------------------------------------- fault injection

    def pause(self, node_id: str) -> None:
        """Isolate a node entirely (both directions)."""
        with self._lock:
            self._paused.add(node_id)

    def resume(self, node_id: str) -> None:
        with self._lock:
            self._paused.discard(node_id)

    def cut(self, a: str, b: str) -> None:
        """Sever the link between two nodes (both directions)."""
        with self._lock:
            self._cut.add((a, b))
            self._cut.add((b, a))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._cut.discard((a, b))
            self._cut.discard((b, a))

    # --------------------------------------------------------------- sending

    def send(self, msg: Message) -> None:
        with self._lock:
            if msg.src in self._paused or msg.dst in self._paused:
                return
            if (msg.src, msg.dst) in self._cut:
                return
            handler = self._handlers.get(msg.dst)
        if handler is not None:
            handler(msg)
