"""Serialization for the cluster data model: dataclasses ↔ dicts ↔ bytes.

Reference: the role of api/*.pb.go generated marshaling + api/snapshot.proto.

The model is plain typed dataclasses (models/types.py, specs.py,
objects.py), so serialization is **schema-driven from the type hints**:
``to_dict`` lowers any model object to JSON-compatible primitives (enums →
ints, bytes → base64 strings); ``from_dict(cls, data)`` reconstructs using
``cls``'s resolved field types — List[X], Dict[str, X], Optional[X], nested
dataclasses, IntEnums.  ``dumps``/``loads`` produce deterministic bytes
(sorted keys, compact separators) for snapshots, the WAL, and the wire.

Forward compatibility: unknown dict keys are ignored on decode, missing
keys take field defaults — the same leniency protobuf gives the reference.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import typing
from typing import Any, Dict, Optional, Type

_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    cached = _HINTS_CACHE.get(cls)
    if cached is None:
        import sys
        mod = sys.modules.get(cls.__module__)
        localns = dict(vars(mod)) if mod else {}
        # nested classes (e.g. VolumePublishStatus.State) resolve via the
        # enclosing class being in scope
        localns[cls.__name__] = cls
        cached = typing.get_type_hints(cls, localns=localns)
        _HINTS_CACHE[cls] = cached
    return cached


def to_dict(obj: Any) -> Any:
    """Lower a model object to JSON-compatible primitives."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        if isinstance(obj, enum.Enum):
            return int(obj)
        return obj
    if isinstance(obj, enum.Enum):
        return int(obj)
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode("ascii")
    if dataclasses.is_dataclass(obj):
        return {f.name: to_dict(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    raise TypeError(f"cannot serialize {type(obj).__name__}")


def _from_typed(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return _from_typed(args[0], data)
        raise TypeError(f"unsupported union {tp}")
    if origin in (list, tuple):
        (item_tp,) = typing.get_args(tp)[:1] or (Any,)
        seq = [_from_typed(item_tp, v) for v in data]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = typing.get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _from_typed(val_tp, v) for k, v in data.items()}
    if isinstance(tp, type):
        if issubclass(tp, enum.Enum):
            return tp(data)
        if tp is bytes:
            return base64.b64decode(data)
        if dataclasses.is_dataclass(tp):
            return from_dict(tp, data)
        if tp is float:
            return float(data)
        if tp is int:
            return int(data)
    return data


def from_dict(cls: Type, data: Optional[Dict[str, Any]]) -> Any:
    """Reconstruct a dataclass instance from to_dict output."""
    if data is None:
        return None
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue  # field default applies (forward compatibility)
        kwargs[f.name] = _from_typed(hints.get(f.name, Any), data[f.name])
    return cls(**kwargs)


def dumps(obj: Any) -> bytes:
    """Deterministic bytes for any to_dict-able value."""
    return json.dumps(to_dict(obj), sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def loads_dict(data: bytes) -> Any:
    return json.loads(data.decode("utf-8"))


def loads(cls: Type, data: bytes) -> Any:
    return from_dict(cls, loads_dict(data))


# ---------------------------------------------------------------------------
# Store snapshots and replicated actions (reference: api/snapshot.proto,
# api.StoreAction)
# ---------------------------------------------------------------------------

def _collection_map():
    from ..models.objects import STORE_OBJECT_TYPES
    return {t.collection: t for t in STORE_OBJECT_TYPES}


def snapshot_to_bytes(snapshot: Dict[str, Any]) -> bytes:
    """Serialize MemoryStore.save() output to deterministic bytes."""
    payload = {
        "version": snapshot["version"],
        "tables": {
            coll: sorted((to_dict(o) for o in objs),
                         key=lambda d: d.get("id", ""))
            for coll, objs in snapshot["tables"].items()
        },
    }
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def snapshot_from_bytes(data: bytes) -> Dict[str, Any]:
    """Deserialize into the dict shape MemoryStore.restore() accepts."""
    payload = json.loads(data.decode("utf-8"))
    classes = _collection_map()
    return {
        "version": payload["version"],
        "tables": {
            coll: [from_dict(classes[coll], d) for d in objs]
            for coll, objs in payload["tables"].items()
            if coll in classes
        },
    }


# --------------------------------------------------------------------------
# Columnar task-block raft entries (ISSUE 13): a scheduler block rides
# consensus as ONE compact binary payload instead of a JSON change list —
# no per-task object churn on either side of the wire.  Decoding has a
# native fast path (hotpath.c block_decode, GIL-released byte scan);
# ``block_from_bytes`` below is its pure-Python differential oracle.
# --------------------------------------------------------------------------

#: binary task-block entry magic; JSON change lists start with "[" so
#: the two wire forms can never be confused
BLOCK_ENTRY_MAGIC = b"SKB1"

# layout (little-endian, no alignment padding):
#   0:4   magic "SKB1"
#   4:8   u32  n (item count)
#   8:16  i64  base_version
#  16:20  i32  state
#  20:28  f64  ts
#  28:32  u32  message byte length, then the message (utf-8)
#   +     u32  ids blob length, then n ids NUL-joined (utf-8)
#   +     u32  run count R, R*u32 run lengths,
#         u32  node-id blob length, then R node ids NUL-joined
# Node ids are run-length encoded: the planner emits placements sorted
# by node, so runs are long (same observation the JSON form exploits).
_BLOCK_HEADER = "<4sIqidI"


def block_to_bytes(action) -> Optional[bytes]:
    """Binary wire form of a TaskBlockAction, or None when an id/node id
    contains NUL or the message is not UTF-8-cleanly representable —
    callers then fall back to the JSON change-list form (the same
    odd-alphabet escape the JSON encoding's ids_list/node_ids takes)."""
    import struct
    ids = action.ids
    node_ids = action.node_ids
    if any("\x00" in s for s in ids) \
            or any("\x00" in s for s in node_ids):
        return None
    ids_blob = "\x00".join(ids)
    counts = []
    run_nids = []
    for nid in node_ids:
        if run_nids and nid == run_nids[-1]:
            counts[-1] += 1
        else:
            run_nids.append(nid)
            counts.append(1)
    try:
        msg = action.message.encode("utf-8")
        ids_b = ids_blob.encode("utf-8")
        nid_b = "\x00".join(run_nids).encode("utf-8")
    except UnicodeEncodeError:
        return None
    r = len(run_nids)
    return b"".join((
        struct.pack(_BLOCK_HEADER, BLOCK_ENTRY_MAGIC, len(ids),
                    action.base_version, action.state, action.ts,
                    len(msg)),
        msg,
        struct.pack("<I", len(ids_b)), ids_b,
        struct.pack(f"<I{r}I", r, *counts),
        struct.pack("<I", len(nid_b)), nid_b,
    ))


def block_from_bytes(data: bytes):
    """Pure-Python decoder for ``block_to_bytes`` output — the
    differential oracle for the native ``block_decode``.  Raises
    ValueError on any truncated/corrupt entry (same contract as the
    native decoder: a bad WAL record must fail loudly, not crash)."""
    import struct
    from .store import TaskBlockAction
    try:
        return _block_from_bytes(data, struct, TaskBlockAction)
    except (struct.error, IndexError, UnicodeDecodeError) as e:
        raise ValueError(f"block: {e}") from e


def _block_from_bytes(data: bytes, struct, TaskBlockAction):
    magic, n, base, state, ts, msg_len = struct.unpack_from(
        _BLOCK_HEADER, data, 0)
    if magic != BLOCK_ENTRY_MAGIC:
        raise ValueError("block: bad magic")
    off = struct.calcsize(_BLOCK_HEADER)
    message = data[off:off + msg_len].decode("utf-8")
    off += msg_len
    (ids_len,) = struct.unpack_from("<I", data, off)
    off += 4
    if len(data) - off < ids_len:
        raise ValueError("block: truncated ids blob")
    ids_blob = data[off:off + ids_len].decode("utf-8")
    off += ids_len
    if n == 0:
        if ids_len:
            raise ValueError("block: dangling blob")
        ids = ()
    else:
        ids = tuple(ids_blob.split("\x00"))
    if len(ids) != n:
        raise ValueError("block: string count mismatch")
    (r,) = struct.unpack_from("<I", data, off)
    off += 4
    counts = struct.unpack_from(f"<{r}I", data, off)
    off += 4 * r
    (nid_len,) = struct.unpack_from("<I", data, off)
    off += 4
    if len(data) - off < nid_len:
        raise ValueError("block: truncated node-id blob")
    nid_blob = data[off:off + nid_len].decode("utf-8")
    off += nid_len
    if off != len(data):
        raise ValueError("block: trailing bytes")
    if r == 0:
        if nid_len:
            raise ValueError("block: dangling blob")
        run_nids = []
    else:
        run_nids = nid_blob.split("\x00")
    if len(run_nids) != r:
        raise ValueError("block: string count mismatch")
    node_ids: list = []
    for nid, count in zip(run_nids, counts):
        node_ids.extend([nid] * count)
    if len(node_ids) != n:
        raise ValueError("block: run counts mismatch n")
    return TaskBlockAction("task_block", ids, tuple(node_ids), base,
                           state, message, ts)


def actions_to_entry_data(actions) -> bytes:
    """Serialize a change list into raft entry payload bytes.  A single
    columnar TaskBlockAction takes the compact binary block form unless
    the commit-plane escape hatch (SWARM_NATIVE_COMMIT=0) or an odd id
    alphabet forces the JSON change-list form; both raft routes
    (RaftNode, the sim's member-bound proposer) call this so leaders
    and followers agree on one wire grammar."""
    if len(actions) == 1 and getattr(actions[0], "action", None) \
            == "task_block":
        from .. import native
        if native.commit_enabled():
            data = block_to_bytes(actions[0])
            if data is not None:
                return data
    return dumps([action_to_dict(a) for a in actions])


def entry_to_actions(data: bytes) -> list:
    """Decode raft entry payload bytes into a change list — the single
    decode seam both raft routes apply through.  Binary block entries
    decode natively when available (regardless of the encode-side
    escape hatch: replicated bytes must always apply); everything else
    is the JSON change-list form."""
    if data[:4] == BLOCK_ENTRY_MAGIC:
        from .. import native
        hp = native.get_commit()
        if hp is not None:
            from .store import TaskBlockAction
            return [hp.block_decode(data, TaskBlockAction)]
        return [block_from_bytes(data)]
    return [action_from_dict(d) for d in loads_dict(data)]


def action_to_dict(action) -> Dict[str, Any]:
    """One replicated store mutation (reference: api.StoreAction).
    Columnar task blocks serialize as parallel id/node arrays plus the
    shared status columns — ~2 strings per task instead of a full Task."""
    if action.action == "task_block":
        # compact wire form: ids joined (new_id hex never contains ","),
        # node ids run-length encoded (the planner emits placements
        # sorted by node, so runs are long) — ~25x smaller than per-task
        # StoreActions at 16k items
        ids = action.ids
        parts: list = []
        run_nid = None
        run_len = 0
        plain = True
        for nid in action.node_ids:
            if nid == run_nid:
                run_len += 1
                continue
            if run_nid is not None:
                parts.append(f"{run_nid}:{run_len}")
            run_nid, run_len = nid, 1
            if ":" in nid or "," in nid:
                plain = False
        if run_nid is not None:
            parts.append(f"{run_nid}:{run_len}")
        out: Dict[str, Any] = {
            "action": "task_block",
            "base_version": action.base_version,
            "state": action.state,
            "message": action.message,
            "ts": action.ts,
        }
        if plain:
            # flat strings: serde's generic to_dict walk sees 2 scalars
            # instead of ~10k nested rle pairs
            out["node_rle"] = ",".join(parts)
        else:
            out["node_ids"] = list(action.node_ids)   # odd id alphabet
        if any("," in s for s in ids):
            out["ids_list"] = list(ids)               # odd id alphabet
        else:
            out["ids"] = ",".join(ids)
        return out
    return {
        "action": action.action,
        "collection": action.obj.collection,
        "obj": to_dict(action.obj),
    }


def action_from_dict(data: Dict[str, Any]):
    from .store import StoreAction, TaskBlockAction
    if data["action"] == "task_block":
        if "ids_list" in data:
            ids = tuple(data["ids_list"])
        else:
            joined = data["ids"]
            ids = tuple(joined.split(",")) if joined else ()
        if "node_ids" in data:
            node_ids = list(data["node_ids"])
        else:
            node_ids = []
            rle = data["node_rle"]
            if rle:
                for part in rle.split(","):
                    nid, _, count = part.rpartition(":")
                    node_ids.extend([nid] * int(count))
        return TaskBlockAction(
            "task_block", ids, tuple(node_ids),
            data["base_version"], data["state"], data["message"],
            data["ts"])
    cls = _collection_map()[data["collection"]]
    return StoreAction(data["action"], from_dict(cls, data["obj"]))
