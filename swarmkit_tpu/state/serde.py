"""Serialization for the cluster data model: dataclasses ↔ dicts ↔ bytes.

Reference: the role of api/*.pb.go generated marshaling + api/snapshot.proto.

The model is plain typed dataclasses (models/types.py, specs.py,
objects.py), so serialization is **schema-driven from the type hints**:
``to_dict`` lowers any model object to JSON-compatible primitives (enums →
ints, bytes → base64 strings); ``from_dict(cls, data)`` reconstructs using
``cls``'s resolved field types — List[X], Dict[str, X], Optional[X], nested
dataclasses, IntEnums.  ``dumps``/``loads`` produce deterministic bytes
(sorted keys, compact separators) for snapshots, the WAL, and the wire.

Forward compatibility: unknown dict keys are ignored on decode, missing
keys take field defaults — the same leniency protobuf gives the reference.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import typing
from typing import Any, Dict, Optional, Type

_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    cached = _HINTS_CACHE.get(cls)
    if cached is None:
        import sys
        mod = sys.modules.get(cls.__module__)
        localns = dict(vars(mod)) if mod else {}
        # nested classes (e.g. VolumePublishStatus.State) resolve via the
        # enclosing class being in scope
        localns[cls.__name__] = cls
        cached = typing.get_type_hints(cls, localns=localns)
        _HINTS_CACHE[cls] = cached
    return cached


def to_dict(obj: Any) -> Any:
    """Lower a model object to JSON-compatible primitives."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        if isinstance(obj, enum.Enum):
            return int(obj)
        return obj
    if isinstance(obj, enum.Enum):
        return int(obj)
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode("ascii")
    if dataclasses.is_dataclass(obj):
        return {f.name: to_dict(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    raise TypeError(f"cannot serialize {type(obj).__name__}")


def _from_typed(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return _from_typed(args[0], data)
        raise TypeError(f"unsupported union {tp}")
    if origin in (list, tuple):
        (item_tp,) = typing.get_args(tp)[:1] or (Any,)
        seq = [_from_typed(item_tp, v) for v in data]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = typing.get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _from_typed(val_tp, v) for k, v in data.items()}
    if isinstance(tp, type):
        if issubclass(tp, enum.Enum):
            return tp(data)
        if tp is bytes:
            return base64.b64decode(data)
        if dataclasses.is_dataclass(tp):
            return from_dict(tp, data)
        if tp is float:
            return float(data)
        if tp is int:
            return int(data)
    return data


def from_dict(cls: Type, data: Optional[Dict[str, Any]]) -> Any:
    """Reconstruct a dataclass instance from to_dict output."""
    if data is None:
        return None
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue  # field default applies (forward compatibility)
        kwargs[f.name] = _from_typed(hints.get(f.name, Any), data[f.name])
    return cls(**kwargs)


def dumps(obj: Any) -> bytes:
    """Deterministic bytes for any to_dict-able value."""
    return json.dumps(to_dict(obj), sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def loads_dict(data: bytes) -> Any:
    return json.loads(data.decode("utf-8"))


def loads(cls: Type, data: bytes) -> Any:
    return from_dict(cls, loads_dict(data))


# ---------------------------------------------------------------------------
# Store snapshots and replicated actions (reference: api/snapshot.proto,
# api.StoreAction)
# ---------------------------------------------------------------------------

def _collection_map():
    from ..models.objects import STORE_OBJECT_TYPES
    return {t.collection: t for t in STORE_OBJECT_TYPES}


def snapshot_to_bytes(snapshot: Dict[str, Any]) -> bytes:
    """Serialize MemoryStore.save() output to deterministic bytes."""
    payload = {
        "version": snapshot["version"],
        "tables": {
            coll: sorted((to_dict(o) for o in objs),
                         key=lambda d: d.get("id", ""))
            for coll, objs in snapshot["tables"].items()
        },
    }
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def snapshot_from_bytes(data: bytes) -> Dict[str, Any]:
    """Deserialize into the dict shape MemoryStore.restore() accepts."""
    payload = json.loads(data.decode("utf-8"))
    classes = _collection_map()
    return {
        "version": payload["version"],
        "tables": {
            coll: [from_dict(classes[coll], d) for d in objs]
            for coll, objs in payload["tables"].items()
            if coll in classes
        },
    }


def action_to_dict(action) -> Dict[str, Any]:
    """One replicated store mutation (reference: api.StoreAction).
    Columnar task blocks serialize as parallel id/node arrays plus the
    shared status columns — ~2 strings per task instead of a full Task."""
    if action.action == "task_block":
        # compact wire form: ids joined (new_id hex never contains ","),
        # node ids run-length encoded (the planner emits placements
        # sorted by node, so runs are long) — ~25x smaller than per-task
        # StoreActions at 16k items
        ids = action.ids
        parts: list = []
        run_nid = None
        run_len = 0
        plain = True
        for nid in action.node_ids:
            if nid == run_nid:
                run_len += 1
                continue
            if run_nid is not None:
                parts.append(f"{run_nid}:{run_len}")
            run_nid, run_len = nid, 1
            if ":" in nid or "," in nid:
                plain = False
        if run_nid is not None:
            parts.append(f"{run_nid}:{run_len}")
        out: Dict[str, Any] = {
            "action": "task_block",
            "base_version": action.base_version,
            "state": action.state,
            "message": action.message,
            "ts": action.ts,
        }
        if plain:
            # flat strings: serde's generic to_dict walk sees 2 scalars
            # instead of ~10k nested rle pairs
            out["node_rle"] = ",".join(parts)
        else:
            out["node_ids"] = list(action.node_ids)   # odd id alphabet
        if any("," in s for s in ids):
            out["ids_list"] = list(ids)               # odd id alphabet
        else:
            out["ids"] = ",".join(ids)
        return out
    return {
        "action": action.action,
        "collection": action.obj.collection,
        "obj": to_dict(action.obj),
    }


def action_from_dict(data: Dict[str, Any]):
    from .store import StoreAction, TaskBlockAction
    if data["action"] == "task_block":
        if "ids_list" in data:
            ids = tuple(data["ids_list"])
        else:
            joined = data["ids"]
            ids = tuple(joined.split(",")) if joined else ()
        if "node_ids" in data:
            node_ids = list(data["node_ids"])
        else:
            node_ids = []
            rle = data["node_rle"]
            if rle:
                for part in rle.split(","):
                    nid, _, count = part.rpartition(":")
                    node_ids.extend([nid] * int(count))
        return TaskBlockAction(
            "task_block", ids, tuple(node_ids),
            data["base_version"], data["state"], data["message"],
            data["ts"])
    cls = _collection_map()[data["collection"]]
    return StoreAction(data["action"], from_dict(cls, data["obj"]))
