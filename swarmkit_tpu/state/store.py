"""Versioned in-memory cluster store with watches and a consensus seam.

Reference: manager/state/store/memory.go (go-memdb based MemoryStore).

Semantics preserved from the reference:

* ``view(cb)`` / ``update(cb)`` transactions; update collects a changelist,
  (optionally) proposes it through a ``Proposer`` (raft), then commits and
  publishes one event per change plus an ``EventCommit``
  (memory.go:395-470).
* Version sequencing: every committed write stamps ``meta.version.index``
  with a monotonically increasing store index; updates require the caller's
  object version to match the stored version (``SequenceConflict``) — the
  scheduler's node-conflict rollback depends on this (scheduler.go:533-544).
* ``batch(cb)`` splits a large write into transactions of at most
  ``MAX_CHANGES_PER_TX`` changes (memory.go:45-51).
* ``view_and_watch`` atomically snapshots + subscribes so no event is lost
  (memory.go:892).
* ``apply_store_actions`` replays follower-side raft log entries
  (memory.go:280).
* ``save``/``restore`` full-store snapshots for raft snapshot transfer.
* Unique, case-preserved names per collection except tasks (naming conflicts
  return ``NameConflict``).

Implementation differs deliberately: plain dicts + per-store RW mutex instead
of a radix-tree MVCC — the control plane is low-write-rate and the scheduler
hot path reads a private mirror, so simplicity wins.  Objects returned by
reads are the stored instances; callers must not mutate them (writes store
defensive copies via ``obj.copy()``).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Type

from ..models.objects import (
    Cluster, Config, Extension, Network, Node, Resource, Secret, Service,
    Task, Volume, STORE_OBJECT_TYPES,
)
from ..models.types import now
from ..utils.metrics import registry as _metrics
from ..utils.pipeline import default_pipeline_depth
from .events import Event, EventCommit, EventSnapshotRestore, EventTaskBlock
from .watch import Queue, Subscription

MAX_CHANGES_PER_TX = 200  # reference: memory.go:45-51
# a transaction (= one raft proposal) also flushes once its changes reach
# this serialized size, whichever bound trips first (reference:
# memory.go:45-51 MaxTransactionBytes = 1.5MB)
MAX_TX_BYTES = 1_500_000
WEDGE_TIMEOUT = 30.0      # reference: memory.go:79-146 deadlock tripwire

log = logging.getLogger("store")

# cached Timer references for the write paths (Registry.reset() resets
# these in place, so holding them is safe)
_UPDATE_TX_TIMER = _metrics.timer("swarm_store_write_tx_latency")
_BATCH_TIMER = _metrics.timer("swarm_store_batch_latency")
_BLOCK_COMMIT_TIMER = _metrics.timer("swarm_store_block_commit_latency")


class _TimedLock:
    """Update-lock wrapper with a lock-age tripwire and hold-time metric
    (reference: memory.go timedMutex — logs when the store wedges)."""

    __slots__ = ("_lock", "_acquired_at", "_holder", "_wait_timer",
                 "_hold_timer")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._acquired_at = 0.0
        self._holder = ""
        # cached Timer references: this runs on the system's hottest
        # lock, so no per-call registry lookup (Registry.reset() resets
        # timers in place precisely to keep held references valid)
        self._wait_timer = _metrics.timer("swarm_store_lock_wait")
        self._hold_timer = _metrics.timer("swarm_store_lock_hold")

    def acquire(self) -> None:
        t0 = time.monotonic()
        while not self._lock.acquire(timeout=WEDGE_TIMEOUT):
            log.error(
                "store update lock wedged: held for %.0fs by %r "
                "(waiter: %r)", time.monotonic() - self._acquired_at,
                self._holder, threading.current_thread().name)
        self._acquired_at = time.monotonic()
        self._holder = threading.current_thread().name
        # reference: memory.go:84-112 lockTimer — contention visibility
        self._wait_timer.observe(self._acquired_at - t0)

    def release(self) -> None:
        held = time.monotonic() - self._acquired_at
        self._holder = ""
        self._lock.release()
        # observed after the release so it never extends the hold
        self._hold_timer.observe(held)
        if held > WEDGE_TIMEOUT:
            log.error("store update lock was held for %.0fs", held)

    def __enter__(self) -> "_TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class AlreadyExists(StoreError):
    pass


class NameConflict(StoreError):
    pass


class SequenceConflict(StoreError):
    """Update out of sequence (stale version)."""


class InvalidStoreAction(StoreError):
    pass


@dataclass(frozen=True)
class StoreAction:
    """One replicated mutation (reference: api.StoreAction)."""

    action: str        # "create" | "update" | "delete"
    obj: Any           # a store object snapshot


@dataclass(frozen=True)
class TaskBlockAction:
    """One replicated columnar scheduler block: N task assignments in a
    single compact raft entry (~2 strings/task instead of N serialized
    Task objects).  Followers apply it straight into the task table's
    overlay — the same lazy-materialization shape the leader commits.
    Replaces N per-task StoreActions for scheduler status flips; the
    reference has no counterpart (it proposes per-object actions,
    manager/state/raft/raft.go:1592 ProposeValue)."""

    action: str            # always "task_block"
    ids: Tuple[str, ...]
    node_ids: Tuple[str, ...]
    base_version: int      # versions run base+1 .. base+len(ids)
    state: int
    message: str
    ts: float


class Proposer:
    """Consensus seam (reference: manager/state/proposer.go:17).

    ``propose`` must block until the change list is committed by consensus
    (or raise).  ``commit_cb`` — the store-side commit — must be invoked
    exactly once, synchronously in the consensus apply path, before the
    applied index advances past this entry; this is what keeps snapshots
    consistent with the entries they claim to cover (the reference passes
    the memstore commit as the wait callback run by wait.trigger inside
    processEntry, raft.go:1917).  On failure commit_cb must NOT run and
    propose raises.  Actions arrive with their final version indices
    already stamped (see MemoryStore.update).  A nil proposer (None) keeps
    the store fully functional standalone — the master test fixture of the
    reference.

    Leadership fencing (optional): proposers that expose a non-None
    ``leadership_epoch`` (RaftNode, the sim's member-bound proposer)
    accept an ``epoch=`` keyword on propose/propose_async and reject a
    proposal whose pinned epoch has been fenced — before serialization,
    again pre-WAL, and again at commit-callback delivery.  The store
    pins every chunk of a multi-proposal commit to the epoch it started
    under, so a chunked commit can never straddle a role change.  Plain
    proposers (this base class, test fakes) ignore fencing entirely.
    """

    #: current leadership-epoch fencing token; None = no fencing support
    leadership_epoch: Optional[int] = None

    def propose(self, actions: Sequence[StoreAction],
                commit_cb: Callable[[], None]) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Find combinators (reference: manager/state/store/by.go)
# ---------------------------------------------------------------------------

class By:
    """Query selector; subclasses know how to use indexes or fall back to
    a linear filter."""


@dataclass(frozen=True)
class All(By):
    pass


@dataclass(frozen=True)
class ByName(By):
    name: str


@dataclass(frozen=True)
class ByNamePrefix(By):
    prefix: str


@dataclass(frozen=True)
class ByIDPrefix(By):
    prefix: str


@dataclass(frozen=True)
class ByService(By):
    service_id: str


@dataclass(frozen=True)
class ByNode(By):
    node_id: str


@dataclass(frozen=True)
class BySlot(By):
    service_id: str
    slot: int


@dataclass(frozen=True)
class ByDesiredState(By):
    state: int


@dataclass(frozen=True)
class ByTaskState(By):
    state: int


@dataclass(frozen=True)
class ByRole(By):
    role: int


@dataclass(frozen=True)
class ByMembership(By):
    membership: int


@dataclass(frozen=True)
class ByReferencedSecret(By):
    secret_id: str


@dataclass(frozen=True)
class ByReferencedConfig(By):
    config_id: str


@dataclass(frozen=True)
class ByReferencedNetwork(By):
    network_id: str


@dataclass(frozen=True)
class ByVolumeGroup(By):
    group: str


@dataclass(frozen=True)
class ByKind(By):
    kind: str


@dataclass(frozen=True)
class ByCustom(By):
    index: str
    value: str


@dataclass(frozen=True)
class Or(By):
    bys: Tuple[By, ...]

    def __init__(self, *bys: By):
        object.__setattr__(self, "bys", tuple(bys))


@dataclass(frozen=True)
class Where(By):
    """Escape hatch: arbitrary predicate (linear scan)."""

    pred: Callable[[Any], bool]


def _task_secret_ids(t: Task) -> Iterable[str]:
    c = t.spec.container
    if c:
        for ref in c.secrets:
            yield ref.secret_id


def _task_config_ids(t: Task) -> Iterable[str]:
    c = t.spec.container
    if c:
        for ref in c.configs:
            yield ref.config_id


def _task_network_ids(t: Task) -> Iterable[str]:
    for a in t.networks:
        yield a.network_id
    for n in t.spec.networks:
        yield n.target


def _service_network_ids(s: Service) -> Iterable[str]:
    for n in s.spec.networks:
        yield n.target
    for n in s.spec.task.networks:
        yield n.target


def _materialize_task(old: Task, node_id: str, version: int, ts: float,
                      state, message: str) -> Task:
    """Build the assigned form of a block-committed task from its
    pre-assignment object + overlay tuple — single recipe shared by lazy
    materialization and changelog replay."""
    from ..models.types import TaskState, TaskStatus
    new = old.copy()
    new.node_id = node_id
    new.status = TaskStatus(state=TaskState(state), timestamp=ts,
                            message=message)
    new.meta.version.index = version
    new.meta.updated_at = ts
    return new


def _obj_name(obj: Any) -> str:
    spec = getattr(obj, "spec", None)
    ann = getattr(spec, "annotations", None) or getattr(obj, "annotations", None)
    if ann is not None and ann.name:
        return ann.name
    # nodes are named by hostname when they have no explicit name
    desc = getattr(obj, "description", None)
    if desc is not None and desc.hostname:
        return desc.hostname
    return ""


class _Table:
    def __init__(self) -> None:
        self.objects: Dict[str, Any] = {}
        self.by_name: Dict[str, str] = {}            # lower(name) -> id
        # index buckets are insertion-ordered {id: None} dicts, NOT
        # sets: indexed find() results feed placement decisions, and set
        # iteration order varies with hash randomization — per-process
        # nondeterminism the sim's byte-identical-report contract forbids
        self.by_service: Dict[str, Dict[str, None]] = {}   # tasks/volumes
        self.by_node: Dict[str, Dict[str, None]] = {}
        self.by_slot: Dict[Tuple[str, int], Dict[str, None]] = {}
        # columnar task-block overlay: id -> (node_id, version, ts, state,
        # message).  A block commit records assignments here instead of
        # materializing per-task objects; reads materialize lazily (see
        # MemoryStore._materialize_locked).  Indexes are maintained
        # eagerly, so only `objects` values can be stale.
        self.overlay: Dict[str, tuple] = {}

    def snapshot(self) -> Dict[str, Any]:
        return dict(self.objects)


class ReadTx:
    """Consistent read view.  Holds the store lock only during method calls;
    objects are immutable-by-convention so the view stays coherent."""

    def __init__(self, store: "MemoryStore"):
        self._store = store

    def get(self, kind: Type, id: str) -> Optional[Any]:
        return self._store.raw_get(kind, id)

    def find(self, kind: Type, by: By = All()) -> List[Any]:
        with self._store._lock:
            return self._store._find_locked(kind, by)


class WriteTx(ReadTx):
    def __init__(self, store: "MemoryStore"):
        super().__init__(store)
        self._changes: List[StoreAction] = []
        self._events: List[Event] = []
        # staged view: id -> obj (or _TOMBSTONE)
        self._staged: Dict[Tuple[str, str], Any] = {}
        # staged name index: (collection, lower-name) -> id, so name-conflict
        # checks stay O(1) even for 10k-create transactions
        self._staged_names: Dict[Tuple[str, str], str] = {}
        self._staged_name_by_id: Dict[Tuple[str, str], str] = {}
        self.closed = False

    # reads see staged writes
    def get(self, kind: Type, id: str) -> Optional[Any]:
        key = (kind.collection, id)
        if key in self._staged:
            obj = self._staged[key]
            return None if obj is _TOMBSTONE else obj
        return super().get(kind, id)

    def find(self, kind: Type, by: By = All()) -> List[Any]:
        base = super().find(kind, by)
        if not self._staged:
            return base
        staged_ids = {i for (c, i) in self._staged if c == kind.collection}
        if not staged_ids:
            return base
        out = [o for o in base if o.id not in staged_ids]
        pred = self._store._predicate_for(kind, by)
        for (c, i), obj in self._staged.items():
            if c != kind.collection or obj is _TOMBSTONE:
                continue
            if pred(obj):
                out.append(obj)
        return out

    def _check_name(self, kind: Type, obj: Any) -> None:
        if kind.collection == "tasks":
            return
        name = _obj_name(obj)
        if not name:
            return
        lname = name.lower()
        staged_holder = self._staged_names.get((kind.collection, lname))
        if staged_holder is not None and staged_holder != obj.id:
            raise NameConflict(f"name conflict: {name!r}")
        with self._store._lock:
            existing = self._store._tables[kind.collection].by_name.get(lname)
        if existing is not None and existing != obj.id:
            # unless the holder is staged for deletion / rename
            holder = self._staged.get((kind.collection, existing))
            if holder is _TOMBSTONE:
                return
            if holder is not None and _obj_name(holder).lower() != lname:
                return
            raise NameConflict(f"name conflict: {name!r}")

    def _stage_name(self, kind: Type, obj: Any) -> None:
        if kind.collection == "tasks":
            return
        # drop any staged name previously held by this id (rename in-tx)
        old = self._staged_name_by_id.pop((kind.collection, obj.id), None)
        if old is not None:
            self._staged_names.pop((kind.collection, old), None)
        name = _obj_name(obj).lower()
        if name:
            self._staged_names[(kind.collection, name)] = obj.id
            self._staged_name_by_id[(kind.collection, obj.id)] = name

    def create(self, obj: Any) -> None:
        kind = type(obj)
        if self.get(kind, obj.id) is not None:
            raise AlreadyExists(obj.id)
        self._check_name(kind, obj)
        cp = obj.copy()
        ts = now()
        cp.meta.created_at = cp.meta.created_at or ts
        cp.meta.updated_at = ts
        self._staged[(kind.collection, obj.id)] = cp
        self._stage_name(kind, cp)
        self._changes.append(StoreAction("create", cp))
        self._events.append(Event("create", cp))

    def update(self, obj: Any) -> None:
        kind = type(obj)
        existing = self.get(kind, obj.id)
        if existing is None:
            raise NotFound(obj.id)
        if existing.meta.version.index != obj.meta.version.index:
            raise SequenceConflict(
                f"{kind.collection}/{obj.id}: stale version "
                f"{obj.meta.version.index} != {existing.meta.version.index}")
        self._check_name(kind, obj)
        cp = obj.copy()
        cp.meta.created_at = existing.meta.created_at
        cp.meta.updated_at = now()
        self._staged[(kind.collection, obj.id)] = cp
        self._stage_name(kind, cp)
        self._changes.append(StoreAction("update", cp))
        self._events.append(Event("update", cp, existing))

    def delete(self, kind: Type, id: str) -> None:
        existing = self.get(kind, id)
        if existing is None:
            raise NotFound(id)
        self._staged[(kind.collection, id)] = _TOMBSTONE
        old = self._staged_name_by_id.pop((kind.collection, id), None)
        if old is not None:
            self._staged_names.pop((kind.collection, old), None)
        self._changes.append(StoreAction("delete", existing))
        self._events.append(Event("delete", existing))


class _Tombstone:
    def __repr__(self) -> str:
        return "<deleted>"


_TOMBSTONE = _Tombstone()


class MemoryStore:
    def __init__(self, proposer: Optional[Proposer] = None):
        self._lock = threading.RLock()
        self._update_lock = _TimedLock()  # serializes writers; tripwired
        self._tables: Dict[str, _Table] = {
            t.collection: _Table() for t in STORE_OBJECT_TYPES
        }
        self._proposer = proposer
        self._version = 0
        # raft block-chunk pipelining window for commit_task_block: with
        # a proposer exposing propose_async/wait_proposal, up to this
        # many chunk proposals ride consensus at once (serialization and
        # WAL writes of chunk i+1 overlap the apply of chunk i); 1 =
        # strictly serial propose->wait per chunk (SWARM_PIPELINE_DEPTH
        # escape hatch)
        self.pipeline_depth = default_pipeline_depth()
        self.queue = Queue()
        # bounded changelog ring for watch-from-version resume
        # (reference: raft.go:1617 ChangesBetween over the raft log).
        # Entries: ("one", version, action, obj, old) or a columnar
        # ("block", base_version, olds, node_ids, state, message, ts)
        # from commit_task_block, expanded lazily on replay.
        self._changelog: deque = deque()
        self._changelog_total = 0
        self.changelog_limit = 8192   # changes retained for resume

    # ------------------------------------------------------------------ reads

    def raw_get(self, kind: Type, id: str) -> Optional[Any]:
        """Lock-free point read: a single GIL-atomic dict lookup of an
        immutable stored object.  The supported fast-read API for hot-path
        friends (scheduler commit checks); everything else should use
        ``view``.  Block-committed tasks materialize on first access."""
        table = self._tables[kind.collection]
        if table.overlay and id in table.overlay:
            with self._lock:
                return self._materialize_locked(table, id)
        return table.objects.get(id)

    # ------------------------------------------- task-block lazy materialization

    def _materialize_locked(self, table: _Table, tid: str) -> Optional[Any]:
        """Turn an overlay entry into a real stored Task (caller holds
        ``_lock``).  Idempotent: a concurrent reader may have materialized
        the id between the overlay check and lock acquisition."""
        entry = table.overlay.pop(tid, None)
        old = table.objects.get(tid)
        if entry is None or old is None:
            return old
        node_id, version, ts, state, message = entry
        new = _materialize_task(old, node_id, version, ts, state, message)
        table.objects[tid] = new
        return new

    def _materialize_all_locked(self, table: _Table) -> None:
        if table.overlay:
            for tid in list(table.overlay):
                self._materialize_locked(table, tid)

    def view(self, cb: Optional[Callable[[ReadTx], Any]] = None) -> Any:
        tx = ReadTx(self)
        if cb is None:
            return tx
        return cb(tx)

    def read_view(self, cb: Optional[Callable[[ReadTx], Any]] = None,
                  linearizable: bool = False,
                  timeout: Optional[float] = None) -> Any:
        """A read transaction with an optional linearizability guarantee.

        ``linearizable=False`` is ``view``: the local replicated state,
        which may trail the leader (serializable, never uncommitted —
        followers only apply committed entries).  ``linearizable=True``
        first runs the proposer's ``read_barrier`` capability (raft
        read-index / leader lease): the barrier returns only once this
        store has applied everything committed cluster-wide at call
        time, so a FOLLOWER store serves linearizable reads without
        touching the leader's store.  Raises the proposer's
        ReadUnavailable when the barrier cannot be confirmed — degraded,
        never stale.  Proposers without the capability (nil/test
        proposers, a standalone store) serve directly: there is no
        replication lag to wait out.

        The barrier deliberately runs OUTSIDE both store locks (it blocks
        on consensus; swarmlint's lock-discipline rule bans it under
        ``_lock``/``_update_lock``)."""
        if linearizable and self._proposer is not None:
            barrier = getattr(self._proposer, "read_barrier", None)
            if barrier is not None:
                if timeout is None:
                    barrier()
                else:
                    barrier(timeout=timeout)
        tx = ReadTx(self)
        if cb is None:
            return tx
        return cb(tx)

    def view_and_watch(self, cb: Callable[[ReadTx], Any],
                       predicate=None, limit: Optional[int] = None,
                       accepts_blocks: bool = False
                       ) -> Tuple[Any, Subscription]:
        """Atomic snapshot + subscribe (reference: memory.go:892)."""
        with self._update_lock:
            sub = (self.queue.subscribe_limited(limit, predicate,
                                                accepts_blocks)
                   if limit else self.queue.subscribe(predicate,
                                                      accepts_blocks))
            result = cb(ReadTx(self))
        return result, sub

    def watch_queue(self) -> Queue:
        return self.queue

    # ----------------------------------------------------------------- writes

    def update(self, cb: Callable[[WriteTx], Any]) -> Any:
        """Run a write transaction; commit via proposer when configured.

        Version indices are stamped *before* proposing so the replicated
        StoreActions carry the exact versions the leader will commit —
        followers replaying them converge bit-for-bit (the reference gets
        this via proposer.GetVersion(); memory.go).
        """
        t0 = time.perf_counter()
        try:
            with self._update_lock:
                tx = WriteTx(self)
                result = cb(tx)  # exceptions roll back (nothing committed)
                self._propose_and_commit(tx)
                return result
        finally:
            _UPDATE_TX_TIMER.observe(time.perf_counter() - t0)

    def _proposer_epoch(self) -> Optional[int]:
        """The proposer's current leadership-epoch fencing token, or None
        when the proposer (or a nil proposer) does not support fencing."""
        return getattr(self._proposer, "leadership_epoch", None)

    @staticmethod
    def _propose_fenced(proposer, actions, commit_cb, epoch):
        """propose() with the epoch pin when fencing is supported; plain
        two-argument propose for legacy/test proposers."""
        if epoch is None:
            proposer.propose(actions, commit_cb)
        else:
            proposer.propose(actions, commit_cb, epoch=epoch)

    def _propose_and_commit(self, tx: "WriteTx") -> None:
        """Stamp versions, run consensus, apply.  Caller holds _update_lock.

        With a proposer, the local commit runs inside the consensus apply
        path (see Proposer.propose) so snapshots taken at an applied index
        always include that index's changes."""
        if tx._changes:
            with self._lock:
                seq = self._version
            for change in tx._changes:
                seq += 1
                if change.action in ("create", "update"):
                    change.obj.meta.version.index = seq
            if self._proposer is not None:
                # the epoch read here travels with the proposal: stamped
                # versions are only valid for the reign they were read
                # under, and the fence makes that a checked invariant
                self._propose_fenced(self._proposer, tx._changes,
                                     lambda: self._commit(tx),
                                     self._proposer_epoch())
                return
        self._commit(tx)

    def batch(self, cb: Callable[["Batch"], Any]) -> Any:
        """Split a large write into transactions bounded by
        MAX_CHANGES_PER_TX *store changes* (reference: memory.go:531).

        Sub-transactions commit incrementally (best-effort): an error midway
        leaves earlier flushes committed, like the reference.
        """
        t0 = time.perf_counter()
        b = Batch(self)
        try:
            result = cb(b)
            b._flush()
            return result
        finally:
            b._abort()
            _BATCH_TIMER.observe(time.perf_counter() - t0)

    def _commit(self, tx: WriteTx) -> None:
        if not tx._changes:
            tx.closed = True
            return
        with self._lock:
            for change, ev in zip(tx._changes, tx._events):
                self._version += 1   # versions pre-stamped in update()
                self._apply_locked(change)
                # stamp the resume token (frozen dataclass: events are
                # immutable to consumers; the store is their minter)
                object.__setattr__(ev, "version", self._version)
                self._log_change_locked(
                    ("one", self._version, ev.action, ev.obj, ev.old), 1)
        tx.closed = True
        for ev in tx._events:
            self.queue.publish(ev)
        self.queue.publish(EventCommit(self._version))

    # -------------------------------------------------- changelog (resume)

    def _log_change_locked(self, entry: tuple, count: int) -> None:
        self._changelog.append(entry)
        self._changelog_total += count
        while self._changelog_total > self.changelog_limit \
                and len(self._changelog) > 1:
            dropped = self._changelog.popleft()
            self._changelog_total -= (1 if dropped[0] == "one"
                                      else len(dropped[2]))

    def _entry_version_range(self, entry: tuple) -> Tuple[int, int]:
        if entry[0] == "one":
            return entry[1], entry[1]
        _, base, olds, *_ = entry
        return base + 1, base + len(olds)

    def changes_between(self, from_version: int) -> List[Event]:
        """Events for every change with version > ``from_version``, in
        commit order (reference: raft.go:1617 ChangesBetween).  Raises
        InvalidStoreAction when that range was compacted out of the
        changelog (snapshot install / ring overflow) — resuming callers
        must re-list instead."""
        with self._lock:
            if from_version > self._version:
                raise InvalidStoreAction(
                    f"version {from_version} is in the future "
                    f"(store at {self._version})")
            if from_version == self._version:
                return []
            entries = list(self._changelog)
        if not entries or \
                self._entry_version_range(entries[0])[0] > from_version + 1:
            raise InvalidStoreAction(
                f"changes since version {from_version} were compacted; "
                "re-list and watch from the current version")
        out: List[Event] = []
        for entry in entries:
            lo, hi = self._entry_version_range(entry)
            if hi <= from_version:
                continue
            if entry[0] == "one":
                out.append(Event(entry[2], entry[3], entry[4],
                                 version=entry[1]))
                continue
            _, base, olds, node_ids, state, message, ts = entry
            for i, old in enumerate(olds):
                ver = base + 1 + i
                if ver <= from_version:
                    continue
                out.append(Event(
                    "update",
                    _materialize_task(old, node_ids[i], ver, ts, state,
                                      message),
                    old, version=ver))
        return out

    def watch_from(self, from_version: int, predicate=None
                   ) -> Tuple[List[Event], "Subscription"]:
        """Atomically: events missed since ``from_version`` plus a live
        subscription from the current version (reference:
        watchapi/watch.go:32 WatchFrom)."""
        with self._update_lock:
            replay = self.changes_between(from_version)
            sub = self.queue.subscribe(predicate)
        return replay, sub

    def _apply_locked(self, change: StoreAction) -> None:
        obj = change.obj
        table = self._tables[obj.collection]
        if table.overlay and obj.id in table.overlay:
            # the unindex below must see the materialized (assigned) form
            self._materialize_locked(table, obj.id)
        old = table.objects.get(obj.id)
        # name index maintenance
        if old is not None:
            oldname = _obj_name(old).lower()
            if oldname and table.by_name.get(oldname) == obj.id:
                del table.by_name[oldname]
        if change.action == "delete":
            table.objects.pop(obj.id, None)
            self._unindex(table, old if old is not None else obj)
            return
        if obj.collection != "tasks":
            name = _obj_name(obj).lower()
            if name:
                table.by_name[name] = obj.id
        if old is not None:
            self._unindex(table, old)
        table.objects[obj.id] = obj
        self._index(table, obj)

    def _index(self, table: _Table, obj: Any) -> None:
        if isinstance(obj, Task):
            if obj.service_id:
                table.by_service.setdefault(obj.service_id, {})[obj.id] = None
                table.by_slot.setdefault((obj.service_id, obj.slot), {})[obj.id] = None
            if obj.node_id:
                table.by_node.setdefault(obj.node_id, {})[obj.id] = None

    def _unindex(self, table: _Table, obj: Any) -> None:
        if isinstance(obj, Task):
            if obj.service_id:
                table.by_service.get(obj.service_id, {}).pop(obj.id, None)
                table.by_slot.get((obj.service_id, obj.slot), {}).pop(obj.id, None)
            if obj.node_id:
                table.by_node.get(obj.node_id, {}).pop(obj.id, None)

    # ------------------------------------------------------- queries (locked)

    def _predicate_for(self, kind: Type, by: By) -> Callable[[Any], bool]:
        if isinstance(by, All):
            return lambda o: True
        if isinstance(by, ByName):
            return lambda o: _obj_name(o).lower() == by.name.lower()
        if isinstance(by, ByNamePrefix):
            return lambda o: _obj_name(o).lower().startswith(by.prefix.lower())
        if isinstance(by, ByIDPrefix):
            return lambda o: o.id.startswith(by.prefix)
        if isinstance(by, ByService):
            return lambda o: getattr(o, "service_id", None) == by.service_id
        if isinstance(by, ByNode):
            return lambda o: getattr(o, "node_id", None) == by.node_id
        if isinstance(by, BySlot):
            return lambda o: (getattr(o, "service_id", None) == by.service_id
                              and getattr(o, "slot", None) == by.slot)
        if isinstance(by, ByDesiredState):
            return lambda o: o.desired_state == by.state
        if isinstance(by, ByTaskState):
            return lambda o: o.status.state == by.state
        if isinstance(by, ByRole):
            return lambda o: o.spec.desired_role == by.role
        if isinstance(by, ByMembership):
            return lambda o: o.spec.membership == by.membership
        if isinstance(by, ByReferencedSecret):
            return lambda o: by.secret_id in set(_task_secret_ids(o)) \
                if isinstance(o, Task) else False
        if isinstance(by, ByReferencedConfig):
            return lambda o: by.config_id in set(_task_config_ids(o)) \
                if isinstance(o, Task) else False
        if isinstance(by, ByReferencedNetwork):
            def net_pred(o):
                if isinstance(o, Task):
                    return by.network_id in set(_task_network_ids(o))
                if isinstance(o, Service):
                    return by.network_id in set(_service_network_ids(o))
                return False
            return net_pred
        if isinstance(by, ByVolumeGroup):
            return lambda o: o.spec.group == by.group
        if isinstance(by, ByKind):
            return lambda o: getattr(o, "kind", None) == by.kind
        if isinstance(by, ByCustom):
            return lambda o: (getattr(o, "annotations", None) or
                              o.spec.annotations).indices.get(by.index) == by.value
        if isinstance(by, Where):
            return by.pred
        if isinstance(by, Or):
            preds = [self._predicate_for(kind, b) for b in by.bys]
            return lambda o: any(p(o) for p in preds)
        raise InvalidStoreAction(f"unsupported selector {by!r}")

    def _find_locked(self, kind: Type, by: By) -> List[Any]:
        table = self._tables[kind.collection]
        # fast paths via indexes
        if kind is Task:
            ids: Optional[Dict[str, None]] = None
            if isinstance(by, ByService):
                ids = table.by_service.get(by.service_id, {})
            elif isinstance(by, ByNode):
                ids = table.by_node.get(by.node_id, {})
            elif isinstance(by, BySlot):
                ids = table.by_slot.get((by.service_id, by.slot), {})
            if ids is not None:
                if table.overlay:
                    # index-driven query: materialize only touched ids
                    return [self._materialize_locked(table, i)
                            if i in table.overlay else table.objects[i]
                            for i in ids if i in table.objects]
                return [table.objects[i] for i in ids
                        if i in table.objects]
            if table.overlay:
                # scan query: the predicate may read node_id/status
                self._materialize_all_locked(table)
        if isinstance(by, All):
            return list(table.objects.values())
        if isinstance(by, ByName) and kind.collection != "tasks":
            oid = table.by_name.get(by.name.lower())
            return [table.objects[oid]] if oid in table.objects else []
        pred = self._predicate_for(kind, by)
        return [o for o in table.objects.values() if pred(o)]

    # --------------------------------------------- columnar scheduler commits

    def bulk_update_tasks(self, new_tasks: Sequence[Task], on_missing,
                          on_assigned,
                          guard_state: int = 192,  # TaskState.ASSIGNED
                          epoch: Optional[int] = None,
                          ) -> Tuple[List[int], List[int]]:
        """Columnar commit path for scheduler decisions (the TPU path's
        array-shaped output).  Semantically one ``batch`` of single-task
        updates (reference: memory.go:531 + scheduler.go:490), stripped of
        per-task transaction machinery; the inner loops run in C when the
        native hotpath module is available (see native/hotpath.c), with an
        identical pure-Python fallback below.

        Per-item semantics (scheduler.go:594-611 applySchedulingDecisions):

        * no stored object                -> ``on_missing(new)``, skipped;
        * status (state, message, err) unchanged -> skipped;
        * stored state >= ``guard_state`` -> ``on_assigned(new)`` returning
          False fails the item (node-version conflict path);
        * stale ``new.meta.version.index`` -> failed (SequenceConflict);
        * otherwise version-stamped and committed.

        ``new_tasks`` ownership transfers to the store — no defensive
        copies; callers must treat them as immutable afterwards (the same
        replace-don't-mutate convention stored objects already follow).
        Proposals/commits/events are chunked at MAX_CHANGES_PER_TX so each
        raft proposal stays within bounds.  StoreAction construction is
        elided with a nil proposer, Event construction when nobody is
        subscribed — both are observable only by their consumers.

        Returns (committed_indices, failed_indices); skipped items appear
        in neither.
        """
        from .. import native
        hp = native.get()
        committed_idx: List[int] = []
        failed_idx: List[int] = []
        n = len(new_tasks)
        ts = now()
        if not isinstance(new_tasks, list):
            new_tasks = list(new_tasks)
        with self._update_lock:
            table = self._tables["tasks"]
            objects = table.objects
            if table.overlay:
                # the C prepare loop reads `objects` directly: flush the
                # lazily-committed ids it may touch
                with self._lock:
                    for t in new_tasks:
                        if t.id in table.overlay:
                            self._materialize_locked(table, t.id)
            want_actions = self._proposer is not None
            want_events = self.queue.has_subscribers()
            if want_actions and epoch is None:
                # pin every chunk of this commit to one reign: a role
                # change mid-commit fails the remaining chunks instead of
                # letting them ride the successor's epoch
                epoch = self._proposer_epoch()
            i = 0
            while i < n:
                stop = min(i + MAX_CHANGES_PER_TX, n)
                with self._lock:
                    seq = self._version
                if hp is not None:
                    committed, failed, stamped, actions, events = \
                        hp.commit_prepare(
                            new_tasks, i, stop, objects, seq, ts,
                            int(guard_state),
                            StoreAction if want_actions else None,
                            Event if want_events else None,
                            on_missing, on_assigned)
                else:
                    committed, failed, stamped, actions, events = \
                        self._commit_prepare_py(
                            new_tasks, i, stop, objects, seq, ts,
                            guard_state, want_actions, want_events,
                            on_missing, on_assigned)
                i = stop
                failed_idx.extend(failed)
                if not stamped:
                    continue

                def apply_chunk(stamped=stamped):
                    with self._lock:
                        if hp is not None:
                            hp.commit_apply(stamped, objects, table.by_node,
                                            self._reindex_pair)
                        else:
                            self._commit_apply_py(stamped, table)
                        self._version += len(stamped)
                        for t in stamped:
                            # old ref elided on this path (replays carry
                            # old=None)
                            self._log_change_locked(
                                ("one", t.meta.version.index, "update",
                                 t, None), 1)

                if want_actions:
                    try:
                        # commit runs inside the consensus apply path (see
                        # Proposer.propose)
                        self._propose_fenced(self._proposer, actions,
                                             apply_chunk, epoch)
                    except Exception:
                        # per-chunk failure granularity: earlier chunks are
                        # committed and stay committed; this chunk and all
                        # remaining items fail so the caller rolls back only
                        # what the store did not apply
                        log.exception("bulk task-update proposal failed")
                        failed_idx.extend(committed)
                        failed_idx.extend(range(i, n))
                        break
                else:
                    apply_chunk()
                committed_idx.extend(committed)
                if want_events:
                    publish = self.queue.publish
                    for ev in events:
                        publish(ev)
                self.queue.publish(EventCommit(self._version))
        return committed_idx, failed_idx

    @property
    def supports_block_commit(self) -> bool:
        """True when scheduler assignments may commit as a columnar block
        (arrays end-to-end, objects materialized lazily on read) — always,
        since round 4: with live watchers the block publishes ONE coalesced
        EventTaskBlock (expanded lazily, shared, per subscriber); with a
        raft proposer it rides a compact columnar TaskBlockAction through
        consensus.  Kept as a property for callers that keyed off the old
        no-watcher/no-proposer restriction."""
        return True

    def commit_task_block(self, *args, **kwargs
                          ) -> Tuple[List[int], List[int]]:
        # timing shell only — signature, defaults, and docs live on the
        # impl so they exist in exactly one place
        with _BLOCK_COMMIT_TIMER.time():
            return self._commit_task_block_impl(*args, **kwargs)

    def _commit_task_block_impl(self, old_tasks: Sequence[Task],
                                node_ids: Sequence[str],
                                state: int, message: str,
                                on_missing, on_assigned,
                                guard_state: int = 192,
                                epoch: Optional[int] = None,
                                ) -> Tuple[List[int], List[int]]:
        """Columnar scheduler commit: assignments stay arrays end-to-end.

        Same per-item semantics as ``bulk_update_tasks`` (scheduler.go:490
        applySchedulingDecisions), but instead of installing pre-built Task
        objects it records (node_id, version, status) in the task table's
        overlay; per-task objects materialize lazily on first read.
        ``old_tasks[i]`` must be the scheduler's mirror of the stored task
        — when it is the stored instance itself (the common case; mirrors
        hold store references), validation is one identity check.

        by_node indexes update eagerly, so index-driven queries stay
        correct without materializing.  Live watchers get one coalesced
        EventTaskBlock per block (expanded to per-task events for
        subscribers that didn't opt into blocks); with a proposer the
        block is validated first, then proposed as chunked columnar
        TaskBlockActions and applied in the consensus apply path
        (reference: raft.go:1592 ProposeValue + wait.trigger).

        Returns (committed_indices, failed_indices); skipped items appear
        in neither.
        """
        from .. import native
        from ..models.types import TaskState
        if int(state) > int(TaskState.RUNNING):
            # contract block-aware consumers rely on: blocks carry
            # scheduler placement transitions only (state<=RUNNING), so
            # restart/reconcile/reaper loops may skip them wholesale —
            # failure and terminal states must go through per-object paths
            raise InvalidStoreAction(
                f"task blocks carry states <= RUNNING, got {state}")
        ts = now()
        committed_idx: List[int] = []
        failed_idx: List[int] = []
        missing: List[Tuple[Task, str]] = []
        if not isinstance(old_tasks, list):
            old_tasks = list(old_tasks)
        if not isinstance(node_ids, list):
            node_ids = list(node_ids)
        if self._proposer is not None:
            return self._commit_task_block_proposed(
                old_tasks, node_ids, int(state), message,
                on_missing, on_assigned, int(guard_state), ts,
                epoch=epoch)
        with self._update_lock:
            table = self._tables["tasks"]
            objects = table.objects
            overlay = table.overlay
            by_node = table.by_node
            hp = native.get()
            with self._lock:
                seq = self._version
                # slow-path index updates batch into ONE pass per chunk
                # (_batch_index_tasks) — runs in the finally so an
                # overlay entry can never outlive its index update
                pend_index: List[Tuple[str, str, str]] = []
                try:
                    slow: Sequence[int] = range(len(old_tasks))
                    if hp is not None:
                        fast, slow, seq = hp.block_commit(
                            old_tasks, node_ids, objects, overlay,
                            by_node, ts, int(state), message, seq,
                            int(guard_state))
                        committed_idx.extend(fast)
                    for i in slow:
                        old = old_tasks[i]
                        tid = old.id
                        cur = objects.get(tid)
                        if cur is not old or tid in overlay:
                            # mirror is not the stored instance: run the
                            # full bulk-path checks against the stored one
                            if cur is not None and tid in overlay:
                                cur = self._materialize_locked(table, tid)
                            if cur is None:
                                # callbacks run after the loop: an
                                # exception here must not strand
                                # committed versions (see finally)
                                missing.append((old, node_ids[i]))
                                continue
                            cs = cur.status
                            if cs.state == state \
                                    and cs.message == message:
                                continue
                            if cs.state >= guard_state and \
                                    not on_assigned(old, node_ids[i]):
                                failed_idx.append(i)
                                continue
                            if cur.meta.version.index != \
                                    old.meta.version.index:
                                failed_idx.append(i)
                                continue
                        elif cur.status.state >= guard_state and \
                                not on_assigned(old, node_ids[i]):
                            failed_idx.append(i)
                            continue
                        seq += 1
                        nid = node_ids[i]
                        overlay[tid] = (nid, seq, ts, state, message)
                        pend_index.append((tid, old.node_id, nid))
                        committed_idx.append(i)
                finally:
                    self._batch_index_tasks(by_node, pend_index)
                    # already-written overlay entries carry versions up to
                    # seq — the counter must advance past them even if a
                    # callback raised, or the next commit would reissue
                    # duplicate version indices
                    base = self._version
                    self._version = seq
                    olds_c = nids_c = None
                    if committed_idx:
                        # one columnar changelog entry for the whole
                        # block: replay materializes per-task lazily.
                        # Version order within the block matches commit
                        # order (fast-path items first, then slow).
                        olds_c = [old_tasks[i] for i in committed_idx]
                        nids_c = [node_ids[i] for i in committed_idx]
                        self._log_change_locked(
                            ("block", base, olds_c, nids_c,
                             int(state), message, ts),
                            len(committed_idx))
            if olds_c and self.queue.has_subscribers():
                # one coalesced event for the whole block; per-task
                # events synthesize lazily, shared across subscribers
                self.queue.publish(EventTaskBlock(
                    olds_c, nids_c, base, int(state), message, ts))
            self.queue.publish(EventCommit(self._version))
        for old, nid in missing:
            on_missing(old, nid)
        return committed_idx, failed_idx

    #: items per columnar raft proposal — ~25B/item serialized (joined
    #: ids + node RLE) keeps each entry under ~1MB, inside the
    #: reference's 1.5MB tx bound (memory.go:45-51)
    BLOCK_PROPOSAL_MAX_ITEMS = 32768

    def _commit_task_block_proposed(self, old_tasks: List[Task],
                                    node_ids: List[str], state: int,
                                    message: str, on_missing, on_assigned,
                                    guard_state: int, ts: float,
                                    epoch: Optional[int] = None,
                                    ) -> Tuple[List[int], List[int]]:
        """Block commit through the consensus seam: validate every item
        against the current store (no writes), stamp versions, then ride
        chunked columnar TaskBlockActions through the proposer — the
        overlay/index writes run inside the consensus apply path, exactly
        like ``update``'s commit callback, so snapshots taken at an
        applied index always include that index's changes.  Chunk failure
        granularity matches ``bulk_update_tasks``: committed chunks stay
        committed, the failing chunk and everything after fail.  All
        chunks are pinned to one leadership epoch (``epoch``, default:
        the proposer's at entry): a role change mid-commit fences the
        remaining chunks at the proposer instead of racing it."""
        from .. import native
        hp = native.get()
        if epoch is None:
            epoch = self._proposer_epoch()
        committed_idx: List[int] = []
        failed_idx: List[int] = []
        missing: List[Tuple[Task, str]] = []
        with self._update_lock:
            table = self._tables["tasks"]
            objects = table.objects
            overlay = table.overlay
            by_node = table.by_node
            with self._lock:
                base = self._version
                if hp is not None:
                    fast, slow = hp.block_validate(
                        old_tasks, node_ids, objects, overlay,
                        int(guard_state))
                    # all-fast blocks keep the range lazy (no 100k-int
                    # list); slow leftovers force a mutable list
                    accepted = list(fast) if slow else fast
                else:
                    accepted = []
                    slow = range(len(old_tasks))
                for i in slow:
                    old = old_tasks[i]
                    tid = old.id
                    cur = objects.get(tid)
                    if cur is not old or tid in overlay:
                        # mirror is not the stored instance: full checks
                        # against the stored one (bulk-path semantics)
                        if cur is not None and tid in overlay:
                            cur = self._materialize_locked(table, tid)
                        if cur is None:
                            missing.append((old, node_ids[i]))
                            continue
                        cs = cur.status
                        if cs.state == state and cs.message == message:
                            continue
                        if cs.state >= guard_state and \
                                not on_assigned(old, node_ids[i]):
                            failed_idx.append(i)
                            continue
                        if cur.meta.version.index != \
                                old.meta.version.index:
                            failed_idx.append(i)
                            continue
                    elif cur.status.state >= guard_state and \
                            not on_assigned(old, node_ids[i]):
                        failed_idx.append(i)
                        continue
                    accepted.append(i)
            # ---- chunked proposals, optionally pipelined.  With a
            # proposer exposing propose_async/wait_proposal and
            # pipeline_depth > 1, up to ``window`` chunk proposals ride
            # consensus at once: chunk i+1 serializes and persists while
            # chunk i is being applied.  Ordering is preserved because
            # same-thread proposals append to the raft log in submission
            # order and apply callbacks run in log order; the caller is
            # only acked (this method returns) after every chunk
            # resolved.  window=1 / missing async API degrades to the
            # strictly serial propose->wait-per-chunk behavior.
            proposer = self._proposer
            window = max(1, self.pipeline_depth)
            can_async = (window > 1
                         and hasattr(proposer, "propose_async")
                         and hasattr(proposer, "wait_proposal"))
            pending: deque = deque()

            def reap(entry) -> bool:
                chunk, olds_c, nids_c, cb_base, waiter = entry
                try:
                    proposer.wait_proposal(waiter)
                except Exception:
                    log.exception("columnar block proposal failed")
                    failed_idx.extend(chunk)
                    return False
                committed_idx.extend(chunk)
                if self.queue.has_subscribers():
                    self.queue.publish(EventTaskBlock(
                        olds_c, nids_c, cb_base, state, message, ts))
                return True

            pos = 0
            chunk_base = base
            n_acc = len(accepted)
            # a failed submit/commit fails the chunk and everything
            # after it (committed chunks stay committed) — same
            # granularity as bulk_update_tasks; chunks already in
            # flight when a failure surfaces resolve by their own
            # waiter (a later chunk cannot commit unless every earlier
            # one did, so results stay consistent with the log)
            ok_to_submit = True
            while pos < n_acc:
                chunk = accepted[pos:pos + self.BLOCK_PROPOSAL_MAX_ITEMS]
                pos += len(chunk)
                if not ok_to_submit:
                    failed_idx.extend(chunk)
                    continue
                # one materialization of the chunk's columns, shared by
                # the action, the changelog entry, and the block event
                olds_c = [old_tasks[i] for i in chunk]
                nids_c = [node_ids[i] for i in chunk]
                action = TaskBlockAction(
                    "task_block", tuple(t.id for t in olds_c),
                    tuple(nids_c), chunk_base, state, message, ts)

                def apply_chunk(chunk=chunk, chunk_base=chunk_base,
                                olds_c=olds_c, nids_c=nids_c):
                    with self._lock:
                        if hp is not None:
                            seq = hp.block_apply(
                                old_tasks, node_ids, chunk, overlay,
                                by_node, ts, state, message, chunk_base)
                        else:
                            seq = chunk_base
                            pend_index = []
                            for i in chunk:
                                seq += 1
                                old = old_tasks[i]
                                tid = old.id
                                nid = node_ids[i]
                                overlay[tid] = (nid, seq, ts, state,
                                                message)
                                pend_index.append((tid, old.node_id, nid))
                            # one batched index pass per chunk
                            self._batch_index_tasks(by_node, pend_index)
                        self._version = seq
                        self._log_change_locked(
                            ("block", chunk_base, olds_c, nids_c,
                             state, message, ts),
                            len(chunk))

                if can_async:
                    try:
                        if epoch is None:
                            # legacy 2-arg proposers have no fencing
                            # swarmlint: disable=epoch-fencing
                            waiter = proposer.propose_async([action],
                                                            apply_chunk)
                        else:
                            waiter = proposer.propose_async(
                                [action], apply_chunk, epoch=epoch)
                    except Exception:
                        log.exception("columnar block proposal failed")
                        failed_idx.extend(chunk)
                        ok_to_submit = False
                        continue
                    pending.append((chunk, olds_c, nids_c, chunk_base,
                                    waiter))
                    if len(pending) >= window \
                            and not reap(pending.popleft()):
                        ok_to_submit = False
                else:
                    try:
                        self._propose_fenced(proposer, [action],
                                             apply_chunk, epoch)
                    except Exception:
                        log.exception("columnar block proposal failed")
                        failed_idx.extend(chunk)
                        ok_to_submit = False
                        continue
                    committed_idx.extend(chunk)
                    if self.queue.has_subscribers():
                        self.queue.publish(EventTaskBlock(
                            olds_c, nids_c, chunk_base, state, message,
                            ts))
                chunk_base += len(chunk)
            while pending:
                reap(pending.popleft())
            self.queue.publish(EventCommit(self._version))
        for old, nid in missing:
            on_missing(old, nid)
        return committed_idx, failed_idx

    def _reindex_pair(self, old: Task, new: Task) -> None:
        table = self._tables["tasks"]
        self._unindex(table, old)
        self._index(table, new)

    def _commit_prepare_py(self, new_tasks, start, stop, objects, seq, ts,
                           guard_state, want_actions, want_events,
                           on_missing, on_assigned):
        """Pure-Python mirror of native commit_prepare (and the
        differential-test oracle for it)."""
        committed: List[int] = []
        failed: List[int] = []
        stamped: List[Task] = []
        actions: List[StoreAction] = []
        events: List[Event] = []
        for i in range(start, stop):
            new = new_tasks[i]
            cur = objects.get(new.id)
            if cur is None:
                on_missing(new)
                continue
            cs, ns = cur.status, new.status
            if (cs.state == ns.state and cs.message == ns.message
                    and cs.err == ns.err):
                continue
            if cs.state >= guard_state and not on_assigned(new):
                failed.append(i)
                continue
            if cur.meta.version.index != new.meta.version.index:
                failed.append(i)
                continue
            seq += 1
            m = new.meta
            m.version.index = seq
            m.created_at = cur.meta.created_at
            m.updated_at = ts
            committed.append(i)
            stamped.append(new)
            if want_actions:
                actions.append(StoreAction("update", new))
            if want_events:
                events.append(Event("update", new, cur))
        return committed, failed, stamped, actions, events

    def _commit_apply_py(self, stamped: List[Task], table: _Table) -> None:
        """Pure-Python apply for ``bulk_update_tasks``.  by_node index
        writes batch through ``_batch_index_tasks`` — ONE pass per
        chunk, like the block-commit paths — instead of a dict
        probe-and-pop per task.  Order preservation: the pending batch
        flushes BEFORE any item that takes the full ``_unindex``/
        ``_index`` route (a service/slot change also touches by_node),
        so every bucket still receives ids in exactly per-item commit
        order — the insertion-ordered ``{id: None}`` contract."""
        objects = table.objects
        by_node = table.by_node
        pend_index: List[Tuple[str, str, str]] = []
        for obj in stamped:
            old = objects.get(obj.id)
            objects[obj.id] = obj
            if old is None:
                continue
            if old.service_id != obj.service_id or old.slot != obj.slot:
                if pend_index:
                    self._batch_index_tasks(by_node, pend_index)
                    pend_index = []
                self._unindex(table, old)
                self._index(table, obj)
            elif old.node_id != obj.node_id:
                pend_index.append((obj.id, old.node_id, obj.node_id))
        if pend_index:
            self._batch_index_tasks(by_node, pend_index)

    # --------------------------------------------------- raft follower replay

    def apply_store_actions(self, actions: Sequence[StoreAction]) -> None:
        """Apply replicated actions without re-proposing
        (reference: memory.go:280).  Columnar TaskBlockActions apply
        straight into the task overlay — followers converge on the same
        lazy-materialization shape the leader committed."""
        events: List[Any] = []
        with self._update_lock:
            with self._lock:
                for change in actions:
                    if change.action == "task_block":
                        ev = self._apply_task_block_locked(change)
                        if isinstance(ev, list):
                            events.extend(ev)
                        elif ev is not None:
                            events.append(ev)
                        continue
                    obj = change.obj.copy()
                    old = self._tables[obj.collection].objects.get(obj.id)
                    if change.action == "create":
                        events.append(Event("create", obj))
                    elif change.action == "update":
                        events.append(Event("update", obj, old))
                    else:
                        events.append(Event("delete", old if old is not None else obj))
                    # The leader's _commit advances _version once per change
                    # (including deletes, whose payload carries the *old*
                    # object version) — mirror that exactly so follower
                    # EventCommit indices and post-failover version counters
                    # match the leader's.
                    if change.action == "delete":
                        self._version += 1
                    else:
                        self._version = max(self._version + 1,
                                            obj.meta.version.index)
                    self._apply_locked(StoreAction(change.action, obj))
                    ev = events[-1]
                    # follower-side resume tokens must match the leader's
                    # stamping bit-for-bit (same version counter flow)
                    object.__setattr__(ev, "version", self._version)
                    self._log_change_locked(
                        ("one", self._version, ev.action, ev.obj, ev.old),
                        1)
            for ev in events:
                self.queue.publish(ev)
            self.queue.publish(EventCommit(self._version))

    @staticmethod
    def _batch_index_tasks(by_node: Dict[str, Dict[str, None]],
                           triples) -> None:
        """One by_node index pass per committed chunk: ``triples`` is an
        iterable of (task_id, old_node_id, new_node_id) in commit order.
        Consecutive same-node placements (the planner emits them sorted
        by node) share one bucket lookup; buckets stay insertion-ordered
        ``{id: None}`` dicts and receive ids in exactly the order the
        per-item loops would have inserted them — the PR 8 determinism
        contract."""
        last_nid: Optional[str] = None
        bucket: Optional[Dict[str, None]] = None
        for tid, old_nid, nid in triples:
            if old_nid and old_nid != nid:
                b = by_node.get(old_nid)
                if b is not None:
                    b.pop(tid, None)
            if nid != last_nid:
                last_nid = nid
                if nid:
                    bucket = by_node.get(nid)
                    if bucket is None:
                        bucket = by_node[nid] = {}
                else:
                    bucket = None
            if bucket is not None:
                bucket[tid] = None

    def _apply_task_block_locked(self, action: "TaskBlockAction"):
        """Apply one replicated columnar block (caller holds both locks).
        Uses the leader's version numbering (base+1..base+n) so overlay
        entries converge bit-for-bit.  Returns one event to publish (an
        EventTaskBlock normally, a list of per-item Events if ids were
        skipped), or None when nothing resolved.

        The healthy-log case (every id stored, none overlaid) runs as
        one native pass — overlay writes plus a batched by_node index
        pass per chunk (hotpath.c block_apply_follower); the Python loop
        below is the fallback and the oracle, and the only path that can
        handle diverged/overlaid ids."""
        from .. import native
        table = self._tables["tasks"]
        objects = table.objects
        overlay = table.overlay
        by_node = table.by_node
        state, message, ts = action.state, action.message, action.ts
        hp = native.get_commit()
        if hp is not None:
            olds = hp.block_apply_follower(
                action.ids, action.node_ids, objects, overlay, by_node,
                ts, state, message, action.base_version)
            if olds is not None:
                self._version = max(
                    self._version, action.base_version + len(action.ids))
                if not olds:
                    return None
                nids = list(action.node_ids)
                self._log_change_locked(
                    ("block", action.base_version, olds, nids, state,
                     message, ts), len(olds))
                return EventTaskBlock(olds, nids, action.base_version,
                                      state, message, ts)
        applied: List[Tuple[Task, str, int]] = []
        for j, (tid, nid) in enumerate(zip(action.ids, action.node_ids)):
            cur = objects.get(tid)
            if cur is not None and tid in overlay:
                cur = self._materialize_locked(table, tid)
            if cur is None:
                # diverged follower (should not happen with a healthy
                # log): the leader still burned this version index
                continue
            ver = action.base_version + 1 + j
            overlay[tid] = (nid, ver, ts, state, message)
            applied.append((cur, nid, ver))
        self._batch_index_tasks(
            by_node,
            ((cur.id, cur.node_id, nid) for cur, nid, _v in applied))
        self._version = max(self._version,
                            action.base_version + len(action.ids))
        if not applied:
            return None
        if len(applied) == len(action.ids):
            # versions are contiguous from base: block changelog entry +
            # block event (both stamp versions as base+1+i)
            olds = [a[0] for a in applied]
            nids = [a[1] for a in applied]
            self._log_change_locked(
                ("block", action.base_version, olds, nids, state,
                 message, ts), len(applied))
            return EventTaskBlock(olds, nids, action.base_version,
                                  state, message, ts)
        # skipped ids broke contiguity: log/publish per item with exact
        # versions so changelog replay and events stamp correctly
        events: List[Event] = []
        for old, nid, ver in applied:
            ev = Event("update",
                       _materialize_task(old, nid, ver, ts, state,
                                         message), old, version=ver)
            self._log_change_locked(
                ("one", ver, "update", ev.obj, ev.old), 1)
            events.append(ev)
        return events

    def save(self) -> Dict[str, Any]:
        """Full-store snapshot (reference: snapshot.proto StoreSnapshot)."""
        with self._lock:
            self._materialize_all_locked(self._tables["tasks"])
            return {
                "version": self._version,
                "tables": {
                    coll: [o.copy() for o in t.objects.values()]
                    for coll, t in self._tables.items()
                },
            }

    def restore(self, snapshot: Dict[str, Any]) -> None:
        with self._update_lock:
            with self._lock:
                for coll in self._tables:
                    self._tables[coll] = _Table()
                for coll, objs in snapshot["tables"].items():
                    table = self._tables[coll]
                    for o in objs:
                        cp = o.copy()
                        table.objects[cp.id] = cp
                        self._index(table, cp)
                        if coll != "tasks":
                            name = _obj_name(cp).lower()
                            if name:
                                table.by_name[name] = cp.id
                self._version = snapshot.get("version", 0)
                # resume continuity is lost across a snapshot install:
                # watch-from callers see "compacted" and must re-list
                self._changelog.clear()
                self._changelog_total = 0
            self.queue.publish(EventSnapshotRestore())

    def save_bytes(self) -> bytes:
        """Deterministic snapshot bytes (raft snapshot transfer / disk)."""
        from . import serde
        return serde.snapshot_to_bytes(self.save())

    def restore_bytes(self, data: bytes) -> None:
        from . import serde
        self.restore(serde.snapshot_from_bytes(data))

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def close(self) -> None:
        self.queue.close()


class Batch:
    """Accumulates updates in one open transaction, committing whenever the
    staged *change count* reaches MAX_CHANGES_PER_TX — the bound a single
    raft proposal must respect (reference: memory.go:45-51, :531).

    Callbacks run immediately against the open transaction; the writer lock
    is held from the first update until the enclosing ``store.batch`` call
    returns (flush or abort).
    """

    def __init__(self, store: MemoryStore):
        self._store = store
        self._tx: Optional[WriteTx] = None
        self.applied = 0    # callbacks run
        self.committed = 0  # changes committed
        self._staged_bytes = 0   # serialized size of staged changes
        self._measured = 0       # changes already size-accounted

    def update(self, cb: Callable[[WriteTx], Any]) -> Any:
        if self._tx is None:
            self._store._update_lock.acquire()
            self._tx = WriteTx(self._store)
        result = cb(self._tx)
        self.applied += 1
        changes = self._tx._changes
        if self._store._proposer is not None:
            # size-account only the changes staged since the last
            # callback; each serializes once here, exactly as it will on
            # the raft wire.  Proposer-less stores skip this — the byte
            # bound exists to cap a single raft proposal, and paying
            # O(serialized bytes) per local batch would tax every
            # orchestrator batch for nothing.
            while self._measured < len(changes):
                from . import serde
                self._staged_bytes += len(serde.dumps(
                    serde.action_to_dict(changes[self._measured])))
                self._measured += 1
        if len(changes) >= MAX_CHANGES_PER_TX \
                or self._staged_bytes >= MAX_TX_BYTES:
            self._flush_tx()
        return result

    def _flush_tx(self) -> None:
        tx, self._tx = self._tx, None
        self._staged_bytes = 0
        self._measured = 0
        try:
            n = len(tx._changes)
            self._store._propose_and_commit(tx)
            self.committed += n
        finally:
            self._store._update_lock.release()

    def _flush(self) -> None:
        if self._tx is not None:
            self._flush_tx()

    def _abort(self) -> None:
        """Discard any uncommitted tail (after an error) and release."""
        if self._tx is not None:
            self._tx = None
            self._staged_bytes = 0
            self._measured = 0
            self._store._update_lock.release()
