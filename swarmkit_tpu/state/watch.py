"""Bounded pub/sub event queue (reference: watch/watch.go:20).

The store publishes every committed change here; control loops subscribe with
a predicate.  Semantics mirror the reference's Queue built on go-events:

* ``subscribe``   — unbounded buffered channel; slow consumers grow the buffer.
* ``subscribe_limited(n)`` — bounded buffer; on overflow the subscription is
  CLOSED (the consumer sees the closure and must resync from a store view),
  matching the reference's close-on-overflow sink behavior.

A subscription is a thread-safe iterator/queue hybrid: ``get(timeout)`` or
iteration; ``close()`` cancels.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Iterable, Iterator, List, Optional

from ..models.types import now as _now, time_source_installed \
    as _virtual_time

Predicate = Callable[[Any], bool]


class Closed(Exception):
    """The subscription was closed (by cancel or overflow)."""


class Subscription:
    def __init__(self, queue: "Queue", predicate: Optional[Predicate],
                 limit: Optional[int], accepts_blocks: bool = False):
        self._queue = queue
        self._predicate = predicate
        self._limit = limit
        self._buf: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self.overflowed = False
        #: opt-in: deliver coalesced block events (objects exposing
        #: ``expand_events``) as-is instead of expanding them into their
        #: per-item events — block-aware consumers read the arrays
        #: directly and skip the per-item synthesis entirely
        self.accepts_blocks = accepts_blocks

    # -- producer side -----------------------------------------------------
    def _publish(self, event: Any) -> None:
        if not self.accepts_blocks \
                and getattr(event, "expand_events", None) is not None:
            # coalesced block for a per-item consumer: buffer the block
            # AS-IS and expand at consumption time, in the CONSUMER's
            # thread — the committing writer pays O(subscribers) per
            # block, never O(items).  The expansion itself is cached on
            # the block, shared across subscribers; each subscriber pays
            # only its own predicate filter.
            with self._cond:
                if self._closed:
                    return
                if self._limit is not None and \
                        len(self._buf) + len(event) > self._limit:
                    self.overflowed = True
                    self._closed = True
                    self._cond.notify_all()
                    return
                self._buf.append(event)
                self._cond.notify()
            return
        if self._predicate is not None:
            try:
                if not self._predicate(event):
                    return
            except Exception:
                return
        with self._cond:
            if self._closed:
                return
            if self._limit is not None and len(self._buf) >= self._limit:
                # close-on-overflow: consumer must resync
                self.overflowed = True
                self._closed = True
                self._cond.notify_all()
                return
            self._buf.append(event)
            self._cond.notify()

    # -- consumer side -----------------------------------------------------
    def _needs_expand(self, item: Any) -> bool:
        return not self.accepts_blocks and \
            getattr(item, "expand_events", None) is not None

    def _expand(self, block: Any) -> List[Any]:
        """Synthesize + filter a block's per-item events.  Runs WITHOUT
        _cond held: expansion is O(len(block)) and must never stall the
        publishing (committing) thread, which takes _cond in _publish.
        A predicate exception drops only the offending event, matching
        the per-event publish path's granularity.  The expansion is
        shared across subscribers (cached on the block, native when
        available); the per-subscriber predicate filter runs as one
        native pass too (hotpath.c fanout_filter) with the loop below
        as fallback and oracle."""
        try:
            events = block.expand_events()
        except Exception:
            return []
        pred = self._predicate
        if pred is None:
            return list(events)
        from .. import native
        hp = native.get_commit()
        if hp is not None:
            return hp.fanout_filter(events, pred)
        out = []
        for e in events:
            try:
                if pred(e):
                    out.append(e)
            except Exception:
                continue
        return out

    def _splice_front_locked(self, events: List[Any]) -> None:
        self._buf.extendleft(reversed(events))

    def get(self, timeout: Optional[float] = None) -> Any:
        """Next event; blocks up to ``timeout`` (forever when None).
        Buffered blocks expand on THIS thread, outside the lock — with
        one consumer per subscription (the usage contract) ordering is
        preserved by re-splicing the tail at the buffer front.  The
        deadline reads through models.types.now() — the determinism
        seam — so a simulated consumer's wait window is a function of
        the virtual clock, not the host's.  In production now() is
        wall-clock: like every other deadline in the control plane
        (dispatcher TTLs, scheduler debounce), a clock step moves it —
        the price of one observable time axis end to end.  A generous
        REAL-time backstop bounds the wait when an installed virtual
        clock is frozen (a test forgot to step it): raise TimeoutError,
        never hang the consumer thread."""
        import time as _time
        deadline = None if timeout is None else _now() + timeout
        if timeout is None:
            real_deadline = None
        else:
            # the backstop must read host time by definition
            # swarmlint: disable=determinism-seam
            real_deadline = _time.monotonic() + timeout * 16.0 + 1.0
        while True:
            with self._cond:
                item = self._buf.popleft() if self._buf else None
                if item is None:
                    if self._closed:
                        raise Closed()
                    if deadline is None:
                        self._cond.wait()
                    else:
                        remaining = deadline - _now()
                        if remaining <= 0:
                            raise TimeoutError()
                        # virtual remaining is not real seconds: under
                        # an installed virtual clock wait in short real
                        # slices so a deadline stepped past mid-wait is
                        # observed promptly, not after the full slice
                        self._cond.wait(min(remaining, 0.05)
                                        if _virtual_time()
                                        else remaining)
                    item = self._buf.popleft() if self._buf else None
                    if item is None:
                        if self._closed:
                            raise Closed()
                        if deadline is not None:
                            # backstop read, see above
                            # swarmlint: disable=determinism-seam
                            hung = _time.monotonic() >= real_deadline
                            if hung or _now() >= deadline:
                                raise TimeoutError()
                        continue
            if not self._needs_expand(item):
                return item
            events = self._expand(item)
            if not events:
                continue   # block filtered to nothing: keep waiting
            if len(events) > 1:
                with self._cond:
                    self._splice_front_locked(events[1:])
            return events[0]

    WAKE = object()   # sentinel returned by get() after wake()

    def wake(self) -> None:
        """Make a blocked get() return Subscription.WAKE promptly — lets a
        worker that multiplexes timers with this subscription react to new
        timers without waiting out its poll timeout."""
        with self._cond:
            if self._closed:
                return
            self._buf.append(Subscription.WAKE)
            self._cond.notify()

    def poll(self) -> Optional[Any]:
        while True:
            with self._cond:
                if not self._buf:
                    return None
                item = self._buf.popleft()
            if not self._needs_expand(item):
                return item
            events = self._expand(item)
            if not events:
                continue
            if len(events) > 1:
                with self._cond:
                    self._splice_front_locked(events[1:])
            return events[0]

    def backlog(self) -> int:
        """Buffered, unconsumed entries — coalesced blocks count their
        expansion size, so with one entry per committed version this is
        the subscription's lag in store versions (the watch plane's
        queue-depth probe reads it; observability only, never consumes)."""
        with self._cond:
            items = list(self._buf)
        n = 0
        for it in items:
            if getattr(it, "expand_events", None) is not None:
                try:
                    n += len(it)
                    continue
                except Exception:
                    pass
            n += 1
        return n

    def drain(self) -> List[Any]:
        with self._cond:
            raw = list(self._buf)
            self._buf.clear()
        items: List[Any] = []
        for item in raw:
            if self._needs_expand(item):
                items.extend(self._expand(item))
            else:
                items.append(item)
        return items

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                yield self.get()
            except Closed:
                return

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed and not self._buf

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class Queue:
    """Broadcast queue: every event goes to every matching subscriber."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: List[Subscription] = []

    def publish(self, event: Any) -> None:
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            sub._publish(event)

    def has_subscribers(self) -> bool:
        return bool(self._subs)

    def publish_all(self, events: Iterable[Any]) -> None:
        for e in events:
            self.publish(e)

    def subscribe(self, predicate: Optional[Predicate] = None,
                  accepts_blocks: bool = False) -> Subscription:
        return self._add(Subscription(self, predicate, None,
                                      accepts_blocks))

    def subscribe_limited(self, limit: int,
                          predicate: Optional[Predicate] = None,
                          accepts_blocks: bool = False) -> Subscription:
        return self._add(Subscription(self, predicate, limit,
                                      accepts_blocks))

    def _add(self, sub: Subscription) -> Subscription:
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        sub.close()
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    def close(self) -> None:
        with self._lock:
            subs = list(self._subs)
            self._subs.clear()
        for sub in subs:
            sub.close()
