"""Bounded pub/sub event queue (reference: watch/watch.go:20).

The store publishes every committed change here; control loops subscribe with
a predicate.  Semantics mirror the reference's Queue built on go-events:

* ``subscribe``   — unbounded buffered channel; slow consumers grow the buffer.
* ``subscribe_limited(n)`` — bounded buffer; on overflow the subscription is
  CLOSED (the consumer sees the closure and must resync from a store view),
  matching the reference's close-on-overflow sink behavior.

A subscription is a thread-safe iterator/queue hybrid: ``get(timeout)`` or
iteration; ``close()`` cancels.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Iterable, Iterator, List, Optional

Predicate = Callable[[Any], bool]


class Closed(Exception):
    """The subscription was closed (by cancel or overflow)."""


class Subscription:
    def __init__(self, queue: "Queue", predicate: Optional[Predicate],
                 limit: Optional[int]):
        self._queue = queue
        self._predicate = predicate
        self._limit = limit
        self._buf: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self.overflowed = False

    # -- producer side -----------------------------------------------------
    def _publish(self, event: Any) -> None:
        if self._predicate is not None:
            try:
                if not self._predicate(event):
                    return
            except Exception:
                return
        with self._cond:
            if self._closed:
                return
            if self._limit is not None and len(self._buf) >= self._limit:
                # close-on-overflow: consumer must resync
                self.overflowed = True
                self._closed = True
                self._cond.notify_all()
                return
            self._buf.append(event)
            self._cond.notify()

    # -- consumer side -----------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Any:
        with self._cond:
            if not self._buf and not self._closed:
                self._cond.wait(timeout)
            if self._buf:
                return self._buf.popleft()
            if self._closed:
                raise Closed()
            raise TimeoutError()

    WAKE = object()   # sentinel returned by get() after wake()

    def wake(self) -> None:
        """Make a blocked get() return Subscription.WAKE promptly — lets a
        worker that multiplexes timers with this subscription react to new
        timers without waiting out its poll timeout."""
        with self._cond:
            if self._closed:
                return
            self._buf.append(Subscription.WAKE)
            self._cond.notify()

    def poll(self) -> Optional[Any]:
        with self._cond:
            if self._buf:
                return self._buf.popleft()
            return None

    def drain(self) -> List[Any]:
        with self._cond:
            items = list(self._buf)
            self._buf.clear()
            return items

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                yield self.get()
            except Closed:
                return

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed and not self._buf

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class Queue:
    """Broadcast queue: every event goes to every matching subscriber."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: List[Subscription] = []

    def publish(self, event: Any) -> None:
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            sub._publish(event)

    def has_subscribers(self) -> bool:
        return bool(self._subs)

    def publish_all(self, events: Iterable[Any]) -> None:
        for e in events:
            self.publish(e)

    def subscribe(self, predicate: Optional[Predicate] = None) -> Subscription:
        return self._add(Subscription(self, predicate, None))

    def subscribe_limited(self, limit: int,
                          predicate: Optional[Predicate] = None) -> Subscription:
        return self._add(Subscription(self, predicate, limit))

    def _add(self, sub: Subscription) -> Subscription:
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        sub.close()
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    def close(self) -> None:
        with self._lock:
            subs = list(self._subs)
            self._subs.clear()
        for sub in subs:
            sub.close()
