"""swarmd: the node daemon — run a manager, join as a worker, or both.

Reference: swarmd/cmd/swarmd/main.go (state-dir, join-addr/token,
listen-remote-api flags; node.New/Start wiring).

    # first manager (bootstraps the cluster, prints join tokens)
    python -m swarmkit_tpu.swarmd --manager --state-dir /tmp/m0 \
        --listen-remote-api 127.0.0.1:4242

    # worker joining it
    python -m swarmkit_tpu.swarmd --state-dir /tmp/w0 \
        --join-addr 127.0.0.1:4242 --join-token SWMTKN-1-...

    # second manager joining the raft group (manager token)
    python -m swarmkit_tpu.swarmd --manager --state-dir /tmp/m1 \
        --join-addr 127.0.0.1:4242 --join-token SWMTKN-1-<manager> \
        --listen-remote-api 127.0.0.1:4243
"""

from __future__ import annotations

import argparse
import logging
import random
import threading
import time
from typing import Optional, Tuple

from .models.types import now as _seam_now

log = logging.getLogger("swarmd")


class ManagerLockedError(Exception):
    """The manager's key material is sealed under an unlock key the
    daemon was not given (reference: autolock, manager.go:116-120)."""


def parse_addr(text: str) -> Tuple[str, int]:
    host, _, port = text.rpartition(":")
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        raise SystemExit(
            f"invalid address {text!r}: expected host:port")


class Swarmd:
    """One node process: always an agent; a manager when --manager."""

    def __init__(self, state_dir: str, hostname: str = "",
                 manager: bool = False,
                 listen_remote_api: Optional[Tuple[str, int]] = None,
                 join_addr: Optional[Tuple[str, int]] = None,
                 join_token: str = "",
                 executor=None,
                 use_device_scheduler: bool = True,
                 migrate_plaintext_wal: bool = False,
                 cert_renew_interval: float = 60.0,
                 unlock_key: str = "",
                 force_new_cluster: bool = False,
                 listen_metrics: Optional[Tuple[str, int]] = None,
                 clock=None, rng: Optional[random.Random] = None):
        import os

        from .agent.testutils import TestExecutor

        self.state_dir = state_dir
        self.hostname = hostname or state_dir.rstrip("/").rsplit("/", 1)[-1]
        if executor == "process":
            from .agent.procexec import ProcessExecutor
            # task logs live under the state dir, cleaned with node state
            executor = ProcessExecutor(
                hostname=self.hostname,
                log_dir=os.path.join(state_dir, "task-logs"))
        elif executor == "test":
            executor = TestExecutor(hostname=self.hostname)
        self.is_manager = manager
        self.listen_remote_api = listen_remote_api
        self.join_addr = join_addr
        self.join_token = join_token
        self.executor = executor or TestExecutor(hostname=self.hostname)
        self.use_device_scheduler = use_device_scheduler
        # one-time replay of a state dir written before WAL encryption
        # existed (--migrate-plaintext-wal); steady state fails closed
        self.migrate_plaintext_wal = migrate_plaintext_wal
        # how often the renewer thread re-checks cert lifetime (the
        # renewal itself triggers past half of validity)
        self.cert_renew_interval = cert_renew_interval
        # operator-held unlock key (autolock): required to open a sealed
        # manager state dir; '' means not provided
        self.unlock_key = unlock_key
        self.locked = False
        # quorum-loss recovery: rebuild a single-member raft from this
        # node's WAL/snapshot (reference: manager.go:99-101)
        self.force_new_cluster = force_new_cluster
        # operator observability HTTP listener (reference: swarmd
        # --listen-metrics, main.go:92-97)
        self.listen_metrics = listen_metrics
        self.metrics_server = None
        self._stop_event = threading.Event()
        self.manager = None
        self.server = None
        self.node = None
        self.raft_node = None
        self.raft_transport = None
        # raft member id: "m-<hostname>" for nodes started as managers; a
        # worker promoted at runtime keeps its node id (the reference uses
        # one node id for both roles, node/node.go:286)
        self.raft_id = "m-" + self.hostname
        # serializes role transitions against stop() and each other
        self._role_mu = threading.Lock()
        # injected clock/rng seams (matching Agent(rng=)): deadlines and
        # reconnect/role-retry backoff read through these so tests and
        # the simulator control them; production defaults are the
        # models.types.now() seam and a per-process unseeded rng
        self._clock = clock or _seam_now
        self._rng = rng or random.Random()

    def start(self) -> None:
        from .node import Node

        if self.listen_metrics is not None:
            from . import obs  # noqa: F401  (registers /debug/* endpoints)
            from .utils.httpdebug import DebugServer
            def health() -> str:
                if self.manager is not None:
                    return self.manager.health_check()
                if self.locked or self.is_manager:
                    return "NOT_SERVING"
                # worker: healthy only while its agent session is live
                node = self.node
                agent = node.agent if node is not None else None
                if agent is None:
                    return "NOT_SERVING"
                return ("SERVING" if agent.session_id
                        else "NOT_SERVING")

            self.metrics_server = DebugServer(
                host=self.listen_metrics[0], port=self.listen_metrics[1],
                health=health)
            self.metrics_server.start()
            log.info("metrics/debug HTTP on %s:%d",
                     *self.metrics_server.addr)

        if not self.is_manager and self.join_addr is not None:
            import os as _os
            if _os.path.exists(self._manager_state_path()):
                # promoted to manager at runtime in a previous life: come
                # back up as that manager (reference: node.go:983
                # runManager restarts from the persisted role)
                self.is_manager = True

        if self.is_manager and self.join_addr is not None:
            self._start_joining_manager()
            return

        if self.is_manager:
            from .security import RootCA

            # a manager is raft-backed from the start so later managers
            # can join its group over the raft_join RPC (reference:
            # manager.go:217 becomes the raft founder).  A restart reuses
            # the persisted CA key + raft listen port: peers know us by
            # that address, and the transport HMAC key must match theirs.
            try:
                state = self._load_manager_state()
            except ManagerLockedError as e:
                # autolock: refuse to serve anything until unlock()
                self.locked = True
                log.warning("manager locked: %s", e)
                return
            ca = (RootCA(state["ca_key"], state["ca_cert"])
                  if state else RootCA())
            self._prev_ca_key = state.get("prev_ca_key") if state else None
            raft_port = state["raft_port"] if state else 0
            api_port = state["api_port"] if state else 0
            self._build_raft_manager(ca, raft_port=raft_port)
            # fresh bootstrap (or lone survivor): we must become leader;
            # a restarted member of a larger group follows whoever leads
            if len(self.raft_node.core.peers) == 1:
                self._wait(lambda: self.raft_node.is_leader
                           and self.raft_node.core.leader_ready,
                           "bootstrap raft never elected")
                self._wait(lambda: self.manager.is_leader
                           and self.manager.dispatcher is not None,
                           "manager never took leadership")
                # restart adoption may swap in the persisted cluster's
                # trust root: re-key both the HMAC fallback and the TLS
                # identity so peers on the adopted root accept us
                self.raft_transport.auth_key = self.manager.root_ca.key
                if self.raft_transport.tls_identity is not None:
                    from .models.types import NodeRole as _NR
                    self.raft_transport.set_identity(
                        self.manager.root_ca.issue(
                            self.raft_id, _NR.MANAGER))
            self._start_remote_api(port_override=api_port)
            if self.server is not None:
                self.manager.api_addrs[self.raft_id] = self.server.addr
                if self.raft_node.is_leader:
                    # replicate our API address so agents can fail over
                    # to us and followers can redirect joins
                    self.raft_node.add_member(
                        self.raft_id, self.raft_transport.addr,
                        self.server.addr)
            self._save_manager_state()
            self._start_manager_agent()
            self._start_manager_identity_renewer()
            self._start_role_watcher()
            if self.manager.is_leader:
                log.info("manager up; worker join token: %s",
                         self.manager.root_ca.join_token(0))
                log.info("manager join token: %s",
                         self.manager.root_ca.join_token(1))
            return

        import os as _os

        from .net import issue_certificate
        from .remotes import (
            ConnectionBroker, FailoverDispatcherClient, PersistentRemotes,
        )
        from .security.ca import SecurityError

        # reuse a persisted identity when present, else join with the token
        self.node = Node(self.executor, self.state_dir)
        cert = None
        try:
            cert, _ = self.node.key_rw.read()
        except (FileNotFoundError, SecurityError):
            pass
        if cert is None and (self.join_addr is None
                             or not self.join_token):
            # restarts ride the persisted identity + remotes; a FIRST
            # join needs the operator's addr+token (reference:
            # node/node.go — JoinAddr only required without stored state)
            raise SystemExit(
                "worker mode needs --join-addr and --join-token")
        if cert is not None and self.join_addr is not None \
                and not self._cert_accepted(cert):
            # a cert from a rebuilt/foreign cluster would make every
            # register() fail with an application-level SecurityError the
            # failover client rightly never retries around — fall back to
            # the operator's join token instead (node.py load_or_join does
            # the same verify-then-rejoin dance against a local CA)
            cert = None
        if cert is None:
            if self.join_addr is None or not self.join_token:
                # the persisted cert was rejected (rebuilt/foreign
                # cluster) and there is nothing to re-join with
                raise SystemExit(
                    "worker mode needs --join-addr and --join-token")
            cert = issue_certificate(self.join_addr, self.node.node_id,
                                     self.join_token)
            self.node.key_rw.write(cert, b"")
        self.node.certificate = cert
        self.node.node_id = cert.node_id
        # weighted failover across known managers, persisted across
        # restarts (reference: node/node.go:1202 persistentRemotes) and
        # seeded with the join address; managers learned from heartbeats
        # are observed into the set and survive the next restart
        seeds = [self.join_addr] if self.join_addr is not None else []
        self.remotes = PersistentRemotes(
            _os.path.join(self.state_dir, "state.json"), *seeds)
        if not self.remotes.weights():
            # persisted identity but no persisted managers and no seed:
            # the agent could only spin on NoSuchRemote forever
            raise SystemExit(
                "no known managers: pass --join-addr (persisted remotes "
                "state.json is empty)")
        client = FailoverDispatcherClient(
            ConnectionBroker(self.remotes), cert)
        self.node.start(client, hostname=self.hostname)
        self._start_cert_renewer(client)
        self._start_role_watcher()
        log.info("worker %s joined %s", self.node.node_id[:8],
                 self.join_addr)

    def _start_cert_renewer(self, client) -> None:
        """Client-side certificate renewal loop (reference: ca/renewer.go
        + certificates.go RequestAndSaveNewCertificates): past half of
        validity, send a fresh CSR to a live manager, persist the new
        identity and swap it in for future connections."""
        from .security.ca import needs_renewal

        def loop():
            from .security.ca import signing_root_digest
            while not self._stop_event.wait(self.cert_renew_interval):
                cert = self.node.certificate
                if cert is None:
                    continue
                # renew at half-life, or immediately when the managers
                # advertise a different root (CA rotation in progress)
                advertised = getattr(client, "last_ca_digest", "") or ""
                rotated = (advertised
                           and advertised != signing_root_digest(cert))
                if not needs_renewal(cert) and not rotated:
                    continue
                fresh = self._renew_via_managers(cert)
                if fresh is not None:
                    self._swap_node_cert(fresh, client)
                    log.info("renewed certificate for %s (expires %.0f)",
                             fresh.node_id[:8], fresh.expires_at)

        threading.Thread(target=loop, name="cert-renewer",
                         daemon=True).start()

    def _renew_via_managers(self, cert):
        """One renewal pass over every reachable manager; returns the
        fresh certificate or None.  The server issues for the node's
        STORE role, so the result also carries promotions/demotions."""
        from .net.client import renew_certificate

        targets = []
        remotes = getattr(self, "remotes", None)
        if remotes is not None:
            targets += list(remotes.weights())
        if self.join_addr is not None and self.join_addr not in targets:
            targets.append(self.join_addr)
        for addr in targets:
            if self._stop_event.is_set():
                return None   # don't hold role transitions across stop()
            try:
                return renew_certificate(addr, cert)
            except Exception as e:
                log.info("cert renewal via %s failed: %s", addr, e)
        return None

    def _swap_node_cert(self, fresh, client) -> None:
        """Persist + activate a renewed identity: future connections
        present the fresh cert (the factory closes over
        client.certificate); drop the live connection so the next
        heartbeat handshakes with the new identity (the leader records
        its issuer for rotation progress)."""
        self.node.key_rw.write(fresh, b"")
        self.node.certificate = fresh
        if client is not None:
            client.certificate = fresh
            reset = getattr(client, "reset_connection", None)
            if reset is not None:
                reset()

    # ------------------------------------------------- runtime role changes

    def _start_role_watcher(self) -> None:
        """React to promotion/demotion decided by the leader's role
        manager.  The node's store-reconciled role rides on every
        heartbeat response; on a mismatch with what we are running, renew
        the certificate (the CA issues for the store role) and start or
        stop the manager component (reference: node/node.go:483
        superviseManager, :947 waitRole, :1086 role-change teardown)."""
        if getattr(self, "_role_watcher_started", False):
            return
        self._role_watcher_started = True
        from .models.types import NodeRole

        from .remotes import backoff_with_jitter

        def loop():
            attempt, next_try = 0, 0.0
            while not self._stop_event.wait(0.5):
                node = self.node
                agent = node.agent if node is not None else None
                cert = node.certificate if node is not None else None
                if agent is None or cert is None:
                    continue
                client = agent.client
                role = getattr(client, "last_role", None)
                if role is None:
                    continue
                try:
                    role = NodeRole(role)
                except ValueError:
                    continue
                wants_promote = (role == NodeRole.MANAGER
                                 and self.manager is None)
                wants_demote = (role == NodeRole.WORKER
                                and self.manager is not None)
                if not wants_promote and not wants_demote:
                    attempt, next_try = 0, 0.0   # settled: reset
                    continue
                if self._clock() < next_try:
                    continue
                try:
                    with self._role_mu:
                        if self._stop_event.is_set():
                            continue
                        if wants_promote and self.manager is None:
                            self._promote_to_manager(client)
                        elif wants_demote and self.manager is not None:
                            self._demote_to_worker(client)
                    attempt, next_try = 0, 0.0
                except Exception:
                    # a failed attempt redials managers and (for
                    # promotion) rebuilds a whole stack — back off with
                    # full jitter through the injected clock/rng seams
                    # instead of churning twice a second (and instead
                    # of a whole fleet retrying in lockstep)
                    delay = backoff_with_jitter(attempt, rng=self._rng,
                                                base=0.5, cap=30.0)
                    log.exception("role transition failed; retrying in "
                                  "%.1fs", delay)
                    next_try = self._clock() + delay
                    attempt += 1

        threading.Thread(target=loop, name="role-watcher",
                         daemon=True).start()

    def _promote_to_manager(self, client) -> None:
        """Runtime worker→manager transition: renew into a MANAGER cert,
        join the raft group under our existing node id, and start the
        Manager composition beside the running agent (reference:
        node/node.go:1099 superviseManager starting runManager)."""
        import base64

        from .models.types import NodeRole
        from .net import join_raft
        from .security import RootCA

        cert = self.node.certificate
        if NodeRole(cert.role) != NodeRole.MANAGER:
            fresh = self._renew_via_managers(cert)
            if fresh is None or NodeRole(fresh.role) != NodeRole.MANAGER:
                raise RuntimeError(
                    "could not obtain a manager certificate")
            self._swap_node_cert(fresh, client)
            cert = fresh
        self.raft_id = self.node.node_id
        boot = join_via = None
        for addr in list(self.remotes.weights()):
            if self._stop_event.is_set():
                raise RuntimeError("daemon stopping; promotion aborted")
            try:
                boot = join_raft(addr, cert, self.raft_id)
                join_via = addr
                break
            except Exception as e:
                log.info("raft bootstrap hop via %s failed: %s", addr, e)
        if boot is None:
            raise RuntimeError("no manager reachable for raft join")
        ca = RootCA(base64.b64decode(boot["ca_key"]),
                    base64.b64decode(boot["ca_cert"]))
        had_listen = self.listen_remote_api
        try:
            self._build_raft_manager(ca, raft_port=0, defer_start=True)
            if self.listen_remote_api is None:
                # a manager serves the remote API (joins/control/failover)
                self.listen_remote_api = ("127.0.0.1", 0)
            self._start_remote_api()
            self._complete_raft_join(join_via, cert)
        except Exception:
            # roll the half-built stack back so the watcher's retry gate
            # (self.manager is None) re-arms and ports don't leak.  If
            # the address-carrying hop already committed our membership,
            # the committed voter survives this rollback — the watcher's
            # retry re-adopts it (the leader's join_raft is idempotent
            # for existing members); should this node die for good
            # instead, the operator demotes it like any dead manager
            # (covered by the demote-a-downed-manager flow)
            self._teardown_manager_stack()
            self.listen_remote_api = had_listen
            raise
        if self.server is not None:
            self.manager.api_addrs[self.raft_id] = self.server.addr
        self._save_manager_state()
        self.is_manager = True
        self._start_manager_identity_renewer()
        log.info("node %s promoted to manager; raft group %s",
                 self.raft_id[:8], sorted(self.raft_node.core.peers))

    def _complete_raft_join(self, join_via, cert) -> None:
        """The address-carrying join hop plus peer seeding and startup —
        the one join protocol shared by a fresh `--manager --join-addr`
        daemon and a runtime promotion (reference: manager.go
        JoinAndStart -> Join RPC)."""
        from .net import join_raft

        resp = None
        for attempt in range(20):
            if self._stop_event.is_set():
                raise RuntimeError("daemon stopping; join aborted")
            try:
                resp = join_raft(
                    join_via, cert, self.raft_id,
                    raft_addr=self.raft_transport.addr,
                    api_addr=self.server.addr if self.server else None)
                break
            except Exception as e:
                # the leader serializes membership changes; concurrent
                # joins are a normal, momentary condition
                log.info("raft join attempt %d failed (%s); retrying",
                         attempt + 1, e)
                self._stop_event.wait(0.5)
        if resp is None:
            raise RuntimeError("could not join the raft group")
        for nid, addr in resp["members"].items():
            if nid != self.raft_id and addr is not None:
                self.raft_transport.set_peer(nid, tuple(addr))
                self.raft_node.core.peers.add(nid)
                self.raft_node.core.peer_addrs[nid] = tuple(addr)
        self.raft_node.start()
        self.manager.run()

    def _demote_to_worker(self, client) -> None:
        """Runtime manager→worker transition.  The leader's role manager
        removes us from raft BEFORE flipping the observed role
        (raft-first demotion), so by the time the heartbeat says WORKER
        our membership is already gone: tear down the manager stack, keep
        the agent running on a WORKER cert (reference: node/node.go:1086
        "role changed to worker, stopping manager")."""
        from .models.types import NodeRole

        cert = self.node.certificate
        if NodeRole(cert.role) != NodeRole.WORKER:
            fresh = self._renew_via_managers(cert)
            if fresh is None or NodeRole(fresh.role) != NodeRole.WORKER:
                raise RuntimeError("could not obtain a worker certificate")
            self._swap_node_cert(fresh, client)
        self._teardown_manager_stack()
        self.is_manager = False
        log.info("manager %s demoted; continuing as worker",
                 self.node.node_id[:8])

    def _teardown_manager_stack(self) -> None:
        """Stop and clear this daemon's manager components and drop their
        on-disk state (a restart must come back as a worker; replaying a
        stale WAL would resurrect a phantom peer)."""
        import os
        import shutil

        server, self.server = self.server, None
        manager, self.manager = self.manager, None
        raft_node, self.raft_node = self.raft_node, None
        transport, self.raft_transport = self.raft_transport, None
        if server is not None:
            server.stop()
        if manager is not None:
            manager.stop()
        if raft_node is not None:
            raft_node.stop()   # unregisters (closes) the transport too
        elif transport is not None:
            # _build_raft_manager binds the transport's listener before
            # the raft node exists; a failure between the two must not
            # leak the bound socket + accept thread
            try:
                transport.unregister(transport.node_id)
            except Exception:
                pass
        try:
            os.remove(self._manager_state_path())
        except FileNotFoundError:
            pass
        shutil.rmtree(os.path.join(self.state_dir, "raft"),
                      ignore_errors=True)

    def _start_manager_identity_renewer(self) -> None:
        """Managers hold the CA, so their serving identities (raft link,
        API server) renew by local re-issue at half of validity — without
        this a long-lived manager's certs expire and every CERT_REQUIRED
        peer handshake starts failing cluster-wide."""
        if getattr(self, "_identity_renewer_started", False):
            return   # demote→re-promote cycle: one thread is enough
        self._identity_renewer_started = True
        from .models.types import NodeRole
        from .security.ca import needs_renewal

        from .security.ca import signing_root_digest

        def stale(ca, ident) -> bool:
            return (needs_renewal(ident)
                    or signing_root_digest(ident) != ca.active_digest)

        def loop():
            while not self._stop_event.wait(self.cert_renew_interval):
                mgr = self.manager
                if mgr is None:
                    continue
                ca = mgr.root_ca
                t = self.raft_transport
                if (t is not None and t.tls_identity is not None
                        and stale(ca, t.tls_identity)):
                    t.set_identity(ca.issue(t.node_id, NodeRole.MANAGER))
                    log.info("renewed raft TLS identity for %s",
                             t.node_id)
                s = self.server
                if (s is not None and getattr(s, "tls_identity", None)
                        is not None and stale(ca, s.tls_identity)):
                    s.set_tls_identity(ca.issue(
                        s.tls_identity.node_id, NodeRole.MANAGER))
                    log.info("renewed API TLS identity")
                # this manager's own agent identity: local re-issue from
                # the CA we hold (managers never CSR themselves)
                node = self.node
                if (node is not None and node.certificate is not None
                        and stale(ca, node.certificate)):
                    fresh = ca.issue(node.certificate.node_id,
                                     NodeRole(node.certificate.role))
                    node.key_rw.write(fresh, b"")
                    node.certificate = fresh
                    agent = node.agent
                    cli = agent.client if agent is not None else None
                    if cli is not None and hasattr(cli, "certificate"):
                        cli.certificate = fresh
                        reset = getattr(cli, "reset_connection", None)
                        if reset is not None:
                            reset()
                    log.info("renewed manager-agent identity for %s",
                             fresh.node_id)

        threading.Thread(target=loop, name="manager-identity-renewer",
                         daemon=True).start()

    def _wait(self, cond, err: str, timeout: float = 20.0) -> None:
        """Poll ``cond`` until true or the injected-clock deadline
        passes.  A loop-count backstop (~10x the nominal window in real
        sleeps) guards against a frozen injected clock: a test that
        forgets to step its virtual clock gets the RuntimeError, not a
        hung harness."""
        deadline = self._clock() + timeout
        for _ in range(max(1, int(timeout / 0.02) * 10)):
            if cond():
                return
            if self._clock() > deadline:
                raise RuntimeError(err)
            time.sleep(0.02)
        raise RuntimeError(err)

    def _cert_accepted(self, cert) -> bool:
        """Probe the remote hello with the persisted cert: the server
        verifies certificates during the handshake, so a SecurityError
        here means the cert does not belong to this cluster."""
        from .net.client import RemoteDispatcherClient
        from .security.ca import SecurityError
        try:
            probe = RemoteDispatcherClient(self.join_addr, cert)
            try:
                probe.heartbeat(cert.node_id, "")
            finally:
                probe.close()
        except (SecurityError, PermissionError):
            # the wire client surfaces the server's "unauthenticated"
            # hello rejection as PermissionError (net/client.py error map)
            return False
        except Exception:
            pass   # app-level errors arrive only after an accepted hello
        return True

    def _start_joining_manager(self) -> None:
        """Join an existing cluster as an additional manager: manager
        cert via the join token, CA key + peer addresses via an
        address-less first raft_join hop, membership via the second hop
        that advertises our transport address, then a raft-backed Manager
        that follows the current leader (reference: manager.go
        JoinAndStart -> Join RPC).  A restart skips the RPCs entirely:
        membership and addresses replay from the WAL."""
        import base64

        from .net import issue_certificate, join_raft
        from .node import Node
        from .security import RootCA

        try:
            state = self._load_manager_state()
        except ManagerLockedError as e:
            self.locked = True
            log.warning("manager locked: %s", e)
            return
        # a runtime-promoted worker persisted its own node id as the raft
        # member id; _load_manager_state restored it into self.raft_id
        raft_id = self.raft_id
        if state is not None:
            # restart: peers + addresses replay from the raft WAL
            self._prev_ca_key = state.get("prev_ca_key")
            if state["api_port"] and self.listen_remote_api is None:
                # we served the remote API before the restart and its
                # address replicated cluster-wide — rebind it
                self.listen_remote_api = ("127.0.0.1", 0)
            self._build_raft_manager(
                RootCA(state["ca_key"], state["ca_cert"]),
                raft_port=state["raft_port"])
            self.node = Node(self.executor, self.state_dir,
                             node_id=raft_id)
            from .security.ca import SecurityError
            try:
                cert, _ = self.node.key_rw.read()
            except (FileNotFoundError, SecurityError) as e:
                # state file exists but the cert doesn't (crash between
                # the two writes) — re-issue with the operator's token
                # rather than crash-looping forever
                if not self.join_token:
                    raise RuntimeError(
                        "persisted manager state has no certificate and "
                        "no --join-token was given") from e
                cert = issue_certificate(self.join_addr, raft_id,
                                         self.join_token)
                self.node.key_rw.write(cert, b"")
            self._start_remote_api(port_override=state["api_port"])
        else:
            if not self.join_token:
                raise SystemExit("manager join needs --join-token")
            cert = None
            for attempt in range(10):
                try:
                    cert = issue_certificate(self.join_addr, raft_id,
                                             self.join_token)
                    break
                except PermissionError:
                    # a follower that has not yet adopted the replicated
                    # cluster state rejects fresh tokens momentarily
                    if attempt == 9:
                        raise
                    time.sleep(0.5)
            # first hop: fetch the cluster CA key (authenticates the raft
            # transport) WITHOUT advertising an address — membership only
            # changes on the second hop, so dying here leaves no phantom
            # peer wedging quorum
            boot = join_raft(self.join_addr, cert, raft_id)
            ca_key = base64.b64decode(boot["ca_key"])
            ca_cert = base64.b64decode(boot["ca_cert"])
            self._build_raft_manager(RootCA(ca_key, ca_cert), raft_port=0,
                                     defer_start=True)
            self._start_remote_api()
            self._complete_raft_join(self.join_addr, cert)
            self._save_manager_state()
        if self.server is not None:
            self.manager.api_addrs[raft_id] = self.server.addr

        # this manager's agent talks to whichever manager leads, like any
        # worker (a follower manager runs no dispatcher)
        if self.node is None:
            self.node = Node(self.executor, self.state_dir,
                             node_id=raft_id)
        self.node.certificate = cert
        self.node.node_id = cert.node_id
        self.node.key_rw.write(cert, b"")
        # seed with every manager API address we know — on a restart the
        # original join address may be long dead, but the WAL replayed
        # the current members' addresses
        extra = [tuple(a) for a in self.raft_node.core.api_addrs.values()]
        self._start_agent_with_failover(cert, self.join_addr, *extra)
        self._start_manager_identity_renewer()
        self._start_role_watcher()
        log.info("manager %s joined raft group %s", raft_id,
                 sorted(self.raft_node.core.peers))

    # ------------------------------------------------------- manager wiring

    def _start_manager_agent(self) -> None:
        """Run this manager node's own agent.  Preferred wiring is the
        failover client over the remote API (it survives leadership
        moves — the in-process dispatcher dies with leadership); only an
        API-less in-process leader binds its dispatcher directly."""
        from .node import Node
        from .security.ca import SecurityError

        # the manager node's cluster identity IS its raft member id, so
        # RoleManager can map Node records to raft voters (the reference
        # uses one node id for both)
        self.node = Node(self.executor, self.state_dir,
                         node_id=self.raft_id)
        cert = None
        try:
            cert, _ = self.node.key_rw.read()
        except (FileNotFoundError, SecurityError):
            pass
        if cert is None:
            if self.manager.dispatcher is None:
                # restarted follower with no persisted identity: nothing
                # local can issue a cert (the CA serves on the leader)
                log.warning("no persisted identity and not the leader; "
                            "manager-node agent not started")
                return
            # a MANAGER certificate: this node's store record must carry
            # the manager role or promotion/demotion can't act on it
            from .models.types import NodeRole
            token = self.manager.root_ca.join_token(NodeRole.MANAGER)
            self.node.load_or_join(self.manager.ca_server, token)
            cert = self.node.certificate
        else:
            self.node.certificate = cert
            self.node.node_id = cert.node_id
        if self.server is None:
            self.node.start(self.manager.dispatcher,
                            store=self.manager.store,
                            hostname=self.hostname)
            return
        seeds = [self.server.addr]
        seeds += [tuple(a) for a in self.raft_node.core.api_addrs.values()]
        self._start_agent_with_failover(cert, *seeds)

    def _start_agent_with_failover(self, cert, seed=None, *extra) -> None:
        import os as _os

        from .remotes import (
            ConnectionBroker, FailoverDispatcherClient, PersistentRemotes,
        )

        addrs = ([tuple(seed)] if seed else []) + [tuple(a) for a in extra]
        self.remotes = PersistentRemotes(
            _os.path.join(self.state_dir, "state.json"), *addrs)
        client = FailoverDispatcherClient(
            ConnectionBroker(self.remotes), cert)
        self.node.start(client, hostname=self.hostname)

    def _build_raft_manager(self, ca, raft_port: int = 0,
                            defer_start: bool = False) -> None:
        """Shared wiring for bootstrap and joining managers: TCP raft
        transport, raft-backed store, and the Manager composition."""
        import os

        from .manager import Manager
        from .net.raft_transport import TCPRaftTransport
        from .state import MemoryStore
        from .state.raft import KeyEncoder, RaftLogger, RaftNode

        raft_id = self.raft_id
        # raft links run mutual TLS on a manager cert self-issued from
        # the cluster CA (reference: ca/transport.go for raft peers)
        from .models.types import NodeRole
        self.raft_transport = TCPRaftTransport(
            raft_id, port=raft_port, auth_key=ca.key,
            tls_identity=ca.issue(raft_id, NodeRole.MANAGER))
        store = MemoryStore()
        prev_key = getattr(self, "_prev_ca_key", None)
        encoder = KeyEncoder(
            ca.key, allow_plaintext=self.migrate_plaintext_wal,
            fallback=KeyEncoder(prev_key) if prev_key else None)
        logger = RaftLogger(os.path.join(self.state_dir, "raft"),
                            encoder=encoder)
        if prev_key:
            # a crash interrupted the rotation re-key: converge all
            # on-disk state to the current key now (decode via fallback)
            logger.rotate_encoder(KeyEncoder(
                ca.key, allow_plaintext=self.migrate_plaintext_wal))
            self._prev_ca_key = None
        self.raft_node = RaftNode(
            raft_id, [raft_id], store, logger, self.raft_transport,
            force_new_cluster=self.force_new_cluster)
        store._proposer = self.raft_node
        self.manager = Manager(
            store=store, raft_node=self.raft_node, root_ca=ca,
            use_device_scheduler=self.use_device_scheduler)
        self.manager.raft_peer_addrs[raft_id] = self.raft_transport.addr
        # after a root rotation finalizes (or is adopted from the leader),
        # everything keyed off the CA key must re-key: the encrypted
        # WAL/snapshots, the transport HMAC fallback, persisted state
        self.manager.on_root_rotated = self._on_root_rotated
        self.manager.on_cluster_changed = self._resave_manager_state
        if not defer_start:
            self.raft_node.start()
            self.manager.run()

    def _resave_manager_state(self) -> None:
        """Cluster changed (possibly the autolock flag / unlock key):
        re-persist local state so sealing matches the cluster's will."""
        if self.manager is None or self.raft_transport is None:
            return
        try:
            self._save_manager_state()
        except Exception:
            log.exception("re-sealing manager state failed")

    def _on_root_rotated(self) -> None:
        """Re-key local material derived from the CA key after a root
        rotation (reference: manager re-encrypts the raft DEK under the
        new KEK, manager/deks.go + storage.go RotateEncryptionKey).

        Crash-safe ordering: (1) persist the state file carrying BOTH
        keys, (2) re-encrypt snapshot+WAL under the new key, (3) persist
        again without the old key.  A crash at any point leaves a state
        file whose key (plus optional prev key fallback) can decode
        everything on disk."""
        from .state.raft import KeyEncoder
        ca = self.manager.root_ca
        old_key = self.raft_transport.auth_key
        try:
            self._save_manager_state(prev_key=old_key)
            self.raft_node.logger.rotate_encoder(KeyEncoder(ca.key))
            self._save_manager_state()
        except Exception:
            log.exception("WAL re-key after CA rotation failed")
        self.raft_transport.auth_key = ca.key
        log.info("re-keyed raft storage under the rotated root CA")

    def _start_remote_api(self, port_override: int = 0) -> None:
        from .net import ManagerServer

        if self.listen_remote_api is not None:
            port = self.listen_remote_api[1] or port_override
            self.server = ManagerServer(
                self.manager, host=self.listen_remote_api[0], port=port)
            self.server.start()
            log.info("remote API on %s:%d", *self.server.addr)

    def _manager_state_path(self) -> str:
        import os
        return os.path.join(self.state_dir, "manager-state.json")

    def _load_manager_state(self):
        import json
        try:
            with open(self._manager_state_path(), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        if raw.startswith(b"LOCK1"):
            # sealed under the operator's unlock key (autolock)
            from .state.raft.storage import DecryptionError, KeyEncoder
            if not self.unlock_key:
                raise ManagerLockedError(
                    "manager state is locked; provide the unlock key")
            try:
                raw = KeyEncoder(self.unlock_key.encode()).decode(raw[5:])
            except DecryptionError:
                raise ManagerLockedError("invalid unlock key")
        try:
            rec = json.loads(raw)
        except ValueError as e:
            raise RuntimeError(
                f"manager state file {self._manager_state_path()!r} is "
                f"unreadable ({e})") from e
        try:
            # restore the raft member id: "m-<hostname>" normally, the
            # node's own id for a runtime-promoted worker
            self.raft_id = rec.get("raft_id") or self.raft_id
            return {"ca_key": bytes.fromhex(rec["ca_key"]),
                    "ca_cert": bytes.fromhex(rec["ca_cert"]),
                    "prev_ca_key": bytes.fromhex(rec["prev_ca_key"])
                    if rec.get("prev_ca_key") else None,
                    "raft_port": rec["raft_port"],
                    "api_port": rec.get("api_port", 0)}
        except (KeyError, ValueError, TypeError) as e:
            # a partial/old-format state file must NOT silently bootstrap
            # a brand-new cluster (fresh CA = every cert and token in the
            # fleet invalidated); make the operator decide
            raise RuntimeError(
                f"manager state file {self._manager_state_path()!r} is "
                f"unreadable or from an incompatible version ({e}); "
                "remove it to bootstrap a new cluster") from e

    def _save_manager_state(self, prev_key: Optional[bytes] = None
                            ) -> None:
        """Persist what a restart cannot recover from the WAL: the CA
        key that authenticates the raft transport (the reference keeps CA
        material in the state dir too, node.go loadSecurityConfig) and our
        raft listen port, which peers know us by."""
        import json
        import os

        os.makedirs(self.state_dir, exist_ok=True)
        payload = json.dumps({
            "raft_id": self.raft_id,
            "ca_key": self.manager.root_ca.key.hex(),
            "ca_cert": self.manager.root_ca.cert_pem.hex(),
            # present only mid-re-key: decode fallback for a crash
            # between the WAL rewrite and this file converging
            "prev_ca_key": prev_key.hex() if prev_key else "",
            "raft_port": self.raft_transport.addr[1],
            # the API port must survive restarts too: it replicated
            # to the whole cluster via the join conf entry, and a
            # follower cannot re-propose a changed address
            "api_port": self.server.addr[1] if self.server else 0,
        }).encode()
        key = self._autolock_key()
        if key:
            # autolock: the CA key (root of every trust + encryption
            # chain) only hits disk sealed under the operator's unlock
            # key (reference: manager/deks.go KEK over the DEK)
            from .state.raft.storage import KeyEncoder
            payload = b"LOCK1" + KeyEncoder(key).encode(payload)
        tmp = self._manager_state_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self._manager_state_path())

    def _autolock_key(self):
        """The cluster's manager unlock key when autolock is enabled
        (bytes), else None."""
        try:
            # unredacted read: the API projection strips unlock_keys
            cluster = self.manager.control_api._default_cluster_raw()
        except Exception:
            return None
        if not cluster.spec.encryption_config.auto_lock_managers:
            return None
        for ek in cluster.unlock_keys:
            if ek.subsystem == "manager" and ek.key:
                return ek.key
        return None

    def unlock(self, key: str) -> None:
        """Unseal a locked manager and complete startup (reference:
        swarm unlock)."""
        if not self.locked:
            return
        self.unlock_key = key
        self._load_manager_state()   # raises ManagerLockedError if wrong
        self.locked = False
        self.start()

    def stop(self) -> None:
        self._stop_event.set()
        # let an in-flight role transition finish before tearing down
        if self._role_mu.acquire(timeout=10):
            self._role_mu.release()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.node is not None:
            self.node.stop()
        if self.server is not None:
            self.server.stop()
        if self.manager is not None:
            self.manager.stop()
        if self.raft_node is not None:
            self.raft_node.stop()


def main(argv=None) -> int:   # pragma: no cover - thin CLI shell
    parser = argparse.ArgumentParser(prog="swarmd")
    parser.add_argument("--state-dir", required=True)
    parser.add_argument("--hostname", default="")
    parser.add_argument("--manager", action="store_true")
    parser.add_argument("--listen-remote-api", default="")
    parser.add_argument("--join-addr", default="")
    parser.add_argument("--join-token", default="")
    parser.add_argument("--no-device-scheduler", action="store_true")
    parser.add_argument("--executor", default="process",
                        choices=["process", "test"],
                        help="task runtime backend: real OS processes "
                             "(default) or the in-memory test executor")
    parser.add_argument("--migrate-plaintext-wal", action="store_true",
                        help="one-time replay of a state dir written "
                             "before WAL encryption existed")
    parser.add_argument("--unlock-key", default="",
                        help="unlock key for an autolocked manager "
                             "state dir")
    parser.add_argument("--force-new-cluster", action="store_true",
                        help="recover from quorum loss: rebuild a "
                             "single-member raft from this node's state")
    parser.add_argument("--listen-metrics", default="",
                        help="serve /metrics, /healthz and /debug/stacks "
                             "over plain HTTP on host:port")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    daemon = Swarmd(
        state_dir=args.state_dir, hostname=args.hostname,
        manager=args.manager,
        listen_remote_api=parse_addr(args.listen_remote_api)
        if args.listen_remote_api else None,
        join_addr=parse_addr(args.join_addr) if args.join_addr else None,
        join_token=args.join_token,
        executor=args.executor,
        use_device_scheduler=not args.no_device_scheduler,
        migrate_plaintext_wal=args.migrate_plaintext_wal,
        unlock_key=args.unlock_key,
        force_new_cluster=args.force_new_cluster,
        listen_metrics=parse_addr(args.listen_metrics)
        if args.listen_metrics else None)
    daemon.start()
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        daemon.stop()
    return 0


if __name__ == "__main__":   # pragma: no cover
    raise SystemExit(main())
