"""swarmd: the node daemon — run a manager, join as a worker, or both.

Reference: swarmd/cmd/swarmd/main.go (state-dir, join-addr/token,
listen-remote-api flags; node.New/Start wiring).

    # first manager (bootstraps the cluster, prints join tokens)
    python -m swarmkit_tpu.swarmd --manager --state-dir /tmp/m0 \
        --listen-remote-api 127.0.0.1:4242

    # worker joining it
    python -m swarmkit_tpu.swarmd --state-dir /tmp/w0 \
        --join-addr 127.0.0.1:4242 --join-token SWMTKN-1-...
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
from typing import Optional, Tuple

log = logging.getLogger("swarmd")


def parse_addr(text: str) -> Tuple[str, int]:
    host, _, port = text.rpartition(":")
    return (host or "127.0.0.1", int(port))


class Swarmd:
    """One node process: always an agent; a manager when --manager."""

    def __init__(self, state_dir: str, hostname: str = "",
                 manager: bool = False,
                 listen_remote_api: Optional[Tuple[str, int]] = None,
                 join_addr: Optional[Tuple[str, int]] = None,
                 join_token: str = "",
                 executor=None,
                 use_device_scheduler: bool = True):
        from .agent.testutils import TestExecutor

        self.state_dir = state_dir
        self.hostname = hostname or state_dir.rstrip("/").rsplit("/", 1)[-1]
        self.is_manager = manager
        self.listen_remote_api = listen_remote_api
        self.join_addr = join_addr
        self.join_token = join_token
        self.executor = executor or TestExecutor(hostname=self.hostname)
        self.use_device_scheduler = use_device_scheduler
        self.manager = None
        self.server = None
        self.node = None

    def start(self) -> None:
        from .node import Node

        if self.is_manager:
            from .manager import Manager
            from .net import ManagerServer

            self.manager = Manager(
                use_device_scheduler=self.use_device_scheduler)
            self.manager.run()
            if self.listen_remote_api is not None:
                self.server = ManagerServer(
                    self.manager, host=self.listen_remote_api[0],
                    port=self.listen_remote_api[1])
                self.server.start()
                log.info("remote API on %s:%d", *self.server.addr)

            # the manager node also runs an agent against itself
            self.node = Node(self.executor, self.state_dir)
            token = self.manager.root_ca.join_token(0)
            self.node.load_or_join(self.manager.ca_server, token)
            self.node.start(self.manager.dispatcher,
                            store=self.manager.store,
                            hostname=self.hostname)
            log.info("manager up; worker join token: %s",
                     self.manager.root_ca.join_token(0))
            log.info("manager join token: %s",
                     self.manager.root_ca.join_token(1))
            return

        if self.join_addr is None or not self.join_token:
            raise SystemExit(
                "worker mode needs --join-addr and --join-token")
        from .net import issue_certificate
        from .remotes import (
            ConnectionBroker, FailoverDispatcherClient, Remotes,
        )
        from .security.ca import SecurityError

        # reuse a persisted identity when present, else join with the token
        self.node = Node(self.executor, self.state_dir)
        cert = None
        try:
            cert, _ = self.node.key_rw.read()
        except (FileNotFoundError, SecurityError):
            pass
        if cert is None:
            cert = issue_certificate(self.join_addr, self.node.node_id,
                                     self.join_token)
            self.node.key_rw.write(cert, b"")
        self.node.certificate = cert
        self.node.node_id = cert.node_id
        # weighted failover across known managers (seeded with the join
        # address; more managers can be observed into self.remotes)
        self.remotes = Remotes(self.join_addr)
        client = FailoverDispatcherClient(
            ConnectionBroker(self.remotes), cert)
        self.node.start(client, hostname=self.hostname)
        log.info("worker %s joined %s", self.node.node_id[:8],
                 self.join_addr)

    def stop(self) -> None:
        if self.node is not None:
            self.node.stop()
        if self.server is not None:
            self.server.stop()
        if self.manager is not None:
            self.manager.stop()


def main(argv=None) -> int:   # pragma: no cover - thin CLI shell
    parser = argparse.ArgumentParser(prog="swarmd")
    parser.add_argument("--state-dir", required=True)
    parser.add_argument("--hostname", default="")
    parser.add_argument("--manager", action="store_true")
    parser.add_argument("--listen-remote-api", default="")
    parser.add_argument("--join-addr", default="")
    parser.add_argument("--join-token", default="")
    parser.add_argument("--no-device-scheduler", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    daemon = Swarmd(
        state_dir=args.state_dir, hostname=args.hostname,
        manager=args.manager,
        listen_remote_api=parse_addr(args.listen_remote_api)
        if args.listen_remote_api else None,
        join_addr=parse_addr(args.join_addr) if args.join_addr else None,
        join_token=args.join_token,
        use_device_scheduler=not args.no_device_scheduler)
    daemon.start()
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        daemon.stop()
    return 0


if __name__ == "__main__":   # pragma: no cover
    raise SystemExit(main())
