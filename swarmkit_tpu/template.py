"""Template expansion of container specs and secret/config payloads.

Reference: template/{context.go,expand.go,getter.go}.

The reference uses Go text/template with a strict context; here the same
strict context drives a small ``{{ ... }}`` expander supporting:

* dotted lookups: ``{{.Service.ID}}``, ``{{.Service.Name}}``,
  ``{{.Service.Labels}}`` (or a specific label via ``index``),
  ``{{.Node.ID}}``, ``{{.Node.Hostname}}``, ``{{.Node.Platform.OS}}``,
  ``{{.Node.Platform.Architecture}}``, ``{{.Task.ID}}``,
  ``{{.Task.Name}}``, ``{{.Task.Slot}}``;
* ``{{index .Service.Labels "key"}}``;
* payload-context functions (secret/config payloads only):
  ``{{secret "name"}}``, ``{{config "name"}}``, ``{{env "VAR"}}``.

Unknown expressions raise ``TemplateError`` — the reference fails task
preparation the same way.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from .models.objects import Task
from .models.types import NodeDescription

_EXPR = re.compile(r"\{\{\s*(.*?)\s*\}\}")
_INDEX = re.compile(r'^index\s+(\.[A-Za-z.]+)\s+"([^"]*)"$')
_FUNC = re.compile(r'^(secret|config|env)\s+"([^"]*)"$')


class TemplateError(Exception):
    pass


def task_name(t: Task) -> str:
    """reference: api/naming — <service>.<slot>.<task id> or
    <service>.<node>.<task id>."""
    base = t.service_annotations.name or t.service_id
    mid = str(t.slot) if t.slot else t.node_id
    return f"{base}.{mid}.{t.id}" if mid else f"{base}.{t.id}"


class Context:
    """Strict template context (reference: context.go:28)."""

    def __init__(self, node: Optional[NodeDescription], t: Task):
        platform = node.platform if node is not None else None
        self._values = {
            ".Service.ID": t.service_id,
            ".Service.Name": t.service_annotations.name,
            ".Node.ID": t.node_id,
            ".Node.Hostname": node.hostname if node is not None else "",
            ".Node.Platform.OS": platform.os if platform else "",
            ".Node.Platform.Architecture":
                platform.architecture if platform else "",
            ".Task.ID": t.id,
            ".Task.Name": task_name(t),
            ".Task.Slot": str(t.slot) if t.slot else t.node_id,
        }
        self._maps = {
            ".Service.Labels": dict(t.service_annotations.labels),
        }

    def _eval(self, expr: str, funcs) -> str:
        expr = expr.strip()
        if expr in self._values:
            return self._values[expr]
        m = _INDEX.match(expr)
        if m:
            mapping = self._maps.get(m.group(1))
            if mapping is None:
                raise TemplateError(f"unknown map {m.group(1)!r}")
            return mapping.get(m.group(2), "")
        m = _FUNC.match(expr)
        if m:
            fn = funcs.get(m.group(1)) if funcs else None
            if fn is None:
                raise TemplateError(
                    f"function {m.group(1)!r} not available in this "
                    "context")
            return fn(m.group(2))
        raise TemplateError(f"cannot evaluate template expression "
                            f"{expr!r}")

    def expand(self, text: str, funcs=None) -> str:
        def repl(m):
            return self._eval(m.group(1), funcs)

        return _EXPR.sub(repl, text)


def expand_container_spec(node: Optional[NodeDescription], t: Task):
    """Return a copy of the task's ContainerSpec with env, hostname, mount
    sources/targets, and labels expanded (reference: expand.go:18)."""
    spec = t.spec.container
    if spec is None:
        return None
    ctx = Context(node, t)
    out = spec.copy()
    out.env = [ctx.expand(e) for e in spec.env]
    out.hostname = ctx.expand(spec.hostname)
    out.labels = {k: ctx.expand(v) for k, v in spec.labels.items()}
    for m in out.mounts:
        m.source = ctx.expand(m.source)
        m.target = ctx.expand(m.target)
    return out


def expand_secret_payload(data: bytes, node: Optional[NodeDescription],
                          t: Task, secrets: Optional[Dict[str, bytes]] = None,
                          configs: Optional[Dict[str, bytes]] = None,
                          env: Optional[Dict[str, str]] = None) -> bytes:
    """Expand a templated secret/config payload with the payload-context
    functions (reference: expand.go:122 expandPayload)."""
    ctx = Context(node, t)
    # the env function sees the container's *expanded* environment
    expanded_env: Dict[str, str] = {}
    c = t.spec.container
    if c is not None:
        for e in c.env:
            k, _, v = e.partition("=")
            try:
                expanded_env[k] = ctx.expand(v)
            except TemplateError:
                expanded_env[k] = v
    if env:
        expanded_env.update(env)

    def env_fn(var: str) -> str:
        if var not in expanded_env:
            raise TemplateError(f"environment variable not present: {var}")
        return expanded_env[var]

    funcs = {
        "secret": lambda name: _lookup(secrets, name, "secret").decode(),
        "config": lambda name: _lookup(configs, name, "config").decode(),
        "env": env_fn,
    }
    try:
        text = data.decode("utf-8")
    except UnicodeDecodeError:
        return data  # binary payloads pass through
    return ctx.expand(text, funcs).encode("utf-8")


def _lookup(mapping, name, what):
    if mapping is None or name not in mapping:
        raise TemplateError(f"{what} not found: {name}")
    return mapping[name]
