from .identity import new_id, new_secret

__all__ = ["new_id", "new_secret"]
