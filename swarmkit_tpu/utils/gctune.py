"""Cyclic-GC control for allocation-heavy hot paths.

The cluster store keeps millions of small objects alive (tasks × nested
dataclasses); CPython's gen-0 collector fires every ~700 allocations and
each run scans a slice of that graph.  A 100k-task scheduling tick
allocates ~1M objects, so GC multiplies the tick's Python cost ~5x
(measured: 4.4µs vs 26µs per task clone).

``paused_gc()`` disables collection for the duration of a tick-sized
critical section.  Nothing the scheduler allocates in a tick is cyclic
garbage (object graphs are trees), so deferring collection is safe; normal
allocation pressure triggers a collection shortly after the section ends.
Re-entrant, and leaves GC untouched if the caller already disabled it.
"""

from __future__ import annotations

import gc
from contextlib import contextmanager

_depth = 0


@contextmanager
def paused_gc():
    global _depth
    outer = _depth == 0 and gc.isenabled()
    if outer:
        gc.disable()
    _depth += 1
    try:
        yield
    finally:
        _depth -= 1
        if outer:
            gc.enable()
