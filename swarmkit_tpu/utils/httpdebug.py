"""Operator observability HTTP listener: /metrics, /healthz, and the
/debug/* family (stacks, trace, health, flightrec).

Reference: swarmd/cmd/swarmd/main.go:92-97 (--listen-metrics serving
Prometheus metrics, --listen-debug serving pprof).  The stacks endpoint
is the Python analogue of a goroutine dump (the reference's integration
tests rely on exactly that for diagnosis).

Endpoints register into a table (path -> handler + description) so ``/``
serves a discoverable index and embedders can add their own via
``register()``.  This module owns only the layer-free builtins
(/metrics, /healthz, /debug/stacks); the obs package contributes
/debug/trace, /debug/health (503 while any SLO check is failing) and
/debug/flightrec through ``register_default_endpoints`` — utils sits
below obs in the layering matrix and must not import it.
"""

from __future__ import annotations

import http.server
import sys
import threading
import traceback
import urllib.parse
from typing import Callable, Dict, Optional, Tuple

from .metrics import registry

# handler(query: {k: [v, ...]}) -> (body bytes, status code, content type)
Handler = Callable[[Dict[str, list]], Tuple[bytes, int, str]]

# registered by higher layers (obs) at import time: each callback gets
# every newly constructed DebugServer and installs its endpoints, so the
# dependency points downward (obs -> utils) instead of utils importing
# the planes it serves
_default_endpoint_hooks: list = []


def register_default_endpoints(hook: Callable[["DebugServer"], None]
                               ) -> None:
    """Install ``hook(server)`` to run for every DebugServer built from
    now on (idempotent per hook object)."""
    if hook not in _default_endpoint_hooks:
        _default_endpoint_hooks.append(hook)


def _all_stacks() -> str:
    frames = sys._current_frames()
    out = []
    by_id = {t.ident: t for t in threading.enumerate()}
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else f"thread-{tid}"
        out.append(f"--- {name} ({tid}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class DebugServer:
    """Plain-HTTP observability endpoints (no TLS: bind to loopback or a
    protected interface, like the reference's --listen-metrics)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 health: Optional[Callable[[], str]] = None,
                 health_evaluator=None):
        self.health = health or (lambda: "SERVING")
        # the SLO evaluator behind /debug/health (served by the obs
        # endpoint hook); None means the obs singleton
        self._evaluator = health_evaluator
        #: path -> (description, handler); see register()
        self.endpoints: Dict[str, Tuple[str, Handler]] = {}
        self._register_builtins()
        for hook in list(_default_endpoint_hooks):
            hook(self)
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def do_GET(self):
                body, code, ctype = outer._dispatch(self.path)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- endpoints

    def register(self, path: str, handler: Handler,
                 description: str) -> None:
        """Add/replace an endpoint; it appears on the ``/`` index."""
        self.endpoints[path] = (description, handler)

    def _register_builtins(self) -> None:
        self.register("/metrics", self._h_metrics,
                      "Prometheus text exposition of the process registry")
        self.register("/healthz", self._h_healthz,
                      "liveness probe: SERVING (200) or NOT_SERVING (503)")
        self.register("/debug/stacks", self._h_stacks,
                      "stack dump of every live thread")

    def _dispatch(self, raw_path: str) -> Tuple[bytes, int, str]:
        parts = urllib.parse.urlsplit(raw_path)
        path = parts.path
        # keep blanks: "?enable=" must reach the handler (and 400)
        # rather than silently degrade to the no-query behavior
        query = urllib.parse.parse_qs(parts.query,
                                      keep_blank_values=True)
        if path in ("", "/"):
            return self._h_index(query)
        entry = self.endpoints.get(path)
        if entry is None:
            return b"not found\n", 404, "text/plain"
        try:
            return entry[1](query)
        except Exception as e:   # an endpoint must never kill the server
            return (f"endpoint error: {e!r}\n".encode(), 500,
                    "text/plain")

    # -------------------------------------------------------------- handlers

    def _h_index(self, query) -> Tuple[bytes, int, str]:
        width = max(len(p) for p in self.endpoints)
        lines = ["swarmkit-tpu debug endpoints:", ""]
        for path in sorted(self.endpoints):
            desc, _ = self.endpoints[path]
            lines.append(f"  {path:<{width}}  {desc}")
        return ("\n".join(lines) + "\n").encode(), 200, "text/plain"

    def _h_metrics(self, query) -> Tuple[bytes, int, str]:
        return (registry.expose().encode(), 200,
                "text/plain; version=0.0.4")

    def _h_healthz(self, query) -> Tuple[bytes, int, str]:
        status = self.health()
        return ((status + "\n").encode(),
                200 if status == "SERVING" else 503, "text/plain")

    def _h_stacks(self, query) -> Tuple[bytes, int, str]:
        return _all_stacks().encode(), 200, "text/plain"

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="debug-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
