"""Operator observability HTTP listener: /metrics, /healthz, and the
/debug/* family (stacks, trace, health, flightrec).

Reference: swarmd/cmd/swarmd/main.go:92-97 (--listen-metrics serving
Prometheus metrics, --listen-debug serving pprof).  The stacks endpoint
is the Python analogue of a goroutine dump (the reference's integration
tests rely on exactly that for diagnosis).

Endpoints register into a table (path -> handler + description) so ``/``
serves a discoverable index and embedders can add their own via
``register()``.  ``/debug/health`` returns 503 while any SLO check is
failing, so load balancers and probes can consume it without parsing.
"""

from __future__ import annotations

import http.server
import json
import sys
import threading
import traceback
import urllib.parse
from typing import Callable, Dict, Optional, Tuple

from .metrics import registry

# handler(query: {k: [v, ...]}) -> (body bytes, status code, content type)
Handler = Callable[[Dict[str, list]], Tuple[bytes, int, str]]


def _all_stacks() -> str:
    frames = sys._current_frames()
    out = []
    by_id = {t.ident: t for t in threading.enumerate()}
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else f"thread-{tid}"
        out.append(f"--- {name} ({tid}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class DebugServer:
    """Plain-HTTP observability endpoints (no TLS: bind to loopback or a
    protected interface, like the reference's --listen-metrics)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 health: Optional[Callable[[], str]] = None,
                 health_evaluator=None):
        self.health = health or (lambda: "SERVING")
        # the SLO evaluator behind /debug/health; defaults to the shared
        # obs.health singleton (late-bound so importing this module never
        # pulls the obs package in)
        self._evaluator = health_evaluator
        #: path -> (description, handler); see register()
        self.endpoints: Dict[str, Tuple[str, Handler]] = {}
        self._register_builtins()
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def do_GET(self):
                body, code, ctype = outer._dispatch(self.path)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- endpoints

    def register(self, path: str, handler: Handler,
                 description: str) -> None:
        """Add/replace an endpoint; it appears on the ``/`` index."""
        self.endpoints[path] = (description, handler)

    def _register_builtins(self) -> None:
        self.register("/metrics", self._h_metrics,
                      "Prometheus text exposition of the process registry")
        self.register("/healthz", self._h_healthz,
                      "liveness probe: SERVING (200) or NOT_SERVING (503)")
        self.register("/debug/stacks", self._h_stacks,
                      "stack dump of every live thread")
        self.register("/debug/trace", self._h_trace,
                      "Chrome trace-event JSON of the span tracer "
                      "(?enable=1/0 toggles recording)")
        self.register("/debug/health", self._h_health,
                      "SLO check report (JSON); 503 while any check "
                      "is failing")
        self.register("/debug/flightrec", self._h_flightrec,
                      "flight-recorder post-mortem dump (JSON): recent "
                      "spans, metric samples, store events, raft "
                      "transitions")

    def _dispatch(self, raw_path: str) -> Tuple[bytes, int, str]:
        parts = urllib.parse.urlsplit(raw_path)
        path = parts.path
        # keep blanks: "?enable=" must reach the handler (and 400)
        # rather than silently degrade to the no-query behavior
        query = urllib.parse.parse_qs(parts.query,
                                      keep_blank_values=True)
        if path in ("", "/"):
            return self._h_index(query)
        entry = self.endpoints.get(path)
        if entry is None:
            return b"not found\n", 404, "text/plain"
        try:
            return entry[1](query)
        except Exception as e:   # an endpoint must never kill the server
            return (f"endpoint error: {e!r}\n".encode(), 500,
                    "text/plain")

    # -------------------------------------------------------------- handlers

    def _h_index(self, query) -> Tuple[bytes, int, str]:
        width = max(len(p) for p in self.endpoints)
        lines = ["swarmkit-tpu debug endpoints:", ""]
        for path in sorted(self.endpoints):
            desc, _ = self.endpoints[path]
            lines.append(f"  {path:<{width}}  {desc}")
        return ("\n".join(lines) + "\n").encode(), 200, "text/plain"

    def _h_metrics(self, query) -> Tuple[bytes, int, str]:
        return (registry.expose().encode(), 200,
                "text/plain; version=0.0.4")

    def _h_healthz(self, query) -> Tuple[bytes, int, str]:
        status = self.health()
        return ((status + "\n").encode(),
                200 if status == "SERVING" else 503, "text/plain")

    def _h_stacks(self, query) -> Tuple[bytes, int, str]:
        return _all_stacks().encode(), 200, "text/plain"

    def _h_trace(self, query) -> Tuple[bytes, int, str]:
        from ..obs.trace import tracer
        enable = query.get("enable")
        if enable:
            value = enable[0].lower()
            if value in ("1", "true", "on", "yes"):
                tracer.reset()
                tracer.enable()
                return b"tracing enabled\n", 200, "text/plain"
            if value in ("0", "false", "off", "no"):
                tracer.disable()
                return b"tracing disabled\n", 200, "text/plain"
            return (f"bad enable value {value!r}; use 1/0\n".encode(),
                    400, "text/plain")
        return tracer.to_json().encode(), 200, "application/json"

    def _get_evaluator(self):
        if self._evaluator is None:
            from ..obs.health import evaluator
            self._evaluator = evaluator
        return self._evaluator

    def _h_health(self, query) -> Tuple[bytes, int, str]:
        ev = self._get_evaluator()
        report = ev.report()
        # probes consume the status code; humans the JSON body
        code = 503 if report["status"] == "fail" else 200
        body = json.dumps(report, sort_keys=True, indent=1).encode()
        return body, code, "application/json"

    def _h_flightrec(self, query) -> Tuple[bytes, int, str]:
        from ..obs.flightrec import flightrec
        return flightrec.dump_json().encode(), 200, "application/json"

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="debug-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
