"""Operator observability HTTP listener: /metrics, /healthz,
/debug/stacks, /debug/trace.

Reference: swarmd/cmd/swarmd/main.go:92-97 (--listen-metrics serving
Prometheus metrics, --listen-debug serving pprof).  The stacks endpoint
is the Python analogue of a goroutine dump (the reference's integration
tests rely on exactly that for diagnosis).
"""

from __future__ import annotations

import http.server
import sys
import threading
import traceback
from typing import Callable, Optional, Tuple

from .metrics import registry


def _all_stacks() -> str:
    frames = sys._current_frames()
    out = []
    by_id = {t.ident: t for t in threading.enumerate()}
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else f"thread-{tid}"
        out.append(f"--- {name} ({tid}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class DebugServer:
    """Plain-HTTP observability endpoints (no TLS: bind to loopback or a
    protected interface, like the reference's --listen-metrics)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 health: Optional[Callable[[], str]] = None):
        self.health = health or (lambda: "SERVING")
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    body = registry.expose().encode()
                    code, ctype = 200, "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    status = outer.health()
                    body = (status + "\n").encode()
                    code = 200 if status == "SERVING" else 503
                    ctype = "text/plain"
                elif self.path == "/debug/stacks":
                    body = _all_stacks().encode()
                    code, ctype = 200, "text/plain"
                elif self.path == "/debug/trace":
                    # Chrome trace-event JSON of the process tracer —
                    # load in chrome://tracing or ui.perfetto.dev.
                    # GET ?enable=1 / ?enable=0 toggles recording.
                    from ..obs.trace import tracer
                    body = tracer.to_json().encode()
                    code, ctype = 200, "application/json"
                elif self.path.startswith("/debug/trace?enable="):
                    from ..obs.trace import tracer
                    value = self.path.split("=", 1)[1].lower()
                    if value in ("1", "true", "on", "yes"):
                        tracer.reset()
                        tracer.enable()
                        body, code = b"tracing enabled\n", 200
                    elif value in ("0", "false", "off", "no"):
                        tracer.disable()
                        body, code = b"tracing disabled\n", 200
                    else:
                        body = (f"bad enable value {value!r}; use 1/0\n"
                                .encode())
                        code = 400
                    ctype = "text/plain"
                else:
                    body, code, ctype = b"not found\n", 404, "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="debug-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
