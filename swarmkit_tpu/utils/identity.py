"""Random identifiers (reference: identity/randomid.go).

IDs are 25-character base36 strings drawn from a cryptographic source, like
the reference's, so they sort uniformly and are URL-safe.

``set_id_source`` is the determinism seam (the identity analogue of
``models.types.set_time_source``): the simulator installs a seeded
counter so ids minted by components it drives — orchestrator task
creation above all — are a pure function of the scenario seed, keeping
event order (agents sort tasks by id) and flight-recorder dumps
byte-reproducible.  Production never installs a source.
"""

from typing import Callable, Optional

import secrets
import string

_ALPHABET = string.digits + string.ascii_lowercase
_ID_LEN = 25
# largest value representable in _ID_LEN base36 digits
_MAX = 36 ** _ID_LEN

# when set, new_id() delegates here (deterministic simulation)
_id_source: Optional[Callable[[], str]] = None


def set_id_source(source: Optional[Callable[[], str]]) -> None:
    """Install (or with None, remove) a deterministic id generator."""
    global _id_source
    _id_source = source


def new_id() -> str:
    if _id_source is not None:
        return _id_source()
    n = secrets.randbelow(_MAX)
    digits = []
    for _ in range(_ID_LEN):
        n, rem = divmod(n, 36)
        digits.append(_ALPHABET[rem])
    return "".join(reversed(digits))


def new_secret(nbytes: int = 16) -> str:
    return secrets.token_hex(nbytes)
