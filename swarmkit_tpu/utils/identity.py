"""Random identifiers (reference: identity/randomid.go).

IDs are 25-character base36 strings drawn from a cryptographic source, like
the reference's, so they sort uniformly and are URL-safe.
"""

import secrets
import string

_ALPHABET = string.digits + string.ascii_lowercase
_ID_LEN = 25
# largest value representable in _ID_LEN base36 digits
_MAX = 36 ** _ID_LEN


def new_id() -> str:
    n = secrets.randbelow(_MAX)
    digits = []
    for _ in range(_ID_LEN):
        n, rem = divmod(n, 36)
        digits.append(_ALPHABET[rem])
    return "".join(reversed(digits))


def new_secret(nbytes: int = 16) -> str:
    return secrets.token_hex(nbytes)
