"""Minimal metrics registry: counters, gauges, and latency timers with a
Prometheus-style text exposition.

Reference role: docker/go-metrics as used by the reference (store tx/lock
timers memory.go:84-112, dispatcher scheduling-delay timer
dispatcher.go:72-77, object-count collector manager/metrics/collector.go).
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

_QUANTILES = (0.5, 0.9, 0.99)


class Timer:
    """Latency accumulator with reservoir-free streaming quantiles
    (bounded ring of recent observations)."""

    def __init__(self, maxlen: int = 2048):
        self._lock = threading.Lock()
        self._buf: List[float] = []
        self._maxlen = maxlen
        self._i = 0
        self.count = 0
        self.total = 0.0

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            if len(self._buf) < self._maxlen:
                self._buf.append(seconds)
            else:
                self._buf[self._i % self._maxlen] = seconds
            self._i += 1

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                timer.observe(time.perf_counter() - self.t0)

        return _Ctx()

    def quantiles(self) -> Dict[float, float]:
        with self._lock:
            buf = sorted(self._buf)
        if not buf:
            return {q: 0.0 for q in _QUANTILES}
        # nearest-rank: the smallest value with at least q*n observations
        # at or below it.  The previous ``int(q*len)`` indexed one element
        # HIGH for exact multiples (p50 of 10 returned the 6th element)
        # while q*n just under len biased to max-1 — on small buffers the
        # reported p99 was systematically off by one rank.
        n = len(buf)
        return {q: buf[max(0, math.ceil(q * n) - 1)] for q in _QUANTILES}

    def reset(self) -> None:
        """Forget every observation (per-bench-config isolation)."""
        with self._lock:
            self._buf = []
            self._i = 0
            self.count = 0
            self.total = 0.0


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, Timer] = {}

    def counter(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += delta

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self.timers.get(name)
            if t is None:
                t = self.timers[name] = Timer()
            return t

    def get_counter(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self.counters.get(name, default)

    def get_gauge(self, name: str, default: Optional[float] = None
                  ) -> Optional[float]:
        """Point read of one gauge; default (None) distinguishes
        never-set from 0.0 — health checks treat no-data as pass."""
        with self._lock:
            return self.gauges.get(name, default)

    def counters_snapshot(self, prefix: str = "") -> Dict[str, float]:
        """Copy of the counter map (optionally prefix-filtered); bench
        diffs two snapshots to attribute counts to one timed region."""
        with self._lock:
            return {k: v for k, v in self.counters.items()
                    if k.startswith(prefix)}

    def gauges_snapshot(self, prefix: str = "") -> Dict[str, float]:
        with self._lock:
            return {k: v for k, v in self.gauges.items()
                    if k.startswith(prefix)}

    def timers_snapshot(self, prefix: str = "") -> Dict[str, Timer]:
        """Name -> live Timer references (the objects are stable across
        ``reset()``); consumers read .count/.total/.quantiles() without
        touching this registry's lock protocol."""
        with self._lock:
            return {k: t for k, t in self.timers.items()
                    if k.startswith(prefix)}

    def get_timer(self, name: str) -> Optional[Timer]:
        with self._lock:
            return self.timers.get(name)

    def reset(self) -> None:
        """Zero all counters/gauges and reset timers IN PLACE — components
        hold Timer references from ``timer(name)``, so the objects must
        survive a reset (per-bench-config isolation)."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            timers = list(self.timers.values())
        for t in timers:
            t.reset()

    def expose(self) -> str:
        """Prometheus-style text format."""
        lines: List[str] = []
        with self._lock:
            for name, v in sorted(self.counters.items()):
                if "{" in name:
                    # labeled counter: the _total suffix belongs on the
                    # metric NAME, before the label braces
                    base, labels = name.split("{", 1)
                    lines.append(f"{base}_total{{{labels} {v:g}")
                else:
                    lines.append(f"{name}_total {v:g}")
            for name, v in sorted(self.gauges.items()):
                lines.append(f"{name} {v:g}")
            timers = list(self.timers.items())
        for name, t in sorted(timers):
            if "{" in name:
                # labeled timer: merge the quantile label into the
                # existing label set, suffix on the metric name
                base, labels = name.split("{", 1)
                labels = labels[:-1]  # strip closing brace
                for q, v in t.quantiles().items():
                    lines.append(f'{base}_seconds{{{labels},'
                                 f'quantile="{q}"}} {v:.6f}')
                lines.append(f"{base}_seconds_count{{{labels}}} {t.count}")
                lines.append(f"{base}_seconds_sum{{{labels}}} "
                             f"{t.total:.6f}")
                continue
            for q, v in t.quantiles().items():
                lines.append(f'{name}_seconds{{quantile="{q}"}} {v:.6f}')
            lines.append(f"{name}_seconds_count {t.count}")
            lines.append(f"{name}_seconds_sum {t.total:.6f}")
        return "\n".join(lines) + "\n"


registry = Registry()
