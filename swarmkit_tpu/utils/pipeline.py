"""Pipeline-depth configuration for the plan/commit software pipeline.

One knob governs every stage of the pipelined scheduler (see
docs/architecture.md "Pipelined scheduling"): the scheduler's bound on
in-flight stages per tick (one dispatched device plan + up to depth-1
unacked group commits) and the store's window of raft block-chunk
proposals in flight at once.

``SWARM_PIPELINE_DEPTH=1`` is the escape hatch: every consumer reverts
to the strictly serial plan -> commit ordering (bit-for-bit the
pre-pipeline behavior).  Values below 1 clamp to 1; unparseable values
fall back to the default.
"""

from __future__ import annotations

import os

DEFAULT_PIPELINE_DEPTH = 2
ENV_VAR = "SWARM_PIPELINE_DEPTH"


def default_pipeline_depth() -> int:
    """The process-wide pipeline depth: ``SWARM_PIPELINE_DEPTH`` when
    set and parseable, else 2.  Read at component construction time, so
    tests can override per instance without touching the environment."""
    raw = os.environ.get(ENV_VAR)
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return DEFAULT_PIPELINE_DEPTH
