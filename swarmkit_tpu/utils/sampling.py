"""Seeded distribution samplers shared by the sim and the bench.

One definition so the deterministic scenarios and the churn bench draw
from the same distribution — a numerical tweak applied to one can never
silently diverge the other.
"""

from __future__ import annotations

import math


def poisson(rng, lam: float) -> int:
    """Knuth's inversion sampler off an injected ``random.Random`` —
    deterministic per seed, no numpy draw-order coupling."""
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1
