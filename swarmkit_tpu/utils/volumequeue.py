"""Retry queue with exponential backoff.

Reference: volumequeue/volumequeue.go — a queue of IDs that pops items
only when their retry deadline passes, doubling the wait on each re-enqueue
up to a cap.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, Optional, Tuple

BASE_RETRY_INTERVAL = 0.1    # reference: volumequeue.go baseRetryInterval
MAX_RETRY_INTERVAL = 600.0   # reference: maxRetryInterval


class VolumeQueue:
    def __init__(self, clock=None) -> None:
        # injectable monotonic time source (deterministic simulation seam);
        # wait() still blocks on the condition using real timeouts, but all
        # deadline arithmetic goes through the clock
        self._clock = clock or time.monotonic
        self._cond = threading.Condition()
        self._heap: list = []            # (ready_at, seq, id)
        self._attempts: Dict[str, int] = {}
        self._pending: Dict[str, float] = {}  # id -> ready_at (dedupe)
        self._seq = 0
        self._closed = False

    def enqueue(self, id: str, retry: bool = False) -> None:
        """Queue an id.  ``retry=False`` (new work) is immediate and does
        not grow the backoff; ``retry=True`` (the operation failed) delays
        by the id's exponential backoff and bumps it — mirroring the
        reference's explicit retry counts (volumequeue.go Enqueue)."""
        with self._cond:
            if retry:
                attempts = self._attempts.get(id, 0) + 1
                self._attempts[id] = attempts
                delay = min(BASE_RETRY_INTERVAL * (2 ** (attempts - 1)),
                            MAX_RETRY_INTERVAL)
            else:
                delay = 0.0
            ready = self._clock() + delay
            if id in self._pending and self._pending[id] <= ready:
                return  # already queued sooner
            self._pending[id] = ready
            self._seq += 1
            heapq.heappush(self._heap, (ready, self._seq, id))
            self._cond.notify()

    def forget(self, id: str) -> None:
        """The operation succeeded: reset backoff state."""
        with self._cond:
            self._attempts.pop(id, None)
            self._pending.pop(id, None)

    def wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Pop the next due id, blocking until one is due (or timeout)."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                if self._closed:
                    return None
                now = self._clock()
                while self._heap and self._heap[0][0] <= now:
                    ready, _, id = heapq.heappop(self._heap)
                    # deliver only the entry matching the CURRENT deadline:
                    # superseded entries (e.g. pre-backoff ones) are stale
                    # and must not fire a retry early
                    if self._pending.get(id) == ready:
                        self._pending.pop(id, None)
                        return id
                if self._heap:
                    wait_for = self._heap[0][0] - now
                else:
                    wait_for = None
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait_for = remaining if wait_for is None \
                        else min(wait_for, remaining)
                self._cond.wait(wait_for)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
