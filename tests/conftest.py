"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so sharding tests (shard_map over
the node axis) run without TPU hardware.

The axon sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon already in the environment, so mutating os.environ here is
too late for jax's config defaults — use jax.config.update instead (backend
initialization is lazy, so this still takes effect as long as no test
touched a device before conftest import, which pytest guarantees).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# Daemon-level tests drive real multi-process-style clusters (threads, TCP,
# heartbeat TTLs, raft elections) on whatever CPU the runner gives us; under
# heavy load a timing assumption can miss once even though the behavior is
# correct (each of these passes consistently in isolation).  Mirror the
# reference CI's flaky-retry pragma: rerun a FAILED test from the known
# timing-sensitive daemon files once before declaring failure.  Genuine
# regressions still fail — twice in a row.

_TIMING_SENSITIVE_FILES = {"test_remotes_swarmd.py", "test_integration.py",
                           "test_ca_rotation.py", "test_external_ca.py",
                           # real threaded elections on a loaded 1-core
                           # runner: a leadership blip mid-test fails a
                           # proposal (by design — epoch fencing rejects
                           # flap-window proposals); correct on retry
                           "test_raft.py"}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: wide sweeps excluded from the tier-1 run (-m 'not slow')")


def pytest_runtest_protocol(item, nextitem):
    from _pytest.runner import runtestprotocol

    if item.fspath.basename not in _TIMING_SENSITIVE_FILES:
        return None
    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        import warnings
        warnings.warn(f"retrying timing-sensitive test {item.nodeid} "
                      "after a failure under load")
        # one retry, freshly set-up; only its outcome is reported
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for r in reports:
        item.ihook.pytest_runtest_logreport(report=r)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True
