"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so sharding tests (shard_map over
the node axis) run without TPU hardware.

The axon sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon already in the environment, so mutating os.environ here is
too late for jax's config defaults — use jax.config.update instead (backend
initialization is lazy, so this still takes effect as long as no test
touched a device before conftest import, which pytest guarantees).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
