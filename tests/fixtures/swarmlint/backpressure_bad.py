"""Fixture: unbounded intake on dispatcher/scheduler hot paths (fires).

Linted AS IF at swarmkit_tpu/manager/fixture.py — every class below
grows an agent-sized container on a session-gated RPC edge or a named
intake edge with no admission knob and no counted fallback.
"""

import heapq
from collections import deque


class Dispatcher:
    def __init__(self):
        self._updates = []            # one entry per agent report
        self._intake = deque()        # no maxlen: agents size it
        self._wheel = []              # deadline heap
        self._backlog = []

    def update_task_status(self, node_id, session_id, updates):
        # RPC edge: whatever the fleet sends, we keep (fires)
        for u in updates:
            self._updates.append(u)

    def heartbeat(self, node_id, session_id):
        # every heartbeat leaves a permanent residue (fires)
        self._intake.appendleft((node_id, session_id))

    def register(self, node_id, description):
        # admission without admission control (fires, heappush form)
        heapq.heappush(self._wheel, (0.0, node_id))


class Scheduler:
    def __init__(self):
        self._queue = deque()

    def _enqueue(self, tasks):
        # scheduler intake edge, batch form (fires)
        self._queue.extend(tasks)
