"""Fixture: the same hot paths with backpressure discipline (clean).

Each growth site from the bad twin, fixed the sanctioned way: an
admission check against a ``max_*`` knob with a counted shed, a
``maxlen`` deque (self-bounding), an evict pass in the same method, or
the growth moved off the hot path entirely.
"""

import heapq
from collections import deque


class Dispatcher:
    def __init__(self, config):
        self.config = config
        self._updates = []
        self._intake = deque(maxlen=1024)   # self-bounding: exempt
        self._wheel = []
        self._sheds = 0

    def update_task_status(self, node_id, session_id, updates):
        # admission check against the declared bound, counted shed
        if len(self._updates) + len(updates) > self.config.max_pending_updates:
            self._sheds += len(updates)
            raise OverflowError("overloaded: shed counted")
        for u in updates:
            self._updates.append(u)

    def heartbeat(self, node_id, session_id):
        # maxlen deque: the container bounds itself
        self._intake.appendleft((node_id, session_id))

    def register(self, node_id, description):
        # evict the expired tail before admitting a new deadline
        evicted = 0
        while self._wheel and self._wheel[0][0] < 0:
            heapq.heappop(self._wheel)
            evicted += 1
        heapq.heappush(self._wheel, (0.0, node_id))


class Scheduler:
    def __init__(self, config):
        self.config = config
        self._queue = deque()

    def _enqueue(self, tasks):
        # partial admission up to the tick budget; remainder deferred
        room = self.config.max_queue_depth - len(self._queue)
        self._queue.extend(tasks[:room])
        return tasks[room:]
