"""Fixture: every determinism-seam bypass in one module (must fire)."""
import os
import random
import time
import uuid


def deadline(timeout):
    return time.time() + timeout          # bare wall clock


def wait_until(timeout):
    return time.monotonic() + timeout     # bare monotonic


def mint_id():
    return uuid.uuid4().hex               # unseamed id


def token():
    return os.urandom(16).hex()           # unseamed entropy


def make_rng():
    return random.Random()                # unseeded, not a seam default


def draw():
    return random.random()                # global unseeded RNG


class ThreadedSupervisor:
    """The rolling-update regression shape (ISSUE 8): a per-service
    worker thread pacing its monitor window off the bare wall clock —
    under the sim's virtual time the window never elapses (or elapses
    instantly), so the FSM is untestable and nondeterministic."""

    monitor = 30.0

    def run(self, slots):
        deadline = time.time() + self.monitor   # bare wall clock
        while slots and time.time() < deadline:  # and again in the loop
            slots.pop()


def load_scorer_weights(path=None):
    """The learned-scorer weight-loading shape (ISSUE 15): a missing
    artifact silently random-inits the policy — placements stop being
    reproducible AND host/device parity is gone."""
    import numpy as np
    if path is None:
        w1 = np.random.default_rng().normal(size=(6, 8))  # unseeded gen
        b1 = np.random.rand(8)                  # numpy global RNG draw
        return w1, b1
    return None
