"""Fixture: the corrected twin — everything flows through the seams."""
import random
import time

from swarmkit_tpu.models.types import now
from swarmkit_tpu.utils import identity


def deadline(timeout):
    return now() + timeout                # the time seam


def mint_id():
    return identity.new_id()              # the id seam


def token():
    return identity.new_secret()          # the entropy seam


class Worker:
    def __init__(self, rng=None, clock=None):
        # the sanctioned constructor-default idiom for injected seams
        self._rng = rng or random.Random()
        self._clock = clock or time.monotonic   # reference, not a call

    def draw(self):
        return self._rng.random()

    def measure(self):
        return time.perf_counter()        # duration measurement: allowed


class DrivenSupervisor:
    """The corrected twin of the threaded-supervisor shape: deadlines
    read the ``now()`` seam and the FSM is pumped by ``drive()`` —
    production wraps it in a thread, the simulator calls it directly
    under virtual time (orchestrator/update.py's design)."""

    monitor = 30.0

    def begin(self, slots):
        self._slots = list(slots)
        self._deadline = now() + self.monitor   # the time seam

    def drive(self):
        if self._slots and now() < self._deadline:
            self._slots.pop()


def load_scorer_weights(path):
    """Learned-scorer weights load ONLY from the checked-in artifact —
    deterministic, and a missing file is an error, not a random init."""
    import json

    with open(path) as f:
        return json.load(f)


def synthesize_trace(seed):
    # offline tooling may draw noise — through an explicitly seeded
    # generator, never the global RNG
    import numpy as np

    return np.random.default_rng(seed).normal(size=8)
