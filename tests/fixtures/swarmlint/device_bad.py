"""Fixture: host syncs inside jitted plan fns (must fire).

The test harness lints this file as ``swarmkit_tpu/ops/fixture.py``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def plan(scores, k):
    best = scores.argmax()
    worst = float(scores.min())            # implicit D2H sync
    return np.take(scores, best), worst    # numpy falls back to host


@functools.partial(jax.jit, static_argnames=("L",))
def plan_hier(scores, L):
    jax.debug.print("scores {s}", s=scores)   # debug in the hot path
    return _accumulate(scores)


def _accumulate(scores):
    # reached from plan_hier: device code by closure
    return scores.sum().item()             # D2H sync in a helper


@functools.partial(jax.jit, static_argnames=("L",))
def plan_fused(shared, groups, carry, L):
    # fused many-service program: the scan step is device code too
    def step(state, g):
        cap = np.minimum(state, g)          # numpy inside the scan step
        spill = state.sum().item()          # D2H sync in the carry math
        return state - g, (cap, spill)

    out, ys = jax.lax.scan(step, carry, groups)
    jax.device_get(out)                     # carry fetched mid-program
    return ys


@jax.jit
def plan_fused_sharded(x):
    from jax.experimental.shard_map import shard_map

    def kernel(xl):
        xl.block_until_ready()              # sync inside the mesh kernel
        return xl.sum()

    return shard_map(kernel, mesh=None, in_specs=None, out_specs=None)(x)


@functools.partial(jax.jit, static_argnames=("picks",))
def select_victims(vprio, vcpu, demand, budget, picks):
    # preemption victim kernel: the pick scan is device code too
    def pick(state, _):
        cost = np.cumsum(vcpu)              # numpy in the pick step
        best = int(cost.argmin())           # int() on a traced value
        return state - best, best

    out, chosen = jax.lax.scan(pick, budget, None, length=picks)
    return jax.device_get(chosen)           # picks fetched mid-program


def _update_rows(cpu, idx, vals):
    host = np.asarray(cpu)     # host read of a resident array mid-program
    return cpu.at[idx].set(vals), host


# resident-state update program: donated buffers update in place
update_resident = jax.jit(_update_rows, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def scatter_pair(cpu, mem, idx, vals):
    return cpu.at[idx].set(vals), mem.at[idx].set(vals)


def drive_streaming(cpu, mem, idx, vals):
    # host driver around the donated update program
    new_cpu, _host = update_resident(cpu, idx, vals)
    stale = cpu.sum()          # reusing a donated buffer after dispatch
    cpu2, mem2 = scatter_pair(new_cpu, mem, idx, vals)
    total = mem.sum()          # the second donated buffer, same bug
    return cpu2, mem2, stale + total


def stage_gang_inputs(batch):
    # host driver: H2D staging the device ledger never sees — the
    # bench transfer gates cannot gate on invisible bytes
    staged = [np.asarray(b) for b in batch]
    return [jax.device_put(s) for s in staged]


def drain_results(handles):
    # host driver: fetch syncs with no device-ledger accounting
    for h in handles:
        h.block_until_ready()               # unaccounted fetch sync
    return [np.asarray(h) for h in handles]


def drive_sharded_chunks(shared, groups, carry, L):
    # host driver of the sharded fused pipeline (ISSUE 19); the ledger
    # call keeps the unaccounted-transfer shapes quiet so only the
    # cross-shard shapes fire here
    from swarmkit_tpu.obs import devicetelemetry
    devicetelemetry.note_h2d("fused_inputs", 0)
    for g in groups:
        carry = jax.device_get(carry)       # mid-chunk D2H of the carry
        _, carry = plan_fused(shared, g, carry, L)
    resident = jax.device_put(carry)
    again = jax.device_put(resident)        # re-put of a resident array
    return carry, again


@functools.partial(jax.jit, static_argnames=("strategy",))
def plan_strategy(caps, scores, weights, strategy):
    # pluggable scoring stage (ISSUE 15): the strategy kernel is device
    # code like any other plan fn — host sorts and D2H casts poison it
    order = np.argsort(scores)             # numpy sort in the score stage
    worst = float(scores.max())            # D2H cast on a traced score
    return caps[order], worst
