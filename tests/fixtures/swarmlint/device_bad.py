"""Fixture: host syncs inside jitted plan fns (must fire).

The test harness lints this file as ``swarmkit_tpu/ops/fixture.py``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def plan(scores, k):
    best = scores.argmax()
    worst = float(scores.min())            # implicit D2H sync
    return np.take(scores, best), worst    # numpy falls back to host


@functools.partial(jax.jit, static_argnames=("L",))
def plan_hier(scores, L):
    jax.debug.print("scores {s}", s=scores)   # debug in the hot path
    return _accumulate(scores)


def _accumulate(scores):
    # reached from plan_hier: device code by closure
    return scores.sum().item()             # D2H sync in a helper
