"""Fixture: the corrected twin — pure device code, syncs in the driver.

The test harness lints this file as ``swarmkit_tpu/ops/fixture.py``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

LOAD_CLAMP = 1 << 20


@jax.jit
def plan(scores, k):
    best = scores.argmax()
    worst = scores.min().astype(jnp.float32)     # stays on device
    clamped = jnp.minimum(scores, float(LOAD_CLAMP))  # static constant
    return jnp.take(clamped, best), worst


@functools.partial(jax.jit, static_argnames=("L",))
def plan_hier(scores, L):
    return _accumulate(scores)


def _accumulate(scores):
    return scores.sum()                          # still a device value


def fetch(arrays):
    # host driver (not jitted): explicit D2H is its job
    return jax.device_get(arrays)


def pad_inputs(a, width):
    # host driver: numpy padding before device placement is fine
    return np.pad(np.asarray(a), (0, width))
