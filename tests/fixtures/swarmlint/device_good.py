"""Fixture: the corrected twin — pure device code, syncs in the driver.

The test harness lints this file as ``swarmkit_tpu/ops/fixture.py``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from swarmkit_tpu.obs import devicetelemetry

LOAD_CLAMP = 1 << 20


@jax.jit
def plan(scores, k):
    best = scores.argmax()
    worst = scores.min().astype(jnp.float32)     # stays on device
    clamped = jnp.minimum(scores, float(LOAD_CLAMP))  # static constant
    return jnp.take(clamped, best), worst


@functools.partial(jax.jit, static_argnames=("L",))
def plan_hier(scores, L):
    return _accumulate(scores)


def _accumulate(scores):
    return scores.sum()                          # still a device value


def fetch(arrays):
    # host driver (not jitted): explicit D2H is its job
    return jax.device_get(arrays)


def pad_inputs(a, width):
    # host driver: numpy padding before device placement is fine
    return np.pad(np.asarray(a), (0, width))


@functools.partial(jax.jit, static_argnames=("L",))
def plan_fused(shared, groups, carry, L):
    # fused many-service program: the scan carry stays device-resident
    def step(state, g):
        cap = jnp.minimum(state, g)
        spill = state.sum() > jnp.zeros((), jnp.float32)
        return state - g, (cap, spill)

    carry_out, ys = jax.lax.scan(step, carry, groups)
    return ys, carry_out                    # caller keeps it on device


@jax.jit
def plan_fused_sharded(x):
    from jax.experimental.shard_map import shard_map

    def kernel(xl):
        # cross-shard reduction, not a host sync
        return jax.lax.psum(xl.sum(), "nodes")

    return shard_map(kernel, mesh=None, in_specs=None, out_specs=None)(x)


def dispatch_chunks(run, chunks):
    # host driver: np staging + device placement happen OUTSIDE jit,
    # and the staged bytes report into the device ledger
    staged = [np.asarray(c) for c in chunks]
    devicetelemetry.note_h2d("fused_inputs",
                             sum(int(s.nbytes) for s in staged))
    return [jax.device_put(s) for s in staged]


def fetch_ready(handles):
    # host driver: the sync is accounted before the fetch returns
    for h in handles:
        h.block_until_ready()
    devicetelemetry.note_d2h("fetch",
                             sum(int(h.nbytes) for h in handles))
    return [np.asarray(h) for h in handles]


@functools.partial(jax.jit, static_argnames=("picks",))
def select_victims(vprio, vcpu, demand, budget, picks):
    # preemption victim kernel: prefix sums + argmin stay on device; the
    # caller (host driver) fetches the finished pick arrays
    def pick(state, _):
        cost = jnp.cumsum(vcpu)
        best = jnp.argmin(cost).astype(jnp.int32)
        return state - best, best

    out, chosen = jax.lax.scan(pick, budget, None, length=picks)
    return chosen


def _update_rows(cpu, idx, vals):
    # resident-state update: pure device math, values stay on device
    return cpu.at[idx].set(vals)


update_resident = jax.jit(_update_rows, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def scatter_pair(cpu, mem, idx, vals):
    return cpu.at[idx].set(vals), mem.at[idx].set(vals)


def drive_streaming(cpu, mem, idx, vals):
    # host driver: every donated buffer is REBOUND from the call's
    # result before any further read — the old buffer is never consumed
    cpu = update_resident(cpu, idx, vals)
    cpu, mem = scatter_pair(cpu, mem, idx, vals)
    return cpu.sum() + mem.sum(), cpu, mem


def drive_sharded_chunks(shared, groups, carry, L):
    # host driver of the sharded fused pipeline (ISSUE 19): the carry
    # stays device-resident ACROSS chunk dispatches and is fetched
    # once, after the last; staging happens once — the resident handle
    # is reused, never re-put
    resident = jax.device_put(carry)
    devicetelemetry.note_h2d("fused_inputs", int(carry.nbytes))
    ys = []
    for g in groups:
        y, resident = plan_fused(shared, g, resident, L)
        ys.append(y)
    return ys, jax.device_get(resident)


@functools.partial(jax.jit, static_argnames=("strategy",))
def plan_strategy(caps, scores, weights, strategy):
    # pluggable scoring stage (ISSUE 15): sorts, shifts and the MLP
    # contraction stay on device; the host driver fetches the finished
    # placements in one round-trip
    order = jnp.argsort(scores)
    packed = jnp.right_shift(scores, 7)
    return caps[order], jnp.max(packed)
