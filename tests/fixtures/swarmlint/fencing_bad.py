"""Fixture: unfenced proposals and a fencing-blind proposer (must fire)."""


class Committer:
    def flush(self, store, tasks):
        # leader-path bulk commit without an epoch pin
        return store.bulk_update_tasks(tasks, on_missing=None)

    def commit_block(self, store, olds, nids, state, msg):
        return store.commit_task_block(olds, nids, state, msg)

    def propose(self, proposer, actions, cb, epoch=None):
        # async proposal that drops the epoch on the floor
        return proposer.propose_async(actions, cb)


class BlindProposer:
    def propose_async(self, actions, commit_cb=None):
        """No epoch parameter: cannot participate in fencing."""
        raise NotImplementedError
