"""Fixture: the corrected twin — every proposal threads its epoch."""


class Committer:
    def flush(self, store, tasks, epoch):
        return store.bulk_update_tasks(tasks, on_missing=None,
                                       epoch=epoch)

    def commit_block(self, store, olds, nids, state, msg, epoch):
        return store.commit_task_block(olds, nids, state, msg,
                                       epoch=epoch)

    def propose(self, proposer, actions, cb, epoch):
        return proposer.propose_async(actions, cb, epoch=epoch)

    def forward(self, proposer, *args, **kwargs):
        # **kwargs forwarding threads whatever the caller pinned
        return proposer.propose_async(*args, **kwargs)


class FencedProposer:
    def propose_async(self, actions, commit_cb=None, epoch=None):
        raise NotImplementedError
