"""Fixture: device-path module reaching into forbidden layers (fires).

The test harness lints this file as ``swarmkit_tpu/ops/fixture.py``.
"""

import swarmkit_tpu.state.store                      # ops -> state
from swarmkit_tpu.manager.dispatcher import Dispatcher   # ops -> manager
from swarmkit_tpu.sim import run_scenario            # production -> sim

from ..orchestrator import common                    # ops -> orchestrator
