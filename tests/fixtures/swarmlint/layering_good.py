"""Fixture: the corrected twin — device path sees only what it may.

The test harness lints this file as ``swarmkit_tpu/ops/fixture.py``.
"""

import jax.numpy as jnp                              # third-party: free

from swarmkit_tpu.models.types import TaskState      # ops -> models
from swarmkit_tpu.utils.metrics import registry      # ops -> utils
from swarmkit_tpu.scheduler.nodeinfo import NodeInfo  # ops -> scheduler
from swarmkit_tpu.obs.trace import tracer            # ops -> obs

from . import hashing                                # within the package
