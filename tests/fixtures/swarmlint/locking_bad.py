"""Fixture: lock-order cycle + blocking work under store locks (fires)."""


class MemoryStore:
    def forward_order(self):
        with self._update_lock:
            with self._lock:
                self.apply()

    def reverse_order(self):
        # opposite nesting of forward_order: a lock-order cycle
        with self._lock:
            with self._update_lock:
                self.apply()

    def read_then_wait(self, proposer, waiter):
        with self._lock:
            proposer.wait_proposal(waiter)   # consensus under view lock

    def commit_with_fetch(self, planner, handle):
        with self._update_lock:
            planner.fetch_group(handle)      # D2H under the writer lock

    def serve_linearizable_locked(self, proposer):
        with self._lock:
            proposer.read_barrier()          # barrier wait under view lock

    def publish_block_expanded(self, hp, block, status, event_cls):
        # GIL-released native fan-out under the WRITER lock: the watch
        # synthesis belongs on consumer threads, never the commit path
        with self._update_lock:
            hp.fanout_expand(block.olds, block.node_ids,
                             block.base_version, block.ts, status,
                             event_cls)
