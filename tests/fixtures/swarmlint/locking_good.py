"""Fixture: the corrected twin — one lock order, blocking work outside."""


class MemoryStore:
    def forward_order(self):
        with self._update_lock:
            with self._lock:
                self.apply()

    def same_order_elsewhere(self):
        with self._update_lock:
            with self._lock:
                self.snapshot()

    def read_then_wait(self, proposer, waiter):
        with self._lock:
            snapshot = self.snapshot()
        proposer.wait_proposal(waiter)       # after release
        return snapshot

    def fetch_then_commit(self, planner, handle):
        out = planner.fetch_group(handle)    # D2H before taking locks
        with self._update_lock:
            with self._lock:
                self.apply(out)

    def propose_under_update_lock(self, proposer, actions, cb, epoch):
        # consensus under the WRITER lock is the sanctioned commit
        # path (writers serialize through consensus by design)
        with self._update_lock:
            proposer.propose(actions, cb, epoch=epoch)

    def serve_linearizable(self, proposer, cb):
        # read barrier FIRST, lock-free; the view takes the lock only
        # per method call afterwards (read_view's sanctioned shape)
        proposer.read_barrier()
        with self._lock:
            return self.snapshot()

    def publish_block(self, block):
        # the commit path publishes the COALESCED block under the lock
        # (O(subscribers) buffering); native fan-out expansion runs on
        # the consumer's thread, after release
        with self._update_lock:
            self.queue.publish(block)
        return block.expand_events()
