"""Fixture: metric names violating the exposition grammar (must fire)."""

from swarmkit_tpu.utils.metrics import registry


def record(route):
    registry.counter("swarm_Tick-Seconds")           # bad characters
    registry.counter('swarm_planner_groups{route="a",mode="b"}')  # unsorted
    registry.gauge(
        f'swarm_health{{check="{route}",check="{route}"}}', 1.0)  # duplicate
    registry.timer('swarm_store_lock{Holder="x"}')   # uppercase label key


def record_per_entity(task, node, session):
    # metric-cardinality shapes: one series per task/node/session id
    # grows with the cluster, not the code — must fire
    registry.counter(f'swarm_task_restarts{{task="{task.id}"}}')
    registry.gauge(f'swarm_node_load{{node_id="{node.id}"}}', 1.0)
    registry.counter(
        f'swarm_dispatcher_acks{{session="{session.id}"}}')
