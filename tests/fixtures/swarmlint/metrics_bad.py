"""Fixture: metric names violating the exposition grammar (must fire)."""

from swarmkit_tpu.utils.metrics import registry


def record(route):
    registry.counter("swarm_Tick-Seconds")           # bad characters
    registry.counter('swarm_planner_groups{route="a",mode="b"}')  # unsorted
    registry.gauge(
        f'swarm_health{{check="{route}",check="{route}"}}', 1.0)  # duplicate
    registry.timer('swarm_store_lock{Holder="x"}')   # uppercase label key
