"""Fixture: the corrected twin — grammar-clean metric call sites."""

from swarmkit_tpu.utils.metrics import registry


def record(route, bucket):
    registry.counter("swarm_scheduler_ticks")
    registry.counter(f'swarm_planner_groups{{mode="b",route="{route}"}}')
    registry.gauge(f'swarm_planner_compiles{{bucket="{bucket}"}}', 1.0)
    registry.timer("swarm_store_lock_hold_seconds")
