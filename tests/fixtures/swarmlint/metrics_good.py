"""Fixture: the corrected twin — grammar-clean metric call sites."""

from swarmkit_tpu.utils.metrics import registry


def record(route, bucket):
    registry.counter("swarm_scheduler_ticks")
    registry.counter(f'swarm_planner_groups{{mode="b",route="{route}"}}')
    registry.gauge(f'swarm_planner_compiles{{bucket="{bucket}"}}', 1.0)
    registry.timer("swarm_store_lock_hold_seconds")


def record_bounded(task, node, svc, tenant):
    # the bounded twins of the per-entity shapes: aggregate over
    # entities, label by operator-facing domains only
    registry.counter("swarm_task_restarts")
    registry.gauge('swarm_plane_occupancy{plane="dispatcher"}', 1.0)
    registry.counter(f'swarm_dispatcher_acks{{service="{svc.id}"}}')
    registry.gauge(f'swarm_tenant_usage{{tenant="{tenant}"}}', 1.0)
