"""Autoscaler + multi-tenant QoS (ISSUE 12): the AutoscaleSupervisor's
policy machinery (hysteresis, rate limits, flap breaker, replicated
resume state), the tenant quota plane (admission clamp + the device
quota-mask column, byte-identical to the host oracle), the tenant-storm
scenario under its four new invariants — each proven LIVE by a
checker-sensitivity test — the batched dispatcher fan-out, the
autoscale_flapping health check, and the chaos-sweep wiring.
"""

import json
import os
import subprocess
import sys

import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    ReplicatedService, Resources, ResourceRequirements, Service,
    ServiceMode, ServiceSpec, Task, TaskSpec, TaskState, TaskStatus,
    Version,
)
from swarmkit_tpu.models import types as mtypes
from swarmkit_tpu.models.objects import Cluster
from swarmkit_tpu.models.specs import AutoscaleConfig, ClusterSpec
from swarmkit_tpu.models.types import TenantQuota, now
from swarmkit_tpu.orchestrator.autoscaler import (
    Supervisor as AutoscaleSupervisor,
)
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.scheduler.quota import TENANT_LABEL, TenantLedger
from swarmkit_tpu.sim.cluster import Sim
from swarmkit_tpu.sim.faults import NetConfig
from swarmkit_tpu.sim.scenario import run_scenario
from swarmkit_tpu.state.store import MemoryStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import chaos_sweep  # noqa: E402

CPU = 2 * 10 ** 9
GB = 1 << 30


@pytest.fixture(autouse=True)
def _restore_autoscale_health_gauges():
    """The flap/out-of-bounds sensitivity tests deliberately drive the
    process-global registry's autoscale gauges into warn/fail states;
    park them back at 0 so every later health assertion in the process
    (e.g. the bench smoke's all-pass verdict) judges its own run — the
    swarm_stale_reads discipline from the follower-reads tests."""
    yield
    from swarmkit_tpu.utils.metrics import registry
    for prefix in ('swarm_autoscale_flapping{service="',
                   'swarm_autoscale_out_of_bounds{service="'):
        for name, v in registry.gauges_snapshot(prefix).items():
            if v:
                registry.gauge(name, 0.0)


# ---------------------------------------------------------------------------
# supervisor policy unit tests (fake clock through the models.types seam)
# ---------------------------------------------------------------------------

def _mk_autoscaled_store(replicas=2, tenant="", **cfg_kwargs):
    store = MemoryStore()
    cfg = AutoscaleConfig(**cfg_kwargs)
    labels = {TENANT_LABEL: tenant} if tenant else {}

    def mk(tx):
        tx.create(Service(
            id="svc-a",
            spec=ServiceSpec(
                annotations=Annotations(name="svc-a", labels=labels),
                mode=ServiceMode.REPLICATED,
                replicated=ReplicatedService(replicas=replicas),
                task=TaskSpec(),
                autoscale=cfg),
            spec_version=Version(index=1)))
    store.update(mk)
    return store


def _replicas(store, sid="svc-a"):
    return store.view(lambda tx: tx.get(Service, sid)) \
        .spec.replicated.replicas


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_supervisor_scales_up_bounded_and_rate_limited():
    clock = _Clock()
    mtypes.set_time_source(clock)
    try:
        store = _mk_autoscaled_store(
            replicas=2, min_replicas=2, max_replicas=10,
            target_utilization=1.0, scale_up_step=3,
            stabilization_window=5.0)
        load = {"v": 40.0}
        sup = AutoscaleSupervisor(
            store, sampler=lambda sid: {"load": load["v"]},
            start_worker=False)
        sup.drive()
        assert _replicas(store) == 5          # one step, not the ideal
        sup.drive()
        assert _replicas(store) == 5          # rate-limited
        assert sup.stats["rate_limited"] >= 1
        clock.t += 6.0
        sup.drive()
        assert _replicas(store) == 8
        clock.t += 6.0
        sup.drive()
        clock.t += 6.0
        sup.drive()
        assert _replicas(store) == 10         # clamped at max
        # load removed: walks back down inside bounds
        load["v"] = 0.0
        for _ in range(8):
            clock.t += 6.0
            sup.drive()
        assert _replicas(store) == 2
        svc = store.view(lambda tx: tx.get(Service, "svc-a"))
        assert svc.autoscale_status is not None
        assert svc.autoscale_status.last_decision_at > 0
    finally:
        mtypes.set_time_source(None)


def test_supervisor_hysteresis_deadband_holds():
    clock = _Clock()
    mtypes.set_time_source(clock)
    try:
        store = _mk_autoscaled_store(
            replicas=4, min_replicas=1, max_replicas=10,
            target_utilization=1.0, hysteresis=0.2,
            stabilization_window=1.0)
        # util = 4.4/4 = 1.1 < 1.2: inside the deadband, no decision
        sup = AutoscaleSupervisor(
            store, sampler=lambda sid: {"load": 4.4},
            start_worker=False)
        for _ in range(5):
            clock.t += 2.0
            sup.drive()
        assert _replicas(store) == 4
        assert sup.stats["decisions"] == 0
    finally:
        mtypes.set_time_source(None)


def test_supervisor_flap_breaker_freezes_policy():
    """An oscillating signal reverses direction every window: after
    flap_reversals reversals the policy freezes (no further writes) and
    exports the flapping gauge the health check warns on."""
    from swarmkit_tpu.utils.metrics import registry as reg
    clock = _Clock()
    mtypes.set_time_source(clock)
    try:
        store = _mk_autoscaled_store(
            replicas=5, min_replicas=1, max_replicas=10,
            target_utilization=1.0, scale_up_step=1, scale_down_step=1,
            stabilization_window=2.0, flap_reversals=3, hysteresis=0.1)
        flip = {"hi": True}

        def sampler(sid):
            # alternate far above / far below target per drive
            return {"load": 50.0 if flip["hi"] else 0.0}

        sup = AutoscaleSupervisor(store, sampler=sampler,
                                  start_worker=False)
        writes_before_freeze = []
        for _ in range(12):
            clock.t += 2.0
            sup.drive()
            flip["hi"] = not flip["hi"]
            svc = store.view(lambda tx: tx.get(Service, "svc-a"))
            if svc.autoscale_status is not None \
                    and svc.autoscale_status.frozen_until > clock.t:
                break
            writes_before_freeze.append(_replicas(store))
        svc = store.view(lambda tx: tx.get(Service, "svc-a"))
        assert svc.autoscale_status.frozen_until > clock.t, \
            "flap breaker never engaged"
        frozen_at = _replicas(store)
        assert reg.get_gauge(
            'swarm_autoscale_flapping{service="svc-a"}') == 1.0
        for _ in range(3):
            clock.t += 2.0
            sup.drive()
            flip["hi"] = not flip["hi"]
        assert _replicas(store) == frozen_at, \
            "frozen policy must not write replica changes"
        assert sup.stats["frozen_skips"] >= 1
    finally:
        mtypes.set_time_source(None)


def test_supervisor_resumes_from_replicated_status():
    """Failover shape: a FRESH supervisor (successor leader) over the
    same store respects the previous reign's stabilization window —
    the stamp rides the Service row, not supervisor memory."""
    clock = _Clock()
    mtypes.set_time_source(clock)
    try:
        store = _mk_autoscaled_store(
            replicas=2, min_replicas=2, max_replicas=10,
            target_utilization=1.0, scale_up_step=2,
            stabilization_window=8.0)
        sampler = lambda sid: {"load": 40.0}   # noqa: E731
        sup1 = AutoscaleSupervisor(store, sampler=sampler,
                                   start_worker=False)
        sup1.drive()
        assert _replicas(store) == 4
        # "failover": a brand-new supervisor, 2s later — still inside
        # the window, must NOT step again
        clock.t += 2.0
        sup2 = AutoscaleSupervisor(store, sampler=sampler,
                                   start_worker=False)
        sup2.drive()
        assert _replicas(store) == 4
        assert sup2.stats["rate_limited"] == 1
        clock.t += 8.0
        sup2.drive()
        assert _replicas(store) == 6
    finally:
        mtypes.set_time_source(None)


# ---------------------------------------------------------------------------
# tenant quota plane: ledger arithmetic + host/device parity
# ---------------------------------------------------------------------------

def test_tenant_ledger_admit_and_charge():
    ledger = TenantLedger()
    cluster = Cluster(id="c", spec=ClusterSpec(
        annotations=Annotations(name="default"),
        tenants={"t": TenantQuota(nano_cpus=6 * CPU, max_tasks=5)}))
    ledger.load_cluster(cluster)
    ledger.begin_tick({})
    assert ledger.admit("other", CPU, 0, 10) is None   # unquota'd
    assert ledger.admit("t", CPU, 0, 10) == 5          # max_tasks binds
    assert ledger.admit("t", 2 * CPU, 0, 10) == 3      # cpu binds
    ledger.charge("t", 2 * CPU, 0, 2)
    assert ledger.admit("t", 2 * CPU, 0, 10) == 1
    ledger.charge("t", 2 * CPU, 0, 1)
    assert ledger.admit("t", 2 * CPU, 0, 10) == 0


def _quota_store(n_nodes=6):
    """Cluster with a tight low-tenant quota: svc-part (10 tasks, quota
    admits 4), svc-blocked (same tenant, wholly exhausted), svc-free
    (untenanted).  Multiple services = a fusable run on the device
    path, so the quota column rides the FUSED program too."""
    store = MemoryStore()
    store.update(lambda tx: tx.create(Cluster(
        id="cluster-default",
        spec=ClusterSpec(
            annotations=Annotations(name="default"),
            tenants={"lo": TenantQuota(nano_cpus=4 * CPU)}))))

    def mk_nodes(tx):
        for i in range(n_nodes):
            tx.create(Node(
                id=f"qn{i}", spec=NodeSpec(
                    annotations=Annotations(name=f"qn{i}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"qn{i}",
                    resources=Resources(nano_cpus=8 * 10 ** 9,
                                        memory_bytes=32 * GB))))
    store.update(mk_nodes)
    res = ResourceRequirements(
        reservations=Resources(nano_cpus=CPU, memory_bytes=GB))

    def mk(tx):
        for sid, tenant, count in (("svc-part", "lo", 10),
                                   ("svc-blocked", "lo", 5),
                                   ("svc-free", "", 8)):
            labels = {TENANT_LABEL: tenant} if tenant else {}
            ann = Annotations(name=sid, labels=labels)
            svc = Service(
                id=sid,
                spec=ServiceSpec(
                    annotations=ann, mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=count),
                    task=TaskSpec(resources=res)),
                spec_version=Version(index=1))
            tx.create(svc)
            for s in range(count):
                tx.create(Task(
                    id=f"{sid}-{s:03d}", service_id=sid, slot=s + 1,
                    desired_state=TaskState.RUNNING,
                    spec=svc.spec.task, spec_version=Version(index=1),
                    service_annotations=ann,
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
    store.update(mk)
    return store


def _placement_claim(store):
    """The host/device equivalence claim: per-service per-node
    placement DISTRIBUTIONS plus per-task (state, err) — per-task node
    identity is not part of the contract (the device path fills node
    slots in column order, the host round-robins)."""
    per_node = {}
    per_task = []
    for t in store.view(lambda tx: tx.find(Task)):
        key = (t.service_id, t.node_id)
        if t.node_id:
            per_node[key] = per_node.get(key, 0) + 1
        per_task.append((t.id, bool(t.node_id), int(t.status.state),
                         t.status.err or ""))
    dist = {}
    for (sid, _node), count in per_node.items():
        dist.setdefault(sid, []).append(count)
    return ({sid: sorted(counts) for sid, counts in dist.items()},
            sorted(per_task))


def _run_quota_tick(planner):
    store = _quota_store()
    sched = Scheduler(store, batch_planner=planner)
    if planner is not None:
        planner.enable_small_group_routing = False
    store.view(sched._setup_tasks_list)
    sched.tick()
    dist, per_task = _placement_claim(store)
    return store, sched, (dist, per_task)


def test_quota_clamps_and_blocks_host_path():
    store, sched, (dist, per_task) = _run_quota_tick(None)
    placed = {"svc-part": 0, "svc-blocked": 0, "svc-free": 0}
    for tid, assigned, state, err in per_task:
        sid = tid.rsplit("-", 1)[0]
        if assigned and state >= int(TaskState.ASSIGNED):
            placed[sid] += 1
    # 4-task quota: svc-part admits 4, svc-blocked wholly blocked
    assert placed == {"svc-part": 4, "svc-blocked": 0, "svc-free": 8}, \
        placed
    assert sched.stats["quota_clamps"] == 6
    errs = {err for tid, _n, _s, err in per_task
            if tid.startswith("svc-blocked")}
    assert errs == {"no suitable node (over tenant quota on 6 nodes)"}, \
        errs
    part_errs = {err for tid, n, _s, err in per_task
                 if tid.startswith("svc-part") and not n}
    assert part_errs == {'over tenant quota (tenant "lo")'}, part_errs


def test_quota_device_path_byte_identical_to_host():
    """The quota mask column end to end: the device planner (per-group
    AND fused routes) must place, defer, and explain exactly like the
    host oracle."""
    from swarmkit_tpu.ops import TPUPlanner
    _, _, host_rows = _run_quota_tick(None)
    planner = TPUPlanner()
    _, sched, dev_rows = _run_quota_tick(planner)
    assert dev_rows == host_rows
    assert sched.quota.stats["blocked_groups"] >= 1
    # the multi-service pending queue fused (quota column in the fused
    # program, not just the per-group one)
    assert planner.stats.get("groups_fused", 0) >= 2, planner.stats


def test_quota_differential_fuzz_random_tenants():
    """Seeded fuzz: random clusters, tenants, quotas and demands —
    device placements (and quota diagnostics) must equal the host
    oracle's byte for byte."""
    import random as _random
    from swarmkit_tpu.ops import TPUPlanner

    for seed in range(6):
        rng = _random.Random(7000 + seed)
        n_nodes = rng.randrange(3, 10)
        tenants = {}
        for ti in range(rng.randrange(1, 4)):
            tenants[f"t{ti}"] = TenantQuota(
                nano_cpus=rng.randrange(0, 8) * CPU,
                max_tasks=rng.randrange(0, 6))
        services = []
        for si in range(rng.randrange(2, 5)):
            services.append((
                f"s{seed}-{si}",
                rng.choice([""] + list(tenants)),
                rng.randrange(1, 8),
                rng.randrange(0, 3) * 10 ** 9))

        def build():
            store = MemoryStore()
            store.update(lambda tx: tx.create(Cluster(
                id="cluster-default",
                spec=ClusterSpec(
                    annotations=Annotations(name="default"),
                    tenants={k: TenantQuota(nano_cpus=q.nano_cpus,
                                            max_tasks=q.max_tasks)
                             for k, q in tenants.items()}))))

            def mk(tx):
                for i in range(n_nodes):
                    tx.create(Node(
                        id=f"fn{i}", spec=NodeSpec(
                            annotations=Annotations(name=f"fn{i}")),
                        status=NodeStatus(state=NodeState.READY),
                        description=NodeDescription(
                            hostname=f"fn{i}",
                            resources=Resources(
                                nano_cpus=8 * 10 ** 9,
                                memory_bytes=32 * GB))))
                for sid, tenant, count, cpu_d in services:
                    labels = {TENANT_LABEL: tenant} if tenant else {}
                    ann = Annotations(name=sid, labels=labels)
                    spec = TaskSpec(resources=ResourceRequirements(
                        reservations=Resources(nano_cpus=cpu_d)))
                    tx.create(Service(
                        id=sid,
                        spec=ServiceSpec(
                            annotations=ann,
                            mode=ServiceMode.REPLICATED,
                            replicated=ReplicatedService(replicas=count),
                            task=spec),
                        spec_version=Version(index=1)))
                    for s in range(count):
                        tx.create(Task(
                            id=f"{sid}-{s:03d}", service_id=sid,
                            slot=s + 1,
                            desired_state=TaskState.RUNNING,
                            spec=spec, spec_version=Version(index=1),
                            service_annotations=ann,
                            status=TaskStatus(
                                state=TaskState.PENDING,
                                timestamp=now())))
            store.update(mk)
            return store

        def run(planner):
            store = build()
            sched = Scheduler(store, batch_planner=planner)
            if planner is not None:
                planner.enable_small_group_routing = False
            store.view(sched._setup_tasks_list)
            sched.tick()
            return _placement_claim(store)

        host = run(None)
        device = run(TPUPlanner())
        assert host == device, (seed, host, device)


def test_quota_clamped_tenant_does_not_preempt():
    """A tenant at its quota must not preempt its way past it: QoS
    clamps at admission, full stop."""
    store = MemoryStore()
    store.update(lambda tx: tx.create(Cluster(
        id="cluster-default",
        spec=ClusterSpec(
            annotations=Annotations(name="default"),
            tenants={"cap": TenantQuota(nano_cpus=2 * CPU)}))))

    def mk(tx):
        tx.create(Node(
            id="n0", spec=NodeSpec(annotations=Annotations(name="n0")),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname="n0",
                resources=Resources(nano_cpus=8 * 10 ** 9,
                                    memory_bytes=32 * GB))))
        res = ResourceRequirements(
            reservations=Resources(nano_cpus=CPU))
        lo_ann = Annotations(name="lo")
        lo_spec = TaskSpec(priority=0, resources=res)
        hi_ann = Annotations(name="hi", labels={TENANT_LABEL: "cap"})
        hi_spec = TaskSpec(priority=9, resources=res)
        for sid, ann, spec, n in (("lo", lo_ann, lo_spec, 2),
                                  ("hi", hi_ann, hi_spec, 4)):
            tx.create(Service(
                id=sid, spec=ServiceSpec(
                    annotations=ann, mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=n),
                    task=spec),
                spec_version=Version(index=1)))
        for s in range(2):
            tx.create(Task(
                id=f"lo-r{s}", service_id="lo", slot=s + 1,
                desired_state=TaskState.RUNNING, spec=lo_spec,
                spec_version=Version(index=1), node_id="n0",
                service_annotations=lo_ann,
                status=TaskStatus(state=TaskState.RUNNING,
                                  timestamp=now())))
        for s in range(4):
            tx.create(Task(
                id=f"hi-p{s}", service_id="hi", slot=s + 1,
                desired_state=TaskState.RUNNING, spec=hi_spec,
                spec_version=Version(index=1),
                service_annotations=hi_ann,
                status=TaskStatus(state=TaskState.PENDING,
                                  timestamp=now())))
    store.update(mk)
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = {t.id: t for t in store.view(lambda tx: tx.find(Task))}
    placed_hi = sum(1 for t in tasks.values()
                    if t.service_id == "hi" and t.node_id)
    # quota admits 2 of the 4 high-band tasks; the node has 2 free cpus,
    # so NO preemption is needed for them — and the other 2 must not
    # evict the low band to get in
    assert placed_hi == 2, placed_hi
    assert tasks["lo-r0"].desired_state == TaskState.RUNNING
    assert tasks["lo-r1"].desired_state == TaskState.RUNNING
    assert sched.stats.get("preemptions", 0) == 0


def test_within_quota_tenant_still_preempts():
    """The other half of the quota/preemption contract: a group FULLY
    inside its quota (admitted and charged this tick) keeps its
    preemption entitlement — its own admission charge must not read as
    'no quota left' when the pass computes headroom."""
    store = MemoryStore()
    store.update(lambda tx: tx.create(Cluster(
        id="cluster-default",
        spec=ClusterSpec(
            annotations=Annotations(name="default"),
            tenants={"cap": TenantQuota(nano_cpus=4 * CPU)}))))

    def mk(tx):
        tx.create(Node(
            id="n0", spec=NodeSpec(annotations=Annotations(name="n0")),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname="n0",
                resources=Resources(nano_cpus=8 * 10 ** 9,
                                    memory_bytes=32 * GB))))
        res = ResourceRequirements(
            reservations=Resources(nano_cpus=CPU))
        lo_ann = Annotations(name="lo")
        lo_spec = TaskSpec(priority=0, resources=res)
        hi_ann = Annotations(name="hi", labels={TENANT_LABEL: "cap"})
        hi_spec = TaskSpec(priority=9, resources=res)
        for sid, ann, spec, n in (("lo", lo_ann, lo_spec, 4),
                                  ("hi", hi_ann, hi_spec, 2)):
            tx.create(Service(
                id=sid, spec=ServiceSpec(
                    annotations=ann, mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=n),
                    task=spec),
                spec_version=Version(index=1)))
        # the low band FILLS the node: the within-quota high band can
        # only place by evicting
        for s in range(4):
            tx.create(Task(
                id=f"lo-r{s}", service_id="lo", slot=s + 1,
                desired_state=TaskState.RUNNING, spec=lo_spec,
                spec_version=Version(index=1), node_id="n0",
                service_annotations=lo_ann,
                status=TaskStatus(state=TaskState.RUNNING,
                                  timestamp=now())))
        for s in range(2):
            tx.create(Task(
                id=f"hi-p{s}", service_id="hi", slot=s + 1,
                desired_state=TaskState.RUNNING, spec=hi_spec,
                spec_version=Version(index=1),
                service_annotations=hi_ann,
                status=TaskStatus(state=TaskState.PENDING,
                                  timestamp=now())))
    store.update(mk)
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = {t.id: t for t in store.view(lambda tx: tx.find(Task))}
    placed_hi = sum(1 for t in tasks.values()
                    if t.service_id == "hi" and t.node_id)
    assert placed_hi == 2, placed_hi
    evicted = sum(1 for t in tasks.values()
                  if t.service_id == "lo"
                  and t.desired_state == TaskState.SHUTDOWN)
    assert evicted == 2, evicted
    assert sched.stats["preemptions"] == 2


# ---------------------------------------------------------------------------
# the scenario: green, deterministic, clamps + autoscale observed
# ---------------------------------------------------------------------------

def test_tenant_storm_green_and_deterministic():
    # warm run compiles the quota-mask jit signatures; byte-identity is
    # judged on the warm pair (the preemption-storm discipline)
    warm = run_scenario("tenant-storm", seed=0)
    assert warm.ok, warm.violations
    r1 = run_scenario("tenant-storm", seed=0)
    assert r1.ok, r1.violations
    r2 = run_scenario("tenant-storm", seed=0)
    assert r2.trace_hash == r1.trace_hash == warm.trace_hash
    assert r2.obs_trace_sha256 == r1.obs_trace_sha256
    ctl = r1.stats["control"]
    assert ctl["quota_clamps"] > 0, ctl
    assert ctl["autoscale_changes"] >= 4, ctl
    assert ctl["attaches"] >= 2, ctl          # leader crash mid-scale-up
    # end state: burst converged to min(2) + high band 4, all RUNNING
    assert r1.stats["tasks"].get("RUNNING", 0) == 6, r1.stats["tasks"]


def test_tenant_storm_coverage_cells():
    r = run_scenario("tenant-storm", seed=0, keep_trace=True)
    assert r.ok, r.violations
    matrix = chaos_sweep.coverage_matrix([r.trace])
    required = chaos_sweep.required_cells(("tenant-storm",))
    assert ("quota-clamp", "scheduler") in required
    assert chaos_sweep.uncovered(matrix, required) == [], \
        json.dumps(matrix, indent=2)
    assert chaos_sweep.classify("autoscale-burst", "") == "scheduler"
    assert "tenant-storm" in chaos_sweep.SUITES["qos"]
    assert "tenant-storm" in chaos_sweep.SUITES["default"]


# ---------------------------------------------------------------------------
# checker-sensitivity: all four new invariants must FIRE when their
# enforcement seams are disabled (house rule since PR 1)
# ---------------------------------------------------------------------------

def _mini_qos_sim(seed, build, duration=55.0, grace=20.0,
                  quota_enabled=True, preemption=True):
    sim = Sim(seed=seed, n_managers=3, n_agents=5,
              net_config=NetConfig(), raft_cp=True)
    with sim:
        cp = sim.cp
        cp.quota_enabled = quota_enabled
        cp.preemption_enabled = preemption
        sim.start_raft_workload(interval=0.8)
        build(sim, cp)
        sim.run(duration)
        sim.finish(grace=grace)
    return sim


def test_sensitivity_quota_never_exceeded():
    """Disable the scheduler's quota plane: the bursting tenant's
    committed usage runs past its quota and the checker must catch it
    from the event stream alone."""
    def build(sim, cp):
        eng = sim.engine
        eng.at(eng.clock.start + 4.0, "tenants",
               lambda: cp.configure_tenants(
                   {"t-x": TenantQuota(nano_cpus=4 * 10 ** 9)}))
        eng.at(eng.clock.start + 6.0, "over-quota band",
               lambda: cp.add_service("svc-x", 6, nano_cpus=CPU,
                                      tenant="t-x"))
    sim = _mini_qos_sim(11, build, quota_enabled=False)
    assert any("quota-never-exceeded" in v
               for v in sim.violations.items), sim.violations.items


def test_sensitivity_autoscale_within_bounds_and_rate(monkeypatch):
    """Disable the supervisor's clamp + rate limit (the built-in seam):
    the runaway policy writes past max and faster than the window — the
    checker must catch it from the committed spec stream."""
    monkeypatch.setattr(AutoscaleSupervisor, "_enforce_bounds", False)

    def build(sim, cp):
        eng = sim.engine
        eng.at(eng.clock.start + 4.0, "autoscaled svc",
               lambda: cp.add_service(
                   "svc-run", 1, nano_cpus=10 ** 8,
                   autoscale=AutoscaleConfig(
                       min_replicas=1, max_replicas=3,
                       target_utilization=1.0, scale_up_step=1,
                       stabilization_window=5.0)))
        eng.at(eng.clock.start + 6.0, "load",
               lambda: cp.set_load("svc-run", 50.0))
    sim = _mini_qos_sim(12, build, duration=40.0)
    assert any("autoscale-within-bounds-and-rate" in v
               for v in sim.violations.items), sim.violations.items


def test_sensitivity_no_cross_band_p99_violation():
    """Disable the cross-band protections (quota AND preemption): a
    low-band flood fills the cluster before the high band arrives, the
    high band starves, and its windowed p99 must blow the derived
    bound (open-ended pending tasks count — starvation cannot hide
    from a percentile)."""
    def build(sim, cp):
        eng = sim.engine
        eng.at(eng.clock.start + 4.0, "tenants",
               lambda: cp.configure_tenants(
                   {"t-lo": TenantQuota(nano_cpus=8 * 10 ** 9)}))
        # 20 x 2cpu fills 5 workers x 8cpu wholesale (quota disabled)
        eng.at(eng.clock.start + 6.0, "flood",
               lambda: cp.add_service("svc-flood", 20, nano_cpus=CPU,
                                      tenant="t-lo"))
        eng.at(eng.clock.start + 14.0, "high band starves",
               lambda: cp.add_service("svc-vip", 4, priority=10,
                                      nano_cpus=CPU))
        cp.expect_band_p99(5, 10.0, 45.0)
    sim = _mini_qos_sim(13, build, quota_enabled=False,
                        preemption=False)
    assert any("no-cross-band-p99-violation" in v
               for v in sim.violations.items), sim.violations.items


def test_sensitivity_autoscale_converges(monkeypatch):
    """Disable scale-down (the built-in seam): load removal leaves the
    replicas stranded at the burst size — the registered convergence
    expectation must fire at finish."""
    monkeypatch.setattr(AutoscaleSupervisor, "_scale_down_enabled",
                        False)

    def build(sim, cp):
        eng = sim.engine
        eng.at(eng.clock.start + 4.0, "autoscaled svc",
               lambda: cp.add_service(
                   "svc-c", 2, nano_cpus=10 ** 8,
                   autoscale=AutoscaleConfig(
                       min_replicas=2, max_replicas=8,
                       target_utilization=1.0, scale_up_step=2,
                       scale_down_step=3,
                       stabilization_window=2.0)))
        eng.at(eng.clock.start + 6.0, "load up",
               lambda: cp.set_load("svc-c", 16.0))
        eng.at(eng.clock.start + 24.0, "load removed",
               lambda: cp.set_load("svc-c", 0.0))
        cp.expect_autoscale_converge("svc-c", to=2, by=50.0)
    sim = _mini_qos_sim(14, build, duration=50.0)
    assert any("autoscale-converges" in v
               for v in sim.violations.items), sim.violations.items


def test_qos_invariants_green_by_default():
    """The harness itself must be quiet on a healthy run: quotas
    honored, autoscale inside policy, convergence green."""
    def build(sim, cp):
        eng = sim.engine
        eng.at(eng.clock.start + 4.0, "tenants",
               lambda: cp.configure_tenants(
                   {"t-a": TenantQuota(nano_cpus=16 * 10 ** 9)}))
        eng.at(eng.clock.start + 6.0, "autoscaled svc",
               lambda: cp.add_service(
                   "svc-g", 2, nano_cpus=CPU, tenant="t-a",
                   autoscale=AutoscaleConfig(
                       min_replicas=2, max_replicas=6,
                       target_utilization=1.0, scale_up_step=2,
                       scale_down_step=2,
                       stabilization_window=3.0)))
        eng.at(eng.clock.start + 10.0, "load",
               lambda: cp.set_load("svc-g", 6.0))
        eng.at(eng.clock.start + 30.0, "load removed",
               lambda: cp.set_load("svc-g", 0.0))
        cp.expect_autoscale("svc-g", at_least=6, by=30.0)
        cp.expect_autoscale_converge("svc-g", to=2, by=60.0)
    sim = _mini_qos_sim(15, build, duration=55.0)
    assert not sim.violations.items, sim.violations.items


# ---------------------------------------------------------------------------
# batched dispatcher fan-out
# ---------------------------------------------------------------------------

def _fanout_store(n_tasks=0):
    store = MemoryStore()
    store.update(lambda tx: tx.create(Node(
        id="w0", spec=NodeSpec(annotations=Annotations(name="w0")),
        status=NodeStatus(state=NodeState.UNKNOWN),
        description=NodeDescription(hostname="w0"))))
    return store


def _mk_assigned_tasks(store, n, base=0, node_id="w0"):
    def cb(tx):
        for i in range(base, base + n):
            tx.create(Task(
                id=f"ft{i:04d}", service_id="s", slot=i + 1,
                node_id=node_id, desired_state=TaskState.RUNNING,
                spec=TaskSpec(), spec_version=Version(index=1),
                status=TaskStatus(state=TaskState.ASSIGNED,
                                  timestamp=now())))
    store.update(cb)


def _drain_stream(stream):
    msgs = []
    while True:
        try:
            msgs.append(stream.get(timeout=0))
        except TimeoutError:
            return msgs
        except Exception:
            return msgs


def test_batched_fanout_bounds_sends():
    """N task assignments to one node produce <= ceil(N/batch)
    incremental sends, not N."""
    from swarmkit_tpu.manager.dispatcher import Config_, Dispatcher
    store = _fanout_store()
    d = Dispatcher(store, Config_(rate_limit_period=0.0,
                                  modification_batch_limit=100))
    d.run(start_worker=False)
    d.enable_batched_fanout()
    session, _ = d.register("w0")
    stream = d.open_assignments("w0", session)
    complete = _drain_stream(stream)
    assert [m.type for m in complete] == ["complete"]

    N = 250
    _mk_assigned_tasks(store, N)
    d.process_deadlines()
    msgs = _drain_stream(stream)
    assert all(m.type == "incremental" for m in msgs)
    assert len(msgs) <= -(-N // 100), (len(msgs), N)   # ceil(N/batch)
    delivered = [obj.id for m in msgs
                 for _a, kind, obj in m.changes if kind == "task"]
    assert len(delivered) == N
    assert len(set(delivered)) == N, "duplicated assignment"
    d.stop(flush=False)


def test_batched_fanout_no_loss_or_dup_across_leader_gap():
    """A session gap (the node's stream dies mid-burst, e.g. leader
    handoff) must not lose or duplicate assignments: the re-opened
    stream's COMPLETE is exactly the store's current set."""
    from swarmkit_tpu.manager.dispatcher import Config_, Dispatcher
    store = _fanout_store()
    d = Dispatcher(store, Config_(rate_limit_period=0.0,
                                  modification_batch_limit=100))
    d.run(start_worker=False)
    d.enable_batched_fanout()
    session, _ = d.register("w0")
    stream = d.open_assignments("w0", session)
    _drain_stream(stream)
    _mk_assigned_tasks(store, 120)
    d.process_deadlines()
    _drain_stream(stream)
    # the gap: more assignments land while the session dies
    _mk_assigned_tasks(store, 60, base=120)
    d.release_session("w0", session)
    assert stream.closed
    d.process_deadlines()      # flush with the stream down: no crash
    # re-register (the re-learn path) and reopen
    session2, _ = d.register("w0")
    stream2 = d.open_assignments("w0", session2)
    msgs = _drain_stream(stream2)
    assert msgs[0].type == "complete"
    got = sorted(obj.id for m in msgs
                 for _a, kind, obj in m.changes if kind == "task")
    want = sorted(t.id for t in store.view(lambda tx: tx.find(Task)))
    assert got == want, (len(got), len(want))
    assert len(got) == len(set(got)) == 180
    d.stop(flush=False)


# ---------------------------------------------------------------------------
# health plane + metric hygiene
# ---------------------------------------------------------------------------

def test_autoscale_flapping_health_check_transitions():
    from swarmkit_tpu.obs.health import HealthEvaluator, default_checks
    from swarmkit_tpu.utils.metrics import Registry
    reg = Registry()
    checks = [c for c in default_checks()
              if c.name == "autoscale_flapping"]
    ev = HealthEvaluator(registry=reg, checks=checks)
    assert ev.evaluate()["autoscale_flapping"] == "pass"   # no data
    reg.gauge('swarm_autoscale_flapping{service="s1"}', 0.0)
    reg.gauge('swarm_autoscale_out_of_bounds{service="s1"}', 0.0)
    assert ev.evaluate()["autoscale_flapping"] == "pass"
    reg.gauge('swarm_autoscale_flapping{service="s1"}', 1.0)
    assert ev.evaluate()["autoscale_flapping"] == "warn"
    reg.gauge('swarm_autoscale_out_of_bounds{service="s1"}', 1.0)
    assert ev.evaluate()["autoscale_flapping"] == "fail"
    reg.gauge('swarm_autoscale_flapping{service="s1"}', 0.0)
    reg.gauge('swarm_autoscale_out_of_bounds{service="s1"}', 0.0)
    assert ev.evaluate()["autoscale_flapping"] == "pass"


# ---------------------------------------------------------------------------
# slow: wide sweep + PYTHONHASHSEED independence
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tenant_storm_wide_sweep():
    """Acceptance: 20 seeds of tenant-storm, all green under all four
    invariants, full coverage, byte-identical re-runs for sampled
    seeds."""
    # warm run first: the quota-mask jit signatures compile once per
    # process, and the cold run's one-off plan.compile events would
    # break byte-identity against warm re-runs (preemption-storm
    # discipline)
    run_scenario("tenant-storm", 0)
    reports = chaos_sweep.sweep(("tenant-storm",), n_seeds=20)
    out = chaos_sweep.verdict(reports, ("tenant-storm",), 20, 0)
    assert out["ok"], json.dumps(
        {"failures": out["failures"],
         "uncovered": out["coverage"]["uncovered"]}, indent=2)
    by_seed = {r.seed: r for r in reports}
    for seed in (0, 7, 13):
        r2 = run_scenario("tenant-storm", seed, keep_trace=True)
        assert r2.trace_hash == by_seed[seed].trace_hash, seed
        assert r2.obs_trace_sha256 == by_seed[seed].obs_trace_sha256, \
            seed


@pytest.mark.slow
def test_tenant_storm_hashseed_independent():
    """Byte-identical across PYTHONHASHSEED: hash-ordered containers
    must not leak into placement or event order."""
    code = ("from swarmkit_tpu.sim.scenario import run_scenario;"
            "r = run_scenario('tenant-storm', 0);"
            "print(r.trace_hash, r.obs_trace_sha256, r.ok)")
    outs = []
    for hs in ("0", "31337"):
        env = dict(os.environ, PYTHONHASHSEED=hs, JAX_PLATFORMS="cpu")
        p = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           env=env, capture_output=True, text=True,
                           timeout=600)
        assert p.returncode == 0, p.stderr[-2000:]
        outs.append(p.stdout.strip().splitlines()[-1])
    assert outs[0] == outs[1], outs
    assert outs[0].endswith("True"), outs
