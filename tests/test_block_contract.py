"""Consumer-side block contract (VERDICT Weak #7).

The store's columnar task-block commit publishes ONE ``EventTaskBlock``
instead of per-task events, and every control loop subscribes with
``accepts_blocks=True`` under a stated contract: assignment blocks only
carry states <= RUNNING, so **blocks are never failures** — no
orchestrator may reconcile, restart, reap, or reject a task merely
because its assignment arrived as a block.

Until now only the producer side was enforced.  These tests run each
consumer loop — replicated and global orchestrators, the restart
supervisor (via the replicated orchestrator), the task reaper, and both
enforcers — against a live block commit and assert the non-failure
contract from the consumer's side.
"""

import time

import pytest

from swarmkit_tpu.models import (
    Annotations, Cluster, Node, NodeSpec, NodeState, NodeStatus,
    NodeDescription, ReplicatedService, Resources, Service, ServiceMode,
    ServiceSpec, Task, TaskSpec, TaskState, TaskStatus, Version,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.orchestrator import (
    ConstraintEnforcer, GlobalOrchestrator, ReplicatedOrchestrator,
    TaskReaper, VolumeEnforcer,
)
from swarmkit_tpu.state import ByService, MemoryStore
from swarmkit_tpu.utils import new_id


def poll(cond, timeout=5.0, interval=0.02, msg="condition not met"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(msg)


def make_cluster_store():
    s = MemoryStore()
    cluster = Cluster(id=new_id(),
                      spec=ClusterSpec(annotations=Annotations(
                          name="default")))
    nodes = [
        Node(id=new_id(),
             spec=NodeSpec(annotations=Annotations(name=f"bn{i}")),
             status=NodeStatus(state=NodeState.READY),
             description=NodeDescription(
                 hostname=f"bn{i}",
                 resources=Resources(nano_cpus=8 * 10 ** 9,
                                     memory_bytes=16 << 30)))
        for i in range(3)]

    def cb(tx):
        tx.create(cluster)
        for n in nodes:
            tx.create(n)

    s.update(cb)
    return s, nodes


def make_service(replicas):
    return Service(
        id=new_id(),
        spec=ServiceSpec(annotations=Annotations(name="blocked"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=replicas),
                         task=TaskSpec()),
        spec_version=Version(index=1))


def make_pending_tasks(svc, n):
    return [Task(id=new_id(), service_id=svc.id, slot=i + 1,
                 desired_state=TaskState.RUNNING, spec=svc.spec.task,
                 spec_version=Version(index=1),
                 status=TaskStatus(state=TaskState.PENDING))
            for i in range(n)]


def commit_block(store, tasks, nodes, state=TaskState.ASSIGNED,
                 message="scheduler assigned task to node (block)"):
    node_ids = [nodes[i % len(nodes)].id for i in range(len(tasks))]
    committed, failed = store.commit_task_block(
        tasks, node_ids, int(state), message,
        lambda t, nid: None, lambda t, nid: False)
    assert len(committed) == len(tasks) and not failed
    return node_ids


def tasks_of(store, svc):
    return store.view(lambda tx: tx.find(Task, ByService(svc.id)))


NON_FAILURE_STATES = {TaskState.ASSIGNED, TaskState.ACCEPTED,
                      TaskState.PREPARING, TaskState.READY,
                      TaskState.STARTING, TaskState.RUNNING}


def assert_block_not_treated_as_failure(store, svc, n):
    """Shared postcondition: the block's tasks are alive, desired still
    RUNNING, never failed/rejected/replaced."""
    tasks = tasks_of(store, svc)
    live = [t for t in tasks if t.desired_state <= TaskState.RUNNING]
    assert len(tasks) == n, \
        f"consumer created/removed tasks on a block: {len(tasks)} != {n}"
    for t in live:
        assert TaskState(t.status.state) in NON_FAILURE_STATES, \
            f"task moved to {TaskState(t.status.state).name} after block"
        assert not t.status.err, t.status.err
        assert t.desired_state == TaskState.RUNNING
    assert len(live) == n, "a consumer shut down block-assigned tasks"


@pytest.mark.parametrize("loop_factory", [
    ReplicatedOrchestrator,       # includes its RestartSupervisor
    TaskReaper,
    ConstraintEnforcer,
    VolumeEnforcer,
], ids=["replicated+restart", "taskreaper", "constraint-enforcer",
        "volume-enforcer"])
def test_consumer_treats_block_as_non_failure(loop_factory):
    store, nodes = make_cluster_store()
    svc = make_service(replicas=6)
    tasks = make_pending_tasks(svc, 6)

    def cb(tx):
        tx.create(svc)
        for t in tasks:
            tx.create(t)

    store.update(cb)
    stored = sorted(tasks_of(store, svc), key=lambda t: t.slot)

    loop = loop_factory(store)
    loop.start()
    try:
        time.sleep(0.3)              # loop settles on the initial state
        commit_block(store, stored, nodes)
        time.sleep(0.7)              # give the loop time to (mis)react
        assert_block_not_treated_as_failure(store, svc, 6)
    finally:
        loop.stop()


def test_replicated_does_not_reconcile_on_block():
    """A block assignment changes neither the slot count nor liveness;
    the replicated orchestrator must not create or remove anything."""
    store, nodes = make_cluster_store()
    svc = make_service(replicas=4)
    tasks = make_pending_tasks(svc, 4)

    def cb(tx):
        tx.create(svc)
        for t in tasks:
            tx.create(t)

    store.update(cb)
    stored = sorted(tasks_of(store, svc), key=lambda t: t.slot)

    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        time.sleep(0.3)
        before_ids = {t.id for t in tasks_of(store, svc)}
        commit_block(store, stored, nodes)
        time.sleep(0.7)
        after = tasks_of(store, svc)
        assert {t.id for t in after} == before_ids, \
            "replicated orchestrator churned tasks on a block commit"
        assert_block_not_treated_as_failure(store, svc, 4)
    finally:
        orch.stop()


def test_global_orchestrator_ignores_assignment_blocks():
    """Global services: a block moving this service's tasks to ASSIGNED
    must not trigger re-reconciliation (duplicate per-node tasks)."""
    store, nodes = make_cluster_store()
    svc = Service(
        id=new_id(),
        spec=ServiceSpec(annotations=Annotations(name="gsvc"),
                         mode=ServiceMode.GLOBAL,
                         task=TaskSpec()),
        spec_version=Version(index=1))
    store.update(lambda tx: tx.create(svc))

    orch = GlobalOrchestrator(store)
    orch.start()
    try:
        poll(lambda: len(tasks_of(store, svc)) == len(nodes),
             msg="global orchestrator never created per-node tasks")
        stored = tasks_of(store, svc)
        # preassigned global tasks: block-commit their ASSIGNED flip
        # (what the scheduler's device path does for global storms)
        committed, failed = store.commit_task_block(
            stored, [t.node_id for t in stored],
            int(TaskState.ASSIGNED), "validated (block)",
            lambda t, nid: None, lambda t, nid: False)
        assert len(committed) == len(stored) and not failed
        time.sleep(0.7)
        after = tasks_of(store, svc)
        assert len(after) == len(nodes), \
            "global orchestrator duplicated tasks after a block"
        for t in after:
            assert t.desired_state == TaskState.RUNNING
            assert TaskState(t.status.state) in NON_FAILURE_STATES
    finally:
        orch.stop()


def test_reaper_does_not_reap_block_assigned_tasks():
    """Blocks carry live states; the reaper's terminal/never-ran rules
    must not match them even with an aggressive retention policy."""
    store, nodes = make_cluster_store()
    svc = make_service(replicas=5)
    tasks = make_pending_tasks(svc, 5)

    def cb(tx):
        tx.create(svc)
        for t in tasks:
            tx.create(t)

    store.update(cb)
    stored = sorted(tasks_of(store, svc), key=lambda t: t.slot)

    reaper = TaskReaper(store)
    reaper.start()
    try:
        time.sleep(0.3)
        commit_block(store, stored, nodes)
        time.sleep(0.5)
        reaper.tick()                 # force a full pass
        assert len(tasks_of(store, svc)) == 5, \
            "task reaper deleted live block-assigned tasks"
    finally:
        reaper.stop()
