"""Root CA rotation + autolock against live daemons.

Reference: ca/reconciler.go (cross-signed root rotation),
controlapi/ca_rotation.go, manager.go:116-120 autolock/UnlockKey.
"""

import tempfile
import time

import pytest

pytest.importorskip(
    "cryptography", reason="CA/TLS tests require the cryptography package")

from swarmkit_tpu.models import Cluster, TaskState
from swarmkit_tpu.models.types import NodeRole
from swarmkit_tpu.net import RemoteControlClient, issue_certificate
from swarmkit_tpu.security.ca import cert_digest, signing_root_digest
from swarmkit_tpu.state.store import ByName
from swarmkit_tpu.swarmd import ManagerLockedError, Swarmd

from test_orchestrator import make_replicated, poll


def test_rootca_rotation_unit():
    """Cross-sign + dual-trust issuance semantics on the RootCA itself."""
    from swarmkit_tpu.security import RootCA

    ca = RootCA()
    old_digest = ca.digest
    pre_cert = ca.issue("old-node", NodeRole.WORKER)

    ca.begin_rotation()
    assert ca.active_digest != old_digest
    assert ca.digest == old_digest          # tokens stay on the old root

    # new issuance signs with the new key + ships the cross-signed chain
    mid_cert = ca.issue("mid-node", NodeRole.WORKER)
    assert signing_root_digest(mid_cert) == ca.active_digest
    assert len(ca.trust_bundle().split(b"-----BEGIN")) - 1 == 2
    # both old- and new-root certs verify during rotation
    ca.verify(pre_cert)
    ca.verify(mid_cert)
    assert ca.issuer_digest(pre_cert) == old_digest
    assert ca.issuer_digest(mid_cert) == ca.active_digest

    ca.finalize_rotation()
    assert ca.digest != old_digest
    ca.verify(mid_cert)
    with pytest.raises(Exception):
        ca.verify(pre_cert)   # old-root certs die with the old root


def test_ca_rotation_live_cluster_no_task_disruption():
    """Rotate the root on a live 2-manager + 1-worker cluster: nodes
    re-certify via their renewers, the reconciler finalizes, tokens
    re-derive from the new root, and running tasks never restart."""
    m0 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m0",
                manager=True, listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False, cert_renew_interval=0.3)
    m0.start()
    m0.manager.ca_rotation_check_interval = 0.3
    mtoken = m0.manager.root_ca.join_token(NodeRole.MANAGER)
    m1 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m1",
                manager=True, join_addr=m0.server.addr, join_token=mtoken,
                listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False, cert_renew_interval=0.3)
    m1.start()
    w = Swarmd(state_dir=tempfile.mkdtemp(), hostname="w0",
               join_addr=m0.server.addr,
               join_token=m0.manager.root_ca.join_token(NodeRole.WORKER),
               cert_renew_interval=0.3)
    w.start()
    try:
        op = issue_certificate(m0.server.addr, "op", mtoken)
        ctl = RemoteControlClient(m0.server.addr, op)
        svc = ctl.create_service(make_replicated("web", 3).spec)

        def running_ids():
            ts = [t for t in ctl.list_tasks(service_id=svc.id)
                  if t.desired_state == TaskState.RUNNING
                  and t.status.state == TaskState.RUNNING]
            return sorted(t.id for t in ts) if len(ts) == 3 else None
        poll(running_ids, timeout=40, msg="3 replicas running")
        before = running_ids()

        old_digest = m0.manager.root_ca.digest
        new_digest = ctl.rotate_ca()
        assert new_digest != old_digest

        def finalized():
            cluster = m0.manager.store.view(
                lambda tx: tx.find(Cluster, ByName("default")))[0]
            return (not cluster.root_ca.root_rotation_in_progress
                    and m0.manager.root_ca.digest == new_digest)
        poll(finalized, timeout=60,
             msg="rotation should finalize once all nodes re-certify")

        # zero task disruption: identical task ids still RUNNING
        assert running_ids() == before

        # the worker's live identity now chains to the new root
        poll(lambda: signing_root_digest(w.node.certificate)
             == new_digest, timeout=20,
             msg="worker cert should chain to the new root")

        # tokens re-derive: a brand-new worker joins with the NEW token
        new_token = m0.manager.root_ca.join_token(NodeRole.WORKER)
        fresh = issue_certificate(m0.server.addr, "late-joiner",
                                  new_token)
        assert signing_root_digest(fresh) == new_digest
        # the API keeps serving under the rotated root
        assert len(ctl.list_nodes()) >= 3
        ctl.close()
    finally:
        w.stop()
        m1.stop()
        m0.stop()


def test_autolock_manager_refuses_until_unlocked():
    """Autolocked manager state: a restart cannot serve (or even read
    its CA material) until the operator supplies the unlock key."""
    state_dir = tempfile.mkdtemp()
    m0 = Swarmd(state_dir=state_dir, hostname="m0", manager=True,
                listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False)
    m0.start()
    api = m0.manager.control_api
    from swarmkit_tpu.cli import run_command
    out = run_command(["cluster", "autolock", "on"], api)
    key = out.rsplit(" ", 1)[-1]
    assert len(key) == 64
    assert run_command(["cluster", "unlock-key"], api) == key
    svc = api.create_service(make_replicated("locked-web", 1).spec)
    poll(lambda: [t for t in api.list_tasks(service_id=svc.id)
                  if t.status.state == TaskState.RUNNING], timeout=30)
    # the re-seal hook fires on the cluster update; give it a beat
    poll(lambda: open(m0._manager_state_path(), "rb").read()
         .startswith(b"LOCK1"), timeout=10,
         msg="state file should be sealed after autolock on")
    m0.stop()

    # restart without the key: locked, serving nothing
    m1 = Swarmd(state_dir=state_dir, hostname="m0", manager=True,
                listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False)
    m1.start()
    assert m1.locked
    assert m1.manager is None and m1.server is None

    # wrong key rejected
    with pytest.raises(ManagerLockedError):
        m1.unlock("00" * 32)
    assert m1.locked

    # right key: unseals, serves, state intact
    m1.unlock(key)
    assert not m1.locked
    poll(lambda: m1.manager is not None and m1.manager.is_leader,
         timeout=30, msg="unlocked manager should lead again")
    names = [s.spec.annotations.name
             for s in m1.manager.control_api.list_services()]
    assert "locked-web" in names
    m1.stop()


def test_force_new_cluster_recovers_from_quorum_loss():
    """Kill 2 of 3 managers; the survivor cannot lead.  Restart it with
    force_new_cluster: single-member raft from its WAL, cluster state
    intact, and new managers can join again (reference:
    manager.go:99-101 --force-new-cluster)."""
    from swarmkit_tpu.models import ReplicatedService

    m0 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m0",
                manager=True, listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False)
    m0.start()
    mtoken = m0.manager.root_ca.join_token(NodeRole.MANAGER)
    joiners = []
    for h in ("m1", "m2"):
        d = Swarmd(state_dir=tempfile.mkdtemp(), hostname=h,
                   manager=True, join_addr=m0.server.addr,
                   join_token=mtoken, listen_remote_api=("127.0.0.1", 0),
                   use_device_scheduler=False)
        d.start()
        joiners.append(d)
    m1, m2 = joiners
    svc = m0.manager.control_api.create_service(
        make_replicated("critical", 2).spec)
    poll(lambda: len(m0.manager.control_api.list_tasks(
        service_id=svc.id)) >= 2, timeout=30)
    # replicate to m2 before the others die
    poll(lambda: m2.manager.store.view(
        lambda tx: tx.get(type(svc), svc.id)) is not None, timeout=20,
        msg="service should replicate to m2")

    survivor_dir = m2.state_dir
    m0.stop()
    m1.stop()
    time.sleep(1.0)
    m2.stop()

    # recovery: single-member rebuild from the survivor's state dir
    rec = Swarmd(state_dir=survivor_dir, hostname="m2", manager=True,
                 listen_remote_api=("127.0.0.1", 0),
                 use_device_scheduler=False, force_new_cluster=True)
    rec.start()
    assert rec.raft_node.core.peers == {"m-m2"}
    poll(lambda: rec.manager.is_leader
         and rec.manager.dispatcher is not None, timeout=30,
         msg="recovered manager should lead alone")
    got = rec.manager.control_api.get_service(svc.id)
    assert got.spec.annotations.name == "critical"

    # the rebuilt cluster accepts new managers and workers again
    token2 = rec.manager.root_ca.join_token(NodeRole.MANAGER)
    m3 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m3",
                manager=True, join_addr=rec.server.addr,
                join_token=token2, listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False)
    m3.start()
    poll(lambda: "m-m3" in rec.raft_node.core.peers, timeout=30,
         msg="a fresh manager should join the rebuilt group")
    w = Swarmd(state_dir=tempfile.mkdtemp(), hostname="w0",
               join_addr=rec.server.addr,
               join_token=rec.manager.root_ca.join_token(NodeRole.WORKER))
    w.start()
    from swarmkit_tpu.models.types import NodeState
    def worker_ready():
        nodes = [n for n in rec.manager.control_api.list_nodes()
                 if n.description and n.description.hostname == "w0"]
        return nodes and nodes[0].status.state == NodeState.READY
    poll(worker_ready, timeout=30, msg="worker joins the rebuilt cluster")
    w.stop()
    m3.stop()
    rec.stop()


def test_health_api_and_metrics_endpoint():
    """Health RPC on the control surface + curl-able /metrics /healthz
    /debug/stacks (reference: manager/health/health.go, swarmd
    --listen-metrics main.go:92-97)."""
    import urllib.error
    import urllib.request

    from swarmkit_tpu.cli import run_command

    m0 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m0",
                manager=True, listen_remote_api=("127.0.0.1", 0),
                listen_metrics=("127.0.0.1", 0),
                use_device_scheduler=False)
    m0.start()
    try:
        # in-process probe via CLI
        assert run_command(["cluster", "health"],
                           m0.manager.control_api) == "SERVING"
        assert run_command(["cluster", "health", "--service", "raft"],
                           m0.manager.control_api) == "SERVING"

        # remote probe over mTLS
        op = issue_certificate(
            m0.server.addr, "op",
            m0.manager.root_ca.join_token(NodeRole.MANAGER))
        ctl = RemoteControlClient(m0.server.addr, op)
        assert ctl.health() == "SERVING"
        assert ctl.health("raft") == "SERVING"
        assert ctl.health("bogus") == "UNKNOWN"
        ctl.close()

        # create some state so the collector gauges are non-trivial
        svc = m0.manager.control_api.create_service(
            make_replicated("obs", 2).spec)
        poll(lambda: len(m0.manager.control_api.list_tasks(
            service_id=svc.id)) == 2, timeout=20)

        base = "http://%s:%d" % m0.metrics_server.addr
        poll(lambda: b"swarm_manager_services 1" in urllib.request.urlopen(
            base + "/metrics", timeout=5).read(), timeout=15,
            msg="collector gauges should surface on /metrics")
        body = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        assert "swarm_store_write_tx_latency_seconds_count" in body
        # per-RPC interceptor metrics: the remote health probes above
        # must have counted (reference: grpc-prometheus interceptors);
        # labeled counters must render valid exposition format
        # (name_total{labels} value)
        assert 'swarm_rpc_total{method="health"}' in body
        assert "swarm_rpc_latency_seconds_count" in body

        assert urllib.request.urlopen(
            base + "/healthz", timeout=5).read().strip() == b"SERVING"
        stacks = urllib.request.urlopen(
            base + "/debug/stacks", timeout=5).read().decode()
        assert "raft-m-m0" in stacks   # thread dump names live threads

        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        m0.stop()


def test_node_cert_expiry_renewal_under_daemon():
    """Short-lived node certs renew automatically at half-life and the
    node stays READY past its original expiry (reference: ca/renewer.go
    renewal loop + CAConfig.NodeCertExpiry driving validity)."""
    from swarmkit_tpu.models.types import NodeState

    m0 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m0", manager=True,
                listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False)
    m0.start()
    w = None
    try:
        api = m0.manager.control_api
        # operator shrinks cert validity via the cluster spec; the
        # leader's CA applies it live
        c = api.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0].copy()
        c.spec.ca_config.node_cert_expiry = 10.0
        api.store.update(lambda tx: tx.update(c))
        poll(lambda: m0.manager.root_ca.node_cert_expiry == 10.0,
             msg="CA picks up node_cert_expiry from the cluster spec")

        w = Swarmd(state_dir=tempfile.mkdtemp(), hostname="w0",
                   join_addr=m0.server.addr,
                   join_token=m0.manager.root_ca.join_token(0),
                   cert_renew_interval=0.5)
        w.start()
        first = w.node.certificate
        # issuance backdates not_valid_before 60s for clock skew, so
        # check the remaining validity rather than the full lifetime
        assert first.expires_at - time.time() < 15.0, \
            "short validity should apply to issuance"

        # the renewer must swap in a fresh cert at ~half-life
        poll(lambda: w.node.certificate.expires_at > first.expires_at,
             timeout=20, msg="cert renews before expiry")
        wid = w.node.node_id

        # past the ORIGINAL expiry the node is still a functioning member
        time.sleep(max(0.0, first.expires_at - time.time()) + 0.5)
        def ready():
            nodes = [n for n in api.list_nodes() if n.id == wid]
            return nodes and nodes[0].status.state == NodeState.READY
        poll(ready, timeout=15,
             msg="node stays READY past its first cert's expiry")
        svc = api.create_service(make_replicated("fresh-cert", 2).spec)
        poll(lambda: len([t for t in api.list_tasks(service_id=svc.id)
                          if t.status.state == TaskState.RUNNING]) == 2,
             timeout=30, msg="tasks still schedule after renewal")
    finally:
        if w is not None:
            w.stop()
        m0.stop()
