"""Columnar zero-copy commit plane + native watch fan-out (ISSUE 13).

Differential suites: every native fast path (binary block entry codec,
follower-side block apply, watch fan-out expansion / per-subscriber
filtering / per-node grouping) is pitted against its pure-Python oracle,
and the whole plane must be byte-identical — snapshot bytes, watch
streams, resume replays — across SWARM_NATIVE_COMMIT={0,1} and both raft
routes (proposer-less store and a real single-voter RaftNode)."""

import json
import os
import random
import shutil
import string
import subprocess
import sys
import tempfile

import pytest

from swarmkit_tpu import native
from swarmkit_tpu.models import (
    Annotations, Node, NodeSpec, Task, TaskState, TaskStatus,
)
from swarmkit_tpu.models import types as mtypes
from swarmkit_tpu.state import MemoryStore, serde
from swarmkit_tpu.state.events import Event, EventCommit, EventTaskBlock
from swarmkit_tpu.state.store import TaskBlockAction
from swarmkit_tpu.utils import new_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def frozen_clock():
    """Deterministic model clock: byte-identity comparisons span runs,
    so every store-stamped timestamp must be a pure function of the
    workload, not the host."""
    t = [1_000_000.0]

    def tick():
        t[0] += 0.001
        return t[0]

    mtypes.set_time_source(tick)
    yield
    mtypes.set_time_source(None)


def _require_native():
    if native.get() is None:
        pytest.skip("native hotpath did not build on this image")


# ---------------------------------------------------------------------------
# binary block entry codec
# ---------------------------------------------------------------------------

def _random_block(rng, n=None):
    n = rng.randrange(0, 60) if n is None else n
    alphabet = string.hexdigits + ",:{}~é"
    ids = tuple("".join(rng.choices(alphabet, k=rng.randrange(1, 24)))
                for _ in range(n))
    nodes = [f"node-{i}" for i in range(rng.randrange(1, 6))]
    nids = tuple(rng.choice(nodes) if rng.random() > 0.05 else ""
                 for _ in range(n))
    return TaskBlockAction(
        "task_block", ids, nids, rng.randrange(0, 1 << 40),
        rng.randrange(0, int(TaskState.RUNNING) + 1),
        "scheduler assigned task to node"[:rng.randrange(0, 31)],
        rng.random() * 1e9)


def test_block_codec_native_matches_python_oracle():
    """Random blocks x seeds: serde.block_to_bytes must round-trip
    identically through the native block_decode and the pure-Python
    block_from_bytes oracle."""
    _require_native()
    hp = native.get()
    for seed in range(5):
        rng = random.Random(seed)
        for _ in range(60):
            action = _random_block(rng)
            data = serde.block_to_bytes(action)
            assert data is not None
            assert data[:4] == serde.BLOCK_ENTRY_MAGIC
            assert serde.block_from_bytes(data) == action
            assert hp.block_decode(data, TaskBlockAction) == action


def test_block_codec_rejects_corruption():
    """Truncated, padded, and structurally-corrupt entries must raise
    ValueError on BOTH decoders — native and oracle must agree on every
    byte string, or members running different planes diverge on
    identical replicated bytes."""
    import struct
    _require_native()
    hp = native.get()
    data = serde.block_to_bytes(_random_block(random.Random(1), n=12))
    corrupt = [data[:cut] for cut in (0, 3, 17, len(data) - 1)]
    corrupt.append(data + b"x")
    # extra NUL separators: n=2 but three id segments
    hdr = struct.pack("<4sIqidI", b"SKB1", 2, 5, 2, 1.0, 1) + b"m"
    blob = b"a\x00b\x00c"
    corrupt.append(hdr + struct.pack("<I", len(blob)) + blob
                   + struct.pack("<II", 1, 2)
                   + struct.pack("<I", 2) + b"n1")
    # n=0 with a dangling non-empty ids blob
    hdr0 = struct.pack("<4sIqidI", b"SKB1", 0, 5, 2, 1.0, 0)
    corrupt.append(hdr0 + struct.pack("<I", 3) + b"xyz"
                   + struct.pack("<I", 0) + struct.pack("<I", 0))
    for bad in corrupt:
        with pytest.raises(ValueError):
            serde.block_from_bytes(bad)
        with pytest.raises(ValueError):
            hp.block_decode(bad, TaskBlockAction)


def test_entry_codec_fallbacks():
    """NUL in an id forces the JSON change-list form; the escape hatch
    forces it too; decode always accepts BOTH wire forms (replicated
    bytes must apply regardless of the local hatch)."""
    odd = TaskBlockAction("task_block", ("a\x00b",), ("n1",), 1, 2,
                          "m", 3.0)
    assert serde.block_to_bytes(odd) is None
    data = serde.actions_to_entry_data([odd])
    assert data[:1] == b"[" and serde.entry_to_actions(data) == [odd]

    plain = _random_block(random.Random(2), n=8)
    binary = serde.actions_to_entry_data([plain])
    assert binary[:4] == serde.BLOCK_ENTRY_MAGIC
    os.environ["SWARM_NATIVE_COMMIT"] = "0"
    try:
        hatched = serde.actions_to_entry_data([plain])
        assert hatched[:1] == b"["
        # decode side is hatch-agnostic: binary bytes still apply
        assert serde.entry_to_actions(binary) == [plain]
        assert serde.entry_to_actions(hatched) == [plain]
    finally:
        del os.environ["SWARM_NATIVE_COMMIT"]
    assert serde.entry_to_actions(binary) == [plain]


def test_native_commit_fallback_counter(monkeypatch):
    """Native requested but unavailable counts fallback ticks (bench
    gate evidence); the explicit escape hatch does not."""
    from swarmkit_tpu.utils.metrics import registry
    monkeypatch.setenv("SWARMKIT_TPU_NO_NATIVE", "1")
    base = registry.get_counter("swarm_native_commit_fallbacks")
    assert native.get_commit() is None
    assert registry.get_counter("swarm_native_commit_fallbacks") \
        == base + 1
    monkeypatch.setenv("SWARM_NATIVE_COMMIT", "0")
    assert native.get_commit() is None
    assert registry.get_counter("swarm_native_commit_fallbacks") \
        == base + 1   # hatch pulled: intentional, not a fallback


# ---------------------------------------------------------------------------
# native watch fan-out vs the Python oracle
# ---------------------------------------------------------------------------

def _mk_block_tasks(n, rng):
    out = []
    for i in range(n):
        t = Task(id=f"t{i:04d}", service_id="svc", slot=i + 1,
                 status=TaskStatus(state=TaskState.PENDING, message="p"),
                 desired_state=TaskState.RUNNING)
        t.meta.version.index = rng.randrange(50)
        t.meta.created_at = 5.0
        out.append(t)
    return out


def _event_key(ev):
    if isinstance(ev, EventCommit):
        return ("commit", ev.version)
    if isinstance(ev, Event):
        return (ev.action, ev.version, serde.to_dict(ev.obj),
                serde.to_dict(ev.old) if ev.old is not None else None)
    return ("block", serde.to_dict(ev.expand_events()[0].obj)
            if len(ev) else None, len(ev))


def test_fanout_expand_matches_oracle(monkeypatch):
    _require_native()
    rng = random.Random(3)
    for n in (0, 1, 17, 50):
        olds = _mk_block_tasks(n, rng)
        nids = [f"n{rng.randrange(3)}" for _ in range(n)]
        args = (olds, nids, 700, int(TaskState.ASSIGNED), "assigned",
                42.5)
        ev_native = EventTaskBlock(*args).expand_events()
        monkeypatch.setenv("SWARM_NATIVE_COMMIT", "0")
        ev_python = EventTaskBlock(*args).expand_events()
        monkeypatch.delenv("SWARM_NATIVE_COMMIT")
        assert [_event_key(e) for e in ev_native] \
            == [_event_key(e) for e in ev_python]
        for a, b in zip(ev_native, ev_python):
            assert a.old is b.old   # both reference the stored mirror


def test_per_node_group_matches_oracle(monkeypatch):
    _require_native()
    rng = random.Random(4)
    olds = _mk_block_tasks(40, rng)
    nids = [f"n{rng.randrange(4)}" for _ in range(40)]
    args = (olds, nids, 100, int(TaskState.ASSIGNED), "m", 1.0)
    g_native = EventTaskBlock(*args).per_node()
    monkeypatch.setenv("SWARM_NATIVE_COMMIT", "0")
    g_python = EventTaskBlock(*args).per_node()
    monkeypatch.delenv("SWARM_NATIVE_COMMIT")
    assert list(g_native) == list(g_python)   # insertion order too
    for k in g_native:
        assert [(o.id, v) for o, v in g_native[k]] \
            == [(o.id, v) for o, v in g_python[k]]


def test_fanout_filter_matches_oracle_with_raising_predicate():
    _require_native()
    hp = native.get()
    rng = random.Random(5)
    olds = _mk_block_tasks(20, rng)
    events = EventTaskBlock(olds, ["n1"] * 20, 0,
                            int(TaskState.ASSIGNED), "m",
                            1.0).expand_events()

    def pred(ev):
        if ev.obj.slot % 7 == 0:
            raise RuntimeError("predicate boom")
        return ev.obj.slot % 2 == 0

    oracle = []
    for e in events:
        try:
            if pred(e):
                oracle.append(e)
        except Exception:
            continue
    assert hp.fanout_filter(events, pred) == oracle
    assert len(oracle) > 0


# ---------------------------------------------------------------------------
# byte-identity across SWARM_NATIVE_COMMIT={0,1} and both raft routes
# ---------------------------------------------------------------------------

def _mk_node(name):
    return Node(id=f"node-{name}",
                spec=NodeSpec(annotations=Annotations(name=name)))


def _drive_workload(store, n_tasks=37):
    """Deterministic mixed workload: block commits (two blocks), a
    delete burst, and a per-object update — the stream shapes satellite
    3 pins (blocks, deletes, resume-token stamping)."""
    nodes = [_mk_node(f"n{i}") for i in range(4)]
    tasks = [Task(id=f"task-{i:04d}", service_id="svc", slot=i + 1,
                  desired_state=TaskState.RUNNING,
                  status=TaskStatus(state=TaskState.PENDING))
             for i in range(n_tasks)]

    def setup(tx):
        for n in nodes:
            tx.create(n)
        for t in tasks:
            tx.create(t)
    store.update(setup)
    stored = sorted(store.view(lambda tx: tx.find(Task)),
                    key=lambda t: t.slot)

    def boom(*a):
        raise AssertionError("unexpected callback")

    half = n_tasks // 2
    c1, f1 = store.commit_task_block(
        stored[:half], [nodes[i % 4].id for i in range(half)],
        int(TaskState.ASSIGNED), "assigned", boom, boom)
    assert len(c1) == half and not f1
    # delete events interleave the block stream
    def deletes(tx):
        for t in stored[half:half + 3]:
            tx.delete(Task, t.id)
    store.update(deletes)
    rest = stored[half + 3:]
    c2, f2 = store.commit_task_block(
        rest, [nodes[(i + 1) % 4].id for i in range(len(rest))],
        int(TaskState.ASSIGNED), "assigned", boom, boom)
    assert len(c2) == len(rest) and not f2
    # a per-object update rides the JSON form alongside the blocks
    n0 = store.view(lambda tx: tx.get(Node, nodes[0].id)).copy()
    n0.spec.annotations.labels["zone"] = "z1"
    store.update(lambda tx: tx.update(n0))


def _run_plane(native_on, route, monkeypatch):
    """One full run: returns (snapshot bytes, per-item subscriber
    stream, block-aware subscriber stream, resume replay) fingerprints."""
    if native_on:
        monkeypatch.delenv("SWARM_NATIVE_COMMIT", raising=False)
    else:
        monkeypatch.setenv("SWARM_NATIVE_COMMIT", "0")
    store = MemoryStore()
    tmp = rn = None
    if route == "raft":
        from swarmkit_tpu.state.raft import (
            LocalNetwork, RaftLogger, RaftNode,
        )
        import time as _time
        tmp = tempfile.mkdtemp(prefix="colcommit-")
        rn = RaftNode("m0", ["m0"], store,
                      RaftLogger(os.path.join(tmp, "m0")), LocalNetwork(),
                      tick_interval=0.005)
        store._proposer = rn
        rn.start()
        deadline = _time.monotonic() + 15
        while not (rn.is_leader and rn.core.leader_ready):
            assert _time.monotonic() < deadline, "no leader"
            _time.sleep(0.005)
    per_item = store.queue.subscribe()
    block_aware = store.queue.subscribe(accepts_blocks=True)
    try:
        _drive_workload(store)
        snap = store.save_bytes()
        items = [_event_key(e) for e in per_item.drain()]
        blocks = [_event_key(e) for e in block_aware.drain()]
        replay = [_event_key(e) for e in store.changes_between(0)]
        return snap, items, blocks, replay
    finally:
        if rn is not None:
            rn.stop()
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


@pytest.mark.parametrize("route", ["standalone", "raft"])
def test_byte_identity_across_native_modes(route, frozen_clock,
                                           monkeypatch):
    """Snapshot bytes, per-subscriber watch streams (per-item AND
    block-aware), and resume replays must be byte-identical between the
    native commit plane and the pure-Python oracle, on both raft
    routes."""
    _require_native()
    snap_n, items_n, blocks_n, replay_n = _run_plane(
        True, route, monkeypatch)
    mtypes.set_time_source(None)   # re-freeze identically for run 2

    t = [1_000_000.0]

    def tick():
        t[0] += 0.001
        return t[0]
    mtypes.set_time_source(tick)
    snap_p, items_p, blocks_p, replay_p = _run_plane(
        False, route, monkeypatch)
    assert snap_n == snap_p
    assert items_n == items_p
    assert blocks_n == blocks_p
    assert replay_n == replay_p
    assert any(k[0] == "delete" for k in items_n)
    # resume tokens: every replayed event carries an exact version stamp
    versions = [k[1] for k in replay_n if k[0] == "update"]
    assert versions == sorted(versions) and versions


def test_follower_apply_differential(frozen_clock, monkeypatch):
    """apply_store_actions over binary-decoded blocks: the native
    follower apply and the Python loop must converge followers
    bit-for-bit (snapshot bytes, streams, by_node bucket order)."""
    _require_native()

    def build_leaderish():
        store = MemoryStore()
        nodes = [_mk_node(f"n{i}") for i in range(3)]
        tasks = [Task(id=f"task-{i:04d}", service_id="svc", slot=i + 1,
                      desired_state=TaskState.RUNNING,
                      status=TaskStatus(state=TaskState.PENDING))
                 for i in range(25)]

        def setup(tx):
            for n in nodes:
                tx.create(n)
            for t in tasks:
                tx.create(t)
        store.update(setup)
        return store, nodes, tasks

    # one canonical entry stream produced by a "leader"
    leader, nodes, tasks = build_leaderish()
    action = TaskBlockAction(
        "task_block", tuple(t.id for t in tasks),
        tuple(nodes[i % 3].id for i in range(len(tasks))),
        leader.version, int(TaskState.ASSIGNED), "assigned", 123.25)
    entry = serde.actions_to_entry_data([action])
    assert entry[:4] == serde.BLOCK_ENTRY_MAGIC

    def follower_state(native_on):
        if native_on:
            monkeypatch.delenv("SWARM_NATIVE_COMMIT", raising=False)
        else:
            monkeypatch.setenv("SWARM_NATIVE_COMMIT", "0")
        store, _nodes, _tasks = build_leaderish()
        sub = store.queue.subscribe()
        store.apply_store_actions(serde.entry_to_actions(entry))
        stream = [_event_key(e) for e in sub.drain()]
        buckets = {nid: list(b)
                   for nid, b in store._tables["tasks"].by_node.items()}
        return store.save_bytes(), stream, buckets, store.version

    mtypes.set_time_source(None)
    t = [2_000_000.0]
    mtypes.set_time_source(lambda: (t.__setitem__(0, t[0] + 0.001)
                                    or t[0]))
    sn, st_n, bk_n, vn = follower_state(True)
    mtypes.set_time_source(None)
    t = [2_000_000.0]
    mtypes.set_time_source(lambda: (t.__setitem__(0, t[0] + 0.001)
                                    or t[0]))
    sp, st_p, bk_p, vp = follower_state(False)
    assert sn == sp and st_n == st_p and vn == vp
    assert bk_n == bk_p
    for nid in bk_n:
        assert bk_n[nid] == bk_p[nid]   # insertion order preserved


def test_follower_apply_diverged_falls_back(frozen_clock):
    """A block naming an unknown id (diverged follower) must take the
    Python slow path: skipped ids burn their version indices and the
    applied remainder publishes per-item events with exact stamps."""
    _require_native()
    store = MemoryStore()
    store.update(lambda tx: tx.create(_mk_node("n0")))
    tasks = [Task(id=f"task-{i}", service_id="svc", slot=i + 1,
                  status=TaskStatus(state=TaskState.PENDING))
             for i in range(3)]
    store.update(lambda tx: [tx.create(t) for t in tasks] and None)
    base = store.version
    action = TaskBlockAction(
        "task_block", (tasks[0].id, "ghost", tasks[2].id),
        ("node-n0", "node-n0", "node-n0"), base,
        int(TaskState.ASSIGNED), "assigned", 1.0)
    sub = store.queue.subscribe()
    store.apply_store_actions([action])
    events = [e for e in sub.drain() if isinstance(e, Event)]
    assert [e.version for e in events] == [base + 1, base + 3]
    assert store.version == base + 3


# ---------------------------------------------------------------------------
# bench gates
# ---------------------------------------------------------------------------

def test_bench_compare_commit_plane_gates(tmp_path):
    """bench_compare exits 1 when cfg6 commit_phase_s regresses > 20%
    or when the native commit plane fell back to Python in the timed
    window; the explicit escape hatch (enabled=False) is exempt."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import bench_compare as bc

    def doc(commit, nc):
        return {"value": 250000, "configs": {
            "6_live_manager_2x100k_x_10k": {
                "decisions_per_sec": 100000, "shape_cost_x": 1.2,
                "commit_phase_s": commit, "native_commit": nc,
                "compiles": {}}}}

    def run(old, new, tag):
        a = tmp_path / f"old-{tag}.json"
        b = tmp_path / f"new-{tag}.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        import contextlib
        import io
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf), \
                contextlib.redirect_stderr(buf):
            return bc.main([str(a), str(b)])

    ok = {"enabled": True, "active": True, "fallbacks": 0}
    assert run(doc(1.0, ok), doc(1.1, ok), "within") == 0
    assert run(doc(1.0, ok), doc(1.3, ok), "regressed") == 1
    assert run(doc(1.0, ok),
               doc(1.0, {"enabled": True, "active": True,
                         "fallbacks": 3}), "fellback") == 1
    assert run(doc(1.0, ok),
               doc(1.0, {"enabled": True, "active": False,
                         "fallbacks": 0}), "inactive") == 1
    assert run(doc(1.0, ok),
               doc(1.0, {"enabled": False, "active": False,
                         "fallbacks": 0}), "hatch") == 0


# ---------------------------------------------------------------------------
# sim: the raft_cp plane rides the columnar commit end to end
# ---------------------------------------------------------------------------

def test_sim_scenario_deterministic_with_native_commit_plane():
    """fused-differential-churn under the native columnar commit plane:
    green, re-run byte-identical, and the coverage line proving a binary
    block rode consensus with native decode active is in the trace."""
    _require_native()
    import logging
    logging.disable(logging.CRITICAL)
    from swarmkit_tpu.sim.scenario import run_scenario
    # warm run: jit signatures compile once per process; a cold run's
    # one-off plan.compile spans would break byte-identity against the
    # warm re-run (preemption-storm discipline)
    run_scenario("fused-differential-churn", seed=11)
    r1 = run_scenario("fused-differential-churn", seed=11,
                      keep_trace=True)
    assert r1.ok, r1.violations
    assert any("fault native-commit-plane store" in line
               for line in r1.trace), \
        "the native columnar commit plane never carried a block"
    r2 = run_scenario("fused-differential-churn", seed=11)
    assert r2.trace_hash == r1.trace_hash
    assert r2.obs_trace_sha256 == r1.obs_trace_sha256


@pytest.mark.slow
def test_sim_columnar_commit_wide_sweep():
    """Acceptance sweep (satellite 4): 20 seeds of the raft_cp
    differential scenario under the columnar commit plane, all green
    with the native-commit coverage cell filled, byte-identical re-runs
    for sampled seeds."""
    _require_native()
    import logging
    logging.disable(logging.CRITICAL)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import chaos_sweep
    from swarmkit_tpu.sim.scenario import run_scenario
    run_scenario("fused-differential-churn", 0)   # warm jit signatures
    reports = chaos_sweep.sweep(("fused-differential-churn",),
                                n_seeds=20)
    out = chaos_sweep.verdict(reports, ("fused-differential-churn",),
                              20, 0)
    assert out["ok"], json.dumps(
        {"failures": out["failures"],
         "uncovered": out["coverage"]["uncovered"]}, indent=2)
    assert out["coverage"]["matrix"]["native-commit-plane"]["store"] > 0
    by_seed = {r.seed: r for r in reports}
    for seed in (0, 7, 13):
        r2 = run_scenario("fused-differential-churn", seed,
                          keep_trace=True)
        assert r2.trace_hash == by_seed[seed].trace_hash, seed
        assert r2.obs_trace_sha256 == by_seed[seed].obs_trace_sha256, \
            seed


@pytest.mark.slow
def test_sim_columnar_commit_hashseed_independent():
    """Byte-identical across PYTHONHASHSEED with the native commit
    plane on: hash-ordered containers must not leak into the columnar
    encode/decode/fan-out order."""
    code = ("from swarmkit_tpu.sim.scenario import run_scenario;"
            "r = run_scenario('fused-differential-churn', 0);"
            "print(r.trace_hash, r.obs_trace_sha256, r.ok)")
    outs = []
    for hs in ("0", "31337"):
        env = dict(os.environ, PYTHONHASHSEED=hs, JAX_PLATFORMS="cpu")
        env.pop("SWARM_NATIVE_COMMIT", None)
        p = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           env=env, capture_output=True, text=True,
                           timeout=600)
        assert p.returncode == 0, p.stderr[-2000:]
        outs.append(p.stdout.strip().splitlines()[-1])
    assert outs[0] == outs[1], outs
    assert outs[0].endswith("True"), outs
