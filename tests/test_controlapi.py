"""Control API tests: validated CRUD with reference-parity error messages
(mirrors manager/controlapi/*_test.go assertions)."""

import pytest

from swarmkit_tpu.manager import ControlAPI
from swarmkit_tpu.manager.controlapi import (
    AlreadyExists, FailedPrecondition, InvalidArgument, NotFound,
)
from swarmkit_tpu.models import (
    Annotations, EndpointSpec, NodeState, PortConfig, PublishMode,
    ReplicatedService, Resources, ResourceRequirements, ServiceMode,
    TaskSpec, UpdateConfig,
)
from swarmkit_tpu.models.specs import (
    ConfigSpec, ContainerSpec, NodeSpec, SecretSpec, ServiceSpec,
)
from swarmkit_tpu.models.types import NodeRole, SecretReference
from swarmkit_tpu.state import MemoryStore

from test_orchestrator import make_node


def spec(name="web", replicas=1, image="nginx", **kw):
    return ServiceSpec(
        annotations=Annotations(name=name),
        task=TaskSpec(container=ContainerSpec(image=image)),
        mode=ServiceMode.REPLICATED,
        replicated=ReplicatedService(replicas=replicas),
        **kw,
    )


@pytest.fixture
def api():
    return ControlAPI(MemoryStore())


def test_create_service_validates_name(api):
    with pytest.raises(InvalidArgument, match="meta: name must be provided"):
        api.create_service(spec(name=""))
    with pytest.raises(InvalidArgument,
                       match="name must be valid as a DNS name component"):
        api.create_service(spec(name="not valid!"))
    with pytest.raises(InvalidArgument,
                       match="name must be 63 characters or fewer"):
        api.create_service(spec(name="x" * 64))


def test_create_service_validates_runtime_and_resources(api):
    s = spec()
    s.task.container = None
    with pytest.raises(InvalidArgument, match="TaskSpec: missing runtime"):
        api.create_service(s)

    s = spec()
    s.task.container.image = ""
    with pytest.raises(InvalidArgument,
                       match="image reference must be provided"):
        api.create_service(s)

    s = spec()
    s.task.resources = ResourceRequirements(
        reservations=Resources(memory_bytes=1024))
    with pytest.raises(InvalidArgument, match="Must be at least 4MiB"):
        api.create_service(s)


def test_create_service_name_conflict(api):
    api.create_service(spec(name="web"))
    with pytest.raises(AlreadyExists):
        api.create_service(spec(name="web"))


def test_create_service_missing_secret(api):
    s = spec()
    s.task.container.secrets = [
        SecretReference(secret_id="nope", secret_name="missing",
                        target="cert")]
    with pytest.raises(InvalidArgument, match="secret not found: missing"):
        api.create_service(s)


def test_create_service_with_existing_secret(api):
    secret = api.create_secret(SecretSpec(
        annotations=Annotations(name="tls-cert"), data=b"shh"))
    s = spec()
    s.task.container.secrets = [
        SecretReference(secret_id=secret.id, secret_name="tls-cert",
                        target="cert")]
    created = api.create_service(s)
    assert created.spec.task.container.secrets[0].secret_id == secret.id


def test_update_service_rules(api):
    created = api.create_service(spec(name="web", replicas=2))
    new_spec = spec(name="web", replicas=5)
    updated = api.update_service(created.id, created.meta.version.index,
                                 new_spec)
    assert updated.spec.replicated.replicas == 5
    assert updated.previous_spec is not None
    assert updated.spec_version.index > created.spec_version.index

    with pytest.raises(InvalidArgument,
                       match="renaming services is not supported"):
        api.update_service(updated.id, updated.meta.version.index,
                           spec(name="web2", replicas=5))

    bad = spec(name="web", replicas=5)
    bad.mode = ServiceMode.GLOBAL
    bad.replicated = None
    with pytest.raises(InvalidArgument,
                       match="service mode change is not allowed"):
        api.update_service(updated.id, updated.meta.version.index, bad)

    # stale version -> FailedPrecondition
    with pytest.raises(FailedPrecondition):
        api.update_service(updated.id, updated.meta.version.index - 1,
                           spec(name="web", replicas=7))


def test_ingress_port_conflict(api):
    s1 = spec(name="a")
    s1.endpoint = EndpointSpec(ports=[PortConfig(
        target_port=80, published_port=8080,
        publish_mode=PublishMode.INGRESS)])
    api.create_service(s1)
    s2 = spec(name="b")
    s2.endpoint = EndpointSpec(ports=[PortConfig(
        target_port=80, published_port=8080,
        publish_mode=PublishMode.INGRESS)])
    with pytest.raises(InvalidArgument,
                       match="already in use by service 'a'"):
        api.create_service(s2)


def test_remove_service(api):
    created = api.create_service(spec())
    api.remove_service(created.id)
    with pytest.raises(NotFound):
        api.get_service(created.id)
    with pytest.raises(NotFound):
        api.remove_service(created.id)


def test_node_remove_rules(api):
    node = make_node("n1")
    api.store.update(lambda tx: tx.create(node))
    with pytest.raises(FailedPrecondition,
                       match="is not down and can't be removed"):
        api.remove_node(node.id)
    api.remove_node(node.id, force=True)
    with pytest.raises(NotFound):
        api.get_node(node.id)


def test_demote_last_manager_fails(api):
    node = make_node("m1")
    node.spec.desired_role = NodeRole.MANAGER
    api.store.update(lambda tx: tx.create(node))
    demote = NodeSpec(annotations=Annotations(name="m1"),
                      desired_role=NodeRole.WORKER)
    with pytest.raises(FailedPrecondition,
                       match="attempting to demote the last manager"):
        api.update_node(node.id, node.meta.version.index, demote)


def test_secret_lifecycle(api):
    with pytest.raises(InvalidArgument):
        api.create_secret(SecretSpec(annotations=Annotations(name="s"),
                                     data=b""))
    secret = api.create_secret(SecretSpec(
        annotations=Annotations(name="s"), data=b"data"))
    with pytest.raises(AlreadyExists):
        api.create_secret(SecretSpec(annotations=Annotations(name="s"),
                                     data=b"x"))

    # list hides data
    listed = api.list_secrets()
    assert listed[0].spec.data == b""
    assert api.get_secret(secret.id).spec.data == b"data"

    with pytest.raises(InvalidArgument,
                       match="only updates to Labels are allowed"):
        api.update_secret(secret.id, secret.meta.version.index,
                          SecretSpec(annotations=Annotations(name="s"),
                                     data=b"different"))
    updated = api.update_secret(
        secret.id, secret.meta.version.index,
        SecretSpec(annotations=Annotations(name="s",
                                           labels={"env": "prod"})))
    assert updated.spec.annotations.labels == {"env": "prod"}
    assert api.get_secret(secret.id).spec.data == b"data"

    api.remove_secret(secret.id)
    with pytest.raises(NotFound):
        api.get_secret(secret.id)


def test_remove_secret_in_use(api):
    secret = api.create_secret(SecretSpec(
        annotations=Annotations(name="tls"), data=b"shh"))
    s = spec(name="web")
    s.task.container.secrets = [
        SecretReference(secret_id=secret.id, secret_name="tls",
                        target="cert")]
    svc = api.create_service(s)
    # materialize a task referencing the secret (orchestrator would)
    from swarmkit_tpu.orchestrator.common import new_task
    t = new_task(None, api.store.view(
        lambda tx: tx.get(type(svc), svc.id)), 1, "")
    api.store.update(lambda tx: tx.create(t))
    with pytest.raises(InvalidArgument,
                       match="is in use by the following service: web"):
        api.remove_secret(secret.id)


def test_update_config_validation(api):
    s = spec()
    s.update = UpdateConfig(max_failure_ratio=1.5)
    with pytest.raises(InvalidArgument, match="maxfailureratio"):
        api.create_service(s)


def test_network_ipam_allocation():
    """Networks get subnets carved from the default pool; services on
    them get VIPs; tasks get per-network addresses (reference:
    manager/allocator network allocation)."""
    import time

    from swarmkit_tpu.manager.allocator import Allocator
    from swarmkit_tpu.models import (
        Annotations, Network, NetworkAttachmentConfig, Task, TaskState,
    )
    from swarmkit_tpu.models.specs import NetworkSpec
    from swarmkit_tpu.state import ByService

    from test_orchestrator import poll

    store = MemoryStore()
    api = ControlAPI(store)
    alloc = Allocator(store)
    alloc.start()
    try:
        n1 = api.create_network(NetworkSpec(
            annotations=Annotations(name="backend")))
        n2 = api.create_network(NetworkSpec(
            annotations=Annotations(name="frontend")))
        poll(lambda: store.view(
            lambda tx: tx.get(Network, n1.id)).ipam is not None,
            msg="subnet allocated")
        nets = store.view(lambda tx: [tx.get(Network, i)
                                      for i in (n1.id, n2.id)])
        subnets = [n.ipam.configs[0].subnet for n in nets]
        assert len(set(subnets)) == 2, "distinct subnets"
        assert all(s.endswith("/24") for s in subnets), subnets
        gws = [n.ipam.configs[0].gateway for n in nets]
        assert all(g.endswith(".1") for g in gws), gws

        # service attached to both networks: VIP per network
        svc_spec = spec("webnet", replicas=2)
        svc_spec.task.networks = [
            NetworkAttachmentConfig(target="backend"),
            NetworkAttachmentConfig(target=n2.id)]
        svc = api.create_service(svc_spec)
        poll(lambda: (api.get_service(svc.id).endpoint is not None
                      and len(api.get_service(svc.id)
                              .endpoint.virtual_ips) == 2),
             msg="VIPs on both networks")
        vips = api.get_service(svc.id).endpoint.virtual_ips
        assert {v.network_id for v in vips} == {n1.id, n2.id}
        assert all(v.addr for v in vips)

        # tasks carry per-network addresses, all distinct (created
        # directly: no orchestrator runs in this test)
        from swarmkit_tpu.models.types import TaskStatus
        from swarmkit_tpu.utils import new_id

        def mk(tx):
            for slot in (1, 2):
                tx.create(Task(
                    id=new_id(), service_id=svc.id, slot=slot,
                    spec=svc_spec.task.copy(),
                    status=TaskStatus(state=TaskState.NEW),
                    desired_state=TaskState.RUNNING))
        store.update(mk)

        def task_addrs():
            ts = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
            if len(ts) < 2 or any(
                    t.status.state < TaskState.PENDING for t in ts):
                return None
            return [a for t in ts for att in t.networks
                    for a in att.addresses]
        addrs = poll(task_addrs, msg="task addresses allocated")
        assert len(addrs) == 4                     # 2 tasks x 2 networks
        assert len(set(addrs)) == 4, "addresses must be unique"
        vip_addrs = {v.addr for v in vips}
        assert not vip_addrs & set(addrs), "VIPs never reused for tasks"
    finally:
        alloc.stop()
