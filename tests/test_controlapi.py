"""Control API tests: validated CRUD with reference-parity error messages
(mirrors manager/controlapi/*_test.go assertions)."""

import pytest

from swarmkit_tpu.manager import ControlAPI
from swarmkit_tpu.manager.controlapi import (
    AlreadyExists, FailedPrecondition, InvalidArgument, NotFound,
)
from swarmkit_tpu.models import (
    Annotations, EndpointSpec, NodeState, PortConfig, PublishMode,
    ReplicatedService, Resources, ResourceRequirements, ServiceMode,
    TaskSpec, UpdateConfig,
)
from swarmkit_tpu.models.specs import (
    ConfigSpec, ContainerSpec, NodeSpec, SecretSpec, ServiceSpec,
)
from swarmkit_tpu.models.types import NodeRole, SecretReference
from swarmkit_tpu.state import MemoryStore

from test_orchestrator import make_node

from swarmkit_tpu.security.ca import HAVE_CRYPTOGRAPHY

requires_crypto = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="requires the 'cryptography' package")



def spec(name="web", replicas=1, image="nginx", **kw):
    return ServiceSpec(
        annotations=Annotations(name=name),
        task=TaskSpec(container=ContainerSpec(image=image)),
        mode=ServiceMode.REPLICATED,
        replicated=ReplicatedService(replicas=replicas),
        **kw,
    )


@pytest.fixture
def api():
    return ControlAPI(MemoryStore())


def test_create_service_validates_name(api):
    with pytest.raises(InvalidArgument, match="meta: name must be provided"):
        api.create_service(spec(name=""))
    with pytest.raises(InvalidArgument,
                       match="name must be valid as a DNS name component"):
        api.create_service(spec(name="not valid!"))
    with pytest.raises(InvalidArgument,
                       match="name must be 63 characters or fewer"):
        api.create_service(spec(name="x" * 64))


def test_create_service_validates_runtime_and_resources(api):
    s = spec()
    s.task.container = None
    with pytest.raises(InvalidArgument, match="TaskSpec: missing runtime"):
        api.create_service(s)

    s = spec()
    s.task.container.image = ""
    with pytest.raises(InvalidArgument,
                       match="image reference must be provided"):
        api.create_service(s)

    s = spec()
    s.task.resources = ResourceRequirements(
        reservations=Resources(memory_bytes=1024))
    with pytest.raises(InvalidArgument, match="Must be at least 4MiB"):
        api.create_service(s)


def test_create_service_name_conflict(api):
    api.create_service(spec(name="web"))
    with pytest.raises(AlreadyExists):
        api.create_service(spec(name="web"))


def test_create_service_missing_secret(api):
    s = spec()
    s.task.container.secrets = [
        SecretReference(secret_id="nope", secret_name="missing",
                        target="cert")]
    with pytest.raises(InvalidArgument, match="secret not found: missing"):
        api.create_service(s)


def test_create_service_with_existing_secret(api):
    secret = api.create_secret(SecretSpec(
        annotations=Annotations(name="tls-cert"), data=b"shh"))
    s = spec()
    s.task.container.secrets = [
        SecretReference(secret_id=secret.id, secret_name="tls-cert",
                        target="cert")]
    created = api.create_service(s)
    assert created.spec.task.container.secrets[0].secret_id == secret.id


def test_update_service_rules(api):
    created = api.create_service(spec(name="web", replicas=2))
    new_spec = spec(name="web", replicas=5)
    updated = api.update_service(created.id, created.meta.version.index,
                                 new_spec)
    assert updated.spec.replicated.replicas == 5
    assert updated.previous_spec is not None
    assert updated.spec_version.index > created.spec_version.index

    with pytest.raises(InvalidArgument,
                       match="renaming services is not supported"):
        api.update_service(updated.id, updated.meta.version.index,
                           spec(name="web2", replicas=5))

    bad = spec(name="web", replicas=5)
    bad.mode = ServiceMode.GLOBAL
    bad.replicated = None
    with pytest.raises(InvalidArgument,
                       match="service mode change is not allowed"):
        api.update_service(updated.id, updated.meta.version.index, bad)

    # stale version -> FailedPrecondition
    with pytest.raises(FailedPrecondition):
        api.update_service(updated.id, updated.meta.version.index - 1,
                           spec(name="web", replicas=7))


def test_ingress_port_conflict(api):
    s1 = spec(name="a")
    s1.endpoint = EndpointSpec(ports=[PortConfig(
        target_port=80, published_port=8080,
        publish_mode=PublishMode.INGRESS)])
    api.create_service(s1)
    s2 = spec(name="b")
    s2.endpoint = EndpointSpec(ports=[PortConfig(
        target_port=80, published_port=8080,
        publish_mode=PublishMode.INGRESS)])
    with pytest.raises(InvalidArgument,
                       match="already in use by service 'a'"):
        api.create_service(s2)


def test_remove_service(api):
    created = api.create_service(spec())
    api.remove_service(created.id)
    with pytest.raises(NotFound):
        api.get_service(created.id)
    with pytest.raises(NotFound):
        api.remove_service(created.id)


def test_node_remove_rules(api):
    node = make_node("n1")
    api.store.update(lambda tx: tx.create(node))
    with pytest.raises(FailedPrecondition,
                       match="is not down and can't be removed"):
        api.remove_node(node.id)
    api.remove_node(node.id, force=True)
    with pytest.raises(NotFound):
        api.get_node(node.id)


def test_demote_last_manager_fails(api):
    node = make_node("m1")
    node.spec.desired_role = NodeRole.MANAGER
    api.store.update(lambda tx: tx.create(node))
    demote = NodeSpec(annotations=Annotations(name="m1"),
                      desired_role=NodeRole.WORKER)
    with pytest.raises(FailedPrecondition,
                       match="attempting to demote the last manager"):
        api.update_node(node.id, node.meta.version.index, demote)


def test_secret_lifecycle(api):
    with pytest.raises(InvalidArgument):
        api.create_secret(SecretSpec(annotations=Annotations(name="s"),
                                     data=b""))
    secret = api.create_secret(SecretSpec(
        annotations=Annotations(name="s"), data=b"data"))
    with pytest.raises(AlreadyExists):
        api.create_secret(SecretSpec(annotations=Annotations(name="s"),
                                     data=b"x"))

    # the payload never leaves the manager — list AND get strip it
    # (reference: secret.go:44,143); the stored object keeps it
    listed = api.list_secrets()
    assert listed[0].spec.data == b""
    assert api.get_secret(secret.id).spec.data == b""
    from swarmkit_tpu.models import Secret as _Secret
    assert api.store.view(
        lambda tx: tx.get(_Secret, secret.id)).spec.data == b"data"

    with pytest.raises(InvalidArgument,
                       match="only updates to Labels are allowed"):
        api.update_secret(secret.id, secret.meta.version.index,
                          SecretSpec(annotations=Annotations(name="s"),
                                     data=b"different"))
    updated = api.update_secret(
        secret.id, secret.meta.version.index,
        SecretSpec(annotations=Annotations(name="s",
                                           labels={"env": "prod"})))
    assert updated.spec.annotations.labels == {"env": "prod"}
    assert updated.spec.data == b""   # responses stay stripped
    assert api.store.view(
        lambda tx: tx.get(_Secret, secret.id)).spec.data == b"data"

    api.remove_secret(secret.id)
    with pytest.raises(NotFound):
        api.get_secret(secret.id)


def test_remove_secret_in_use(api):
    secret = api.create_secret(SecretSpec(
        annotations=Annotations(name="tls"), data=b"shh"))
    s = spec(name="web")
    s.task.container.secrets = [
        SecretReference(secret_id=secret.id, secret_name="tls",
                        target="cert")]
    svc = api.create_service(s)
    # materialize a task referencing the secret (orchestrator would)
    from swarmkit_tpu.orchestrator.common import new_task
    t = new_task(None, api.store.view(
        lambda tx: tx.get(type(svc), svc.id)), 1, "")
    api.store.update(lambda tx: tx.create(t))
    with pytest.raises(InvalidArgument,
                       match="is in use by the following service: web"):
        api.remove_secret(secret.id)


def test_update_config_validation(api):
    s = spec()
    s.update = UpdateConfig(max_failure_ratio=1.5)
    with pytest.raises(InvalidArgument, match="maxfailureratio"):
        api.create_service(s)


def test_network_ipam_allocation():
    """Networks get subnets carved from the default pool; services on
    them get VIPs; tasks get per-network addresses (reference:
    manager/allocator network allocation)."""
    import time

    from swarmkit_tpu.manager.allocator import Allocator
    from swarmkit_tpu.models import (
        Annotations, Network, NetworkAttachmentConfig, Task, TaskState,
    )
    from swarmkit_tpu.models.specs import NetworkSpec
    from swarmkit_tpu.state import ByService

    from test_orchestrator import poll

    store = MemoryStore()
    api = ControlAPI(store)
    alloc = Allocator(store)
    alloc.start()
    try:
        n1 = api.create_network(NetworkSpec(
            annotations=Annotations(name="backend")))
        n2 = api.create_network(NetworkSpec(
            annotations=Annotations(name="frontend")))
        poll(lambda: store.view(
            lambda tx: all(tx.get(Network, i).ipam is not None
                           for i in (n1.id, n2.id))),
            msg="subnets allocated")
        nets = store.view(lambda tx: [tx.get(Network, i)
                                      for i in (n1.id, n2.id)])
        subnets = [n.ipam.configs[0].subnet for n in nets]
        assert len(set(subnets)) == 2, "distinct subnets"
        assert all(s.endswith("/24") for s in subnets), subnets
        gws = [n.ipam.configs[0].gateway for n in nets]
        assert all(g.endswith(".1") for g in gws), gws

        # service attached to both networks: VIP per network
        svc_spec = spec("webnet", replicas=2)
        svc_spec.task.networks = [
            NetworkAttachmentConfig(target="backend"),
            NetworkAttachmentConfig(target=n2.id)]
        svc = api.create_service(svc_spec)
        poll(lambda: (api.get_service(svc.id).endpoint is not None
                      and len(api.get_service(svc.id)
                              .endpoint.virtual_ips) == 2),
             msg="VIPs on both networks")
        vips = api.get_service(svc.id).endpoint.virtual_ips
        assert {v.network_id for v in vips} == {n1.id, n2.id}
        assert all(v.addr for v in vips)

        # tasks carry per-network addresses, all distinct (created
        # directly: no orchestrator runs in this test)
        from swarmkit_tpu.models.types import TaskStatus
        from swarmkit_tpu.utils import new_id

        def mk(tx):
            for slot in (1, 2):
                tx.create(Task(
                    id=new_id(), service_id=svc.id, slot=slot,
                    spec=svc_spec.task.copy(),
                    status=TaskStatus(state=TaskState.NEW),
                    desired_state=TaskState.RUNNING))
        store.update(mk)

        def task_addrs():
            ts = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
            if len(ts) < 2 or any(
                    t.status.state < TaskState.PENDING for t in ts):
                return None
            return [a for t in ts for att in t.networks
                    for a in att.addresses]
        addrs = poll(task_addrs, msg="task addresses allocated")
        assert len(addrs) == 4                     # 2 tasks x 2 networks
        assert len(set(addrs)) == 4, "addresses must be unique"
        vip_addrs = {v.addr for v in vips}
        assert not vip_addrs & set(addrs), "VIPs never reused for tasks"
    finally:
        alloc.stop()


# ------------------------------------------------- volumes (volume.go parity)

def _vol_spec(name="vol1", driver="csi.example", group="", sharing=None,
              secrets=None):
    from swarmkit_tpu.models.specs import VolumeSpec
    from swarmkit_tpu.models.types import Driver, VolumeAccessMode

    return VolumeSpec(
        annotations=Annotations(name=name), group=group,
        driver=Driver(name=driver),
        access_mode=VolumeAccessMode(sharing=sharing or 0),
        secrets=dict(secrets or {}))


def test_volume_crud_lifecycle(api):
    from swarmkit_tpu.models.types import VolumeAvailability

    with pytest.raises(InvalidArgument, match="driver must be specified"):
        api.create_volume(_vol_spec(driver=""))
    with pytest.raises(InvalidArgument, match="name must be provided"):
        api.create_volume(_vol_spec(name=""))

    v = api.create_volume(_vol_spec())
    assert api.get_volume(v.id).spec.annotations.name == "vol1"
    with pytest.raises(AlreadyExists):
        api.create_volume(_vol_spec())

    # only labels + availability are mutable
    spec2 = v.spec.copy()
    spec2.group = "changed"
    with pytest.raises(InvalidArgument, match="Group cannot be updated"):
        api.update_volume(v.id, v.meta.version.index, spec2)
    spec3 = v.spec.copy()
    spec3.annotations.labels["tier"] = "fast"
    spec3.availability = int(VolumeAvailability.DRAIN)
    updated = api.update_volume(v.id, v.meta.version.index, spec3)
    assert updated.spec.annotations.labels == {"tier": "fast"}
    assert updated.spec.availability == int(VolumeAvailability.DRAIN)

    assert [x.id for x in api.list_volumes()] == [v.id]
    api.remove_volume(v.id)           # unused -> marked pending delete
    assert api.get_volume(v.id).pending_delete
    api.remove_volume(v.id, force=True)
    with pytest.raises(NotFound):
        api.get_volume(v.id)


def test_volume_create_reports_all_missing_secrets(api):
    with pytest.raises(InvalidArgument, match="secrets not found"):
        api.create_volume(_vol_spec(secrets={"a": "sec-a", "b": "sec-b"}))


def test_volume_in_use_refuses_remove(api):
    from swarmkit_tpu.models.objects import Volume
    from swarmkit_tpu.models.types import VolumePublishStatus

    v = api.create_volume(_vol_spec())

    def publish(tx):
        cur = tx.get(Volume, v.id).copy()
        cur.publish_status.append(VolumePublishStatus(node_id="n1"))
        tx.update(cur)
    api.store.update(publish)
    with pytest.raises(FailedPrecondition, match="still in use"):
        api.remove_volume(v.id)


# -------------------------------- extensions + resources (extension.go parity)

def test_extension_and_resource_lifecycle(api):
    with pytest.raises(InvalidArgument, match="name must be provided"):
        api.create_extension(Annotations(name=""))
    ext = api.create_extension(Annotations(name="widgets"),
                               "custom widget type")
    with pytest.raises(AlreadyExists):
        api.create_extension(Annotations(name="widgets"))

    with pytest.raises(InvalidArgument, match="not registered"):
        api.create_resource(Annotations(name="w1"), "gadgets")
    r = api.create_resource(Annotations(name="w1"), "widgets",
                            b"payload-1")
    assert api.get_resource(r.id).payload == b"payload-1"
    assert [x.id for x in api.list_resources(kind="widgets")] == [r.id]

    # extension removal is refused while resources of its kind exist
    with pytest.raises(InvalidArgument, match="in use by resources"):
        api.remove_extension(ext.id)

    # payload + labels mutable; renames rejected
    ann = r.annotations.copy()
    ann.name = "renamed"
    with pytest.raises(InvalidArgument, match="Name cannot be updated"):
        api.update_resource(r.id, r.meta.version.index, annotations=ann)
    r2 = api.update_resource(r.id, r.meta.version.index,
                             payload=b"payload-2")
    assert r2.payload == b"payload-2"

    api.remove_resource(r.id)
    api.remove_extension(ext.id)
    with pytest.raises(NotFound):
        api.get_extension(ext.id)


# ------------------------------------------------------------- join tokens

@requires_crypto
def test_rotate_join_token_via_api():
    from swarmkit_tpu.manager import Manager
    from swarmkit_tpu.models import Cluster
    from swarmkit_tpu.state.store import ByName

    m = Manager(use_device_scheduler=False)
    m.run()
    try:
        cluster = m.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0]
        old = cluster.root_ca.join_tokens.worker
        new = m.control_api.rotate_join_token(NodeRole.WORKER)
        assert new != old
        assert m.root_ca.join_token(NodeRole.WORKER) == new
        cluster = m.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0]
        assert cluster.root_ca.join_tokens.worker == new
        with pytest.raises(Exception):
            m.root_ca.role_for_token(old)
    finally:
        m.stop()


# ------------------------------------------------------------------ CLI nouns

@requires_crypto
def test_cli_volume_network_cluster_nouns():
    from swarmkit_tpu.cli import run_command
    from swarmkit_tpu.manager import Manager

    m = Manager(use_device_scheduler=False)
    m.run()
    api2 = m.control_api
    try:
        vid = run_command(["volume", "create", "data1",
                           "--driver", "csi.example",
                           "--group", "fast"], api2)
        out = run_command(["volume", "ls"], api2)
        assert "data1" in out and "fast" in out
        out = run_command(["volume", "inspect", "data1"], api2)
        assert vid in out
        run_command(["volume", "drain", "data1"], api2)
        run_command(["volume", "rm", "data1", "--force"], api2)
        assert "data1" not in run_command(["volume", "ls"], api2)

        nid = run_command(["network", "create", "backend",
                           "--subnet", "10.99.0.0/24"], api2)
        assert "backend" in run_command(["network", "ls"], api2)
        assert "10.99.0.0/24" in run_command(
            ["network", "inspect", "backend"], api2)
        run_command(["network", "rm", "backend"], api2)

        out = run_command(["cluster", "inspect"], api2)
        assert "SWMTKN-1-" in out
        ls = run_command(["cluster", "ls"], api2)
        assert "default" in ls and "AUTOLOCK" in ls
        token = run_command(["cluster", "rotate-token", "worker"], api2)
        assert token.startswith("SWMTKN-1-")
        assert token in run_command(["cluster", "inspect"], api2)

        run_command(["extension", "create", "widgets"], api2)
        run_command(["resource", "create", "w1", "widgets"], api2)
        assert "w1" in run_command(["resource", "ls"], api2)
        run_command(["resource", "rm", "w1"], api2)
        run_command(["extension", "rm", "widgets"], api2)
    finally:
        m.stop()


@requires_crypto
def test_list_service_statuses():
    """Desired/running counts per service — the `service ls` helper
    (reference: manager/controlapi/service.go:1047 ListServiceStatuses:
    replicated desired = replicas; global desired counts live tasks;
    unknown ids return zeroed statuses)."""
    from swarmkit_tpu.cli import run_command
    from swarmkit_tpu.manager import Manager
    from swarmkit_tpu.models import (
        Annotations, ContainerSpec, ServiceMode, ServiceSpec, TaskSpec,
    )

    from test_orchestrator import poll

    m = Manager(use_device_scheduler=False)
    m.run()
    api = m.control_api
    try:
        run_command(["service", "create", "--name", "web",
                     "--image", "nginx", "--replicas", "3"], api)
        svc = api.list_services("web")[0]
        gsvc = api.create_service(ServiceSpec(
            annotations=Annotations(name="agent-everywhere"),
            task=TaskSpec(container=ContainerSpec(image="agent")),
            mode=ServiceMode.GLOBAL))
        # no agents: replicated tasks never RUN, but desired is 3 now
        sts = {st["service_id"]: st for st in api.list_service_statuses(
            [svc.id, gsvc.id, "no-such-service"])}
        assert sts[svc.id]["desired_tasks"] == 3
        assert sts["no-such-service"] == {
            "service_id": "no-such-service", "desired_tasks": 0,
            "running_tasks": 0, "completed_tasks": 0}

        # a node joins: global desired becomes 1, and once tasks run the
        # running counts follow
        from swarmkit_tpu.agent.testutils import TestExecutor
        from swarmkit_tpu.node import Node as ClusterNode
        import tempfile
        node = ClusterNode(TestExecutor(hostname="w1"), tempfile.mkdtemp())
        cluster = api.get_default_cluster()
        node.load_or_join(m.ca_server, cluster.root_ca.join_tokens.worker)
        node.start(m.dispatcher, store=m.store, hostname="w1")
        try:
            def counts():
                sts = {st["service_id"]: st
                       for st in api.list_service_statuses(
                           [svc.id, gsvc.id])}
                return (sts[svc.id]["running_tasks"] == 3
                        and sts[gsvc.id]["desired_tasks"] == 1
                        and sts[gsvc.id]["running_tasks"] == 1)
            poll(counts, timeout=20,
                 msg="statuses should reach 3/3 and 1/1")
            ls = run_command(["service", "ls"], api)
            assert "3/3" in ls and "1/1" in ls
        finally:
            node.stop()
    finally:
        m.stop()


@requires_crypto
def test_cli_nouns_over_remote_control_client():
    """The same CLI nouns drive a remote manager through the mTLS control
    client (reference: swarmctl against a live manager)."""
    from swarmkit_tpu.cli import run_command
    from swarmkit_tpu.manager import Manager
    from swarmkit_tpu.models import Cluster
    from swarmkit_tpu.net import ManagerServer, RemoteControlClient, issue_certificate
    from swarmkit_tpu.state.store import ByName
    from swarmkit_tpu.utils import new_id

    m = Manager(use_device_scheduler=False)
    m.run()
    srv = ManagerServer(m)
    srv.start()
    try:
        cluster = m.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0]
        op = issue_certificate(srv.addr, new_id(),
                               cluster.root_ca.join_tokens.manager)
        ctl = RemoteControlClient(srv.addr, op)
        run_command(["volume", "create", "rv", "--driver", "csi.x"], ctl)
        assert "rv" in run_command(["volume", "ls"], ctl)
        run_command(["volume", "rm", "rv", "--force"], ctl)
        run_command(["network", "create", "rnet"], ctl)
        assert "rnet" in run_command(["network", "ls"], ctl)
        run_command(["network", "rm", "rnet"], ctl)
        tok = run_command(["cluster", "rotate-token", "worker"], ctl)
        assert tok.startswith("SWMTKN-1-")
        run_command(["extension", "create", "kinds"], ctl)
        run_command(["resource", "create", "k1", "kinds"], ctl)
        assert "k1" in run_command(["resource", "ls"], ctl)
        run_command(["resource", "rm", "k1"], ctl)
        run_command(["secret", "create", "rs", "payload"], ctl)
        insp = run_command(["secret", "inspect", "rs"], ctl)
        assert "Name: rs" in insp and "payload" not in insp
        run_command(["secret", "rm", "rs"], ctl)
        run_command(["config", "create", "rc", "k=v"], ctl)
        assert "Data: k=v" in run_command(["config", "inspect", "rc"], ctl)
        run_command(["config", "rm", "rc"], ctl)
        run_command(["extension", "rm", "kinds"], ctl)
        # service ls pulls running/desired through the wire statuses RPC
        run_command(["service", "create", "--name", "rweb",
                     "--image", "nginx", "--replicas", "2"], ctl)
        assert "0/2" in run_command(["service", "ls"], ctl)
        run_command(["service", "rm", "rweb"], ctl)
        ctl.close()
    finally:
        srv.stop()
        m.stop()


@requires_crypto
def test_csi_volume_lifecycle_e2e_from_cli():
    """VERDICT r2 item 3 done-criterion: volume create -> schedule a task
    using it -> publish -> drain -> unpublish, all driven from the CLI
    (reference: volume.go + csi manager + VolumesFilter together)."""
    import time

    from swarmkit_tpu.agent import Agent
    from swarmkit_tpu.agent.testutils import TestExecutor
    from swarmkit_tpu.cli import run_command
    from swarmkit_tpu.manager import Manager
    from swarmkit_tpu.manager.dispatcher import Config_
    from swarmkit_tpu.models import Task, TaskState
    from swarmkit_tpu.models.types import VolumePublishStatus

    from test_orchestrator import poll
    from test_scheduler import make_ready_node

    m = Manager(dispatcher_config=Config_(
        heartbeat_period=0.3, heartbeat_epsilon=0.02,
        process_updates_interval=0.02, assignment_batching_wait=0.02),
        use_device_scheduler=False)
    m.run()
    api2 = m.control_api
    n = make_ready_node("csi-n1")
    m.store.update(lambda tx, n=n: tx.create(n))
    agent = Agent(n.id, TestExecutor(hostname="csi-n1"), m.dispatcher)
    agent.start()
    try:
        vid = run_command(["volume", "create", "data1",
                           "--driver", "inmem"], api2)
        # csi manager creates it plugin-side
        poll(lambda: api2.get_volume(vid).volume_info is not None
             and api2.get_volume(vid).volume_info.volume_id,
             timeout=10, msg="csi manager should create the volume")

        run_command(["service", "create", "--name", "dbsvc",
                     "--image", "db", "--replicas", "1",
                     "--csi-volume", "data1:/data"], api2)

        def task_running_with_volume():
            ts = [t for t in api2.list_tasks()
                  if t.service_annotations.name == "dbsvc"
                  and t.desired_state == TaskState.RUNNING]
            return (ts and ts[0].status.state == TaskState.RUNNING
                    and any(va.id == vid for va in ts[0].volumes))
        poll(task_running_with_volume, timeout=20,
             msg="task should run with the volume attached")

        def published():
            v = api2.get_volume(vid)
            return any(p.node_id == n.id and p.state ==
                       VolumePublishStatus.State.PUBLISHED
                       for p in v.publish_status)
        poll(published, timeout=10,
             msg="csi manager should controller-publish on the node")
        assert "published" in run_command(
            ["volume", "inspect", "data1"], api2)

        # drain: the volume enforcer evicts the task, the csi manager
        # unpublishes once unused
        run_command(["volume", "drain", "data1"], api2)

        def unpublished():
            v = api2.get_volume(vid)
            return not v.publish_status
        poll(unpublished, timeout=20,
             msg="drained volume should unpublish after eviction")

        # and now removable without force
        run_command(["service", "rm", "dbsvc"], api2)
        run_command(["volume", "rm", "data1"], api2)
        poll(lambda: not [v for v in api2.list_volumes()
                          if v.spec.annotations.name == "data1"],
             timeout=10, msg="pending-delete volume should be deleted")
    finally:
        agent.stop()
        m.stop()


@requires_crypto
def test_node_side_csi_staging_with_process_executor(tmp_path):
    """Worker-side CSI (reference: agent/csi/volumes.go): the agent
    stages/publishes the volume to a local path before the process task
    starts, exposes it via env, and unstages after shutdown."""
    import os

    from swarmkit_tpu.agent import Agent
    from swarmkit_tpu.agent.procexec import ProcessExecutor
    from swarmkit_tpu.cli import run_command
    from swarmkit_tpu.manager import Manager
    from swarmkit_tpu.manager.dispatcher import Config_
    from swarmkit_tpu.models import TaskState
    from swarmkit_tpu.models.specs import (
        ContainerSpec, ServiceSpec,
    )
    from swarmkit_tpu.models import (
        ReplicatedService, ServiceMode, TaskSpec,
    )
    from swarmkit_tpu.models.types import Mount, MountType

    from test_orchestrator import poll
    from test_scheduler import make_ready_node

    m = Manager(dispatcher_config=Config_(
        heartbeat_period=0.3, heartbeat_epsilon=0.02,
        process_updates_interval=0.02, assignment_batching_wait=0.02),
        use_device_scheduler=False)
    m.run()
    api2 = m.control_api
    n = make_ready_node("csi-p1")
    m.store.update(lambda tx, n=n: tx.create(n))
    agent = Agent(n.id, ProcessExecutor(
        hostname="csi-p1", log_dir=str(tmp_path / "logs")), m.dispatcher,
        task_db_path=str(tmp_path / "node" / "tasks.db"))
    agent.start()
    try:
        vid = run_command(["volume", "create", "pdata",
                           "--driver", "inmem"], api2)
        poll(lambda: api2.get_volume(vid).volume_info is not None
             and api2.get_volume(vid).volume_info.volume_id, timeout=10)

        marker = tmp_path / "proof"
        svc = api2.create_service(ServiceSpec(
            annotations=Annotations(name="vol-writer"),
            task=TaskSpec(container=ContainerSpec(
                image="process",
                command=["sh", "-c",
                         f'echo "$SWARM_VOLUME_DATA" > {marker}; '
                         'touch "$SWARM_VOLUME_DATA/wrote"; sleep 30'],
                mounts=[Mount(type=MountType.CSI, source="pdata",
                              target="/data")])),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=1)))

        def running():
            ts = [t for t in api2.list_tasks(service_id=svc.id)
                  if t.desired_state == TaskState.RUNNING]
            return ts and ts[0].status.state == TaskState.RUNNING
        poll(running, timeout=20, msg="volume task should run")

        poll(lambda: marker.exists() and marker.read_text().strip(),
             timeout=10, msg="task should see the volume path env")
        vol_path = marker.read_text().strip()
        assert os.path.isdir(vol_path), vol_path
        assert os.path.exists(os.path.join(vol_path, "wrote"))
        assert agent.volumes.ready(vid)

        # removal: task goes away, node unstages, path is gone
        api2.remove_service(svc.id)
        poll(lambda: not agent.volumes.ready(vid), timeout=20,
             msg="volume should unstage after the task is removed")
        poll(lambda: not os.path.exists(vol_path), timeout=10,
             msg="published path should be cleaned up")
        poll(lambda: not api2.get_volume(vid).publish_status, timeout=20,
             msg="controller-unpublish should complete")
    finally:
        agent.stop()
        m.stop()


def test_cli_cluster_update_live_settings():
    """swarmctl cluster update flags flow into the watched ClusterSpec
    (reference: swarmctl cluster update)."""
    from swarmkit_tpu.cli import run_command
    from swarmkit_tpu.manager.controlapi import ControlAPI
    from swarmkit_tpu.models import Cluster
    from swarmkit_tpu.models.specs import ClusterSpec
    from swarmkit_tpu.models.types import Annotations
    from swarmkit_tpu.state import MemoryStore

    store = MemoryStore()
    store.update(lambda tx: tx.create(Cluster(
        id="c1", spec=ClusterSpec(annotations=Annotations(name="default")))))
    api = ControlAPI(store)
    out = run_command(["cluster", "update", "--heartbeat-period", "2.5",
                       "--cert-expiry", "3600",
                       "--task-history-limit", "9"], api)
    assert "heartbeat-period=2.5s" in out
    c = api.get_default_cluster()
    assert c.spec.dispatcher.heartbeat_period == 2.5
    assert c.spec.ca_config.node_cert_expiry == 3600
    assert c.spec.orchestration.task_history_retention_limit == 9
    assert run_command(["cluster", "update"], api) == "nothing to update"


def test_cluster_responses_redact_key_material():
    """get/list/get_default cluster strip signing + unlock keys but keep
    join tokens, and a redacted inspect→update round trip preserves the
    stored signing CA material (reference: controlapi/cluster.go:252
    redactClusters)."""
    from swarmkit_tpu.manager.controlapi import ControlAPI
    from swarmkit_tpu.models import Cluster
    from swarmkit_tpu.models.objects import RootCAState
    from swarmkit_tpu.models.specs import ClusterSpec
    from swarmkit_tpu.models.types import (
        Annotations, EncryptionKey, JoinTokens,
    )
    from swarmkit_tpu.state import MemoryStore

    store = MemoryStore()
    spec = ClusterSpec(annotations=Annotations(name="default"))
    spec.ca_config.signing_ca_key = b"SIGNKEY"
    spec.ca_config.signing_ca_cert = b"SIGNCERT"
    store.update(lambda tx: tx.create(Cluster(
        id="c1", spec=spec,
        root_ca=RootCAState(
            ca_key=b"CAKEY", ca_cert=b"CACERT",
            rotation_ca_key=b"ROTKEY",
            join_tokens=JoinTokens(worker="SWMTKN-w", manager="SWMTKN-m")),
        unlock_keys=[EncryptionKey(subsystem="manager", key=b"UNLOCK")],
        network_bootstrap_keys=[
            EncryptionKey(subsystem="networking", key=b"GOSSIP")])))
    api = ControlAPI(store)

    for c in (api.get_cluster("c1"), api.get_default_cluster(),
              *api.list_clusters()):
        assert c.spec.ca_config.signing_ca_key == b""
        assert c.spec.ca_config.signing_ca_cert == b""
        assert c.root_ca.ca_key == b""
        assert c.root_ca.rotation_ca_key == b""
        assert c.unlock_keys == []
        assert c.network_bootstrap_keys == []
        # public material survives redaction
        assert c.root_ca.ca_cert == b"CACERT"
        assert c.root_ca.join_tokens.worker == "SWMTKN-w"

    # in-process raw reads still see the key material (autolock path)
    assert api._default_cluster_raw().unlock_keys[0].key == b"UNLOCK"

    # redacted round trip: update with a blanked spec keeps signing keys
    c = api.get_default_cluster()
    new_spec = c.spec.copy()
    new_spec.dispatcher.heartbeat_period = 7.0
    api.update_cluster(c.id, c.meta.version.index, new_spec)
    stored = api._default_cluster_raw()
    assert stored.spec.dispatcher.heartbeat_period == 7.0
    assert stored.spec.ca_config.signing_ca_key == b"SIGNKEY"
    assert stored.spec.ca_config.signing_ca_cert == b"SIGNCERT"
