"""CSI manager + volumequeue tests (reference: manager/csi/*_test.go)."""

import time

import pytest

from swarmkit_tpu.manager import CSIManager, InMemoryCSIPlugin
from swarmkit_tpu.models import Annotations, Volume
from swarmkit_tpu.models.specs import VolumeSpec
from swarmkit_tpu.models.types import Driver, VolumePublishStatus
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.utils import new_id
from swarmkit_tpu.utils.volumequeue import VolumeQueue

from test_orchestrator import poll


def make_volume(name, driver="inmem"):
    return Volume(id=new_id(),
                  spec=VolumeSpec(annotations=Annotations(name=name),
                                  driver=Driver(name=driver)))


def test_volumequeue_backoff_ordering():
    q = VolumeQueue()
    q.enqueue("a")
    assert q.wait(timeout=1) == "a"
    # fresh work stays immediate
    t0 = time.monotonic()
    q.enqueue("a")
    assert q.wait(timeout=1) == "a"
    assert time.monotonic() - t0 < 0.09
    # failures back off exponentially
    t0 = time.monotonic()
    q.enqueue("a", retry=True)
    assert q.wait(timeout=2) == "a"
    assert time.monotonic() - t0 >= 0.09
    t0 = time.monotonic()
    q.enqueue("a", retry=True)    # second failure: doubled delay
    assert q.wait(timeout=2) == "a"
    assert time.monotonic() - t0 >= 0.19
    q.forget("a")
    q.enqueue("a", retry=True)    # reset: back to base delay
    t0 = time.monotonic()
    assert q.wait(timeout=2) == "a"
    assert time.monotonic() - t0 < 0.19
    q.close()
    assert q.wait(timeout=0.1) is None


def test_csi_create_publish_unpublish_delete():
    store = MemoryStore()
    plugin = InMemoryCSIPlugin()
    mgr = CSIManager(store, plugins={"inmem": plugin})
    mgr.start()
    try:
        vol = make_volume("data")
        store.update(lambda tx: tx.create(vol))

        # created against the plugin
        poll(lambda: (store.view(lambda tx: tx.get(Volume, vol.id))
                      .volume_info is not None), msg="volume created")
        info = store.view(lambda tx: tx.get(Volume, vol.id)).volume_info
        assert info.volume_id in plugin.volumes

        # scheduler adds a pending publish (what commit_one does)
        def add_publish(tx):
            cur = tx.get(Volume, vol.id).copy()
            cur.publish_status.append(VolumePublishStatus(
                node_id="node1",
                state=VolumePublishStatus.State.PENDING_PUBLISH))
            tx.update(cur)
        store.update(add_publish)
        poll(lambda: all(
            ps.state == VolumePublishStatus.State.PUBLISHED
            for ps in store.view(
                lambda tx: tx.get(Volume, vol.id)).publish_status),
            msg="pending publish should become PUBLISHED")
        assert "node1" in plugin.published[info.volume_id]
        got = store.view(lambda tx: tx.get(Volume, vol.id))
        assert got.publish_status[0].publish_context["device"] \
            == f"/dev/{info.volume_id}"

        # unpublish then delete
        def mark_unpublish(tx):
            cur = tx.get(Volume, vol.id).copy()
            cur.publish_status[0].state = \
                VolumePublishStatus.State.PENDING_UNPUBLISH
            cur.pending_delete = True
            tx.update(cur)
        store.update(mark_unpublish)
        poll(lambda: store.view(lambda tx: tx.get(Volume, vol.id)) is None,
             msg="unpublished pending-delete volume should be removed")
        assert info.volume_id not in plugin.volumes
    finally:
        mgr.stop()


def test_csi_retries_with_backoff_on_failure():
    store = MemoryStore()
    plugin = InMemoryCSIPlugin()
    plugin.fail_next = "create"
    mgr = CSIManager(store, plugins={"inmem": plugin})
    mgr.start()
    try:
        vol = make_volume("flaky")
        store.update(lambda tx: tx.create(vol))
        # first attempt fails; the retry (with backoff) succeeds
        poll(lambda: (store.view(lambda tx: tx.get(Volume, vol.id))
                      .volume_info is not None), timeout=10,
             msg="creation should succeed on retry")
    finally:
        mgr.stop()
