"""Device-telemetry ledger tests (ISSUE 18).

The contract under test, in order of importance:

* **byte-identity** — enabling the ledger must not move a single
  placement byte: telemetry-on vs telemetry-off runs of the same
  workload produce identical decisions, store snapshots and watch-event
  streams on every dispatch route (fused, per-group, streaming);
* **determinism** — the snapshot document is PYTHONHASHSEED-independent
  (sorted keys, crc32 shape hashes): two subprocesses with different
  seeds serialize byte-identical ledgers;
* **bounded cardinality** — a pathological workload minting unbounded
  bucket names costs O(cap) rows with counted overflow, and unknown
  transfer reasons lump into "other" instead of minting labels;
* **donation balance** — a read of a still-donated buffer is a counted,
  returned violation (the runtime twin of the swarmlint rule), and the
  check never raises;
* **render-on-empty** — ``/debug/device`` serves a fresh process with
  empty tables (the _h_planes discipline);
* **flightrec embedding** — live dumps carry the device ledger +
  compile-cache snapshot; deterministic (sim) captures stay seed-pure.
"""

import json
import os
import subprocess
import sys

import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    Placement, PlacementPreference, ReplicatedService, Resources,
    ResourceRequirements, Service, ServiceMode, ServiceSpec, SpreadOver,
    Task, TaskSpec, TaskState, TaskStatus, Version,
)
from swarmkit_tpu.models import types as model_types
from swarmkit_tpu.obs import devicetelemetry
from swarmkit_tpu.ops import TPUPlanner
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.state.events import (
    Event, EventCommit, EventSnapshotRestore, EventTaskBlock,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def frozen_clock():
    model_types.set_time_source(lambda: 1_700_000_000.0)
    try:
        yield
    finally:
        model_types.set_time_source(None)


@pytest.fixture(autouse=True)
def fresh_ledger():
    """Every test gets its own ledger; the process-wide one (and its
    enabled flag) is restored afterwards — the save/restore lifecycle
    every obs singleton shares."""
    prev = devicetelemetry.save_state()
    devicetelemetry.reset()
    devicetelemetry.set_enabled(True)
    try:
        yield
    finally:
        devicetelemetry.restore_state(prev)


# ------------------------------------------------------------ workload

_RES = ResourceRequirements(
    reservations=Resources(nano_cpus=10 ** 8, memory_bytes=64 << 20))


def _mk_node(i, cpus=8 * 10 ** 9, mem=32 << 30):
    return Node(
        id=f"n{i:04d}",
        spec=NodeSpec(annotations=Annotations(
            name=f"node-{i:04d}",
            labels={"rack": f"r{i % 3}",
                    "tier": "web" if i % 2 else "db"})),
        status=NodeStatus(state=NodeState.READY),
        description=NodeDescription(
            hostname=f"node-{i:04d}",
            resources=Resources(nano_cpus=cpus, memory_bytes=mem)))


def _mk_service(sid, n_tasks, spec):
    svc = Service(
        id=sid,
        spec=ServiceSpec(annotations=Annotations(name=f"svc-{sid}"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=n_tasks),
                         task=spec),
        spec_version=Version(index=1))
    tasks = [Task(id=f"{sid}-t{k:04d}", service_id=sid, slot=k + 1,
                  desired_state=TaskState.RUNNING, spec=spec,
                  spec_version=Version(index=1),
                  status=TaskStatus(state=TaskState.PENDING,
                                    timestamp=model_types.now()))
             for k in range(n_tasks)]
    return svc, tasks


def _build_store(n_nodes=24):
    store = MemoryStore()
    store.update(lambda tx: [tx.create(_mk_node(i))
                             for i in range(n_nodes)])
    specs = {
        "sva": TaskSpec(resources=_RES),
        "svb": TaskSpec(resources=_RES,
                        placement=Placement(
                            constraints=["node.labels.tier==web"])),
        "svc": TaskSpec(resources=_RES,
                        placement=Placement(preferences=[
                            PlacementPreference(spread=SpreadOver(
                                spread_descriptor="node.labels.rack"))])),
    }
    seeded = {"sva": 20, "svb": 12, "svc": 9}

    def mk(tx):
        for sid, spec in specs.items():
            svc, tasks = _mk_service(sid, seeded[sid], spec)
            tx.create(svc)
            for t in tasks:
                tx.create(t)
    store.update(mk)
    return store, specs, dict(seeded)


def _event_key(ev):
    if isinstance(ev, EventTaskBlock):
        return ("block", tuple(o.id for o in ev.olds),
                tuple(ev.node_ids), ev.base_version, ev.state, ev.message)
    if isinstance(ev, EventCommit):
        return ("commit", ev.version)
    if isinstance(ev, Event):
        obj = ev.obj
        return (ev.action, obj.id, getattr(obj, "node_id", None),
                int(obj.status.state) if hasattr(obj, "status") else None,
                obj.meta.version.index)
    return ("other", repr(ev))


def _pump(sched, sub):
    while True:
        ev = sub.poll()
        if ev is None:
            return
        if isinstance(ev, EventSnapshotRestore):
            sched._resync()
        elif isinstance(ev, Event):
            sched._handle_event(ev)


def _run_route(route: str, enabled: bool):
    """Cold tick + one incremental tick (arrivals + failures) through
    the scheduler's real event feed, on one dispatch route."""
    devicetelemetry.reset()
    devicetelemetry.set_enabled(enabled)
    store, specs, seqs = _build_store()
    planner = TPUPlanner()
    planner.enable_small_group_routing = False
    planner.fused_enabled = route != "group"
    planner.streaming_enabled = route == "streaming"
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    _, sub = store.view_and_watch(
        lambda tx: sched._setup_tasks_list(tx), accepts_blocks=True)
    obs = store.queue.subscribe(accepts_blocks=True)

    decisions = sched.tick()                      # cold tick
    spec = specs["sva"]
    base = seqs["sva"]

    def add(tx):
        for k in range(5):
            tx.create(Task(
                id=f"sva-t{base + k:04d}", service_id="sva",
                slot=base + k + 1, desired_state=TaskState.RUNNING,
                spec=spec, spec_version=Version(index=1),
                status=TaskStatus(state=TaskState.PENDING,
                                  timestamp=model_types.now())))
    store.update(add)
    victims = sorted(
        (t for t in store.view(lambda tx: tx.find(Task))
         if t.service_id == "svb" and t.node_id),
        key=lambda t: t.id)[:2]

    def fail(tx):
        for v in victims:
            cur = tx.get(Task, v.id).copy()
            cur.status = TaskStatus(state=TaskState.FAILED,
                                    timestamp=model_types.now(),
                                    message="churn exit")
            tx.update(cur)
    store.update(fail)
    _pump(sched, sub)
    decisions += sched.tick()                     # incremental tick

    events = [_event_key(e) for e in obs.drain()]
    store.queue.unsubscribe(obs)
    store.queue.unsubscribe(sub)
    tasks = store.view(lambda tx: tx.find(Task))
    state = sorted((t.id, t.node_id, int(t.status.state),
                    t.status.message, t.meta.version.index)
                   for t in tasks)
    return decisions, state, events, store.save_bytes(), planner


# --------------------------------------------------------- byte identity

@pytest.mark.parametrize("route", ["fused", "group", "streaming"])
def test_placements_byte_identical_telemetry_on_off(frozen_clock, route):
    """The ledger observes; it must never steer.  Placements, store
    snapshot bytes and the watch-event stream are identical with the
    ledger on and off, per dispatch route."""
    d_on, s_on, e_on, b_on, p_on = _run_route(route, True)
    snap = devicetelemetry.snapshot()
    d_off, s_off, e_off, b_off, _p = _run_route(route, False)
    off_snap = devicetelemetry.snapshot()

    assert (d_on, s_on, e_on) == (d_off, s_off, e_off)
    assert b_on == b_off

    # the on-run actually recorded the route (non-vacuous differential)
    routes = {k.split("|", 1)[1] for k in snap["kernel"]}
    if route == "fused":
        assert p_on.stats.get("groups_fused", 0) > 0
        assert "fused" in routes, snap["kernel"]
        assert "h2d" in snap["transfers"] \
            and "cold_build" in snap["transfers"]["h2d"]
    elif route == "group":
        assert p_on.stats.get("groups_planned", 0) > 0
        assert routes & {"group", "strategy"}, snap["kernel"]
    else:
        st = p_on.streaming_snapshot()
        assert st["enabled"] and st["incremental_ticks"] >= 1, st
        h2d = snap["transfers"]["h2d"]
        assert "cold_build" in h2d, h2d
        assert {"dirty_scatter", "wide_reupload"} & set(h2d), h2d
        assert "device_resident" in snap["memory"]

    # ...and the off-run recorded nothing at all
    assert off_snap["kernel"] == {}
    assert off_snap["transfers"] == {"d2h": {}, "h2d": {}}
    assert off_snap["compile_cache"] == {}


# ----------------------------------------------------------- determinism

_DET_SCRIPT = """\
import json, sys
sys.path.insert(0, sys.argv[1])
from swarmkit_tpu.obs import devicetelemetry as dt
dt.reset(); dt.set_enabled(True)
for i in [3, 1, 4, 1, 5, 9, 2, 6]:
    dt.note_kernel("nb1024_g%d" % i, "group", dispatch_s=0.001 * i,
                   task_rows=10 * i, node_rows=100)
    dt.note_compile("nb1024_g%d" % i, 0.01 * i)
    dt.note_cache_hit("nb1024_g%d" % i)
for r in ["fused_inputs", "cold_build", "group_inputs", "bogus"]:
    dt.note_h2d(r, 1000)
dt.note_d2h("fetch", 512)
dt.note_d2h("weird", 7)
dt.set_watermark("device_resident", 4096)
dt.note_donated([11, 22, 33])
dt.note_retired([22])
dt.check_live([11, 44])
print(json.dumps(dt.snapshot(), sort_keys=True))
"""


def test_ledger_serialization_hashseed_independent():
    """Two subprocesses with different PYTHONHASHSEED values produce
    byte-identical snapshot JSON (sorted keys + crc32 shape hashes —
    no id()/hash() ordering anywhere in the document)."""
    outs = []
    for seed in ("0", "1"):
        env = dict(os.environ, PYTHONHASHSEED=seed)
        proc = subprocess.run(
            [sys.executable, "-c", _DET_SCRIPT, REPO],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=120)
        assert proc.returncode == 0, proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]
    doc = json.loads(outs[0])
    assert doc["transfers"]["h2d"]["other"]["bytes"] == 1000
    assert doc["transfers"]["d2h"]["other"]["count"] == 1
    assert doc["donation"]["violations"] == 1


# ----------------------------------------------------- bounded cardinality

def test_bounded_cardinality_under_pathological_buckets(monkeypatch):
    """1000 distinct bucket names cost O(cap) ledger rows; the excess
    is aggregated under the overflow row and counted, never dropped.
    The live metrics registry is isolated here so the pathological
    bucket names can't leak series into the process-wide exposition
    (which other tests bound)."""
    from swarmkit_tpu.utils.metrics import Registry
    sandbox = Registry()
    monkeypatch.setattr(devicetelemetry, "_metrics", sandbox)
    for i in range(1000):
        devicetelemetry.note_kernel(f"bucket{i:04d}", "group")
        devicetelemetry.note_compile(f"bucket{i:04d}", 0.001)
    for i in range(50):
        devicetelemetry.note_h2d(f"reason{i}", 10)
    snap = devicetelemetry.snapshot()

    # exported label combos are capped independently of ledger rows:
    # MAX_METRIC_SERIES distinct (bucket, route) pairs + the overflow
    # series; dispatch counts are conserved across them
    series = sandbox.counters_snapshot("swarm_device_kernel_dispatches")
    assert len(series) <= devicetelemetry.MAX_METRIC_SERIES + 1
    assert any('bucket="__overflow__"' in k for k in series)
    assert sum(series.values()) == 1000

    # +1: the "__overflow__|group" aggregation row itself
    assert len(snap["kernel"]) <= devicetelemetry.MAX_KERNEL_ROWS + 1
    assert snap["kernel_overflow"] == 1000 - devicetelemetry.MAX_KERNEL_ROWS
    assert sum(r["dispatches"] for r in snap["kernel"].values()) == 1000
    assert "__overflow__|group" in snap["kernel"]

    assert len(snap["compile_cache"]) <= devicetelemetry.MAX_CACHE_ROWS
    assert snap["compile_cache_overflow"] \
        == 1000 - devicetelemetry.MAX_CACHE_ROWS

    # unknown reasons lump into "other" — reason labels stay a fixed set
    assert set(snap["transfers"]["h2d"]) == {"other"}
    assert snap["transfers"]["h2d"]["other"]["count"] == 50

    devicetelemetry.note_donated(range(2 * devicetelemetry.MAX_DONATED_IDS))
    assert devicetelemetry.snapshot()["donation"]["outstanding"] \
        <= devicetelemetry.MAX_DONATED_IDS


# ------------------------------------------------------- donation balance

def test_donation_balance_detects_read_after_donation():
    """note_donated → check_live on the same id is a counted, returned
    violation; a retired id is clean; the check never raises."""
    a, b = object(), object()
    devicetelemetry.note_donated([id(a), id(b)])
    devicetelemetry.note_retired([id(b)])
    bad = devicetelemetry.check_live([id(a), id(b)])
    assert bad == [id(a)]
    don = devicetelemetry.snapshot()["donation"]
    assert don == {"donated": 2, "retired": 1,
                   "outstanding": 1, "violations": 1}
    assert devicetelemetry.check_live([id(b)]) == []
    # retiring an id that was never donated is a no-op, not an error
    devicetelemetry.note_retired([id(a) + 12345])
    assert devicetelemetry.snapshot()["donation"]["retired"] == 1


# ------------------------------------------------------- render-on-empty

def test_debug_device_page_renders_on_empty():
    """/debug/device on a fresh process: 200, valid JSON, empty tables
    — never a 500 because nothing has dispatched yet."""
    from swarmkit_tpu.obs import debugpages
    body, status, ctype = debugpages._h_device(None, {})
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert doc["device_telemetry"]["kernel"] == {}
    assert doc["device_telemetry"]["donation"]["donated"] == 0
    assert "device_plane" in doc
    # the plane sub-rows contract: empty dict before any device work
    assert devicetelemetry.sub_plane_rows() == {}
    assert devicetelemetry.journey_sub_attribution(1.0) is None


# --------------------------------------------------- flightrec embedding

def test_flightrec_dump_embeds_device_ledger(tmp_path):
    """A live flight-recorder dump carries the device ledger and the
    per-signature compile cache (read back from disk); deterministic
    captures omit it (wall-clock-tainted ns fields stay out of
    seed-pure sim dumps)."""
    from swarmkit_tpu.obs.flightrec import flightrec
    state = flightrec.save_state()
    flightrec.reset(deterministic=False)
    try:
        devicetelemetry.note_kernel("nb1024", "fused", dispatch_s=0.002,
                                    groups=4, task_rows=200)
        devicetelemetry.note_compile("nb1024", 0.5)
        devicetelemetry.note_h2d("cold_build", 4096)
        path = str(tmp_path / "dump.json")
        digest = flightrec.dump(path)
        assert len(digest) == 64          # dump() returns the sha256
        with open(path) as f:
            doc = json.load(f)
        led = doc["device_telemetry"]
        assert led["kernel"]["nb1024|fused"]["dispatches"] == 1
        assert led["kernel"]["nb1024|fused"]["groups"] == 4
        cc = led["compile_cache"]["nb1024"]
        assert cc["compiles"] == 1 and cc["compile_ns"] == 500_000_000
        assert cc["shape_hash"] == __import__("zlib").crc32(b"nb1024")
        assert led["transfers"]["h2d"]["cold_build"]["bytes"] == 4096

        flightrec.reset(deterministic=True)
        assert "device_telemetry" not in flightrec.snapshot()
    finally:
        flightrec.restore_state(state)
