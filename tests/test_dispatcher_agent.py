"""Dispatcher + agent + exec FSM tests: the SURVEY §7.5 end-to-end slice.

One process: store → orchestrator → scheduler → dispatcher → agent(fake
executor) → RUNNING status written back; heartbeat expiry → node DOWN →
restart elsewhere (mirrors manager/dispatcher/dispatcher_test.go and
integration/integration_test.go behaviors).
"""

import time

import pytest

from swarmkit_tpu.agent import Agent
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.manager import Allocator, Dispatcher
from swarmkit_tpu.manager.dispatcher import (
    Config_, ErrNodeNotFound, ErrSessionInvalid,
)
from swarmkit_tpu.models import (
    Annotations, Cluster, Node, NodeState, Task, TaskState, TaskStatus,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import now
from swarmkit_tpu.orchestrator import ReplicatedOrchestrator
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import ByService, MemoryStore
from swarmkit_tpu.utils import new_id

from test_orchestrator import make_node, make_replicated, poll
from test_scheduler import make_ready_node


@pytest.fixture
def store():
    s = MemoryStore()
    cluster = Cluster(id=new_id(), spec=ClusterSpec(
        annotations=Annotations(name="default")))
    s.update(lambda tx: tx.create(cluster))
    yield s
    s.close()


def fast_config(**kw):
    defaults = dict(heartbeat_period=0.3, heartbeat_epsilon=0.02,
                    grace_multiplier=3, process_updates_interval=0.02,
                    assignment_batching_wait=0.02, orphan_timeout=2.0)
    defaults.update(kw)
    return Config_(**defaults)


def test_register_requires_known_node(store):
    d = Dispatcher(store, fast_config())
    d.run()
    try:
        with pytest.raises(ErrNodeNotFound):
            d.register("nope")
    finally:
        d.stop()


def test_register_rate_limit(store):
    """Re-registration is rate limited per node (reference: nodes.go:90
    CheckRateLimit — RATE_LIMIT_COUNT re-registrations per period, reset
    once the last registration ages past the period)."""
    from swarmkit_tpu.manager.dispatcher import ErrRateLimited

    d = Dispatcher(store, fast_config(rate_limit_period=0.5))
    d.run()
    node = make_ready_node("n1")
    store.update(lambda tx: tx.create(node))
    try:
        d.register(node.id)
        for _ in range(3):        # three rapid re-registrations pass
            d.register(node.id)
        with pytest.raises(ErrRateLimited):
            d.register(node.id)   # the fourth within the period fails
        time.sleep(0.6)           # ...and ages out
        d.register(node.id)

        # disabled limit (period 0): unlimited re-registration
        d2 = Dispatcher(store, fast_config(rate_limit_period=0.0))
        d2.run()
        try:
            for _ in range(10):
                d2.register(node.id)
        finally:
            d2.stop()
    finally:
        d.stop()


def test_heartbeat_session_validation(store):
    d = Dispatcher(store, fast_config())
    d.run()
    node = make_ready_node("n1")
    store.update(lambda tx: tx.create(node))
    try:
        session, period = d.register(node.id)
        assert period > 0
        assert d.heartbeat(node.id, session) > 0
        with pytest.raises(ErrSessionInvalid):
            d.heartbeat(node.id, "bogus")
    finally:
        d.stop()


def test_heartbeat_expiry_marks_node_down(store):
    d = Dispatcher(store, fast_config())
    d.run()
    node = make_ready_node("n1")
    store.update(lambda tx: tx.create(node))
    try:
        d.register(node.id)
        poll(lambda: store.view(
            lambda tx: tx.get(Node, node.id)).status.state
            == NodeState.READY)
        # no heartbeats: after period * grace the node must go DOWN
        poll(lambda: store.view(
            lambda tx: tx.get(Node, node.id)).status.state
            == NodeState.DOWN,
            timeout=5, msg="node should go DOWN after heartbeat expiry")
    finally:
        d.stop()


def test_orphan_timeout_moves_tasks_to_orphaned(store):
    d = Dispatcher(store, fast_config(orphan_timeout=0.5))
    d.run()
    node = make_ready_node("n1")
    t = Task(id=new_id(), service_id=new_id(), slot=1, node_id=node.id,
             desired_state=TaskState.RUNNING,
             status=TaskStatus(state=TaskState.RUNNING))

    def setup(tx):
        tx.create(node)
        tx.create(t)
    store.update(setup)
    try:
        d.register(node.id)
        poll(lambda: store.view(
            lambda tx: tx.get(Node, node.id)).status.state
            == NodeState.DOWN, timeout=5)
        poll(lambda: store.view(
            lambda tx: tx.get(Task, t.id)).status.state
            == TaskState.ORPHANED,
            timeout=5, msg="tasks on long-dead node become ORPHANED")
    finally:
        d.stop()


def test_assignments_stream_complete_and_incremental(store):
    d = Dispatcher(store, fast_config())
    d.run()
    node = make_ready_node("n1")
    t1 = Task(id=new_id(), service_id="svc", slot=1, node_id=node.id,
              desired_state=TaskState.RUNNING,
              status=TaskStatus(state=TaskState.ASSIGNED))

    def setup(tx):
        tx.create(node)
        tx.create(t1)
    store.update(setup)
    try:
        session, _ = d.register(node.id)
        stream = d.open_assignments(node.id, session)
        msg = stream.get(timeout=2)
        assert msg.type == "complete"
        assert [obj.id for _, kind, obj in msg.changes
                if kind == "task"] == [t1.id]

        # a new assignment arrives incrementally
        t2 = Task(id=new_id(), service_id="svc", slot=2, node_id=node.id,
                  desired_state=TaskState.RUNNING,
                  status=TaskStatus(state=TaskState.ASSIGNED))
        store.update(lambda tx: tx.create(t2))
        # create events don't reach agents (tasks are never created
        # ASSIGNED by the real pipeline); an update does
        t2b = store.view(lambda tx: tx.get(Task, t2.id)).copy()
        t2b.status = TaskStatus(state=TaskState.ASSIGNED, timestamp=now())
        t2b.desired_state = TaskState.RUNNING
        store.update(lambda tx: tx.update(t2b))

        msg = stream.get(timeout=2)
        assert msg.type == "incremental"
        assert {obj.id for _, kind, obj in msg.changes} >= {t2.id}
        assert msg.applies_to == "1"
    finally:
        d.stop()


def test_assignments_stream_from_block_commit_with_raft(store, tmp_path):
    """Columnar block commits with a LIVE raft proposer still produce
    correct per-session assignment diffs: each session receives exactly
    its node's slice of the block (as materialized ASSIGNED tasks), and
    the block rides consensus as a compact TaskBlockAction (VERDICT r3
    item 1 'done' criterion)."""
    import os as _os

    from swarmkit_tpu.state.raft import LocalNetwork, RaftLogger, RaftNode

    rn = RaftNode("m0", ["m0"], store,
                  RaftLogger(_os.path.join(str(tmp_path), "m0")),
                  LocalNetwork())
    store._proposer = rn
    rn.start()
    poll(lambda: rn.is_leader and rn.core.leader_ready, timeout=10)

    d = Dispatcher(store, fast_config())
    d.run()
    n1, n2 = make_ready_node("n1"), make_ready_node("n2")
    tasks = [Task(id=new_id(), service_id="svc", slot=i,
                  desired_state=TaskState.RUNNING,
                  status=TaskStatus(state=TaskState.PENDING))
             for i in range(6)]

    def setup(tx):
        tx.create(n1)
        tx.create(n2)
        for t in tasks:
            tx.create(t)
    store.update(setup)

    try:
        s1, _ = d.register(n1.id)
        s2, _ = d.register(n2.id)
        st1 = d.open_assignments(n1.id, s1)
        st2 = d.open_assignments(n2.id, s2)
        assert st1.get(timeout=2).type == "complete"
        assert st2.get(timeout=2).type == "complete"

        # columnar commit: evens to n1, odds to n2, one block
        stored = [store.raw_get(Task, t.id) for t in tasks]
        nids = [n1.id if i % 2 == 0 else n2.id for i in range(6)]
        committed, failed = store.commit_task_block(
            stored, nids, int(TaskState.ASSIGNED), "assigned",
            lambda t, n: None, lambda t, n: False)
        assert committed == list(range(6)) and failed == []

        msg1 = st1.get(timeout=2)
        assert msg1.type == "incremental"
        got1 = {obj.id for _, kind, obj in msg1.changes if kind == "task"}
        assert got1 == {tasks[i].id for i in (0, 2, 4)}
        for _, kind, obj in msg1.changes:
            if kind == "task":
                assert obj.node_id == n1.id
                assert obj.status.state == TaskState.ASSIGNED

        msg2 = st2.get(timeout=2)
        got2 = {obj.id for _, kind, obj in msg2.changes if kind == "task"}
        assert got2 == {tasks[i].id for i in (1, 3, 5)}
    finally:
        d.stop()
        rn.stop()


def test_update_task_status_rejects_foreign_node(store):
    d = Dispatcher(store, fast_config())
    d.run()
    n1, n2 = make_ready_node("n1"), make_ready_node("n2")
    t = Task(id=new_id(), service_id="svc", slot=1, node_id=n2.id,
             desired_state=TaskState.RUNNING,
             status=TaskStatus(state=TaskState.ASSIGNED))

    def setup(tx):
        tx.create(n1)
        tx.create(n2)
        tx.create(t)
    store.update(setup)
    try:
        session, _ = d.register(n1.id)
        with pytest.raises(Exception):
            d.update_task_status(
                n1.id, session,
                [(t.id, TaskStatus(state=TaskState.RUNNING))])
    finally:
        d.stop()


def test_e2e_service_to_running_via_dispatcher_and_agent(store):
    """The minimum end-to-end slice (SURVEY §7.5): service create →
    orchestrator → scheduler → dispatcher → agent → fake executor →
    RUNNING status written back through the dispatcher."""
    d = Dispatcher(store, fast_config())
    d.run()
    alloc = Allocator(store)
    sched = Scheduler(store)
    orch = ReplicatedOrchestrator(store)

    node = make_ready_node("n1", cpus=8)
    store.update(lambda tx: tx.create(node))

    agent = Agent(node.id, TestExecutor(hostname="n1"), d)
    alloc.start()
    sched.start()
    orch.start()
    agent.start()
    try:
        svc = make_replicated("web", 3)
        store.update(lambda tx: tx.create(svc))

        def all_running():
            got = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state == TaskState.RUNNING]
            return (len(got) == 3
                    and all(t.status.state == TaskState.RUNNING
                            for t in got)
                    and all(t.node_id == node.id for t in got))

        poll(all_running, timeout=20,
             msg="3 replicas should reach RUNNING through the full pipeline")

        # the worker runs exactly the assigned tasks
        poll(lambda: len(agent.worker.task_managers) == 3)

        # scale down: agent must stop the removed tasks
        cur = store.view(lambda tx: tx.get(Service, svc.id)).copy()
        from swarmkit_tpu.models import ReplicatedService, Service as _S
        cur.spec.replicated = ReplicatedService(replicas=1)
        store.update(lambda tx: tx.update(cur))

        def scaled():
            got = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
            live = [t for t in got
                    if t.desired_state == TaskState.RUNNING]
            shut = [t for t in got
                    if t.desired_state >= TaskState.SHUTDOWN]
            return (len(live) == 1
                    and all(t.status.state >= TaskState.SHUTDOWN
                            for t in shut))
        poll(scaled, timeout=20,
             msg="scaled-down tasks should be shut down by the agent")
    finally:
        agent.stop()
        orch.stop()
        sched.stop()
        alloc.stop()
        d.stop()


from swarmkit_tpu.models import Service  # noqa: E402  (used in poll closures)


def test_e2e_agent_death_reschedules_tasks(store):
    """Kill the agent (stop heartbeating) → node DOWN → orchestrator
    replaces tasks → scheduler assigns to the surviving node → its agent
    runs them."""
    d = Dispatcher(store, fast_config())
    d.run()
    alloc = Allocator(store)
    alloc.start()
    sched = Scheduler(store)
    orch = ReplicatedOrchestrator(store)

    n1, n2 = make_ready_node("n1", cpus=8), make_ready_node("n2", cpus=8)
    store.update(lambda tx: (tx.create(n1), tx.create(n2)))

    agent1 = Agent(n1.id, TestExecutor(hostname="n1"), d)
    agent2 = Agent(n2.id, TestExecutor(hostname="n2"), d)
    sched.start()
    orch.start()
    agent1.start()
    agent2.start()
    try:
        svc = make_replicated("web", 2)
        store.update(lambda tx: tx.create(svc))

        def all_running():
            got = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state == TaskState.RUNNING]
            return (len(got) == 2
                    and all(t.status.state == TaskState.RUNNING
                            for t in got))
        poll(all_running, timeout=20)

        # kill agent1: heartbeats stop, node n1 goes DOWN, tasks restarted
        agent1.stop()

        def healed():
            got = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state == TaskState.RUNNING]
            return (len(got) == 2
                    and all(t.status.state == TaskState.RUNNING
                            for t in got)
                    and all(t.node_id == n2.id for t in got))
        poll(healed, timeout=20,
             msg="tasks should be rescheduled onto the surviving node")
    finally:
        agent2.stop()
        orch.stop()
        sched.stop()
        alloc.stop()
        d.stop()


def test_driver_backed_secrets_fetch_per_task_values(store):
    """Secrets with spec.driver fetch their value from a provider plugin
    at assignment time; DoNotReuse providers yield task-specific secret
    ids/values (reference: manager/drivers/secrets.go + assignments.go
    assignSecret)."""
    from swarmkit_tpu.manager.drivers import DriverProvider
    from swarmkit_tpu.models import Secret
    from swarmkit_tpu.models.specs import ContainerSpec, SecretSpec, TaskSpec
    from swarmkit_tpu.models.types import Driver, SecretReference

    calls = []

    def plugin(req):
        calls.append(req)
        import base64
        value = f"v-for-{req['TaskID']}".encode()
        return {"Value": base64.b64encode(value).decode(),
                "DoNotReuse": True}

    provider = DriverProvider({"vault": plugin})
    d = Dispatcher(store, fast_config(), driver_provider=provider)
    d.run()
    node = make_ready_node("n1")
    secret = Secret(id=new_id(), spec=SecretSpec(
        annotations=Annotations(name="db-pass"),
        driver=Driver(name="vault")))

    def mk_task(slot):
        return Task(id=new_id(), service_id="svc", slot=slot,
                    node_id=node.id, desired_state=TaskState.RUNNING,
                    status=TaskStatus(state=TaskState.ASSIGNED),
                    spec=TaskSpec(container=ContainerSpec(
                        image="img", secrets=[SecretReference(
                            secret_id=secret.id, secret_name="db-pass")])))

    t1, t2 = mk_task(1), mk_task(2)

    def setup(tx):
        tx.create(node)
        tx.create(secret)
        tx.create(t1)
        tx.create(t2)
    store.update(setup)
    try:
        session, _ = d.register(node.id)
        stream = d.open_assignments(node.id, session)
        msg = stream.get(timeout=2)
        assert msg.type == "complete"
        secrets = {obj.id: obj for _, kind, obj in msg.changes
                   if kind == "secret"}
        assert set(secrets) == {f"{secret.id}.{t1.id}",
                                f"{secret.id}.{t2.id}"}, \
            "DoNotReuse secrets must get task-specific ids"
        assert secrets[f"{secret.id}.{t1.id}"].spec.data == \
            f"v-for-{t1.id}".encode()
        assert secrets[f"{secret.id}.{t2.id}"].spec.data == \
            f"v-for-{t2.id}".encode()
        assert all(s.internal for s in secrets.values())
        assert len(calls) == 2
        assert calls[0]["SecretName"] == "db-pass"
        assert calls[0]["NodeID"] == node.id
    finally:
        d.stop()


def test_driver_secret_fetch_error_skips_assignment(store):
    """Provider failures leave the secret unassigned rather than shipping
    an empty value (reference: assignments.go fetch-error path)."""
    from swarmkit_tpu.manager.drivers import DriverProvider
    from swarmkit_tpu.models import Secret
    from swarmkit_tpu.models.specs import ContainerSpec, SecretSpec, TaskSpec
    from swarmkit_tpu.models.types import Driver, SecretReference

    def bad_plugin(req):
        return {"Err": "vault is sealed"}

    provider = DriverProvider({"vault": bad_plugin})
    d = Dispatcher(store, fast_config(), driver_provider=provider)
    d.run()
    node = make_ready_node("n1")
    secret = Secret(id=new_id(), spec=SecretSpec(
        annotations=Annotations(name="db-pass"),
        driver=Driver(name="vault")))
    t1 = Task(id=new_id(), service_id="svc", slot=1, node_id=node.id,
              desired_state=TaskState.RUNNING,
              status=TaskStatus(state=TaskState.ASSIGNED),
              spec=TaskSpec(container=ContainerSpec(
                  image="img", secrets=[SecretReference(
                      secret_id=secret.id, secret_name="db-pass")])))

    def setup(tx):
        tx.create(node)
        tx.create(secret)
        tx.create(t1)
    store.update(setup)
    try:
        session, _ = d.register(node.id)
        stream = d.open_assignments(node.id, session)
        msg = stream.get(timeout=2)
        assert msg.type == "complete"
        assert [obj.id for _, kind, obj in msg.changes
                if kind == "secret"] == [], \
            "failed driver fetch must not ship a secret"
        assert [obj.id for _, kind, obj in msg.changes
                if kind == "task"] == [t1.id], "the task still ships"
    finally:
        d.stop()


def test_driver_secret_retries_until_provider_recovers(store):
    """A transient provider outage heals: the assignments loop retries
    failed fetches on idle ticks and ships the secret once the provider
    answers."""
    from swarmkit_tpu.manager.drivers import DriverProvider
    from swarmkit_tpu.models import Secret
    from swarmkit_tpu.models.specs import ContainerSpec, SecretSpec, TaskSpec
    from swarmkit_tpu.models.types import Driver, SecretReference

    state = {"n": 0}

    def flaky_plugin(req):
        state["n"] += 1
        if state["n"] <= 2:
            return {"Err": "vault sealed"}
        import base64
        return {"Value": base64.b64encode(b"recovered").decode()}

    provider = DriverProvider({"vault": flaky_plugin})
    d = Dispatcher(store, fast_config(), driver_provider=provider)
    d.run()
    node = make_ready_node("n1")
    secret = Secret(id=new_id(), spec=SecretSpec(
        annotations=Annotations(name="db-pass"),
        driver=Driver(name="vault")))
    t1 = Task(id=new_id(), service_id="svc", slot=1, node_id=node.id,
              desired_state=TaskState.RUNNING,
              status=TaskStatus(state=TaskState.ASSIGNED),
              spec=TaskSpec(container=ContainerSpec(
                  image="img", secrets=[SecretReference(
                      secret_id=secret.id, secret_name="db-pass")])))

    def setup(tx):
        tx.create(node)
        tx.create(secret)
        tx.create(t1)
    store.update(setup)
    try:
        session, _ = d.register(node.id)
        stream = d.open_assignments(node.id, session)
        msg = stream.get(timeout=2)
        assert msg.type == "complete"
        assert not [o for _, k, o in msg.changes if k == "secret"]

        # the loop's idle-tick retry eventually ships it
        deadline = time.time() + 10
        got = None
        while time.time() < deadline:
            d.heartbeat(node.id, session)   # keep the session alive
            try:
                msg = stream.get(timeout=0.25)
            except TimeoutError:
                continue
            secrets = [o for _, k, o in msg.changes if k == "secret"]
            if secrets:
                got = secrets[0]
                break
        assert got is not None, "secret never shipped after recovery"
        assert got.spec.data == b"recovered"
        assert state["n"] >= 3
    finally:
        d.stop()
