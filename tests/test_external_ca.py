"""External CA: CFSSL-style delegated node-cert signing.

Reference: ca/external.go (ExternalCA.Sign), ca/server.go signing path.
A CFSSL-compatible HTTP signer backed by the SAME cluster root signs
CSRs; the manager delegates issuance/renewal to it when
ClusterSpec.ca_config.external_cas is set, and falls back to local
signing when every signer is down (documented deviation).
"""

import json
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

from swarmkit_tpu.models import Cluster
from swarmkit_tpu.security.ca import RootCA, signing_root_digest
from swarmkit_tpu.security.external import ExternalCA, ExternalSigningError
from swarmkit_tpu.state.store import ByName
from swarmkit_tpu.swarmd import Swarmd

from test_orchestrator import poll
import pytest

pytest.importorskip(
    "cryptography", reason="CA/TLS tests require the cryptography package")


class CFSSLServer:
    """Minimal cfssl 'sign' endpoint backed by a RootCA instance."""

    def __init__(self, root_ca: RootCA):
        outer = self
        self.root_ca = root_ca
        self.requests = []

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length))
                outer.requests.append(body)
                csr = body["certificate_request"].encode()
                subject = body.get("subject", {})
                node_id = subject.get("CN", "")
                names = subject.get("names") or [{}]
                ou = names[0].get("OU", "swarm-worker")
                role = 1 if ou == "swarm-manager" else 0
                cert_pem = outer.root_ca.sign_csr(csr, node_id, role)
                resp = {"success": True,
                        "result": {"certificate": cert_pem.decode()}}
                payload = json.dumps(resp).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):
                pass

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


def test_external_ca_unit_sign_and_failover():
    root = RootCA()
    good = CFSSLServer(root)
    try:
        from swarmkit_tpu.security.ca import generate_key_pem, make_csr
        key_pem = generate_key_pem()
        csr = make_csr("node-1", key_pem)
        # a dead URL first: the client must fail over to the live one
        ext = ExternalCA(["http://127.0.0.1:1", good.url], org=root.org)
        cert_pem = ext.sign_csr(csr, "node-1", 0)
        from swarmkit_tpu.security.ca import Certificate
        cert = Certificate(cert_pem=cert_pem, key_pem=key_pem,
                           ca_cert_pem=root.trust_bundle())
        root.verify(cert)
        assert cert.node_id == "node-1" and cert.role == 0
        assert ext.stats["signed"] == 1 and ext.stats["errors"] == 1

        ext_dead = ExternalCA(["http://127.0.0.1:1"], org=root.org)
        try:
            ext_dead.sign_csr(csr, "node-1", 0)
            raise AssertionError("dead signer should raise")
        except ExternalSigningError:
            pass
    finally:
        good.stop()


def test_external_ca_signs_cluster_joins_and_renewals():
    m0 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="m0", manager=True,
                listen_remote_api=("127.0.0.1", 0),
                use_device_scheduler=False)
    m0.start()
    signer = CFSSLServer(m0.manager.root_ca)
    w = None
    try:
        api = m0.manager.control_api
        c = api.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0].copy()
        c.spec.ca_config.node_cert_expiry = 10.0   # force fast renewal
        api.store.update(lambda tx: tx.update(c))
        # the operator surface: swarmctl cluster external-ca <url>
        from swarmkit_tpu.cli import run_command
        out = run_command(["cluster", "external-ca", signer.url], api)
        assert signer.url in out
        poll(lambda: m0.manager.ca_server.external is not None,
             msg="manager wires the external signer from the spec")

        w = Swarmd(state_dir=tempfile.mkdtemp(), hostname="w0",
                   join_addr=m0.server.addr,
                   join_token=m0.manager.root_ca.join_token(0),
                   cert_renew_interval=0.5)
        w.start()
        assert len(signer.requests) >= 1, \
            "the join CSR must be signed externally"
        cert0 = w.node.certificate
        m0.manager.root_ca.verify(cert0)
        assert signing_root_digest(cert0) == m0.manager.root_ca.digest

        # renewal also routes through the external signer
        n_before = len(signer.requests)
        poll(lambda: w.node.certificate.expires_at > cert0.expires_at,
             timeout=20, msg="renewal happens")
        assert len(signer.requests) > n_before, \
            "the renewal CSR must be signed externally"

        # signer dies: issuance falls back to the local root (documented
        # deviation) and the cluster keeps admitting nodes
        signer.stop()
        w2 = Swarmd(state_dir=tempfile.mkdtemp(), hostname="w1",
                    join_addr=m0.server.addr,
                    join_token=m0.manager.root_ca.join_token(0))
        w2.start()
        try:
            m0.manager.root_ca.verify(w2.node.certificate)
        finally:
            w2.stop()
    finally:
        if w is not None:
            w.stop()
        try:
            signer.stop()
        except Exception:
            pass
        m0.stop()


def test_external_ca_bad_signer_falls_back_to_local():
    """A signer that 'succeeds' with a cert from the WRONG root must not
    poison node identity: validation rejects it and the local root
    signs."""
    from swarmkit_tpu.security.ca import CAServer, generate_key_pem, make_csr

    cluster_root = RootCA()
    foreign_root = RootCA()          # evil/misconfigured signer backing
    bad = CFSSLServer(foreign_root)
    try:
        server = CAServer(cluster_root)
        server.external = ExternalCA([bad.url], org=cluster_root.org)
        key_pem = generate_key_pem()
        csr = make_csr("node-x", key_pem)
        token = cluster_root.join_token(0)
        cert_pem = server.issue_node_certificate("node-x", token,
                                                 csr_pem=csr)
        assert len(bad.requests) == 1, "the bad signer was consulted"
        from swarmkit_tpu.security.ca import Certificate
        cert = Certificate(cert_pem=cert_pem, key_pem=key_pem,
                           ca_cert_pem=cluster_root.trust_bundle())
        cluster_root.verify(cert)    # locally-signed fallback chains
    finally:
        bad.stop()
