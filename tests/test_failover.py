"""Leader-failover robustness: epoch-fenced proposals (both fence
points), the raft-attached sim control plane with its two new
invariants, WAL/snapshot integrity, reconnect jitter, the flight
recorder's crash hook, restart-timer re-arming across failover, and the
planner's degraded-mode circuit breaker.
"""

import base64
import json
import os
import subprocess
import sys
import threading

import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    ReplicatedService, Resources, Service, ServiceMode, ServiceSpec, Task,
    TaskSpec, TaskState, TaskStatus, Version,
)
from swarmkit_tpu.models import types as mtypes
from swarmkit_tpu.models.types import RestartPolicy
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.state.raft import (
    LocalNetwork, ProposalDropped, RaftLogger, RaftNode,
)
from swarmkit_tpu.state.raft.core import LEADER
from swarmkit_tpu.state.raft.node import StaleEpoch
from swarmkit_tpu.state.store import StoreAction

from test_orchestrator import poll


# ---------------------------------------------------------------------------
# Epoch fencing: both fence points, unit-level (no raft thread — the
# test drives the consensus loop by hand so the role change lands
# exactly between proposal creation and each fence point).
# ---------------------------------------------------------------------------

def _single_node(tmp_path):
    net = LocalNetwork()
    store = MemoryStore()
    logger = RaftLogger(os.path.join(str(tmp_path), "m0"))
    rn = RaftNode("m0", ["m0"], store, logger, net)
    store._proposer = rn
    _elect(rn)
    return rn


def _elect(rn, max_ticks=500):
    for _ in range(max_ticks):
        if rn.core.leader_ready:
            return
        rn.core.tick()
        rn._process_ready()
    raise AssertionError("single-member node failed to elect itself")


def _mk_node_action(name):
    return StoreAction("create", Node(
        id=name, spec=NodeSpec(annotations=Annotations(name=name))))


def test_pre_wal_fence_rejects_stale_epoch(tmp_path):
    """A proposal created under epoch E is rejected on the raft thread —
    before it can reach the log or WAL — once E is fenced by a
    depose-and-re-elect cycle that a naive role check would miss."""
    rn = _single_node(tmp_path)
    epoch0 = rn.leadership_epoch
    waiter = rn.propose_async([_mk_node_action("stale")])
    assert waiter.epoch == epoch0

    # forced role change while the proposal sits in the inbox: depose,
    # then re-elect (the member is leader AGAIN, but under a new epoch)
    rn.core.step_down()
    _elect(rn)
    assert rn.core.role == LEADER
    assert rn.leadership_epoch > epoch0

    last = rn.core.last_index()
    item = rn._inbox.get_nowait()
    rn._handle_proposal(*item)
    # fence point 1: nothing appended, waiter failed, reject counted
    assert rn.core.last_index() == last
    assert waiter.event.is_set() and not waiter.ok
    assert rn.stats["stale_epoch_rejects"] >= 1
    with pytest.raises(ProposalDropped):
        rn.wait_proposal(waiter)
    assert rn.store.raw_get(Node, "stale") is None
    rn.logger.close()


def test_commit_callback_fence_rejects_stale_epoch(tmp_path):
    """An entry that reaches the log under epoch E but commits after E
    was fenced must fail its proposer WITHOUT running the commit
    callback — while the store still converges via the follower-style
    remote apply (the entry is committed cluster state)."""
    rn = _single_node(tmp_path)
    ran = []
    waiter = rn.propose_async([_mk_node_action("fenced")],
                              commit_cb=lambda: ran.append(1))
    # append the entry under the current epoch (passes fence point 1)
    rn._handle_proposal(*rn._inbox.get_nowait())
    assert rn.core.last_index() > 0

    # the race under test: leadership epoch is fenced AFTER the entry is
    # in the log but BEFORE its commit callback is delivered
    rn.core.fence_epoch()
    rn._process_ready()   # commits + applies the entry

    assert waiter.event.is_set() and not waiter.ok
    assert ran == [], "commit callback must not run under a fenced epoch"
    with pytest.raises(ProposalDropped):
        rn.wait_proposal(waiter)
    # convergence: the committed entry still applied (remote-apply path)
    assert rn.store.raw_get(Node, "fenced") is not None
    assert rn.stats["stale_epoch_rejects"] >= 1
    rn.logger.close()


def test_epoch_pin_rejected_before_serialization(tmp_path):
    """propose_async(epoch=E) with a fenced E raises StaleEpoch
    immediately — multi-chunk commits pinned to a dead reign never even
    serialize their later chunks."""
    rn = _single_node(tmp_path)
    epoch0 = rn.leadership_epoch
    rn.core.step_down()
    _elect(rn)
    with pytest.raises(StaleEpoch):
        rn.propose_async([_mk_node_action("x")], epoch=epoch0)
    # unpinned proposals under the new reign still work (the node has
    # no raft thread, so drain the inbox by hand before waiting)
    ran = []
    w = rn.propose_async([_mk_node_action("fresh")],
                         commit_cb=lambda: ran.append(1))
    _drain_and_commit(rn)
    rn.wait_proposal(w)
    assert ran == [1]
    rn.logger.close()


def _drain_and_commit(rn, max_ticks=50):
    # the node has no thread: drain the inbox + Ready loop by hand
    import queue as _q
    for _ in range(max_ticks):
        try:
            item = rn._inbox.get_nowait()
        except _q.Empty:
            break
        rn._handle_proposal(*item)
    rn._process_ready()


def test_epoch_survives_restart_monotonic(tmp_path):
    """Epochs after a crash-restart are strictly above every pre-crash
    epoch — INCLUDING epochs inflated well past the term by
    deposal/re-election flaps and explicit handler fences (the
    term-stride epoch space) — so a restarted proposer can never
    accidentally match a pre-crash pin."""
    rn = _single_node(tmp_path)
    w = rn.propose_async([_mk_node_action("a")])
    _drain_and_commit(rn)
    rn.wait_proposal(w)
    # inflate the epoch far past the bare term: flaps + explicit fences
    for _ in range(3):
        rn.core.step_down()
        _elect(rn)
        rn.core.fence_epoch()
        rn.core.fence_epoch()
    epoch0 = rn.leadership_epoch
    assert epoch0 > rn.core.term, "flaps must outpace the term"
    rn.logger.close()

    net2 = LocalNetwork()
    store2 = MemoryStore()
    logger2 = RaftLogger(os.path.join(str(tmp_path), "m0"))
    rn2 = RaftNode("m0", ["m0"], store2, logger2, net2)
    store2._proposer = rn2
    _elect(rn2)
    assert rn2.leadership_epoch > epoch0
    rn2.logger.close()


# ---------------------------------------------------------------------------
# Raft-attached sim control plane + failover scenarios
# ---------------------------------------------------------------------------

def test_failover_scenarios_fast():
    """Tier-1 sweep: every failover scenario (leader crash mid-tick and
    partition mid-pipelined-commit at store pipeline depths 1 and 2,
    plus rollout churn) across a small deterministic seed set — all
    invariants hold and every live task is re-placed on the successor."""
    from swarmkit_tpu.sim import run_scenario
    from swarmkit_tpu.sim.scenario import FAILOVER_SCENARIOS
    for name in FAILOVER_SCENARIOS:
        for seed in (0, 7):
            r = run_scenario(name, seed=seed)
            assert r.ok, (name, seed, r.violations)
            ctl = r.stats["control"]
            assert ctl["attaches"] >= 2, \
                (name, seed, "failover never handed the loops over")
            assert r.stats["tasks"].get("RUNNING", 0) > 0, (name, seed)


def test_failover_scenario_deterministic():
    from swarmkit_tpu.sim import run_scenario
    r1 = run_scenario("leader-crash-mid-tick", seed=7, keep_trace=True)
    r2 = run_scenario("leader-crash-mid-tick", seed=7)
    assert r1.ok, r1.violations
    assert r1.trace_hash == r2.trace_hash
    assert any("mid-tick" in line for line in r1.trace), \
        "the mid-tick strike must fire"
    assert any("control detach" in line for line in r1.trace)
    assert any("control attach" in line for line in r1.trace)


@pytest.mark.slow
def test_failover_fuzz_wide_sweep():
    """Acceptance sweep: >= 20 seeds of leader-crash-mid-tick and
    partition-pipelined-commit at depths 1 and 2, zero violations
    (no-stale-epoch-commit and control-loops-only-on-leader hold
    everywhere)."""
    from swarmkit_tpu.sim import run_scenario
    bad = []
    for name in ("leader-crash-mid-tick", "leader-crash-mid-tick-d1",
                 "partition-pipelined-commit",
                 "partition-pipelined-commit-d1"):
        for seed in range(20):
            r = run_scenario(name, seed=seed)
            if not r.ok:
                bad.append((name, seed, r.violations[:3]))
    assert not bad, bad


def test_failover_fuzz_cli():
    """scripts/failover_fuzz.py: exit 0 on a clean deterministic run,
    machine-readable JSON verdict on stdout."""
    proc = subprocess.run(
        [sys.executable, "scripts/failover_fuzz.py", "--fuzz", "1",
         "--scenario", "failover-churn-rollout", "--quiet"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout)
    assert verdict["ok"] is True
    assert verdict["runs"] == 1


def test_restarted_member_store_keeps_proposer():
    """A crashed member rebuilds its replicated store from the WAL on
    restart; the rebuilt store must keep its member-bound proposer — a
    proposer-less rebuild would let a re-elected ex-leader commit
    locally with no consensus and no fencing."""
    from swarmkit_tpu.sim.cluster import Sim
    sim = Sim(seed=3, raft_cp=True)
    with sim:
        eng = sim.engine
        sim.cp.create_tasks(4)
        sim.run(8.0)
        lead = sim.cp.active.member
        lead.crash()
        sim.run(eng.clock.elapsed() + 3.0)
        lead.restart()
        assert lead.store._proposer is sim.cp.proposers[lead.id]
        sim.run(eng.clock.elapsed() + 5.0)
        sim.cp.stopped = True
        sim.finishing = True
        for m in sim.managers:
            m.stopped = True
    assert not sim.violations.items, sim.violations.items


def test_stale_epoch_commit_checker_fires():
    """Checker sensitivity: with fencing force-disabled, a commit
    callback delivered under a fenced epoch RUNS — and the
    no-stale-epoch-commit invariant must flag it."""
    from swarmkit_tpu.sim.cluster import Sim
    sim = Sim(seed=9, raft_cp=True)
    with sim:
        eng = sim.engine
        sim.cp.create_tasks(4)
        sim.run(8.0)
        mc = sim.cp.active
        assert mc is not None, "control plane never attached"
        member = mc.member
        proposer = sim.cp.proposers[member.id]
        proposer.enforce_fencing = False
        ran = []
        proposer.propose_async([_mk_node_action("wx")],
                               commit_cb=lambda: ran.append(1))
        # fence lands AFTER the entry entered the log, BEFORE commit
        # delivery — exactly the race fencing exists to close
        member.core.fence_epoch()
        sim.run(eng.clock.elapsed() + 3.0)
        sim.cp.stopped = True
        sim.finishing = True
        for m in sim.managers:
            m.stopped = True
    assert ran == [1], "with fencing disabled the stale commit must run"
    assert any("no-stale-epoch-commit" in v
               for v in sim.violations.items), sim.violations.items


def test_control_loops_only_on_leader_checker_fires():
    """Checker sensitivity: break the detach-on-deposal handler and
    force a stepdown — the control-loops-only-on-leader invariant must
    catch the deposed member still holding live loops."""
    from swarmkit_tpu.sim.cluster import Sim
    sim = Sim(seed=9, raft_cp=True)
    with sim:
        eng = sim.engine
        sim.cp.create_tasks(4)
        sim.run(8.0)
        assert sim.cp.active is not None
        sim.cp.detach_on_depose = False     # the injected bug
        sim.stepdown_leader()
        sim.run(eng.clock.elapsed() + 3.0)
        sim.cp.stopped = True
        sim.finishing = True
        for m in sim.managers:
            m.stopped = True
    assert any("control-loops-only-on-leader" in v
               for v in sim.violations.items), sim.violations.items


# ---------------------------------------------------------------------------
# WAL/snapshot integrity (CRC32 + body hash + quarantine)
# ---------------------------------------------------------------------------

def _wal_lines(path):
    with open(path, "rb") as f:
        return [ln for ln in f.read().splitlines() if ln.strip()]


def _rewrite_wal(path, lines):
    with open(path, "wb") as f:
        f.write(b"\n".join(lines) + b"\n")


def test_wal_crc_catches_bit_flip(tmp_path):
    from swarmkit_tpu.state.raft.core import Entry, HardState
    logger = RaftLogger(str(tmp_path))
    logger.save(HardState(term=1, voted_for="m0", commit=0),
                [Entry(term=1, index=i, data=f"e{i}".encode())
                 for i in (1, 2, 3)])
    logger.close()

    wal = os.path.join(str(tmp_path), "wal.jsonl")
    lines = _wal_lines(wal)
    # flip a bit INSIDE entry 2's payload such that base64/JSON still
    # parse — only the CRC can catch this class of corruption
    rec = json.loads(base64.b64decode(lines[2]))
    assert rec["index"] == 2
    data = bytearray(base64.b64decode(rec["data"]))
    data[0] ^= 0x40
    rec["data"] = base64.b64encode(bytes(data)).decode("ascii")
    lines[2] = base64.b64encode(json.dumps(
        rec, sort_keys=True, separators=(",", ":")).encode())
    _rewrite_wal(wal, lines)

    logger2 = RaftLogger(str(tmp_path))
    hs, entries, _ = logger2.bootstrap()
    # replay truncates AT the corrupt record: entry 1 survives, the
    # flipped entry 2 and everything after it do not
    assert [e.index for e in entries] == [1]
    logger2.close()


def test_wal_legacy_record_without_crc_replays(tmp_path):
    from swarmkit_tpu.state.raft.core import Entry, HardState
    logger = RaftLogger(str(tmp_path))
    logger.save(HardState(term=1, voted_for="", commit=0),
                [Entry(term=1, index=1, data=b"one")])
    logger.close()
    wal = os.path.join(str(tmp_path), "wal.jsonl")
    lines = _wal_lines(wal)
    # append a pre-CRC-era record by hand
    legacy = {"t": "ent", "term": 1, "index": 2, "type": 0,
              "data": base64.b64encode(b"two").decode("ascii")}
    lines.append(base64.b64encode(json.dumps(
        legacy, sort_keys=True, separators=(",", ":")).encode()))
    _rewrite_wal(wal, lines)
    logger2 = RaftLogger(str(tmp_path))
    _, entries, _ = logger2.bootstrap()
    assert [e.index for e in entries] == [1, 2]
    assert entries[1].data == b"two"
    logger2.close()


def test_snapshot_bit_flip_quarantined_wal_fallback(tmp_path):
    from swarmkit_tpu.state.raft.core import Entry, HardState, Snapshot
    logger = RaftLogger(str(tmp_path))
    logger.save(HardState(term=1, voted_for="", commit=3),
                [Entry(term=1, index=i, data=f"e{i}".encode())
                 for i in (1, 2, 3)])
    logger.save_snapshot(Snapshot(index=2, term=1, data=b"snapbody"),
                         keep_entries_from=2)
    logger.close()

    snap_path = os.path.join(str(tmp_path), "snapshot")
    rec = json.loads(open(snap_path, "rb").read())
    body = bytearray(base64.b64decode(rec["data"]))
    body[3] ^= 0x01
    rec["data"] = base64.b64encode(bytes(body)).decode("ascii")
    with open(snap_path, "w") as f:
        f.write(json.dumps(rec))

    logger2 = RaftLogger(str(tmp_path))
    hs, entries, snapshot = logger2.bootstrap()
    # corrupt snapshot: quarantined, not restored — bootstrap falls
    # back to WAL-only replay of the post-snapshot tail
    assert snapshot is None
    assert os.path.exists(snap_path + ".corrupt")
    assert not os.path.exists(snap_path)
    assert [e.index for e in entries] == [3]
    logger2.close()


def test_snapshot_intact_roundtrip_still_loads(tmp_path):
    from swarmkit_tpu.state.raft.core import Snapshot
    logger = RaftLogger(str(tmp_path))
    logger.save_snapshot(Snapshot(index=5, term=2, data=b"payload"),
                         keep_entries_from=5)
    snap = logger.load_snapshot()
    assert snap is not None and snap.data == b"payload"
    logger.close()


# ---------------------------------------------------------------------------
# Jittered reconnect backoff
# ---------------------------------------------------------------------------

def test_backoff_caps_and_grows():
    import random
    from swarmkit_tpu.remotes import backoff_with_jitter
    rng = random.Random(1)
    # ceiling doubles per attempt and caps at 8s; the draw never
    # exceeds its ceiling and never collapses to a hot-loop zero
    for attempt in range(0, 64):
        d = backoff_with_jitter(attempt, rng)
        ceiling = min(8.0, 0.1 * 2 ** min(attempt, 30))
        assert 0.0 < d <= ceiling
    # deep attempts saturate at the cap (no overflow)
    assert backoff_with_jitter(10_000, rng) <= 8.0


def test_backoff_jitter_desynchronizes_two_agents():
    import random
    from swarmkit_tpu.remotes import backoff_with_jitter
    a = [backoff_with_jitter(n, random.Random(1)) for n in range(12)]
    b = [backoff_with_jitter(n, random.Random(2)) for n in range(12)]
    # same failure schedule, different rng streams: the storms spread
    assert a != b
    assert sum(1 for x, y in zip(a, b) if abs(x - y) > 1e-6) >= 10
    # and the injected-rng seam is deterministic per seed
    assert a == [backoff_with_jitter(n, random.Random(1))
                 for n in range(12)]


# ---------------------------------------------------------------------------
# Flight-recorder crash hook
# ---------------------------------------------------------------------------

@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_crash_hook_dumps_postmortem(tmp_path, monkeypatch, caplog):
    import logging
    import sys as _sys
    import swarmkit_tpu.obs.flightrec  # noqa: F401 — module, not the singleton
    fr = _sys.modules["swarmkit_tpu.obs.flightrec"]
    monkeypatch.setenv("SWARM_FLIGHTREC_DIR", str(tmp_path))
    saved = fr.flightrec.save_state()
    fr.flightrec.reset()
    fr.flightrec.enabled = True
    fr.install_crash_hook()
    try:
        with caplog.at_level(logging.ERROR, logger="flightrec"):
            t = threading.Thread(
                target=lambda: (_ for _ in ()).throw(
                    RuntimeError("injected control-loop crash")),
                name="scheduler", daemon=True)
            t.start()
            t.join(timeout=10)
        dumps = list(tmp_path.glob("flightrec_crash_scheduler_*.json"))
        assert len(dumps) == 1, "exactly one post-mortem per crash"
        doc = json.loads(dumps[0].read_text())
        notes = [n[1] for n in doc["notes"]]
        assert any("injected control-loop crash" in n for n in notes)
        # path + sha are logged so the operator can find the evidence
        msg = "\n".join(r.getMessage() for r in caplog.records)
        assert str(dumps[0]) in msg and "sha256" in msg
    finally:
        fr.uninstall_crash_hook()
        fr.flightrec.enabled = False
        fr.flightrec.restore_state(saved)
    # hook chain restored
    assert threading.excepthook is not fr._crash_excepthook


# ---------------------------------------------------------------------------
# Restart supervisor: delayed-restart timers across leader failover
# ---------------------------------------------------------------------------

def _mk_restart_service(delay):
    return Service(
        id="svc-r",
        spec=ServiceSpec(
            annotations=Annotations(name="svc-r"),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=1),
            task=TaskSpec(restart=RestartPolicy(delay=delay))),
        spec_version=Version(index=1))


def test_restart_timer_rearms_on_new_leader_after_failover():
    """A delayed restart armed by the old leader survives failover: the
    new leader's taskinit pass re-arms it from the replicated store —
    exactly one replacement, started exactly once (no lost and no
    duplicated restarts across the handoff)."""
    from swarmkit_tpu.orchestrator import (
        ReplicatedOrchestrator, RestartSupervisor, taskinit,
    )
    store = MemoryStore()
    service = _mk_restart_service(delay=0.4)
    failed = Task(
        id="t-old", service_id=service.id, slot=1,
        desired_state=TaskState.RUNNING,
        spec=service.spec.task, spec_version=Version(index=1),
        status=TaskStatus(state=TaskState.FAILED,
                          timestamp=mtypes.now(), message="boom"))
    store.update(lambda tx: (tx.create(service), tx.create(failed)))

    # ---- old leader arms the delayed restart...
    sup_a = RestartSupervisor(store, start_worker=False)

    def cb(tx):
        t = tx.get(Task, "t-old")
        sup_a.restart(tx, None, service, t)
    store.update(cb)
    tasks = store.view(lambda tx: tx.find(Task))
    repl = [t for t in tasks if t.id != "t-old"]
    assert len(repl) == 1
    assert repl[0].desired_state == TaskState.READY, \
        "replacement must be delayed (READY), not started yet"

    # ---- ...and is deposed before the delay elapses
    sup_a.stop()
    after_stop = store.view(lambda tx: tx.get(Task, repl[0].id))
    assert after_stop.desired_state == TaskState.READY, \
        "a deposed leader must not fire the start on its way out"

    # ---- the new leader cold-starts from the replicated store
    sup_b = RestartSupervisor(store, start_worker=False)
    orch = ReplicatedOrchestrator(store, restarts=sup_b)
    taskinit.check_tasks(store, store.view(), orch, sup_b)
    # timer re-armed, not lost — and not fired early either
    cur = store.view(lambda tx: tx.get(Task, repl[0].id))
    if cur.desired_state == TaskState.READY:
        assert repl[0].id in sup_b._delays

    def started():
        sup_b.drive()
        t = store.view(lambda tx: tx.get(Task, repl[0].id))
        return t if t.desired_state == TaskState.RUNNING else None
    poll(started, timeout=10, msg="re-armed delayed restart never fired")

    # no duplicated restarts: a second taskinit pass (e.g. yet another
    # failover) must not mint a second replacement or re-delay the task
    sup_c = RestartSupervisor(store, start_worker=False)
    orch_c = ReplicatedOrchestrator(store, restarts=sup_c)
    taskinit.check_tasks(store, store.view(), orch_c, sup_c)
    tasks = store.view(lambda tx: tx.find(Task))
    assert len([t for t in tasks if t.id != "t-old"]) == 1
    assert store.view(lambda tx: tx.get(
        Task, repl[0].id)).desired_state == TaskState.RUNNING
    sup_b.stop()
    sup_c.stop()


# ---------------------------------------------------------------------------
# Planner degraded-mode circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_state_machine_and_gauge():
    from swarmkit_tpu.ops.planner import (
        BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN, PlannerBreaker,
    )
    from swarmkit_tpu.utils.metrics import registry
    t = [1000.0]
    mtypes.set_time_source(lambda: t[0])
    try:
        b = PlannerBreaker(threshold=3, cooldown=10.0)
        assert registry.get_gauge("swarm_planner_breaker_state") \
            == BREAKER_CLOSED
        assert b.allow_device()
        b.record_failure()
        b.record_failure()
        assert b.state == BREAKER_CLOSED, "below threshold"
        b.record_failure()
        assert b.state == BREAKER_OPEN
        assert registry.get_gauge("swarm_planner_breaker_state") \
            == BREAKER_OPEN
        assert not b.allow_device(), "open: host fallback"

        t[0] += 10.5
        assert b.allow_device(), "cooldown elapsed: half-open probe"
        assert b.state == BREAKER_HALF_OPEN
        assert registry.get_gauge("swarm_planner_breaker_state") \
            == BREAKER_HALF_OPEN
        assert not b.allow_device(), "one probe at a time"
        b.record_failure()
        assert b.state == BREAKER_OPEN, "failed probe re-opens"

        t[0] += 10.5
        assert not b.allow_device(), "cooldown doubled after failed probe"
        t[0] += 10.0
        assert b.allow_device()
        b.record_success()
        assert b.state == BREAKER_CLOSED
        assert registry.get_gauge("swarm_planner_breaker_state") \
            == BREAKER_CLOSED
        assert b.stats["trips"] == 2
    finally:
        mtypes.set_time_source(None)
        PlannerBreaker()   # restore the exported gauge to closed


def test_breaker_probe_slot_released_on_discarded_inflight():
    """An aborted tick (discard_inflight) may drop the half-open probe
    plan before its outcome is observed: the probe slot must be
    released, or the breaker wedges in half-open and the device is
    never retried."""
    from swarmkit_tpu.ops import TPUPlanner
    from swarmkit_tpu.ops.planner import BREAKER_HALF_OPEN, PlannerBreaker
    t = [1000.0]
    mtypes.set_time_source(lambda: t[0])
    try:
        p = TPUPlanner(plan_fn=lambda *a: None)
        p.breaker = PlannerBreaker(threshold=1, cooldown=5.0)
        p.breaker.record_failure()              # OPEN
        t[0] += 6.0
        assert p.breaker.allow_device()         # probe admitted
        assert not p.breaker.allow_device()     # slot held
        p.discard_inflight()                    # tick aborted mid-probe
        assert p.breaker.state == BREAKER_HALF_OPEN
        assert p.breaker.allow_device(), \
            "discard must release the probe slot"
    finally:
        mtypes.set_time_source(None)
        PlannerBreaker()   # restore the exported gauge


def _breaker_cluster(n_nodes=4, n_tasks=6, n_services=2):
    store = MemoryStore()

    def mk(tx):
        for i in range(n_nodes):
            tx.create(Node(
                id=f"n{i}",
                spec=NodeSpec(annotations=Annotations(name=f"n{i}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"n{i}",
                    resources=Resources(nano_cpus=8 * 10 ** 9,
                                        memory_bytes=32 << 30))))
        for s in range(n_services):
            svc = Service(
                id=f"svc{s}",
                spec=ServiceSpec(annotations=Annotations(name=f"svc{s}"),
                                 mode=ServiceMode.REPLICATED,
                                 replicated=ReplicatedService(
                                     replicas=n_tasks),
                                 task=TaskSpec()),
                spec_version=Version(index=1))
            tx.create(svc)
            for i in range(n_tasks):
                tx.create(Task(
                    id=f"t{s}-{i}", service_id=svc.id, slot=i + 1,
                    desired_state=TaskState.RUNNING, spec=svc.spec.task,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=mtypes.now())))
    store.update(mk)
    return store


def test_breaker_trips_device_failures_to_host_fallback():
    """Consecutive device dispatch failures degrade groups to the host
    oracle (the tick never fails, placements stay valid), trip the
    breaker open, and the planner_breaker health check goes to fail."""
    from swarmkit_tpu.obs.health import HealthEvaluator, default_checks
    from swarmkit_tpu.ops import TPUPlanner
    from swarmkit_tpu.ops.planner import BREAKER_OPEN, PlannerBreaker
    from swarmkit_tpu.scheduler import Scheduler

    def boom(*a, **k):
        raise RuntimeError("injected device failure")

    planner = TPUPlanner(plan_fn=boom)
    planner.enable_small_group_routing = False
    planner.breaker = PlannerBreaker(threshold=2, cooldown=300.0)
    store = _breaker_cluster(n_services=3)
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    store.view(sched._setup_tasks_list)
    n = sched.tick()

    # every task placed by the host fallback despite a dead device
    assert n == 18
    tasks = store.view(lambda tx: tx.find(Task))
    assert all(t.node_id for t in tasks)
    assert planner.breaker.state == BREAKER_OPEN
    assert planner.stats["groups_device_error"] == 2   # trip threshold
    assert planner.stats["groups_breaker_to_host"] >= 1

    health = HealthEvaluator(checks=default_checks())
    states = health.evaluate()
    assert states["planner_breaker"] == "fail"
    PlannerBreaker()   # restore the exported gauge for other tests


def test_breaker_half_open_probe_recovers():
    """After the cooldown, one probe group goes back to the device; a
    healthy device closes the breaker and the health check recovers."""
    from swarmkit_tpu.obs.health import HealthEvaluator, default_checks
    from swarmkit_tpu.ops import TPUPlanner
    from swarmkit_tpu.ops.planner import (
        BREAKER_CLOSED, BREAKER_OPEN, PlannerBreaker,
    )
    from swarmkit_tpu.scheduler import Scheduler

    calls = {"n": 0, "fail": True}
    import swarmkit_tpu.ops.kernel as kernel

    def flaky(nodes_in, group_in, L, hier):
        calls["n"] += 1
        if calls["fail"]:
            raise RuntimeError("injected device failure")
        return kernel.plan_group_jit(nodes_in, group_in, L, hier)

    t = [mtypes.now()]
    mtypes.set_time_source(lambda: t[0])
    try:
        planner = TPUPlanner(plan_fn=flaky)
        planner.enable_small_group_routing = False
        planner.breaker = PlannerBreaker(threshold=2, cooldown=5.0)
        store = _breaker_cluster(n_services=2)
        sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
        store.view(sched._setup_tasks_list)
        assert sched.tick() == 12          # host fallback placed all
        assert planner.breaker.state == BREAKER_OPEN

        # device healed + cooldown elapsed: the next group is the probe
        calls["fail"] = False
        t[0] += 6.0
        store2 = _breaker_cluster(n_services=2)
        sched2 = Scheduler(store2, batch_planner=planner,
                           pipeline_depth=1)
        store2.view(sched2._setup_tasks_list)
        assert sched2.tick() == 12
        assert planner.breaker.state == BREAKER_CLOSED
        assert planner.stats.get("groups_planned", 0) >= 1

        health = HealthEvaluator(checks=default_checks())
        assert health.evaluate()["planner_breaker"] == "pass"
    finally:
        mtypes.set_time_source(None)
        PlannerBreaker()   # restore the exported gauge
