"""Flight recorder, health/SLO plane, and compile observability.

Covers this PR's acceptance surface:
* ring-buffer eviction bounds (the black box stays bounded, evictions
  are counted);
* post-mortem determinism — a sim scenario with an injected invariant
  violation dumps a flight-recorder JSON whose sha256 is identical
  across two runs of the same seed, with the violation visible in
  context (spans + store events + raft transitions around it);
* health-check state machine: pass -> warn -> fail -> recover, with
  transitions logged and ``swarm_health{check=...}`` gauges exported;
* DebugServer: ``/`` serves an endpoint index, ``/debug/health``
  returns 503 (not 200) while any check fails, ``/debug/flightrec``
  serves the dump;
* compile counters: a second same-bucket planner call records zero new
  compiles (cache misses are observed via jit cache size, not timing);
* metric hygiene: every live registry name matches the exposition
  grammar with sorted, bounded-cardinality labels.
"""

import functools
import json
import os
import re
import sys
import urllib.request

from swarmkit_tpu.obs import Check, HealthEvaluator, flightrec
from swarmkit_tpu.obs.flightrec import FlightRecorder, Ring
from swarmkit_tpu.obs.health import FAIL, PASS, WARN, timer_p99
from swarmkit_tpu.utils.metrics import Registry

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ---------------------------------------------------------------- ring buffer

def test_ring_eviction_bounds():
    ring = Ring(maxlen=8)
    for i in range(20):
        ring.append(i)
    assert len(ring) == 8
    assert ring.items() == list(range(12, 20))   # oldest evicted first
    assert ring.dropped == 12
    ring.clear()
    assert len(ring) == 0 and ring.dropped == 0

    # the recorder's rings honor their configured bounds end to end
    rec = FlightRecorder(max_notes=4, max_raft=2)
    rec.enabled = True
    for i in range(10):
        rec.note(f"n{i}")
        rec.record_raft("m0", "leader", i)
    assert len(rec.notes) == 4 and rec.notes.dropped == 6
    assert len(rec.raft) == 2
    doc = json.loads(rec.dump_json())
    assert len(doc["notes"]) == 4
    assert doc["dropped"]["notes"] == 6

    # disabled recorder records nothing
    rec2 = FlightRecorder()
    rec2.note("ghost")
    rec2.record_raft("m0", "leader", 1)
    assert len(rec2.notes) == 0 and len(rec2.raft) == 0


def test_save_restore_survives_reset():
    """An embedded capture (the sim runner) must not destroy the
    embedder's black box: reset() rebinds fresh rings, so the state
    captured by save_state survives and restore_state brings the
    original history back."""
    rec = FlightRecorder()
    rec.enabled = True
    rec.note("embedder history")
    saved = rec.save_state()
    rec.reset(deterministic=True)
    rec.note("sim capture")
    assert [m for _, m in rec.notes.items()] == ["sim capture"]
    rec.restore_state(saved)
    assert [m for _, m in rec.notes.items()] == ["embedder history"]
    assert rec.deterministic is False


# ----------------------------------------------------- post-mortem determinism

def _durability_bug_scenario(sim):
    """A seeded invariant violation: a follower crashes losing acked WAL
    records (the missing-fsync bug), then a flipped partition lets the
    amnesiac half commit divergent entries at the lost indices — the
    committed-ledger checker must fire (same recipe as
    tests/test_sim.py::test_checker_detects_seeded_durability_bug, as a
    runner scenario so the post-mortem path engages)."""
    eng = sim.engine
    sim.start_raft_workload(interval=0.5)
    sim.cp.create_tasks(4)

    def strike():
        lead = sim.leader()
        if lead is None:
            eng.after(1.0, "await leader", strike)
            return
        iso, keeper = [m for m in sim.managers if m is not lead]
        sim.net.split([iso.id], [lead.id, keeper.id])

        def burst():
            for i in range(12):
                sim.propose(f"critical-{i:02d}".encode())

            def bug():
                keeper.crash(truncate_wal=10)
                keeper.restart()
                sim.net.split([lead.id], [iso.id, keeper.id])

            eng.after(2.0, "durability bug", bug)

        eng.after(2.0, "burst", burst)

    eng.at(eng.clock.start + 5.0, "strike", strike)
    return 30.0


def test_flightrec_dump_deterministic_per_seed(tmp_path):
    from swarmkit_tpu.sim.scenario import SCENARIOS, run_scenario

    SCENARIOS["_durability-bug"] = _durability_bug_scenario
    try:
        d1, d2 = tmp_path / "a", tmp_path / "b"
        d1.mkdir(), d2.mkdir()
        r1 = run_scenario("_durability-bug", seed=5, flightrec_dir=str(d1))
        r2 = run_scenario("_durability-bug", seed=5, flightrec_dir=str(d2))
    finally:
        del SCENARIOS["_durability-bug"]

    # the violation fired and the post-mortem was written automatically
    assert not r1.ok
    assert any("no-committed-entry-loss" in v for v in r1.violations)
    assert r1.flightrec_path and os.path.exists(r1.flightrec_path)
    assert "flightrec_path" in r1.to_dict()

    # identity: same seed => same sha, byte for byte
    assert r1.flightrec_sha256 == r2.flightrec_sha256
    with open(r1.flightrec_path) as fa, open(r2.flightrec_path) as fb:
        assert fa.read() == fb.read()

    # the dump is evidence, not a verdict: the violation note sits next
    # to surrounding state — spans, store events, raft role history,
    # and delta-based metric samples, all under virtual time
    doc = json.load(open(r1.flightrec_path))
    assert any("INVARIANT no-committed-entry-loss" in msg
               for _, msg in doc["notes"])
    assert doc["spans"], "recent spans must be captured"
    assert doc["store_events"], "store events must be captured"
    roles = {role for _, _, role, _ in doc["raft_transitions"]}
    assert "leader" in roles and "candidate" in roles
    assert doc["samples"], "periodic metric samples must be captured"
    # deterministic captures never embed live wall-clock registry totals
    assert "counters" not in doc

    # a clean run of a clean scenario writes no post-mortem
    r3 = run_scenario("crash-leader-mid-commit", seed=7,
                      flightrec_dir=str(tmp_path))
    assert r3.ok and r3.flightrec_path == ""


# --------------------------------------------------------------- health plane

def test_health_state_transitions():
    reg = Registry()
    rec = FlightRecorder()
    rec.enabled = True
    check = Check("latency_p99", timer_p99("swarm_x_latency"),
                  warn=1.0, fail=5.0, unit="s",
                  window_prefixes=("swarm_x_",))
    hev = HealthEvaluator(registry=reg, recorder=rec, checks=[check])

    # no data => pass (a fresh process is healthy, not unknown)
    assert hev.evaluate() == {"latency_p99": PASS}
    t = reg.timer("swarm_x_latency")
    t.observe(0.1)
    assert hev.evaluate() == {"latency_p99": PASS}
    assert reg.gauges['swarm_health{check="latency_p99"}'] == 0

    t.observe(2.0)          # p99 -> 2.0 >= warn
    assert hev.evaluate() == {"latency_p99": WARN}
    assert reg.gauges['swarm_health{check="latency_p99"}'] == 1

    t.observe(10.0)         # p99 -> 10.0 >= fail
    assert hev.evaluate() == {"latency_p99": FAIL}
    assert hev.failing() and hev.status() == FAIL
    assert reg.gauges['swarm_health{check="latency_p99"}'] == 2

    t.reset()
    t.observe(0.1)          # recovered
    assert hev.evaluate() == {"latency_p99": PASS}
    assert not hev.failing() and hev.status() == PASS
    assert reg.gauges['swarm_health{check="latency_p99"}'] == 0

    # the full transition history was tracked and noted to the recorder
    edges = [(a, b) for _, _, a, b in hev.transitions]
    assert edges == [(PASS, WARN), (WARN, FAIL), (FAIL, PASS)]
    notes = [msg for _, msg in rec.notes.items()]
    assert any("warn -> fail" in n for n in notes)

    # report carries the offending window for non-pass checks
    t.observe(10.0)
    rec.record_sample({"t": 1.0,
                       "counters": {"swarm_x_latency_seen": 1},
                       "timer_counts": {"swarm_x_latency": 3}})
    report = hev.report()
    assert report["status"] == FAIL
    entry = report["checks"]["latency_p99"]
    assert entry["state"] == FAIL and entry["value"] == 10.0
    assert entry["window"], "failing check must carry its sample window"
    assert report["transitions"][-1]["to"] == FAIL


# ----------------------------------------------------------------- debug http

def _get(addr, path):
    url = f"http://{addr[0]}:{addr[1]}{path}"
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_server_index_health_and_flightrec():
    from swarmkit_tpu.utils.httpdebug import DebugServer

    reg = Registry()
    check = Check("latency_p99", timer_p99("swarm_x_latency"),
                  warn=1.0, fail=5.0)
    hev = HealthEvaluator(registry=reg, recorder=FlightRecorder(),
                          checks=[check])
    srv = DebugServer(health_evaluator=hev)
    srv.start()
    try:
        # index page lists every registered endpoint
        code, body = _get(srv.addr, "/")
        assert code == 200
        for path in ("/metrics", "/healthz", "/debug/stacks",
                     "/debug/trace", "/debug/health",
                     "/debug/flightrec"):
            assert path in body, body

        # healthy: 200 with a JSON report
        code, body = _get(srv.addr, "/debug/health")
        assert code == 200
        report = json.loads(body)
        assert report["status"] == PASS
        assert report["checks"]["latency_p99"]["state"] == PASS

        # failing: 503 so probes need no JSON parsing
        reg.timer("swarm_x_latency").observe(30.0)
        code, body = _get(srv.addr, "/debug/health")
        assert code == 503
        assert json.loads(body)["status"] == FAIL

        # recovery flips it back
        reg.timer("swarm_x_latency").reset()
        code, _ = _get(srv.addr, "/debug/health")
        assert code == 200

        # the flight recorder dump is served as JSON
        code, body = _get(srv.addr, "/debug/flightrec")
        assert code == 200
        doc = json.loads(body)
        assert {"spans", "samples", "store_events", "raft_transitions",
                "notes", "dropped"} <= set(doc)

        # unknown paths still 404
        code, _ = _get(srv.addr, "/debug/nope")
        assert code == 404
    finally:
        srv.stop()


# ----------------------------------------------------------- compile counters

def test_compile_counter_zero_on_second_same_bucket_call():
    """A planner call through a FRESH jit records exactly the compiles
    the XLA cache reports; a second call on the same static shape bucket
    records zero — so bench's per-bucket counts separate "compiled in
    the timed region" from "ran warm", which timing alone cannot."""
    import jax

    from bench import build_cluster, one_tick
    from swarmkit_tpu.ops import TPUPlanner
    from swarmkit_tpu.ops.kernel import plan_group
    from swarmkit_tpu.utils.metrics import registry

    @functools.partial(jax.jit, static_argnames=("L",))
    def fresh_plan_fn(nodes, group, L, hier=()):
        return plan_group(nodes, group, L, hier=hier)

    def run_once():
        store, svc, nodes, tasks = build_cluster(64, 256)
        planner = TPUPlanner(plan_fn=fresh_plan_fn)
        planner.enable_small_group_routing = False
        one_tick(store, planner)

    def compile_counts():
        return registry.counters_snapshot("swarm_planner_compiles")

    snap0 = compile_counts()
    run_once()
    snap1 = compile_counts()
    first = {k: v - snap0.get(k, 0.0) for k, v in snap1.items()}
    first = {k: v for k, v in first.items() if v}
    assert first, "first call on a fresh jit must record a compile"
    (bucket_key,) = first
    assert re.match(
        r'^swarm_planner_compiles\{bucket="nb\d+_cc\d+_p\d+_L\d+_h\d+"\}$',
        bucket_key), bucket_key

    run_once()
    snap2 = compile_counts()
    second = {k: v - snap1.get(k, 0.0) for k, v in snap2.items()}
    assert not any(second.values()), \
        f"second same-bucket call must record zero new compiles: {second}"


# ------------------------------------------------------------- metric hygiene
#
# The name-grammar / sorted-labels / static-labelset lint moved to
# swarmlint (swarmkit_tpu/analysis/rules/metrics.py, rule
# `metric-hygiene`): it now checks every registry call site in SOURCE,
# including names only emitted on rare error paths, instead of whatever
# a test run happened to populate.  What stays here is the part only a
# live process can check: runtime-interpolated label VALUES — their
# cardinality fan-out (the static rule sees one placeholder labelset
# per f-string) and that they parse back out of the exposition.

_MAX_LABEL_CARDINALITY = 64


def test_live_exposition_parses_and_cardinality_bounded():
    """After a sim run, the exposition built from the live registry —
    real interpolated label values included — must parse line by line,
    and no base name may fan out past the cardinality bound (an
    unbounded label value bloats exposition and flight-recorder dumps;
    the static grammar lint cannot see runtime values)."""
    from swarmkit_tpu.sim.scenario import run_scenario
    from swarmkit_tpu.utils.metrics import registry

    r = run_scenario("crash-leader-mid-commit", seed=3)
    assert r.ok, r.violations

    names = (list(registry.counters_snapshot())
             + list(registry.gauges_snapshot())
             + list(registry.timers_snapshot()))
    assert names, "the run must have populated the registry"
    cardinality = {}
    for name in names:
        base, _, rest = name.partition("{")
        if rest:
            cardinality.setdefault(base, set()).add(rest)
    for base, labelsets in cardinality.items():
        assert len(labelsets) <= _MAX_LABEL_CARDINALITY, \
            f"{base} has {len(labelsets)} label combinations " \
            f"(> {_MAX_LABEL_CARDINALITY}): unbounded label value?"
    expo = registry.expose()
    line_re = re.compile(
        r'^[a-z0-9_]+(\{[a-z0-9_]+="[^"]*"(,[a-z0-9_]+="[^"]*")*\})? '
        r"-?[0-9.e+-]+$")
    for line in expo.strip().split("\n"):
        assert line_re.match(line), f"unparseable exposition line: {line}"
