"""Follower-served reads (ISSUE 11): raft read-index/lease protocol,
linearizable store views, watch resume tokens across members, dispatcher
follower mode, agent session failover, and the three new sim invariants
(each proven LIVE by a checker-sensitivity test)."""

import logging

import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    Resources, Service, ServiceSpec, Task, TaskSpec, TaskState,
    TaskStatus, Version,
)
from swarmkit_tpu.state.raft.core import LEADER, Message, RaftCore
from swarmkit_tpu.state.raft.node import ReadUnavailable
from swarmkit_tpu.state.store import MemoryStore

logging.disable(logging.CRITICAL)


# --------------------------------------------------------------- helpers

def mk_cluster(n=3):
    """N connected RaftCores with a synchronous message pump."""
    ids = [f"n{i}" for i in range(n)]
    import random
    cores = {i: RaftCore(i, ids, rng=random.Random(hash(i) & 0xFFFF))
             for i in ids}

    def pump():
        for _ in range(200):
            moved = False
            for c in cores.values():
                msgs, c._msgs = c._msgs, []
                for m in msgs:
                    moved = True
                    if m.dst in cores:
                        cores[m.dst].step(m)
            if not moved:
                return

    def elect(i):
        c = cores[i]
        while c.role != LEADER:
            c.tick()
            pump()
        # drain ready so the no-op applies (leader_ready)
        rd = c.ready()
        c.advance(rd)
        c.applied_index = c.commit_index
        return c

    return cores, pump, elect


def mk_task(i, sid="svc"):
    return Task(id=f"t{i:03d}", service_id=sid, slot=i,
                desired_state=TaskState.RUNNING, spec=TaskSpec(),
                spec_version=Version(index=1),
                status=TaskStatus(state=TaskState.PENDING, timestamp=1.0))


# ------------------------------------------------------- core read-index

def test_read_index_quorum_round_on_leader():
    cores, pump, elect = mk_cluster()
    leader = elect("n0")
    leader.lease_duration = None    # force the quorum round
    seq = leader.request_read()
    assert seq is not None and seq not in leader.read_results
    pump()   # heartbeat round + echoes
    index, ok, lease = leader.read_results.pop(seq)
    assert ok and not lease
    assert index == leader.commit_index


def test_follower_read_index_round_trip():
    cores, pump, elect = mk_cluster()
    leader = elect("n0")
    leader.lease_duration = None
    follower = cores["n1"]
    # the follower learns the leader via a heartbeat
    leader._broadcast_append(heartbeat=True)
    pump()
    assert follower.leader_id == "n0"
    seq = follower.request_read()
    assert seq is not None
    pump()
    index, ok, lease = follower.read_results.pop(seq)
    assert ok and not lease
    assert index == leader.commit_index


def test_lease_fast_path_and_expiry(monkeypatch):
    from swarmkit_tpu.models import types as mtypes
    t = [100.0]
    mtypes.set_time_source(lambda: t[0])
    try:
        cores, pump, elect = mk_cluster()
        leader = elect("n0")
        leader.lease_duration = 1.0
        # earn the lease: one quorum-acked heartbeat round
        leader._broadcast_append(heartbeat=True)
        pump()
        assert leader.lease_valid()
        seq = leader.request_read()
        index, ok, lease = leader.read_results.pop(seq)
        assert ok and lease and index == leader.commit_index
        assert leader.read_stats["lease_served"] == 1
        # past the (margin-shaved) expiry the lease must NOT serve
        t[0] += 1.0
        assert not leader.lease_valid()
        seq = leader.request_read()
        assert seq not in leader.read_results   # quorum round in flight
        pump()
        index, ok, lease = leader.read_results.pop(seq)
        assert ok and not lease
    finally:
        mtypes.set_time_source(None)


def test_lease_gate_vetoes_fast_path(monkeypatch):
    from swarmkit_tpu.models import types as mtypes
    t = [50.0]
    mtypes.set_time_source(lambda: t[0])
    try:
        cores, pump, elect = mk_cluster()
        leader = elect("n0")
        leader.lease_duration = 5.0
        leader._broadcast_append(heartbeat=True)
        pump()
        assert leader.lease_valid()
        leader.lease_gate = lambda: False   # clock-skew fault active
        seq = leader.request_read()
        assert seq not in leader.read_results   # forced quorum round
        pump()
        index, ok, lease = leader.read_results.pop(seq)
        assert ok and not lease
        assert leader.read_stats["lease_refused_gate"] == 1
    finally:
        mtypes.set_time_source(None)


def test_deposed_leader_fails_pending_reads():
    cores, pump, elect = mk_cluster()
    leader = elect("n0")
    leader.lease_duration = None
    seq = leader.request_read()
    assert seq not in leader.read_results
    # a higher-term message deposes the leader before the round confirms
    leader.step(Message(type="vote", term=leader.term + 5, src="n1",
                        dst="n0", last_log_index=99, last_log_term=99))
    index, ok, lease = leader.read_results.pop(seq)
    assert not ok


def test_single_member_reads_immediately():
    import random
    c = RaftCore("solo", ["solo"], rng=random.Random(1))
    while c.role != LEADER:
        c.tick()
    c.applied_index = c.commit_index
    seq = c.request_read()
    index, ok, lease = c.read_results.pop(seq)
    assert ok and index == c.commit_index


# --------------------------------------------------- store read_view seam

class _BarrierProposer:
    """Fake proposer exposing the read_barrier capability."""

    leadership_epoch = None

    def __init__(self):
        self.barriers = 0

    def propose(self, actions, commit_cb=None, epoch=None):
        commit_cb()

    def read_barrier(self, timeout=None):
        self.barriers += 1


def test_read_view_runs_barrier_only_when_linearizable():
    p = _BarrierProposer()
    store = MemoryStore(proposer=p)
    store.update(lambda tx: tx.create(mk_task(1)))
    n = store.read_view(lambda tx: len(tx.find(Task)))
    assert n == 1 and p.barriers == 0
    n = store.read_view(lambda tx: len(tx.find(Task)),
                        linearizable=True)
    assert n == 1 and p.barriers == 1
    # plain proposers without the capability serve directly
    store2 = MemoryStore()
    store2.update(lambda tx: tx.create(mk_task(2)))
    assert store2.read_view(lambda tx: len(tx.find(Task)),
                            linearizable=True) == 1


# ----------------------------------------------------- watch resume tokens

def test_watch_events_carry_resume_tokens_including_deletes():
    from swarmkit_tpu.manager.watchapi import WatchRequest, WatchServer
    store = MemoryStore()
    server = WatchServer(store)
    stream = server.watch(WatchRequest(kinds=[Task]))
    store.update(lambda tx: tx.create(mk_task(1)))
    store.update(lambda tx: tx.delete(Task, "t001"))
    ev1 = stream.get(timeout=1)
    ev2 = stream.get(timeout=1)
    assert ev1.action == "create" and ev1.version > 0
    assert ev2.action == "delete" and ev2.version == ev1.version + 1
    assert stream.poll() is None
    stream.close()


def test_resume_token_continues_without_gap_or_dup():
    from swarmkit_tpu.manager.watchapi import WatchRequest, WatchServer
    store = MemoryStore()
    server = WatchServer(store)
    stream = server.watch(WatchRequest(kinds=[Task]))
    for i in range(1, 4):
        store.update(lambda tx, i=i: tx.create(mk_task(i)))
    seen = [stream.get(timeout=1) for _ in range(2)]
    token = seen[-1].version
    stream.close()
    # more commits while detached
    for i in range(4, 6):
        store.update(lambda tx, i=i: tx.create(mk_task(i)))
    resumed = server.watch(WatchRequest(kinds=[Task],
                                        resume_from_version=token))
    got = []
    while True:
        ev = resumed.poll()
        if ev is None:
            break
        got.append(ev)
    ids = [e.obj.id for e in got]
    assert ids == ["t003", "t004", "t005"]
    versions = [e.version for e in got]
    assert versions == sorted(versions) and versions[0] == token + 1
    resumed.close()


def test_resume_token_is_member_portable():
    """A token minted on the leader store resumes on a follower replica
    (identical version stamping through apply_store_actions)."""
    from swarmkit_tpu.manager.watchapi import WatchRequest, WatchServer
    from swarmkit_tpu.state.store import StoreAction
    leader = MemoryStore()
    follower = MemoryStore()

    class Replicator:
        leadership_epoch = None

        def propose(self, actions, commit_cb=None, epoch=None):
            commit_cb()
            follower.apply_store_actions(
                [StoreAction(a.action, a.obj.copy()) for a in actions])

    leader._proposer = Replicator()
    stream = WatchServer(leader).watch(WatchRequest(kinds=[Task]))
    for i in range(1, 5):
        leader.update(lambda tx, i=i: tx.create(mk_task(i)))
    token = None
    for _ in range(2):
        token = stream.get(timeout=1).version
    stream.close()
    assert follower.version == leader.version
    resumed = WatchServer(follower).watch(
        WatchRequest(kinds=[Task], resume_from_version=token))
    ids = []
    while True:
        ev = resumed.poll()
        if ev is None:
            break
        ids.append(ev.obj.id)
    assert ids == ["t003", "t004"]
    resumed.close()


def test_resume_compacted_raises():
    from swarmkit_tpu.manager.watchapi import (
        ResumeCompacted, WatchRequest, WatchServer,
    )
    store = MemoryStore()
    store.changelog_limit = 4
    for i in range(1, 10):
        store.update(lambda tx, i=i: tx.create(mk_task(i)))
    with pytest.raises(ResumeCompacted):
        WatchServer(store).watch(
            WatchRequest(kinds=[Task], resume_from_version=1))


# ------------------------------------------------- watch filter parity

def _filter_events(request, events):
    from swarmkit_tpu.manager.watchapi import compile_filter
    pred = compile_filter(request)
    return [ev for ev in events if pred(ev)]


def test_watch_field_filters_and_custom_indices():
    from swarmkit_tpu.manager.watchapi import WatchRequest
    from swarmkit_tpu.state.events import Event
    t1 = mk_task(1, sid="a")
    t2 = mk_task(2, sid="b")
    t2.desired_state = TaskState.SHUTDOWN
    svc = Service(id="s1", spec=ServiceSpec(
        annotations=Annotations(name="Web",
                                indices={"tier": "frontend"})),
        spec_version=Version(index=1))
    events = [Event("create", t1), Event("create", t2),
              Event("create", svc)]
    # slot selector
    got = _filter_events(WatchRequest(slots=[("a", 1)]), events)
    assert [e.obj.id for e in got] == ["t001"]
    # desired-state selector
    got = _filter_events(
        WatchRequest(desired_states=[int(TaskState.SHUTDOWN)]), events)
    assert [e.obj.id for e in got] == ["t002"]
    # exact-name selector (case-insensitive, like the store index)
    got = _filter_events(WatchRequest(names=["web"]), events)
    assert [e.obj.id for e in got] == ["s1"]
    # custom index exact + prefix
    got = _filter_events(
        WatchRequest(custom_indices=[("tier", "frontend")]), events)
    assert [e.obj.id for e in got] == ["s1"]
    got = _filter_events(
        WatchRequest(custom_index_prefixes=[("tier", "front")]), events)
    assert [e.obj.id for e in got] == ["s1"]
    got = _filter_events(
        WatchRequest(custom_indices=[("tier", "backend")]), events)
    assert got == []


def test_watch_filters_member_agnostic():
    """The same compiled filter applied to the leader's and a follower's
    event payloads selects the same stream (shared by both serve paths
    and by the sim's continuity ledger)."""
    from swarmkit_tpu.manager.watchapi import WatchRequest, compile_filter
    from swarmkit_tpu.state.store import StoreAction
    leader, follower = MemoryStore(), MemoryStore()
    req = WatchRequest(kinds=[Task], service_ids=["a"])
    pred = compile_filter(req)
    lsub = leader.queue.subscribe(pred)
    fsub = follower.queue.subscribe(pred)

    class Replicator:
        leadership_epoch = None

        def propose(self, actions, commit_cb=None, epoch=None):
            commit_cb()
            follower.apply_store_actions(
                [StoreAction(a.action, a.obj.copy()) for a in actions])

    leader._proposer = Replicator()
    for i, sid in ((1, "a"), (2, "b"), (3, "a")):
        leader.update(lambda tx, i=i, sid=sid: tx.create(mk_task(i, sid)))
    from swarmkit_tpu.state.events import event_version
    lgot = []
    while True:
        ev = lsub.poll()
        if ev is None:
            break
        lgot.append((event_version(ev), ev.obj.id))
    fgot = []
    while True:
        ev = fsub.poll()
        if ev is None:
            break
        fgot.append((event_version(ev), ev.obj.id))
    assert lgot == fgot == [(1, "t001"), (3, "t003")]


# ------------------------------------------------ dispatcher follower mode

def _mk_node(nid):
    return Node(id=nid, spec=NodeSpec(annotations=Annotations(name=nid)),
                status=NodeStatus(state=NodeState.UNKNOWN),
                description=NodeDescription(
                    hostname=nid,
                    resources=Resources(nano_cpus=10 ** 9,
                                        memory_bytes=1 << 30)))


def test_follower_dispatcher_routes_writes_to_write_store():
    from swarmkit_tpu.manager.dispatcher import Config_, Dispatcher
    local = MemoryStore()      # the follower's replicated store (reads)
    leader = MemoryStore()     # write target
    for s in (local, leader):
        s.update(lambda tx: tx.create(_mk_node("w0")))
    d = Dispatcher(local, Config_(rate_limit_period=0.0),
                   write_store=leader)
    d.run(start_worker=False)
    session, _ = d.register("w0")
    d._flush_updates()
    # the READY write landed on the leader store, not the local one
    assert leader.raw_get(Node, "w0").status.state == NodeState.READY
    assert local.raw_get(Node, "w0").status.state == NodeState.UNKNOWN
    d.stop()


def test_follower_dispatcher_requeues_on_forward_failure():
    from swarmkit_tpu.manager.dispatcher import (
        Config_, Dispatcher, DispatcherError,
    )
    local = MemoryStore()
    local.update(lambda tx: tx.create(_mk_node("w0")))

    class GappyStore:
        def __init__(self):
            self.fail = True

        def batch(self, cb):
            if self.fail:
                raise DispatcherError("no leader to forward the write to")
            return local.batch(cb)

    gap = GappyStore()
    d = Dispatcher(local, Config_(rate_limit_period=0.0),
                   write_store=gap)
    d.run(start_worker=False)
    d.register("w0")
    d._flush_updates()   # forward fails: re-queued, not lost
    assert local.raw_get(Node, "w0").status.state == NodeState.UNKNOWN
    gap.fail = False
    d._flush_updates()
    assert local.raw_get(Node, "w0").status.state == NodeState.READY
    d.stop()


def test_shard_filter_and_release_session():
    from swarmkit_tpu.manager.dispatcher import Config_, Dispatcher
    store = MemoryStore()
    for nid in ("w0", "w1"):
        store.update(lambda tx, nid=nid: tx.create(_mk_node(nid)))
    d = Dispatcher(store, Config_(rate_limit_period=0.0),
                   shard_filter=lambda nid: nid == "w0")
    d.run(start_worker=False)
    # only the shard's node got a registration-grace deadline
    kinds = [(k, n) for (_, _, k, n) in d._heap if k == "reg"]
    assert kinds == [("reg", "w0")]
    session, _ = d.register("w0")
    d.release_session("w0", session)
    d._flush_updates()
    # released WITHOUT a DOWN write (graceful handoff)
    assert store.raw_get(Node, "w0").status.state == NodeState.READY
    with pytest.raises(Exception):
        d.heartbeat("w0", session)
    d.stop()


def test_reg_grace_check_vetoes_down_for_foreign_sessions():
    from swarmkit_tpu.manager.dispatcher import Config_, Dispatcher
    from swarmkit_tpu.models import types as mtypes
    t = [1000.0]
    mtypes.set_time_source(lambda: t[0])
    try:
        store = MemoryStore()
        store.update(lambda tx: tx.create(_mk_node("w0")))
        owned_elsewhere = {"w0"}
        d = Dispatcher(store, Config_(rate_limit_period=0.0))
        d.reg_grace_check = lambda nid: nid not in owned_elsewhere
        d.run(start_worker=False)
        t[0] += 3600.0
        d.process_deadlines()
        assert store.raw_get(Node, "w0").status.state \
            == NodeState.UNKNOWN   # vetoed: session lives elsewhere
        owned_elsewhere.clear()
        d.adopt_registration_grace(["w0"])
        t[0] += 3600.0
        d.process_deadlines()
        assert store.raw_get(Node, "w0").status.state == NodeState.DOWN
        d.stop()
    finally:
        mtypes.set_time_source(None)


# ------------------------------------------------- agent session failover

def test_failover_client_rotates_on_session_invalid():
    from swarmkit_tpu.net.client import SessionInvalid
    from swarmkit_tpu.remotes import (
        ConnectionBroker, FailoverDispatcherClient, Remotes,
    )
    import random

    calls = []

    class FakeClient:
        def __init__(self, addr):
            self.addr = addr

        def heartbeat(self, node_id, session_id):
            calls.append(self.addr)
            if len(calls) == 1:
                raise SessionInvalid("session gone")
            return 1.0

        def close(self):
            pass

    remotes = Remotes(("a", 1), ("b", 2), rng=random.Random(0))
    broker = ConnectionBroker(remotes)
    fc = FailoverDispatcherClient(broker, None,
                                  client_factory=FakeClient)
    with pytest.raises(SessionInvalid):
        fc.heartbeat("w0", "s1")
    fc.heartbeat("w0", "s1")
    assert len(calls) == 2
    assert calls[0] != calls[1], \
        "session-invalid must re-resolve to a DIFFERENT manager"
    # the healthy link never shifted weights
    w = remotes.weights()
    assert w[calls[1]] >= w[calls[0]]


def test_agent_counts_reconnects_by_reason():
    from swarmkit_tpu.utils.metrics import registry
    from swarmkit_tpu.remotes import count_reconnect
    base = registry.get_counter(
        'swarm_agent_reconnects{reason="session_invalid"}')
    count_reconnect("session_invalid")
    assert registry.get_counter(
        'swarm_agent_reconnects{reason="session_invalid"}') == base + 1


# ----------------------------------------------------- health: stale reads

def test_stale_read_risk_transitions():
    from swarmkit_tpu.obs.health import stale_read_risk_value
    from swarmkit_tpu.utils.metrics import Registry
    reg = Registry()
    val = stale_read_risk_value(read_index_p99_bound=0.5)
    assert val(reg) is None                      # no read plane yet
    reg.gauge("swarm_lease_enabled", 1.0)
    assert val(reg) == 0.0                       # lease on, no staleness
    reg.gauge("swarm_lease_enabled", 0.0)
    t = reg.timer("swarm_read_index_latency")
    for _ in range(20):
        t.observe(2.0)                           # slow quorum rounds
    assert val(reg) == 1.0                       # warn: degraded
    reg.counter("swarm_stale_reads")
    assert val(reg) == 2.0                       # fail: stale serve


# ---------------------------------------------------------- sim scenarios

def _quiet():
    logging.disable(logging.CRITICAL)


def test_follower_read_failover_green_and_deterministic():
    from swarmkit_tpu.sim.scenario import run_scenario
    _quiet()
    r1 = run_scenario("follower-read-failover", 0, keep_trace=True)
    assert r1.ok, r1.violations
    r2 = run_scenario("follower-read-failover", 0)
    assert r2.trace_hash == r1.trace_hash
    assert r2.obs_trace_sha256 == r1.obs_trace_sha256
    reads = r1.stats["reads"]
    # consumers stayed off the coordinator...
    assert reads["leader_share"] <= 0.05, reads
    # ...while the plane actually carried traffic and failed over
    assert reads["watch_events"] > 0
    assert reads["watch_hops"] >= 1, \
        "a watcher must have resumed on a different member"
    assert reads["agent_reconnects"] >= 1
    assert reads["lease"] > 0 and reads["read_index"] > 0
    # the stranded ex-leader was probed and refused to serve stale
    assert any("fault stale-read-probe" in line for line in r1.trace)
    assert reads["stale_probe_refused"] >= 1


def test_read_storm_degraded_green():
    from swarmkit_tpu.sim.scenario import run_scenario
    _quiet()
    r = run_scenario("read-storm-degraded", 0)
    assert r.ok, r.violations
    reads = r.stats["reads"]
    assert reads["probe_ok"] > 10
    assert reads["probe_unavailable"] == 0
    assert reads["leader_share"] <= 0.05, reads


# ------------------------------------------ checker-sensitivity (3 new)

def _sim_with_leader(seed=3):
    """A raft_cp sim pumped until a leader control plane is attached and
    bootstrapped."""
    from swarmkit_tpu.sim.cluster import Sim
    sim = Sim(seed, raft_cp=True)
    eng = sim.engine
    while (sim.cp.active is None or not sim.cp._bootstrapped) \
            and eng.clock.elapsed() < 30.0:
        eng.run_until(eng.clock.elapsed() + 0.5)
    assert sim.cp.active is not None
    return sim


@pytest.fixture
def restore_stale_counter():
    """The stale-serve counter latches the stale_read_risk health check
    to FAIL (by design — production never increments it); a sensitivity
    test that deliberately forces a stale serve must put the global
    registry back or every later health assertion in the process
    inherits the failure."""
    from swarmkit_tpu.utils.metrics import registry
    before = registry.get_counter("swarm_stale_reads")
    yield
    delta = registry.get_counter("swarm_stale_reads") - before
    if delta:
        registry.counter("swarm_stale_reads", -delta)


def test_checker_fires_when_read_barrier_skipped(restore_stale_counter):
    """Serve a follower view WITHOUT waiting for the read barrier while
    the follower is partitioned behind committed writes:
    follower-reads-never-uncommitted must fire."""
    _quiet()
    with _sim_with_leader() as sim:
        eng = sim.engine
        cp = sim.cp
        leader = sim.leader()
        follower = next(m for m in sim.managers if m is not leader)
        sim.net.isolate(follower.id)
        cp.scale(4)
        eng.run_until(eng.clock.elapsed() + 5.0)
        assert follower.store.version < cp.read_inv.committed_version()
        # control: enforcement ON -> the read refuses rather than serve
        with pytest.raises(ReadUnavailable):
            follower.store.read_view(lambda tx: len(tx.find(Task)),
                                     linearizable=True, timeout=3.0)
        assert not any("follower-reads-never-uncommitted" in v
                       for v in sim.violations.items)
        # seam off: the stale view IS served -> checker must fire
        cp.proposers[follower.id].enforce_read_barrier = False
        follower.store.read_view(lambda tx: len(tx.find(Task)),
                                 linearizable=True, timeout=3.0)
        assert any("follower-reads-never-uncommitted" in v
                   for v in sim.violations.items)


def test_checker_fires_on_lease_read_under_skew():
    """Widen the lease past the drift margin (gate removed) under an
    injected clock-skew fault: lease-read-safe-under-skew must fire."""
    _quiet()
    with _sim_with_leader() as sim:
        eng = sim.engine
        cp = sim.cp
        leader = sim.leader()
        # control: with the gate live, skew degrades to read-index
        other = next(m for m in sim.managers if m is not leader)
        other.tick_scale = 2.0
        cp.linearizable_read(leader, lambda tx: len(tx.find(Task)))
        assert not any("lease-read-safe-under-skew" in v
                       for v in sim.violations.items)
        # seam: widen the lease and drop the skew gate entirely
        from swarmkit_tpu.models.types import now as vnow
        leader.core.lease_gate = None
        leader.core.lease_duration = 1e6
        leader.core._lease_expiry = vnow() + 1e6
        res = leader.store._proposer.read_barrier()
        assert res["lease"], "seam must force the lease fast path"
        assert any("lease-read-safe-under-skew" in v
                   for v in sim.violations.items)


def test_checker_fires_on_dropped_resume_token():
    """Drop a resume-token increment on reattach (resume_skew=-1 re-
    delivers the last event): watch-resume-no-gap-no-dup must fire."""
    _quiet()
    from swarmkit_tpu.sim.cluster import SimWatcher
    with _sim_with_leader() as sim:
        eng = sim.engine
        cp = sim.cp
        cp.add_watchers(1)
        w = cp.watchers[0]
        w.resume_skew = -1
        cp.scale(4)
        eng.run_until(eng.clock.elapsed() + 8.0)
        assert w.events_seen > 0
        # force a reattach mid-stream (member hop with a skewed token)
        m = w.member
        assert m is not None
        m.crash()
        eng.run_until(eng.clock.elapsed() + 4.0)
        m.restart()
        eng.run_until(eng.clock.elapsed() + 8.0)
        w.drain()
        w.continuity.ensure()
        w.continuity.drain()
        w.continuity.judge(w)
        assert any("watch-resume-no-gap-no-dup" in v
                   for v in sim.violations.items), \
            "a dropped token increment must be caught as dup/gap"


# ----------------------------------------------------------- slow sweeps

@pytest.mark.slow
def test_follower_planes_get_batched_fanout_by_default(monkeypatch):
    """ISSUE 13 satellite: the follower-served dispatcher planes come up
    with the batched assignment fan-out ON (opt-out via
    SWARM_BATCH_FANOUT=0, not opt-in), and a session gap through a
    plane rebuilds a COMPLETE set with nothing lost or duplicated."""
    _quiet()
    from swarmkit_tpu.models import TaskState, TaskStatus
    from swarmkit_tpu.state.store import ByNode

    # pin the default-on half against an inherited escape hatch
    monkeypatch.delenv("SWARM_BATCH_FANOUT", raising=False)

    def _mk_assigned(sim, nid, start, n):
        """Assigned tasks for ``nid`` written through the LEADER store
        (they replicate to every member's plane store)."""
        leader_store = sim.leader().store

        def cb(tx):
            for i in range(start, start + n):
                t = mk_task(i, sid="fan-svc")
                t.node_id = nid
                t.status = TaskStatus(state=TaskState.ASSIGNED)
                tx.create(t)
        leader_store.update(cb)

    def _agentless_sim(seed=3):
        # no sim agents: the test drives the plane's session itself, and
        # a main-thread leader write must never race an agent's
        # leader-forwarded write (that shape deadlocks by design — the
        # scenarios route all traffic through the engine)
        from swarmkit_tpu.sim.cluster import Sim
        sim = Sim(seed, raft_cp=True, n_agents=0)
        eng = sim.engine
        while (sim.cp.active is None or not sim.cp._bootstrapped) \
                and eng.clock.elapsed() < 30.0:
            eng.run_until(eng.clock.elapsed() + 0.5)
        assert sim.cp.active is not None
        return sim

    with _agentless_sim() as sim:
        cp = sim.cp
        cp.enable_follower_reads()
        leader = sim.leader()
        follower = next(m for m in sim.managers if m is not leader)
        plane = cp.plane_for(follower)
        assert plane is not None
        assert plane.fanout is not None, \
            "follower plane must default to the batched fan-out"
        # a session + stream through the PLANE (reads local, writes
        # forwarded to the leader), then a gap and a rebuild
        nid = "fanout-w0"
        leader.store.update(lambda tx: tx.create(_mk_node(nid)))
        cp.session_owner[nid] = follower.id
        eng = sim.engine
        eng.run_until(eng.clock.elapsed() + 2.0)
        session, _ = plane.register(nid)
        stream = plane.open_assignments(nid, session)
        assert stream.get(timeout=0).type == "complete"
        # assignments land via replication; the flush pass batches them
        _mk_assigned(sim, nid, 0, 5)
        eng.run_until(eng.clock.elapsed() + 4.0)
        plane.process_deadlines()
        inc = []
        while True:
            try:
                inc.append(stream.get(timeout=0))
            except TimeoutError:
                break
        assert inc and all(m.type == "incremental" for m in inc)
        # the gap: session released mid-flow, more assignments land,
        # plane flushes with the stream down (no crash, nothing lost)
        plane.release_session(nid, session)
        assert stream.closed
        _mk_assigned(sim, nid, 5, 3)
        eng.run_until(eng.clock.elapsed() + 4.0)
        plane.process_deadlines()
        session2, _ = plane.register(nid)
        stream2 = plane.open_assignments(nid, session2)
        rebuilt = stream2.get(timeout=0)
        assert rebuilt.type == "complete"
        want = sorted(
            t.id for t in follower.store.view(
                lambda tx: tx.find(Task, ByNode(nid))))
        got = sorted(obj.id for _a, kind, obj in rebuilt.changes
                     if kind == "task")
        assert got == want and len(want) == 8
        assert len(got) == len(set(got))
    # opt-out: the escape hatch restores the thread-per-stream plane
    monkeypatch.setenv("SWARM_BATCH_FANOUT", "0")
    with _agentless_sim(seed=5) as sim2:
        cp2 = sim2.cp
        cp2.enable_follower_reads()
        leader2 = sim2.leader()
        follower2 = next(m for m in sim2.managers if m is not leader2)
        plane2 = cp2.plane_for(follower2)
        assert plane2 is not None and plane2.fanout is None


def test_read_scenarios_20_seed_sweep_byte_identical():
    from swarmkit_tpu.sim.scenario import run_scenario
    _quiet()
    hashes = {}
    for name in ("follower-read-failover", "read-storm-degraded"):
        for seed in range(20):
            r = run_scenario(name, seed)
            assert r.ok, (name, seed, r.violations)
            hashes[(name, seed)] = (r.trace_hash, r.obs_trace_sha256)
    # byte-identity: re-running a seed reproduces the exact trace
    for name, seed in (("follower-read-failover", 7),
                       ("read-storm-degraded", 3)):
        r = run_scenario(name, seed)
        assert (r.trace_hash, r.obs_trace_sha256) == hashes[(name, seed)]
