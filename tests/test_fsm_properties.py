"""Task FSM safety properties, inspired by the reference's TLA+ specs
(design/tla/{Tasks,WorkerSpec}.tla, model-checked with TLC there):

  P1. observed task state is monotonically non-decreasing;
  P2. desired state never moves backwards;
  P3. terminal tasks are never resurrected (state stays terminal);
  P4. a task only carries a node once ASSIGNED or preassigned.

The checker subscribes to the store and validates every committed task
transition while a full cluster scenario (create / scale / fail / drain /
job completion) churns through the real components."""

import threading
import time

from swarmkit_tpu.manager import Allocator, Dispatcher
from swarmkit_tpu.manager.dispatcher import Config_
from swarmkit_tpu.models import (
    Annotations, Cluster, NodeAvailability, Node, ReplicatedService,
    Service, Task, TaskState, TaskStatus,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import TERMINAL_STATES, now
from swarmkit_tpu.agent import Agent
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.orchestrator import ReplicatedOrchestrator, TaskReaper
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import ByService, MemoryStore
from swarmkit_tpu.state.events import Event
from swarmkit_tpu.utils import new_id

from test_orchestrator import make_node, make_replicated, poll


class FSMInvariantChecker:
    def __init__(self, store):
        self.store = store
        self.violations = []
        self._last = {}
        self._sub = store.queue.subscribe(
            lambda ev: isinstance(ev, Event) and isinstance(ev.obj, Task))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        from swarmkit_tpu.state.watch import Closed
        while not self._stop.is_set():
            try:
                ev = self._sub.get(timeout=0.1)
            except TimeoutError:
                continue
            except Closed:
                return
            t = ev.obj
            if ev.action == "delete":
                self._last.pop(t.id, None)
                continue
            prev = self._last.get(t.id)
            if prev is not None:
                prev_state, prev_desired = prev
                if t.status.state < prev_state:
                    self.violations.append(
                        f"P1: task {t.id[:8]} state went backwards "
                        f"{prev_state.name} -> {t.status.state.name}")
                if t.desired_state < prev_desired:
                    self.violations.append(
                        f"P2: task {t.id[:8]} desired went backwards "
                        f"{prev_desired.name} -> {t.desired_state.name}")
                if prev_state in TERMINAL_STATES and \
                        t.status.state != prev_state and \
                        t.status.state not in TERMINAL_STATES:
                    self.violations.append(
                        f"P3: terminal task {t.id[:8]} resurrected to "
                        f"{t.status.state.name}")
            if t.status.state >= TaskState.ASSIGNED and not t.node_id \
                    and t.status.state <= TaskState.RUNNING:
                self.violations.append(
                    f"P4: task {t.id[:8]} in {t.status.state.name} "
                    "without a node")
            self._last[t.id] = (t.status.state, t.desired_state)

    def stop(self):
        self._stop.set()
        self.store.queue.unsubscribe(self._sub)
        self._thread.join(timeout=2)


def test_fsm_invariants_under_cluster_churn():
    store = MemoryStore()
    store.update(lambda tx: tx.create(Cluster(
        id=new_id(),
        spec=ClusterSpec(annotations=Annotations(name="default")))))
    checker = FSMInvariantChecker(store)

    d = Dispatcher(store, Config_(heartbeat_period=0.3,
                                  heartbeat_epsilon=0.02,
                                  process_updates_interval=0.02,
                                  assignment_batching_wait=0.02))
    d.run()
    alloc = Allocator(store)
    sched = Scheduler(store)
    orch = ReplicatedOrchestrator(store)
    reaper = TaskReaper(store)
    nodes = [make_node(f"n{i}") for i in range(3)]
    for n in nodes:
        n.description.resources.nano_cpus = 8 * 10**9
        n.description.resources.memory_bytes = 32 << 30
        store.update(lambda tx, n=n: tx.create(n))
    agents = [Agent(n.id, TestExecutor(), d) for n in nodes]
    alloc.start()
    sched.start()
    orch.start()
    reaper.start()
    for a in agents:
        a.start()
    try:
        svc = make_replicated("churn", 6)
        store.update(lambda tx: tx.create(svc))

        def n_running(k):
            got = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state == TaskState.RUNNING
                and t.status.state == TaskState.RUNNING]
            return len(got) == k
        poll(lambda: n_running(6), timeout=30)

        # fail a task
        victim = store.view(
            lambda tx: tx.find(Task, ByService(svc.id)))[0]

        def fail(tx):
            t = tx.get(Task, victim.id)
            if t is not None and t.status.state <= TaskState.RUNNING:
                t = t.copy()
                t.status = TaskStatus(state=TaskState.FAILED,
                                      timestamp=now(), err="churn")
                tx.update(t)
        store.update(fail)
        poll(lambda: n_running(6), timeout=30)

        # drain a node
        def drain(tx):
            n = tx.get(Node, nodes[0].id).copy()
            n.spec.availability = NodeAvailability.DRAIN
            tx.update(n)
        store.update(drain)
        poll(lambda: n_running(6), timeout=30)

        # scale down, then delete
        cur = store.view(lambda tx: tx.get(Service, svc.id)).copy()
        cur.spec.replicated = ReplicatedService(replicas=2)
        store.update(lambda tx: tx.update(cur))
        poll(lambda: n_running(2), timeout=30)
        store.update(lambda tx: tx.delete(Service, svc.id))
        time.sleep(1.0)

        assert not checker.violations, "\n".join(checker.violations[:10])
    finally:
        for a in agents:
            a.stop()
        orch.stop()
        reaper.stop()
        sched.stop()
        alloc.stop()
        d.stop()
        checker.stop()


def test_resourceapi_attach_detach():
    from swarmkit_tpu.manager import ResourceAPI
    from swarmkit_tpu.manager.controlapi import InvalidArgument, NotFound
    from swarmkit_tpu.models import Network
    from swarmkit_tpu.models.specs import NetworkSpec
    import pytest

    store = MemoryStore()
    node = make_node("n1")
    net = Network(id=new_id(), spec=NetworkSpec(
        annotations=Annotations(name="overlay1"), attachable=True))
    sealed = Network(id=new_id(), spec=NetworkSpec(
        annotations=Annotations(name="internal1")))
    store.update(lambda tx: (tx.create(node), tx.create(net),
                             tx.create(sealed)))
    api = ResourceAPI(store)

    with pytest.raises(NotFound):
        api.attach_network("nope", net.id)
    with pytest.raises(InvalidArgument, match="not attachable"):
        api.attach_network(node.id, sealed.id)

    attachment_id = api.attach_network(node.id, net.id,
                                       container_id="c1")
    t = store.view(lambda tx: tx.get(Task, attachment_id))
    assert t.spec.attachment.container_id == "c1"
    assert t.node_id == node.id
    assert t.networks[0].network_id == net.id

    with pytest.raises(InvalidArgument):
        api.detach_network("other-node", attachment_id)
    api.detach_network(node.id, attachment_id)
    assert store.view(lambda tx: tx.get(Task, attachment_id)) is None
