"""Differential tests for the fused many-service planner.

The fused path (ops/fusedbatch.py + kernel.plan_fused) packs a run of
consecutive fusable groups into ONE scan-over-groups program per chunk;
the contract is that fusion changes only the number of device
round-trips — placements, store snapshot bytes, and the watch-event
stream must be byte-identical to the per-group path
(SWARM_FUSED_PLANNER=0) for the same workload, in both the pipelined
and the serial (sim-shaped, depth-1) tick.  Degraded routes — bucket
overflow, device errors, spread spill — must fall back group-by-group,
never fail the tick.
"""

import numpy as np
import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    Placement, PlacementPreference, Platform, ReplicatedService, Resources,
    ResourceRequirements, Service, ServiceMode, ServiceSpec, SpreadOver,
    Task, TaskSpec, TaskState, TaskStatus, Version,
)
from swarmkit_tpu.models import types as model_types
from swarmkit_tpu.ops import TPUPlanner
from swarmkit_tpu.ops import fusedbatch
from swarmkit_tpu.ops import planner as planner_mod
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.state.events import Event, EventCommit, EventTaskBlock


@pytest.fixture
def frozen_clock():
    model_types.set_time_source(lambda: 1_700_000_000.0)
    try:
        yield
    finally:
        model_types.set_time_source(None)


def _mk_nodes(n, cpus=16 * 10**9, mem=64 << 30):
    return [Node(
        id=f"n{i:04d}",
        spec=NodeSpec(annotations=Annotations(
            name=f"node-{i:04d}",
            labels={"rack": f"r{i % 5}",
                    "tier": "web" if i % 2 else "db"})),
        status=NodeStatus(state=NodeState.READY),
        description=NodeDescription(
            hostname=f"node-{i:04d}",
            platform=Platform(os="linux", architecture="amd64"),
            resources=Resources(nano_cpus=cpus, memory_bytes=mem)))
        for i in range(n)]


def _mk_service(sid, n_tasks, spec=None):
    svc = Service(
        id=sid,
        spec=ServiceSpec(annotations=Annotations(name=f"svc-{sid}"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=n_tasks),
                         task=spec or TaskSpec()),
        spec_version=Version(index=1))
    tasks = [Task(id=f"{sid}-t{k:04d}", service_id=sid, slot=k + 1,
                  desired_state=TaskState.RUNNING, spec=svc.spec.task,
                  spec_version=Version(index=1),
                  status=TaskStatus(state=TaskState.PENDING))
             for k in range(n_tasks)]
    return svc, tasks


_RES = ResourceRequirements(
    reservations=Resources(nano_cpus=10**8, memory_bytes=64 << 20))


def _many_service_store(n_services=6, n_nodes=40, base=40, specs=None):
    """``n_services`` fusable replicated services of varying sizes."""
    store = MemoryStore()
    nodes = _mk_nodes(n_nodes)
    store.update(lambda tx: [tx.create(n) for n in nodes])
    batches = []
    for si in range(n_services):
        spec = (specs[si] if specs is not None
                else TaskSpec(resources=_RES))
        batches.append(_mk_service(f"svc{si}", base + 7 * si, spec))
    def mk(tx):
        for svc, tasks in batches:
            tx.create(svc)
            for t in tasks:
                tx.create(t)
    store.update(mk)
    return store


def _event_key(ev):
    if isinstance(ev, EventTaskBlock):
        return ("block", tuple(o.id for o in ev.olds),
                tuple(ev.node_ids), ev.base_version, ev.state, ev.message)
    if isinstance(ev, EventCommit):
        return ("commit", ev.version)
    if isinstance(ev, Event):
        obj = ev.obj
        return (ev.action, obj.id, getattr(obj, "node_id", None),
                int(obj.status.state) if hasattr(obj, "status") else None,
                obj.meta.version.index)
    return ("other", repr(ev))


def _run_tick(store, depth, fused=True, planner=None, ticks=1,
              pre_tick=None):
    sub = store.queue.subscribe(accepts_blocks=True)
    if planner is None:
        planner = TPUPlanner()
    planner.enable_small_group_routing = False  # deterministic routing
    planner.fused_enabled = fused
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=depth)
    store.view(sched._setup_tasks_list)
    if pre_tick is not None:
        pre_tick(store, sched)
    decisions = 0
    for _ in range(ticks):
        decisions += sched.tick()
    events = [_event_key(e) for e in sub.drain()]
    store.queue.unsubscribe(sub)
    tasks = store.view(lambda tx: tx.find(Task))
    state = sorted((t.id, t.node_id, int(t.status.state),
                    t.status.message, t.meta.version.index)
                   for t in tasks)
    return decisions, state, events, sched, planner


# --------------------------------------------------------------- parity

@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("n_services", [3, 6])
def test_fused_tick_byte_identical_to_per_group(frozen_clock, depth,
                                                n_services):
    """Fused placements, store snapshot bytes, and watch-event streams
    must equal the per-group path's, pipelined and serial."""
    dn, sn, en, schedn, pn = _run_tick(
        _many_service_store(n_services), depth, fused=True)
    d0, s0, e0, sched0, p0 = _run_tick(
        _many_service_store(n_services), depth, fused=False)
    # the fused path actually engaged, replacing per-group dispatches
    assert pn.stats.get("groups_fused", 0) == n_services
    assert pn.stats.get("groups_planned", 0) == 0
    assert p0.stats.get("groups_fused", 0) == 0
    assert p0.stats["groups_planned"] == n_services
    assert (dn, sn, en) == (d0, s0, e0)
    bn = _run_tick(_many_service_store(n_services), depth,
                   fused=True)[3].store.save_bytes()
    b0 = _run_tick(_many_service_store(n_services), depth,
                   fused=False)[3].store.save_bytes()
    assert bn == b0


def test_fused_fewer_dispatches_than_groups(frozen_clock):
    """The amortization claim itself: a fused run of G groups dispatches
    ceil(G / chunk) programs, not G."""
    _, _, _, _, planner = _run_tick(_many_service_store(8), 2,
                                    fused=True)
    assert planner.stats["groups_fused"] == 8
    assert 0 < planner.stats["fused_chunks"] < 8


def test_fused_mixed_with_unfusable_groups(frozen_clock):
    """Unfusable groups (here: a spread service and a host-path CSI
    volume mount; node.ip constraints ride the device hash/prefix
    columns now, so they no longer qualify) break the run and ride
    their usual routes; surrounding fusable groups still fuse;
    everything matches the per-group path."""
    from swarmkit_tpu.models.specs import ContainerSpec
    from swarmkit_tpu.models.types import Mount, MountType
    specs = [
        TaskSpec(resources=_RES),
        TaskSpec(resources=_RES),
        TaskSpec(container=ContainerSpec(
            image="x", mounts=[Mount(type=MountType.CSI, source="vol",
                                     target="/data")])),  # host fallback
        TaskSpec(placement=Placement(preferences=[
            PlacementPreference(spread=SpreadOver(
                spread_descriptor="node.labels.rack"))]),
            resources=_RES),                          # fusable (flat)
        TaskSpec(resources=_RES),
    ]
    dn, sn, en, _, pn = _run_tick(
        _many_service_store(5, specs=specs), 2, fused=True)
    d0, s0, e0, _, p0 = _run_tick(
        _many_service_store(5, specs=specs), 2, fused=False)
    assert (dn, sn, en) == (d0, s0, e0)
    assert pn.stats["groups_fallback"] == 1
    assert pn.stats.get("groups_fused", 0) >= 2


def test_fused_conflict_rollback_matches_per_group(frozen_clock):
    """A mid-flight concurrent assignment fails the block item, rolls
    back mirrors, and requeues — identically with fusion on and off,
    across two ticks (second tick re-places the rolled-back tasks)."""
    def conflict(store, sched):
        def cb(tx):
            for tid in ("svc0-t0000", "svc1-t0001"):
                cur = tx.get(Task, tid).copy()
                cur.node_id = "n0000"
                cur.status = TaskStatus(state=TaskState.ASSIGNED,
                                        timestamp=1.0,
                                        message="concurrent writer")
                tx.update(cur)
        store.update(cb)

    out1 = _run_tick(_many_service_store(4), 2, fused=True,
                     pre_tick=conflict, ticks=2)
    out0 = _run_tick(_many_service_store(4), 2, fused=False,
                     pre_tick=conflict, ticks=2)
    assert out1[:3] == out0[:3]
    assert sorted(out1[3].unassigned_tasks) == sorted(
        out0[3].unassigned_tasks)


# ------------------------------------------------------ segment masking

def test_segment_masked_constraints_never_cross(frozen_clock):
    """Two groups with conflicting constraints in one fused batch must
    never share placements: each group's constraint rows mask only its
    own scan step."""
    specs = [
        TaskSpec(placement=Placement(
            constraints=["node.labels.tier==web"]), resources=_RES),
        TaskSpec(placement=Placement(
            constraints=["node.labels.tier==db"]), resources=_RES),
    ]
    store = _many_service_store(2, n_nodes=30, base=30, specs=specs)
    _, state, _, _, planner = _run_tick(store, 2, fused=True)
    assert planner.stats.get("groups_fused", 0) == 2
    node_tier = {f"n{i:04d}": ("web" if i % 2 else "db")
                 for i in range(30)}
    placed = {tid: nid for tid, nid, st, _, _ in state if nid}
    assert placed, "nothing placed"
    for tid, nid in placed.items():
        want = "web" if tid.startswith("svc0") else "db"
        assert node_tier[nid] == want, (tid, nid)


def test_fused_kernel_carry_sequencing():
    """Kernel-level: two groups of the SAME service with maxrep=1 — the
    scan carry must feed group 0's placements into group 1's per-node
    service counts, so the two groups land on disjoint nodes; and two
    groups with opposite constraints score disjoint node sets."""
    import jax.numpy as jnp
    from swarmkit_tpu.ops.hashing import str_hash
    from swarmkit_tpu.ops.kernel import (
        FusedCarry, FusedGroups, FusedShared, plan_fused_jit,
    )

    nb, g, cc, sb = 16, 4, 1, 2
    web = np.array([i % 2 == 0 for i in range(nb)])
    with fusedbatch.x64():
        valid = np.ones(nb, bool)
        shared = FusedShared(
            valid=jnp.asarray(valid), ready=jnp.asarray(valid),
            os_hash=jnp.zeros((2, nb), jnp.int32),
            arch_hash=jnp.zeros((2, nb), jnp.int32),
            svc0=jnp.zeros((sb, nb), jnp.int32))
        con_hash = np.zeros((g, cc, 2, nb), np.int32)
        con_op = np.full((g, cc), 2, np.int32)
        con_exp = np.zeros((g, cc, 2), np.int32)
        for i in range(nb):
            hv = fusedbatch.split_hash(
                str_hash("web" if web[i] else "db"))
            con_hash[2, 0, :, i] = hv
            con_hash[3, 0, :, i] = hv
        con_op[2, 0] = 0
        con_exp[2, 0] = fusedbatch.split_hash(str_hash("web"))
        con_op[3, 0] = 0
        con_exp[3, 0] = fusedbatch.split_hash(str_hash("db"))
        groups = FusedGroups(
            # groups 0+1: same service slot, maxrep=1, k=4 each
            # groups 2+3: conflicting tier constraints, k=3 each
            k=jnp.asarray(np.array([4, 4, 3, 3], np.int32)),
            slot=jnp.asarray(np.array([0, 0, 1, 1], np.int32)),
            maxrep=jnp.asarray(np.array([1, 1, 0, 0], np.int32)),
            cpu_d=jnp.zeros(g, jnp.int64),
            mem_d=jnp.zeros(g, jnp.int64),
            con_hash=jnp.asarray(con_hash),
            con_op=jnp.asarray(con_op), con_exp=jnp.asarray(con_exp),
            plat=jnp.full((g, 1, 4), -1, jnp.int32),
            failures=jnp.zeros((g, nb), jnp.int32),
            leaf=jnp.zeros((g, nb), jnp.int32),
            extra_mask=jnp.ones((g, nb), jnp.bool_))
        carry = FusedCarry(
            total=jnp.zeros(nb, jnp.int32),
            cpu=jnp.zeros(nb, jnp.int64), mem=jnp.zeros(nb, jnp.int64),
            svc_acc=jnp.zeros((sb, nb), jnp.int32))
        xs, fcs, spills, out = plan_fused_jit(shared, groups, carry, 1)
        xs = np.asarray(xs)
    # carry sequencing: same-service maxrep=1 groups on disjoint nodes
    assert xs[0].sum() == 4 and xs[1].sum() == 4
    assert np.all(xs[0] * xs[1] == 0), (xs[0], xs[1])
    # segment masking: conflicting constraints score disjoint node sets
    assert xs[2].sum() == 3 and xs[3].sum() == 3
    assert np.all(xs[2][~web] == 0), xs[2]
    assert np.all(xs[3][web] == 0), xs[3]
    # carry accounting matches the placements
    acc = np.asarray(out.svc_acc)
    assert np.array_equal(acc[0], xs[0] + xs[1])
    assert np.array_equal(acc[1], xs[2] + xs[3])


# ------------------------------------------------------ degraded routes

def test_constraint_overflow_breaks_run_at_probe(frozen_clock):
    """A group whose constraint count overflows the shared bucket ladder
    is not fusable; it breaks the run and rides the per-group (-> host
    fallback) path while its neighbors still fuse."""
    many = [f"node.labels.k{i}==v" for i in range(20)]  # > CC max (16)
    specs = [
        TaskSpec(resources=_RES),
        TaskSpec(placement=Placement(constraints=many), resources=_RES),
        TaskSpec(resources=_RES),
    ]
    dn, sn, en, _, pn = _run_tick(
        _many_service_store(3, specs=specs), 2, fused=True)
    d0, s0, e0, _, p0 = _run_tick(
        _many_service_store(3, specs=specs), 2, fused=False)
    assert (dn, sn, en) == (d0, s0, e0)
    assert pn.stats["groups_fallback"] == 1   # the 20-constraint group


def test_fused_build_failure_falls_back_group_by_group(frozen_clock,
                                                       monkeypatch):
    """A fused batch that cannot be built degrades to per-group
    dispatches with identical placements — never a failed tick."""
    ref = _run_tick(_many_service_store(4), 2, fused=False)
    monkeypatch.setattr(fusedbatch, "build_run",
                        lambda planner, sched, specs: None)
    out = _run_tick(_many_service_store(4), 2, fused=True)
    assert out[:3] == ref[:3]
    assert out[4].stats.get("groups_fused", 0) == 0
    assert out[4].stats["groups_planned"] == 4
    assert out[4].stats.get("fused_overflows", 0) >= 1


def test_fused_dispatch_error_falls_back_group_by_group(frozen_clock,
                                                        monkeypatch):
    """A device error inside the fused dispatch marks the fused path
    dead for the tick; every group still places via the per-group path
    and the tick's outputs are unchanged."""
    ref = _run_tick(_many_service_store(4), 2, fused=False)

    def boom(*a, **k):
        raise RuntimeError("injected fused dispatch failure")

    monkeypatch.setattr(planner_mod, "plan_fused_jit", boom)
    out = _run_tick(_many_service_store(4), 2, fused=True)
    assert out[:3] == ref[:3]
    p = out[4]
    assert p.stats.get("groups_fused", 0) == 0
    assert p.stats["groups_planned"] == 4
    assert p.stats.get("groups_device_error", 0) >= 1
    assert p._fused_dead


def test_fused_spill_routes_group_to_host(frozen_clock):
    """A spread branch saturating mid-run aborts the fused run and the
    group takes the host oracle, exactly like the per-group spill route;
    placements match the per-group path."""
    # rack r4 holds a single tiny node (capacity 2); spreading 40 tasks
    # over 5 racks wants 8 there -> the branch saturates -> spill
    store_fused = MemoryStore()
    store_plain = MemoryStore()
    spread = TaskSpec(placement=Placement(preferences=[
        PlacementPreference(spread=SpreadOver(
            spread_descriptor="node.labels.rack"))]),
        resources=ResourceRequirements(reservations=Resources(
            nano_cpus=10**9, memory_bytes=1 << 30)))
    plain = TaskSpec(resources=_RES)
    for store in (store_fused, store_plain):
        nodes = _mk_nodes(16)
        nodes.append(Node(
            id="n9999",
            spec=NodeSpec(annotations=Annotations(
                name="tiny", labels={"rack": "r9", "tier": "web"})),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname="tiny",
                platform=Platform(os="linux", architecture="amd64"),
                resources=Resources(nano_cpus=2 * 10**9,
                                    memory_bytes=2 << 30))))
        store.update(lambda tx, nodes=nodes:
                     [tx.create(n) for n in nodes])
        batches = [_mk_service("svc0", 30, plain),
                   _mk_service("svc1", 60, spread),
                   _mk_service("svc2", 30, plain)]
        def mk(tx, batches=batches):
            for svc, tasks in batches:
                tx.create(svc)
                for t in tasks:
                    tx.create(t)
        store.update(mk)
    dn, sn, en, _, pn = _run_tick(store_fused, 2, fused=True)
    d0, s0, e0, _, p0 = _run_tick(store_plain, 2, fused=False)
    assert (dn, sn, en) == (d0, s0, e0)
    assert p0.stats.get("groups_spill_to_host", 0) >= 1, \
        "workload no longer spills; rebuild it so the route is covered"
    assert pn.stats.get("groups_spill_to_host", 0) >= 1


# ------------------------------------------------------------- sharding

def test_fused_mesh_parity(frozen_clock):
    """ShardedPlanFn's fused path (node axis over a 4-device mesh) must
    produce byte-identical state/events to the single-device program."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices (conftest forces 8 host devices)")
    from swarmkit_tpu.parallel import ShardedPlanFn, make_mesh
    mesh_fn = ShardedPlanFn(make_mesh(jax.devices()[:4]))
    dm, sm, em, _, pm = _run_tick(_many_service_store(5), 2, fused=True,
                                  planner=TPUPlanner(plan_fn=mesh_fn))
    d1, s1, e1, _, p1 = _run_tick(_many_service_store(5), 2, fused=True)
    assert pm.stats.get("groups_fused", 0) == 5
    assert (dm, sm, em) == (d1, s1, e1)


# -------------------------------------------------------- sim differential

def test_fused_differential_scenario():
    """The sim's differential scenario: fused placements must equal
    per-service placements per seed under churn (host-fallback, failure
    down-weighting, drains, breaker trip, leadership stepdown)."""
    from swarmkit_tpu.sim import run_scenario
    r = run_scenario("fused-differential-churn", seed=7)
    assert r.ok, r.violations


def test_fused_differential_detects_divergence(monkeypatch):
    """Checker sensitivity: a fused batch that mis-densifies the
    per-service base counts MUST diverge from the per-service oracle,
    and the differential must catch it — a comparison that can't fire
    is a no-op."""
    from swarmkit_tpu.sim import run_scenario
    orig = fusedbatch.build_run

    def broken(planner, sched, specs):
        run = orig(planner, sched, specs)
        if run is not None:
            run.shared = run.shared._replace(
                svc0=np.zeros_like(run.shared.svc0))
        return run

    monkeypatch.setattr(fusedbatch, "build_run", broken)
    r = run_scenario("fused-differential-churn", seed=7)
    assert any("fused-differential" in v and "diverged" in v
               for v in r.violations), r.violations


def test_bench_compare_shape_and_compile_gates(tmp_path):
    """bench_compare exits 1 when the NEW run's cfg6/cfg7 shape_cost_x
    exceeds the bar or when timed-region compile counts grew; clean
    runs pass."""
    import json
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), "..",
                                      "scripts"))
    try:
        import bench_compare
    finally:
        _sys.path.pop(0)

    def record(shape6=1.2, shape7=1.3, compiles=0, headline_compiles=0):
        return {"t": 1.0, "value": 250000.0, "unit": "d/s",
                "metric": "m", "health": "pass",
                "planner_compiles": headline_compiles,
                "configs": {
                    "6_live_manager_2x100k_x_10k": {
                        "decisions_per_sec": 170000.0,
                        "shape_cost_x": shape6, "compiles": compiles},
                    "7_many_service_10x": {
                        "decisions_per_sec": 170000.0,
                        "shape_cost_x": shape7, "compiles": 0}},
                "pipeline_depth": 2, "plan_hidden_frac": 0.5,
                "plan_commit_overlap_s": 0.05,
                "plan_overlap_source": "cfg6"}

    hist = tmp_path / "hist.jsonl"

    def run(old, new):
        with open(hist, "w") as f:
            f.write(json.dumps(old) + "\n")
            f.write(json.dumps(new) + "\n")
        return bench_compare.main(["--history", str(hist)])

    assert run(record(), record()) == 0
    # shape bar is judged on the NEW run alone, per live config
    assert run(record(), record(shape6=1.9)) == 1
    assert run(record(), record(shape7=2.4)) == 1
    # an old run that also missed the bar must not disarm the gate
    assert run(record(shape6=3.0), record(shape6=1.9)) == 1
    # compile growth in a shared config or the headline fails
    assert run(record(), record(compiles=2)) == 1
    assert run(record(), record(headline_compiles=1)) == 1
    # equal nonzero compile counts are flat, not growth
    assert run(record(compiles=1), record(compiles=1)) == 0


def test_mesh_env_knob(monkeypatch):
    """SWARM_PLANNER_MESH builds the mesh at planner construction; a
    count beyond the available devices is a loud error."""
    import jax
    monkeypatch.setenv("SWARM_PLANNER_MESH", "2")
    p = TPUPlanner()
    assert p.mesh is not None and p.mesh.shape["nodes"] == 2
    assert p._fused_fn is p._plan_fn
    monkeypatch.setenv("SWARM_PLANNER_MESH", "1")
    assert TPUPlanner().mesh is None
    monkeypatch.setenv("SWARM_PLANNER_MESH",
                       str(len(jax.devices()) + 1))
    with pytest.raises(RuntimeError):
        TPUPlanner()
