"""Gang scheduling & pipeline workflows (ISSUE 16): spec serde +
forward compatibility, control-API validation (gang shape, DAG cycles),
the gang_fit device kernel vs its numpy host oracle (differential fuzz,
per-group AND fused routes), atomic admission (single-commit placement,
rollback on shortfall, deterministic two-gang ordering), the
preemption-entitlement bugfix (starved priority-0 gangs acquire victims
under tenant quota), the scheduler's pipeline gate, the
PipelineSupervisor release/halt FSM, non-gang byte-identity, and
checker sensitivity for the two new sim invariants.
"""

import dataclasses

import numpy as np
import pytest

from swarmkit_tpu.models import (
    Annotations, ContainerSpec, GangConfig, Node, NodeDescription,
    NodeSpec, NodeState, NodeStatus, PipelineStatus, Placement,
    ReplicatedJob, ReplicatedService, Resources, ResourceRequirements,
    Service, ServiceMode, ServiceSpec, Task, TaskSpec, TaskState,
    TaskStatus, Version,
)
from swarmkit_tpu.models.objects import Cluster
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import TenantQuota, now
from swarmkit_tpu.manager.controlapi import ControlAPI, InvalidArgument
from swarmkit_tpu.ops.kernel import (
    GroupInputs, NodeInputs, gang_fit_fused_jit, gang_fit_jit,
)
from swarmkit_tpu.orchestrator.pipeline import (
    POISON_FAILURES, PipelineSupervisor,
)
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.scheduler import gang as gang_mod
from swarmkit_tpu.scheduler.quota import TENANT_LABEL
from swarmkit_tpu.sim.cluster import Sim
from swarmkit_tpu.sim.faults import NetConfig
from swarmkit_tpu.state import serde
from swarmkit_tpu.state.store import MemoryStore
from swarmkit_tpu.utils import new_id

CPU = 2 * 10 ** 9
GB = 1 << 30


# ---------------------------------------------------------------------------
# serde: round-trip + forward compatibility
# ---------------------------------------------------------------------------

def _gang_task():
    return Task(
        id=new_id(), service_id="svc1", slot=1,
        desired_state=TaskState.RUNNING,
        spec=TaskSpec(
            placement=Placement(gang=GangConfig(min_size=8)),
            gang_id="ring-0",
            resources=ResourceRequirements(
                reservations=Resources(nano_cpus=CPU))),
        spec_version=Version(index=1),
        status=TaskStatus(state=TaskState.PENDING))


def _pipeline_service():
    return Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name="stage-b"),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=3),
            task=TaskSpec(),
            depends_on=["stage-a"],
            on_upstream_failure="rollback"),
        spec_version=Version(index=1),
        pipeline_status=PipelineStatus(
            state="released", reason="", updated_at=5.0))


@pytest.mark.parametrize("obj", [_gang_task(), _pipeline_service()],
                         ids=["gang-task", "pipeline-service"])
def test_gang_fields_roundtrip_serde(obj):
    data = serde.dumps(obj)
    back = serde.loads(type(obj), data)
    assert dataclasses.asdict(back) == dataclasses.asdict(obj)
    assert serde.dumps(back) == data


def test_old_records_decode_to_gang_off_defaults():
    """Forward compatibility: records written before this PR (no gang /
    pipeline keys) decode to the gang-off defaults, and the copy()
    paths preserve the new fields."""
    t = _gang_task()
    d = serde.to_dict(t)
    del d["spec"]["gang_id"]
    del d["spec"]["placement"]["gang"]
    back = serde.from_dict(Task, d)
    assert back.spec.gang_id == ""
    assert back.spec.placement.gang is None
    assert not gang_mod.is_gang(back)

    s = _pipeline_service()
    d = serde.to_dict(s)
    del d["spec"]["depends_on"]
    del d["spec"]["on_upstream_failure"]
    del d["pipeline_status"]
    back = serde.from_dict(Service, d)
    assert back.spec.depends_on == []
    assert back.spec.on_upstream_failure == ""
    assert back.pipeline_status is None

    # deep-copy keeps the opt-in fields intact
    t2 = t.copy()
    assert t2.spec.gang_id == "ring-0"
    assert t2.spec.placement.gang.min_size == 8
    s2 = s.copy()
    assert s2.spec.depends_on == ["stage-a"]
    assert s2.pipeline_status.state == "released"
    # and is a real copy, not an alias
    s2.spec.depends_on.append("x")
    assert s.spec.depends_on == ["stage-a"]


def test_gang_unit_key_resolution():
    t = _gang_task()
    assert gang_mod.gang_unit(t) == "ring-0"
    t.spec.gang_id = ""
    assert gang_mod.gang_unit(t) == "svc1"


# ---------------------------------------------------------------------------
# control API: gang shape + DAG validation, exact error strings
# ---------------------------------------------------------------------------

def _svc_spec(name, depends_on=(), on_upstream_failure="",
              gang_min=None):
    placement = Placement()
    if gang_min is not None:
        placement = Placement(gang=GangConfig(min_size=gang_min))
    return ServiceSpec(
        annotations=Annotations(name=name),
        mode=ServiceMode.REPLICATED,
        replicated=ReplicatedService(replicas=2),
        task=TaskSpec(container=ContainerSpec(image="nginx"),
                      placement=placement),
        depends_on=list(depends_on),
        on_upstream_failure=on_upstream_failure)


def test_controlapi_validates_gang_and_pipeline_fields():
    api = ControlAPI(MemoryStore())

    with pytest.raises(InvalidArgument) as e:
        api.create_service(_svc_spec("g", gang_min=-1))
    assert str(e.value) == \
        "Placement: gang min_size must be a non-negative integer"

    with pytest.raises(InvalidArgument) as e:
        api.create_service(_svc_spec("p", depends_on=[""]))
    assert str(e.value) == ("ServiceSpec: depends_on entries must be "
                            "non-empty service names")

    with pytest.raises(InvalidArgument) as e:
        api.create_service(_svc_spec("p", depends_on=["p"]))
    assert str(e.value) == \
        'ServiceSpec: service "p" cannot depend on itself'

    with pytest.raises(InvalidArgument) as e:
        api.create_service(_svc_spec("p", on_upstream_failure="retry"))
    assert str(e.value) == ("ServiceSpec: unknown on_upstream_failure "
                            "'retry' (known: halt, rollback)")

    # valid opt-ins are accepted (forward reference to a not-yet-created
    # upstream is legal: the gate fails safe while it is absent)
    api.create_service(_svc_spec("ok-gang", gang_min=4))
    api.create_service(_svc_spec("ok-stage", depends_on=["upstream"],
                                 on_upstream_failure="rollback"))


def test_controlapi_rejects_dependency_cycles():
    api = ControlAPI(MemoryStore())
    api.create_service(_svc_spec("a", depends_on=["b"]))

    # closing the 2-cycle through the existing edge set is rejected
    with pytest.raises(InvalidArgument) as e:
        api.create_service(_svc_spec("b", depends_on=["a"]))
    assert str(e.value) == "ServiceSpec: depends_on cycle: b -> a -> b"

    # a longer cycle through an intermediate stage too
    api.create_service(_svc_spec("b", depends_on=["c"]))
    with pytest.raises(InvalidArgument) as e:
        api.create_service(_svc_spec("c", depends_on=["a"]))
    assert str(e.value) == \
        "ServiceSpec: depends_on cycle: c -> a -> b -> c"

    # update_service runs the same walk
    b = api.store.view(lambda tx: next(
        s for s in tx.find(Service) if s.spec.annotations.name == "b"))
    with pytest.raises(InvalidArgument):
        api.update_service(b.id, b.meta.version.index,
                           _svc_spec("b", depends_on=["b"]))


# ---------------------------------------------------------------------------
# gang_fit: device kernel vs numpy host oracle (differential fuzz)
# ---------------------------------------------------------------------------

def _random_gang_inputs(rng, nb, L=None):
    """One random densified (NodeInputs, GroupInputs) pair covering
    every filter column gang_fit folds: readiness, reservations,
    plugin masks, constraints (== / != / disabled), platforms, ports,
    max-replicas, and the optional tenant-quota column.  ``L`` pins
    the constraint-row count (the fused route stacks same-shape
    gangs)."""
    n = int(rng.integers(1, nb))
    valid = np.zeros(nb, bool)
    valid[:n] = True
    L = int(rng.integers(1, 3)) if L is None else L
    con_hash = rng.integers(0, 3, (L, 2, nb)).astype(np.int32)
    con_exp = rng.integers(0, 3, (L, 2)).astype(np.int32)
    con_op = rng.integers(0, 3, L).astype(np.int32)
    plat = np.full((2, 4), -1, np.int32)
    if rng.random() < 0.5:
        plat[0] = rng.integers(0, 2, 4).astype(np.int32)
    os_hash = rng.integers(0, 2, (2, nb)).astype(np.int32)
    nodes = NodeInputs(
        valid=valid,
        ready=valid & (rng.random(nb) < 0.9),
        res_ok=valid & (rng.random(nb) < 0.9),
        res_cap=np.where(valid, rng.integers(0, 12, nb), 0).astype(
            np.int32),
        svc_tasks=rng.integers(0, 6, nb).astype(np.int32),
        total_tasks=rng.integers(0, 40, nb).astype(np.int32),
        failures=rng.integers(0, 4, nb).astype(np.int32),
        leaf=np.zeros(nb, np.int32),
        os_hash=os_hash,
        arch_hash=rng.integers(0, 2, (2, nb)).astype(np.int32),
        port_conflict=rng.random(nb) < 0.2,
        extra_mask=rng.random(nb) < 0.95,
        quota_ok=(rng.random(nb) < 0.8) if rng.random() < 0.5
        else None)
    group = GroupInputs(
        k=np.int32(rng.integers(1, 40)),
        con_hash=con_hash, con_op=con_op, con_exp=con_exp,
        plat=plat,
        maxrep=np.int32(rng.choice([0, 0, 2, 4])),
        port_limited=np.bool_(rng.random() < 0.3))
    return nodes, group


def test_gang_fit_device_matches_host_oracle_fuzz():
    """Per-group route: (fit, fail_counts) bit-equal to the numpy
    oracle over random clusters — the contract the planner breaker's
    host demotion stands on."""
    for seed in range(12):
        rng = np.random.default_rng(seed)
        nb = int(rng.choice([64, 128]))
        nodes, group = _random_gang_inputs(rng, nb)
        fit_d, fc_d = gang_fit_jit(nodes, group)
        fit_h, fc_h = gang_mod.gang_fit_host(nodes, group)
        assert bool(fit_d) == fit_h, seed
        assert (np.asarray(fc_d) == fc_h).all(), seed


def test_gang_fit_fused_matches_host_oracle_fuzz():
    """Fused route: G gangs stacked on a leading axis, every verdict
    bit-equal to the per-gang oracle."""
    for seed in range(6):
        rng = np.random.default_rng(100 + seed)
        nb = 64
        rows = [_random_gang_inputs(rng, nb, L=2) for _ in range(3)]
        # quota presence must be uniform across the stack (the fused
        # caller buckets by it); strip it for the stacked run
        rows = [(n._replace(quota_ok=None), g) for n, g in rows]
        stacked_nodes = NodeInputs(*[
            None if f == "quota_ok"
            else np.stack([getattr(n, f) for n, _ in rows])
            for f in NodeInputs._fields])
        stacked_groups = GroupInputs(*[
            np.stack([getattr(g, f) for _, g in rows])
            for f in GroupInputs._fields])
        fits, fcs = gang_fit_fused_jit(stacked_nodes, stacked_groups)
        for i, (n, g) in enumerate(rows):
            fit_h, fc_h = gang_mod.gang_fit_host(n, g)
            assert bool(fits[i]) == fit_h, (seed, i)
            assert (np.asarray(fcs[i]) == fc_h).all(), (seed, i)


def test_gang_fit_boundary_exact_fit():
    """sum(cap) == k is feasible; one less is not — the f32 capacity
    comparison decides the boundary exactly (docstring contract)."""
    rng = np.random.default_rng(0)
    nodes, group = _random_gang_inputs(rng, 64)
    nodes = nodes._replace(
        valid=np.arange(64) < 4, ready=np.arange(64) < 4,
        res_ok=np.arange(64) < 4, extra_mask=np.ones(64, bool),
        port_conflict=np.zeros(64, bool),
        res_cap=np.where(np.arange(64) < 4, 3, 0).astype(np.int32),
        quota_ok=None)
    group = group._replace(
        con_op=np.full(group.con_op.shape, 2, np.int32),
        plat=np.full_like(group.plat, -1),
        maxrep=np.int32(0), port_limited=np.bool_(False))
    for k, want in ((12, True), (13, False)):
        g = group._replace(k=np.int32(k))
        assert bool(gang_fit_jit(nodes, g)[0]) is want
        assert gang_mod.gang_fit_host(nodes, g)[0] is want


# ---------------------------------------------------------------------------
# atomic admission: single commit, rollback, deterministic ordering
# ---------------------------------------------------------------------------

def _mk_store(n_nodes, services, node_cpu=4 * 10 ** 9, cluster=None):
    """services: (sid, priority, n_pending, n_running, gang_min,
    gang_id, depends_on, tenant) tuples; running tasks round-robin."""
    store = MemoryStore()
    if cluster is not None:
        store.update(lambda tx: tx.create(cluster))

    def mk(tx):
        for i in range(n_nodes):
            tx.create(Node(
                id=f"n{i:03d}",
                spec=NodeSpec(annotations=Annotations(name=f"n{i:03d}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"n{i:03d}",
                    resources=Resources(nano_cpus=node_cpu,
                                        memory_bytes=16 * GB))))
        for (sid, prio, n_pending, n_running, gang_min, gang_id,
                depends_on, tenant) in services:
            placement = (Placement(gang=GangConfig(min_size=gang_min))
                         if gang_min else Placement())
            spec = TaskSpec(
                priority=prio, placement=placement, gang_id=gang_id,
                resources=ResourceRequirements(reservations=Resources(
                    nano_cpus=CPU, memory_bytes=GB)))
            ann = Annotations(
                name=sid,
                labels={TENANT_LABEL: tenant} if tenant else {})
            tx.create(Service(
                id=sid,
                spec=ServiceSpec(
                    annotations=ann, mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(
                        replicas=n_pending + n_running),
                    task=spec, depends_on=list(depends_on)),
                spec_version=Version(index=1)))
            for s in range(n_running):
                tx.create(Task(
                    id=f"{sid}-r{s:03d}", service_id=sid, slot=s + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    service_annotations=ann,
                    node_id=f"n{s % n_nodes:03d}",
                    status=TaskStatus(state=TaskState.RUNNING,
                                      timestamp=now())))
            for s in range(n_pending):
                tx.create(Task(
                    id=f"{sid}-p{s:03d}", service_id=sid,
                    slot=n_running + s + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    service_annotations=ann,
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
    store.update(mk)
    return store


def _tick(store):
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    return sched


def test_gang_places_whole_unit_in_one_commit():
    from swarmkit_tpu.state.events import Event, commit_or
    # 3 nodes x 2 slots = 6; a 6-member gang fits exactly
    store = _mk_store(3, [("svc-g", 0, 6, 0, 6, "", (), "")])
    sub = store.queue.subscribe(commit_or(
        lambda ev: isinstance(ev, Event) and isinstance(ev.obj, Task)))
    sched = _tick(store)
    tasks = [t for t in store.view(lambda tx: tx.find(Task))]
    assert all(t.node_id and t.status.state == TaskState.ASSIGNED
               for t in tasks)
    # one transaction: every assignment event lands before a single
    # commit boundary — no commit interleaves a strict subset
    stream, assigned = [], 0
    ev = sub.poll()
    while ev is not None:
        if isinstance(ev, Event) and isinstance(ev.obj, Task) \
                and ev.obj.node_id:
            assigned += 1
            stream.append("assign")
        elif not isinstance(ev, Event):
            stream.append("commit")
        ev = sub.poll()
    assert assigned == 6
    first = stream.index("assign")
    last = len(stream) - 1 - stream[::-1].index("assign")
    assert "commit" not in stream[first:last], stream
    assert sched.gang.stats["gangs_admitted"] == 1
    assert sched.gang.stats["gang_tasks_placed"] == 6
    assert not sched.gang.blocked


def test_gang_rolls_back_entirely_on_shortfall():
    # 2 nodes x 2 slots = 4 < 6 members: nothing may commit, and the
    # scratch reservations must roll back (mirrors stay clean)
    store = _mk_store(2, [("svc-g", 0, 6, 0, 6, "", (), "")])
    sched = _tick(store)
    tasks = store.view(lambda tx: tx.find(Task))
    assert not any(t.node_id for t in tasks)
    errs = {t.status.err for t in tasks}
    assert errs == {'gang "svc-g" deferred: all-or-nothing placement '
                    'infeasible'}, errs
    assert "svc-g" in sched.gang.blocked
    assert sched.gang.stats["gangs_admitted"] == 0
    # node mirrors untouched: full capacity still available
    free = [info.available_resources.nano_cpus
            for info in sched.node_set.nodes.values()]
    assert free == [4 * 10 ** 9] * 2, free


def test_two_gangs_admit_in_deterministic_order():
    # capacity for ONE 6-gang; the key-ordered admission places
    # svc-a whole and defers svc-b whole — no interleaved livelock
    store = _mk_store(3, [("svc-a", 0, 6, 0, 6, "", (), ""),
                          ("svc-b", 0, 6, 0, 6, "", (), "")])
    _tick(store)
    tasks = store.view(lambda tx: tx.find(Task))
    a = [t for t in tasks if t.service_id == "svc-a"]
    b = [t for t in tasks if t.service_id == "svc-b"]
    assert all(t.node_id for t in a)
    assert not any(t.node_id for t in b)
    # priority outranks key order
    store2 = _mk_store(3, [("svc-a", 0, 6, 0, 6, "", (), ""),
                           ("svc-z", 5, 6, 0, 6, "", (), "")])
    _tick(store2)
    tasks2 = store2.view(lambda tx: tx.find(Task))
    assert all(t.node_id for t in tasks2 if t.service_id == "svc-z")
    assert not any(t.node_id for t in tasks2
                   if t.service_id == "svc-a")


def test_cross_service_gang_is_one_atomic_unit():
    # two 3-replica services share gang_id (min_size 6); capacity 4
    # defers BOTH services entirely
    svcs = [("svc-h1", 0, 3, 0, 6, "ring", (), ""),
            ("svc-h2", 0, 3, 0, 6, "ring", (), "")]
    store = _mk_store(2, svcs)
    _tick(store)
    assert not any(t.node_id
                   for t in store.view(lambda tx: tx.find(Task)))
    # with capacity they admit together
    store2 = _mk_store(3, svcs)
    _tick(store2)
    assert all(t.node_id and t.status.state == TaskState.ASSIGNED
               for t in store2.view(lambda tx: tx.find(Task)))


def test_incomplete_gang_waits_for_materialization():
    # only 4 of min_size 6 pending (orchestrator still materializing):
    # defer with the incomplete stamp, not a placement attempt
    store = _mk_store(3, [("svc-g", 0, 4, 0, 6, "", (), "")])
    sched = _tick(store)
    tasks = store.view(lambda tx: tx.find(Task))
    assert not any(t.node_id for t in tasks)
    errs = {t.status.err for t in tasks}
    assert errs == {'gang "svc-g" incomplete (4/6 members pending)'}
    assert "svc-g" not in sched.gang.blocked


def test_gang_over_quota_defers_atomically_and_uncharges():
    cluster = Cluster(
        id="cluster-default",
        spec=ClusterSpec(
            annotations=Annotations(name="default"),
            tenants={"lo": TenantQuota(nano_cpus=2 * CPU)}))
    store = _mk_store(4, [("svc-g", 0, 4, 0, 4, "", (), "lo")],
                      cluster=cluster)
    sched = _tick(store)
    tasks = store.view(lambda tx: tx.find(Task))
    assert not any(t.node_id for t in tasks)
    errs = {t.status.err for t in tasks}
    assert errs == {'gang "svc-g" over tenant quota (tenant "lo")'}
    # the all-or-nothing charge rolled back: the ledger shows zero use
    assert sched.quota.used.get("lo", [0, 0, 0])[2] == 0


# ---------------------------------------------------------------------------
# preemption entitlement (ROADMAP item 7 residual)
# ---------------------------------------------------------------------------

def test_starved_gang_acquires_victims_under_tenant_quota():
    """A priority-0 gang blocked on capacity held by strictly-lower
    work must enter the preemption pass (the old trigger required
    priority > 0 and starved it forever) — evict-only, then place
    atomically once the capacity frees."""
    cluster = Cluster(
        id="cluster-default",
        spec=ClusterSpec(
            annotations=Annotations(name="default"),
            tenants={"lo": TenantQuota(nano_cpus=8 * CPU)}))
    store = _mk_store(
        3, [("svc-victim", -5, 0, 6, 0, "", (), ""),
            ("svc-g", 0, 4, 0, 4, "", (), "lo")],
        cluster=cluster)
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = store.view(lambda tx: tx.find(Task))
    gang_tasks = [t for t in tasks if t.service_id == "svc-g"]
    victims = [t for t in tasks
               if "swarm.preempted.at" in t.annotations.labels]
    # tick 1: capacity-blocked gang is entitled — victims evicted,
    # but the gang itself did NOT place (evict-only keeps atomicity)
    assert "svc-g" in sched.gang.blocked
    assert len(victims) == 4
    assert all(v.desired_state == TaskState.SHUTDOWN for v in victims)
    assert not any(t.node_id for t in gang_tasks)

    # agents shut the victims down; the next tick places the gang whole
    def down(tx):
        for v in victims:
            cur = tx.get(Task, v.id).copy()
            cur.status = TaskStatus(state=TaskState.SHUTDOWN,
                                    timestamp=now())
            tx.update(cur)
    store.update(down)
    # production drains these watch events on the scheduler thread;
    # the threadless harness feeds them through the same handler
    for v in store.view(lambda tx: [tx.get(Task, v.id)
                                    for v in victims]):
        sched._update_task(v)
    sched.tick()
    gang_tasks = [t for t in store.view(lambda tx: tx.find(Task))
                  if t.service_id == "svc-g"]
    assert all(t.node_id and t.status.state == TaskState.ASSIGNED
               for t in gang_tasks)
    assert sched.gang.stats["gangs_admitted"] == 1


def test_aged_gang_is_preempt_entitled(monkeypatch):
    monkeypatch.setenv("SWARM_PREEMPT_AGE", "5")
    store = _mk_store(2, [("svc-g", 0, 6, 0, 6, "", (), "")])
    sched = _tick(store)
    t0 = next(t for t in store.view(lambda tx: tx.find(Task)))
    # capacity-blocked: entitled through the blocked set
    assert gang_mod.preempt_entitled(sched, t0)
    # age path: a unit pending past SWARM_PREEMPT_AGE stays entitled
    # even once the capacity-blocked marker is gone
    sched.gang.blocked.clear()
    sched.gang.first_pending["svc-g"] = now() - 6.0
    assert gang_mod.preempt_entitled(sched, t0)
    sched.gang.first_pending["svc-g"] = now() - 1.0
    assert not gang_mod.preempt_entitled(sched, t0)


# ---------------------------------------------------------------------------
# the scheduler's pipeline gate
# ---------------------------------------------------------------------------

def test_pipeline_gate_defers_until_released():
    store = _mk_store(3, [("stage-b", 0, 2, 0, 0, "", ("stage-a",),
                           "")])
    _tick(store)
    tasks = store.view(lambda tx: tx.find(Task))
    assert not any(t.node_id for t in tasks)
    assert {t.status.err for t in tasks} == \
        {"awaiting upstream pipeline stage"}

    # the supervisor's released verdict opens the gate
    def rel(tx):
        cur = tx.get(Service, "stage-b").copy()
        cur.pipeline_status = PipelineStatus(state="released")
        tx.update(cur)
    store.update(rel)
    _tick(store)
    assert all(t.node_id
               for t in store.view(lambda tx: tx.find(Task)))


def test_pipeline_gate_reports_halt_reason():
    store = _mk_store(3, [("stage-b", 0, 2, 0, 0, "", ("stage-a",),
                           "")])

    def halt(tx):
        cur = tx.get(Service, "stage-b").copy()
        cur.pipeline_status = PipelineStatus(
            state="halted", reason='upstream "stage-a" halted')
        tx.update(cur)
    store.update(halt)
    _tick(store)
    errs = {t.status.err
            for t in store.view(lambda tx: tx.find(Task))}
    assert errs == {'pipeline halted (upstream "stage-a" halted)'}


# ---------------------------------------------------------------------------
# PipelineSupervisor: release bars, stickiness, failure cascades
# ---------------------------------------------------------------------------

def _mk_service(store, sid, mode=ServiceMode.REPLICATED, replicas=2,
                depends_on=(), on_upstream_failure="",
                total_completions=0):
    spec = ServiceSpec(
        annotations=Annotations(name=sid), mode=mode,
        replicated=(ReplicatedService(replicas=replicas)
                    if mode == ServiceMode.REPLICATED else None),
        replicated_job=(ReplicatedJob(
            total_completions=total_completions)
            if mode == ServiceMode.REPLICATED_JOB else None),
        task=TaskSpec(),
        depends_on=list(depends_on),
        on_upstream_failure=on_upstream_failure)
    store.update(lambda tx: tx.create(Service(
        id=sid, spec=spec, spec_version=Version(index=1))))


def _set_tasks(store, sid, states):
    def cb(tx):
        for t in tx.find(Task):
            if t.service_id == sid:
                tx.delete(Task, t.id)
        for i, st in enumerate(states):
            tx.create(Task(
                id=f"{sid}-t{i:03d}-{new_id()[:6]}", service_id=sid,
                slot=i + 1, desired_state=TaskState.RUNNING,
                spec=TaskSpec(), spec_version=Version(index=1),
                node_id="n000",
                status=TaskStatus(state=st, timestamp=now())))
    store.update(cb)


def _status(store, sid):
    return store.view(lambda tx: tx.get(Service, sid)).pipeline_status


def test_supervisor_releases_when_upstream_running_and_sticky():
    store = MemoryStore()
    _mk_service(store, "a", replicas=2)
    _mk_service(store, "b", depends_on=("a",))
    sup = PipelineSupervisor(store, start_worker=False)
    sup.drive()
    assert _status(store, "b") is None    # 0/2 upstream running
    _set_tasks(store, "a", [TaskState.RUNNING])
    sup.drive()
    assert _status(store, "b") is None    # 1/2: bar not met
    _set_tasks(store, "a", [TaskState.RUNNING, TaskState.RUNNING])
    sup.drive()
    assert _status(store, "b").state == "released"
    # sticky: upstream churn never re-gates
    _set_tasks(store, "a", [])
    sup.drive()
    assert _status(store, "b").state == "released"
    assert sup.stats["released"] == 1


def test_supervisor_job_upstream_releases_on_completions():
    store = MemoryStore()
    _mk_service(store, "job", mode=ServiceMode.REPLICATED_JOB,
                total_completions=2)
    _mk_service(store, "b", depends_on=("job",))
    sup = PipelineSupervisor(store, start_worker=False)
    _set_tasks(store, "job", [TaskState.COMPLETE, TaskState.RUNNING])
    sup.drive()
    assert _status(store, "b") is None
    _set_tasks(store, "job", [TaskState.COMPLETE, TaskState.COMPLETE])
    sup.drive()
    assert _status(store, "b").state == "released"


def test_supervisor_poison_halts_and_rolls_back_downstream():
    store = MemoryStore()
    _mk_service(store, "a", replicas=2)
    _mk_service(store, "b", depends_on=("a",),
                on_upstream_failure="halt")
    _mk_service(store, "c", replicas=3, depends_on=("a",),
                on_upstream_failure="rollback")
    sup = PipelineSupervisor(store, start_worker=False)
    # three distinct failed task ids push "a" over the threshold
    _set_tasks(store, "a", [TaskState.FAILED] * POISON_FAILURES)
    sup.drive()
    st_b = _status(store, "b")
    assert st_b.state == "halted"
    assert st_b.reason == (f'upstream "a" poisoned '
                           f'({POISON_FAILURES} task failures)')
    st_c = _status(store, "c")
    assert st_c.state == "halted"
    svc_c = store.view(lambda tx: tx.get(Service, "c"))
    assert svc_c.spec.replicated.replicas == 0    # rolled back
    assert sup.stats["rollbacks"] == 1
    # halt is sticky even after the upstream heals
    _set_tasks(store, "a", [TaskState.RUNNING, TaskState.RUNNING])
    sup.drive()
    assert _status(store, "b").state == "halted"


def test_supervisor_poison_count_survives_leader_crash():
    """ISSUE 16 residual: failure observations replicate via
    ``PipelineStatus.failed_ids``.  A leader crashing at 2/3
    observations must NOT reset the poison count — the successor's
    supervisor (fresh ``_failed_seen``) trips the threshold on its
    first new observation."""
    store = MemoryStore()
    _mk_service(store, "a", replicas=2)
    _mk_service(store, "b", depends_on=("a",))
    sup1 = PipelineSupervisor(store, start_worker=False)
    # 2/3: below the threshold, but the observations must commit
    _set_tasks(store, "a", [TaskState.FAILED, TaskState.FAILED])
    sup1.drive()
    assert _status(store, "b") is None or \
        _status(store, "b").state != "halted"
    st_a = _status(store, "a")
    assert st_a is not None and len(st_a.failed_ids) == 2
    # leader crash: the successor's supervisor has no local memory and
    # the old tasks are gone (reaped) — only the replicated row remains
    sup2 = PipelineSupervisor(store, start_worker=False)
    _set_tasks(store, "a", [TaskState.FAILED])    # 3rd distinct id
    sup2.drive()
    st_b = _status(store, "b")
    assert st_b is not None and st_b.state == "halted"
    assert "poisoned" in st_b.reason
    # all three observations are on the replicated row now
    assert len(_status(store, "a").failed_ids) == POISON_FAILURES


def test_supervisor_verdict_preserves_failed_ids():
    """Release/halt verdict writes must carry ``failed_ids`` forward —
    a stage that is both a downstream (gets verdicts) and an upstream
    (accrues observations) must not lose its count to a verdict."""
    store = MemoryStore()
    _mk_service(store, "a", replicas=1)
    _mk_service(store, "b", replicas=2, depends_on=("a",))
    _mk_service(store, "c", depends_on=("b",))
    sup = PipelineSupervisor(store, start_worker=False)
    # b accrues one failure observation (below threshold), then its
    # upstream readies and b gets a released verdict
    _set_tasks(store, "b", [TaskState.FAILED])
    sup.drive()
    assert len(_status(store, "b").failed_ids) == 1
    _set_tasks(store, "a", [TaskState.RUNNING])
    sup.drive()
    st_b = _status(store, "b")
    assert st_b.state == "released"
    assert len(st_b.failed_ids) == 1


def test_supervisor_halted_upstream_cascades():
    store = MemoryStore()
    _mk_service(store, "a", replicas=1)
    _mk_service(store, "b", depends_on=("a",))
    _mk_service(store, "d", depends_on=("b",))
    sup = PipelineSupervisor(store, start_worker=False)

    def halt_b(tx):
        cur = tx.get(Service, "b").copy()
        cur.pipeline_status = PipelineStatus(state="halted",
                                             reason="injected")
        tx.update(cur)
    store.update(halt_b)
    sup.drive()
    st = _status(store, "d")
    assert st.state == "halted"
    assert st.reason == 'upstream "b" halted'


def test_supervisor_threadless_reraises_store_failures(monkeypatch):
    store = MemoryStore()
    _mk_service(store, "a", replicas=0)
    _mk_service(store, "b", depends_on=("a",))
    sup = PipelineSupervisor(store, start_worker=False)

    def boom(cb):
        raise RuntimeError("deposed")
    monkeypatch.setattr(store, "update", boom)
    with pytest.raises(RuntimeError):
        sup.drive()


# ---------------------------------------------------------------------------
# non-gang byte-identity: the subsystem is a pure no-op without opt-in
# ---------------------------------------------------------------------------

def _placements(store):
    return sorted(
        (t.id, t.node_id or "", int(t.status.state),
         t.status.err or "")
        for t in store.view(lambda tx: tx.find(Task)))


def test_non_gang_workload_byte_identical(monkeypatch):
    """A workload with no gang/pipeline opt-in never reaches
    admit_gangs, and its placements are byte-identical to a run where
    the gang path is poisoned — the extraction is a pure no-op."""
    svcs = [("svc-a", 0, 5, 0, 0, "", (), ""),
            ("svc-b", 3, 4, 1, 0, "", (), "")]
    store1 = _mk_store(4, svcs)
    _tick(store1)

    def never(*a, **kw):
        raise AssertionError("admit_gangs reached without gang tasks")
    monkeypatch.setattr(gang_mod, "admit_gangs", never)
    store2 = _mk_store(4, svcs)
    _tick(store2)
    assert _placements(store1) == _placements(store2)


# ---------------------------------------------------------------------------
# checker sensitivity: the two new invariants must FIRE when their
# enforcement seam is off (house rule since PR 1)
# ---------------------------------------------------------------------------

def _gang_mini(seed, gang=12, duration=50.0):
    """Capacity-starved gang sim: 3 of 5 workers die (8 slots left), a
    12-member gang arrives after node-down detection has settled —
    atomic admission must hold it back whole until the heal at
    finish.  (Arriving before detection would let the first commit
    place all 12, 4 of them onto dying nodes — a full commit, which
    is not the strict-subset shape the seam-off test needs.)"""
    sim = Sim(seed=seed, n_managers=3, n_agents=5,
              net_config=NetConfig(), raft_cp=True)
    with sim:
        eng = sim.engine
        cp = sim.cp
        sim.start_raft_workload(interval=0.8)
        a = cp.agents
        eng.at(eng.clock.start + 4.0, "node death w0", a[0].crash)
        eng.at(eng.clock.start + 5.0, "node death w1", a[1].crash)
        eng.at(eng.clock.start + 6.0, "node death w2", a[2].crash)
        eng.at(eng.clock.start + 20.0, "gang arrives",
               lambda: cp.add_service("svc-gang", gang, gang_min=gang,
                                      nano_cpus=CPU))
        sim.run(duration)
        sim.finish(grace=20.0)
    return sim


def test_sensitivity_gang_atomicity_fires_when_seam_off(monkeypatch):
    """Disable atomic enforcement: the shortfall tick commits a strict
    subset and the left-behind members stay pending past the checker's
    grace — gang-atomicity must fire."""
    monkeypatch.setattr(gang_mod, "ATOMIC_ENFORCED", False)
    sim = _gang_mini(21)
    assert any("gang-atomicity" in v
               for v in sim.violations.items), sim.violations.items


def _pipeline_mini(seed, duration=40.0):
    """Unplaceable upstream (no node fits its reservation) + placeable
    downstream: with the gate enforced the downstream never runs; with
    the seam off it runs before its upstream ever did."""
    sim = Sim(seed=seed, n_managers=3, n_agents=5,
              net_config=NetConfig(), raft_cp=True)
    with sim:
        eng = sim.engine
        cp = sim.cp
        sim.start_raft_workload(interval=0.8)
        eng.at(eng.clock.start + 4.0, "upstream (unplaceable)",
               lambda: cp.add_service("svc-up", 2,
                                      nano_cpus=100 * CPU))
        eng.at(eng.clock.start + 6.0, "downstream",
               lambda: cp.add_service("svc-down", 2, nano_cpus=CPU,
                                      depends_on=["svc-up"]))
        sim.run(duration)
        sim.finish(grace=15.0)
    return sim


def test_sensitivity_pipeline_order_fires_when_gate_off(monkeypatch):
    monkeypatch.setattr(gang_mod, "GATE_ENFORCED", False)
    sim = _pipeline_mini(22)
    assert any("pipeline-order" in v
               for v in sim.violations.items), sim.violations.items


def test_gang_mini_green_with_enforcement_on():
    """The sensitivity harness itself is green with the seams on —
    the tests above fail for the injected reason, nothing else."""
    sim = _gang_mini(23)
    assert not sim.violations.items, sim.violations.items


# ---------------------------------------------------------------------------
# scenarios: green runs + registry wiring (slow sweep lives in tier 2)
# ---------------------------------------------------------------------------

def test_gang_scenarios_registered():
    from scripts import chaos_sweep
    from swarmkit_tpu.sim.scenario import (
        FUZZ_POOL, GANG_SCENARIOS, SCENARIOS,
    )
    assert GANG_SCENARIOS == ("gang-deadlock", "pipeline-chaos")
    for name in GANG_SCENARIOS:
        assert name in SCENARIOS
        assert name in FUZZ_POOL
    assert chaos_sweep.SUITES["gang"] == GANG_SCENARIOS
    assert set(GANG_SCENARIOS) <= set(chaos_sweep.SUITES["default"])
    for name in GANG_SCENARIOS:
        assert name in chaos_sweep.REQUIRED_CELLS


def test_gang_deadlock_scenario_green():
    from swarmkit_tpu.sim.scenario import run_scenario
    r = run_scenario("gang-deadlock", seed=0)
    assert r.ok, r.violations


def test_pipeline_chaos_scenario_green():
    from swarmkit_tpu.sim.scenario import run_scenario
    r = run_scenario("pipeline-chaos", seed=3)
    assert r.ok, r.violations


@pytest.mark.slow
def test_gang_scenarios_seed_sweep():
    """20-seed slow sweep: both gang scenarios hold their invariants
    and expectations across the fuzzed fault schedule."""
    from swarmkit_tpu.sim.scenario import run_scenario
    for name in ("gang-deadlock", "pipeline-chaos"):
        for seed in range(10):
            r = run_scenario(name, seed=seed)
            assert r.ok, (name, seed, r.violations)
