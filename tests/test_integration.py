"""Integration: the full control-plane slice in one process.

Mirrors the reference's integration suite approach (integration/
cluster_test.go — real components wired together, no containers):
service create → replicated orchestrator → TPU scheduler → fake agent →
RUNNING; failure → restart → re-placement; scale-down → REMOVE → reaper.
"""

from swarmkit_tpu.models import (
    Annotations, Cluster, ReplicatedService, Service, Task, TaskState,
    TaskStatus,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import now
from swarmkit_tpu.ops import TPUPlanner
from swarmkit_tpu.orchestrator import (
    GlobalOrchestrator, ReplicatedOrchestrator, TaskReaper,
)
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import ByService, MemoryStore

from test_orchestrator import FakeAgent, make_global, make_replicated, poll
from test_scheduler import make_ready_node


def test_full_slice_service_to_running_with_healing():
    store = MemoryStore()
    cluster = Cluster(id="c1", spec=ClusterSpec(
        annotations=Annotations(name="default")))
    nodes = [make_ready_node(f"n{i}", cpus=8) for i in range(5)]

    def setup(tx):
        tx.create(cluster)
        for n in nodes:
            tx.create(n)

    store.update(setup)

    sched = Scheduler(store, batch_planner=TPUPlanner())
    orch = ReplicatedOrchestrator(store)
    reaper = TaskReaper(store)
    agent = FakeAgent(store)
    sched.start()
    orch.start()
    reaper.start()

    try:
        svc = make_replicated("web", 10)
        store.update(lambda tx: tx.create(svc))

        def all_running():
            got = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state == TaskState.RUNNING]
            return (len(got) == 10
                    and all(t.status.state == TaskState.RUNNING
                            and t.node_id for t in got))

        poll(all_running, timeout=30,
             msg="10 replicas should reach RUNNING on nodes")
        got = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
        per_node = {}
        for t in got:
            per_node[t.node_id] = per_node.get(t.node_id, 0) + 1
        assert sorted(per_node.values()) == [2, 2, 2, 2, 2], per_node

        # failure healing
        victim = got[0]

        def fail(tx):
            t = tx.get(Task, victim.id).copy()
            t.status = TaskStatus(state=TaskState.FAILED, timestamp=now(),
                                  err="sim crash")
            tx.update(t)

        store.update(fail)

        def healed():
            live = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state <= TaskState.RUNNING
                and t.id != victim.id]
            return (len(live) == 10
                    and all(t.status.state == TaskState.RUNNING
                            and t.node_id for t in live))

        poll(healed, timeout=30,
             msg="failed task should be replaced and re-placed")

        # scale down + reap
        cur = store.view(lambda tx: tx.get(Service, svc.id)).copy()
        cur.spec.replicated = ReplicatedService(replicas=3)
        store.update(lambda tx: tx.update(cur))

        def scaled():
            all_t = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
            live = [t for t in all_t
                    if t.desired_state == TaskState.RUNNING]
            return len(live) == 3 and len(all_t) <= 6

        poll(scaled, timeout=30,
             msg="scale down to 3 with REMOVE'd tasks reaped")

        # global service on the side, sharing the restart supervisor
        gsvc = make_global("monitor")
        store.update(lambda tx: tx.create(gsvc))
        gorch = GlobalOrchestrator(store, restarts=orch.restarts)
        gorch.start()
        try:
            def global_done():
                got = [t for t in store.view(
                    lambda tx: tx.find(Task, ByService(gsvc.id)))
                    if t.desired_state <= TaskState.RUNNING]
                return (len(got) == 5
                        and {t.node_id for t in got}
                        == {n.id for n in nodes}
                        and all(t.status.state == TaskState.RUNNING
                                for t in got))

            poll(global_done, timeout=30,
                 msg="global service should run on all 5 nodes")
        finally:
            gorch.stop()
    finally:
        sched.stop()
        orch.stop()
        reaper.stop()
        agent.stop()
