"""Integration: black-box cluster scenarios over real components.

Mirrors the reference's integration suite (integration/
integration_test.go:196-919 — real daemons wired together, no
containers): the full control-plane slice; promotion/demotion under the
daemon incl. a downed manager; node rejoin; rolling manager restarts.
"""

import tempfile
import time

from swarmkit_tpu.models import (
    Annotations, Cluster, ReplicatedService, Service, Task, TaskState,
    TaskStatus,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import now
from swarmkit_tpu.ops import TPUPlanner
from swarmkit_tpu.orchestrator import (
    GlobalOrchestrator, ReplicatedOrchestrator, TaskReaper,
)
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import ByService, MemoryStore

from test_orchestrator import FakeAgent, make_global, make_replicated, poll
from test_scheduler import make_ready_node
import pytest

from swarmkit_tpu.security.ca import HAVE_CRYPTOGRAPHY

requires_crypto = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="requires the 'cryptography' package")



def test_full_slice_service_to_running_with_healing():
    store = MemoryStore()
    cluster = Cluster(id="c1", spec=ClusterSpec(
        annotations=Annotations(name="default")))
    nodes = [make_ready_node(f"n{i}", cpus=8) for i in range(5)]

    def setup(tx):
        tx.create(cluster)
        for n in nodes:
            tx.create(n)

    store.update(setup)

    sched = Scheduler(store, batch_planner=TPUPlanner())
    orch = ReplicatedOrchestrator(store)
    reaper = TaskReaper(store)
    agent = FakeAgent(store)
    sched.start()
    orch.start()
    reaper.start()

    try:
        svc = make_replicated("web", 10)
        store.update(lambda tx: tx.create(svc))

        def all_running():
            got = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state == TaskState.RUNNING]
            return (len(got) == 10
                    and all(t.status.state == TaskState.RUNNING
                            and t.node_id for t in got))

        poll(all_running, timeout=30,
             msg="10 replicas should reach RUNNING on nodes")
        got = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
        per_node = {}
        for t in got:
            per_node[t.node_id] = per_node.get(t.node_id, 0) + 1
        assert sorted(per_node.values()) == [2, 2, 2, 2, 2], per_node

        # failure healing
        victim = got[0]

        def fail(tx):
            t = tx.get(Task, victim.id).copy()
            t.status = TaskStatus(state=TaskState.FAILED, timestamp=now(),
                                  err="sim crash")
            tx.update(t)

        store.update(fail)

        def healed():
            live = [t for t in store.view(
                lambda tx: tx.find(Task, ByService(svc.id)))
                if t.desired_state <= TaskState.RUNNING
                and t.id != victim.id]
            return (len(live) == 10
                    and all(t.status.state == TaskState.RUNNING
                            and t.node_id for t in live))

        poll(healed, timeout=30,
             msg="failed task should be replaced and re-placed")

        # scale down + reap
        cur = store.view(lambda tx: tx.get(Service, svc.id)).copy()
        cur.spec.replicated = ReplicatedService(replicas=3)
        store.update(lambda tx: tx.update(cur))

        def scaled():
            all_t = store.view(lambda tx: tx.find(Task, ByService(svc.id)))
            live = [t for t in all_t
                    if t.desired_state == TaskState.RUNNING]
            return len(live) == 3 and len(all_t) <= 6

        poll(scaled, timeout=30,
             msg="scale down to 3 with REMOVE'd tasks reaped")

        # global service on the side, sharing the restart supervisor
        gsvc = make_global("monitor")
        store.update(lambda tx: tx.create(gsvc))
        gorch = GlobalOrchestrator(store, restarts=orch.restarts)
        gorch.start()
        try:
            def global_done():
                got = [t for t in store.view(
                    lambda tx: tx.find(Task, ByService(gsvc.id)))
                    if t.desired_state <= TaskState.RUNNING]
                return (len(got) == 5
                        and {t.node_id for t in got}
                        == {n.id for n in nodes}
                        and all(t.status.state == TaskState.RUNNING
                                for t in got))

            poll(global_done, timeout=30,
                 msg="global service should run on all 5 nodes")
        finally:
            gorch.stop()
    finally:
        sched.stop()
        orch.stop()
        reaper.stop()
        agent.stop()


# --------------------------------------------------------------------------
# Daemon-level black-box scenarios (reference: integration/
# integration_test.go:196 TestDemotePromote and friends)

def _manager_daemon(name, **kw):
    from swarmkit_tpu.swarmd import Swarmd
    kw.setdefault("listen_remote_api", ("127.0.0.1", 0))
    kw.setdefault("use_device_scheduler", False)
    return Swarmd(state_dir=kw.pop("state_dir", tempfile.mkdtemp()),
                  hostname=name, manager=True, **kw)


def _worker_daemon(name, join_addr, token, **kw):
    from swarmkit_tpu.swarmd import Swarmd
    return Swarmd(state_dir=kw.pop("state_dir", tempfile.mkdtemp()),
                  hostname=name, join_addr=join_addr, join_token=token,
                  **kw)


def _speed_up_heartbeats(api, period=0.5):
    """Shrink the dispatcher heartbeat period so role changes (which ride
    heartbeat responses) propagate quickly in tests."""
    from swarmkit_tpu.models import Cluster
    from swarmkit_tpu.state.store import ByName
    c = api.store.view(
        lambda tx: tx.find(Cluster, ByName("default")))[0].copy()
    c.spec.dispatcher.heartbeat_period = period
    api.store.update(lambda tx: tx.update(c))


def _set_role(api, node_id, role):
    """Role flip with read-modify-write retry: the agent's status and
    description writes race the version we read, and the control API
    rightly rejects stale versions (SequenceConflict semantics) — real
    clients re-read and retry, so these helpers do too."""
    from swarmkit_tpu.manager.controlapi import FailedPrecondition
    last = None
    for _ in range(10):
        n = api.get_node(node_id)
        spec = n.spec.copy()
        spec.desired_role = role
        try:
            return api.update_node(n.id, n.meta.version.index, spec)
        except FailedPrecondition as e:
            if "stale version" not in str(e):
                raise
            last = e
            time.sleep(0.1)
    raise last


def _promote(api, node_id):
    from swarmkit_tpu.models.types import NodeRole
    _set_role(api, node_id, NodeRole.MANAGER)


def _demote(api, node_id):
    from swarmkit_tpu.models.types import NodeRole
    _set_role(api, node_id, NodeRole.WORKER)


@requires_crypto
def test_promote_worker_to_manager_under_daemon():
    """A running worker daemon promoted via the control API renews into a
    manager cert, joins raft, and serves as a manager — without restart
    (reference: integration_test.go:196 promote path)."""
    from swarmkit_tpu.models.types import NodeRole, NodeState

    m0 = _manager_daemon("m0")
    m0.start()
    w = None
    try:
        api = m0.manager.control_api
        _speed_up_heartbeats(api)
        w = _worker_daemon("w0", m0.server.addr,
                           m0.manager.root_ca.join_token(0))
        w.start()
        wid = w.node.node_id
        poll(lambda: (api.get_node(wid).status.state == NodeState.READY
                      if _has_node(api, wid) else False),
             msg="worker registers READY")

        _promote(api, wid)
        poll(lambda: w.manager is not None and w.raft_node is not None,
             timeout=45, msg="promoted worker starts its manager")
        poll(lambda: wid in m0.raft_node.core.peers, timeout=20,
             msg="promoted node joins the raft group")
        assert NodeRole(w.node.certificate.role) == NodeRole.MANAGER
        assert api.get_node(wid).role == int(NodeRole.MANAGER)
        # the new manager replicates cluster state
        svc = api.create_service(make_replicated("promoted", 2).spec)
        from swarmkit_tpu.models import Service
        poll(lambda: w.manager.store.view(
            lambda tx: tx.get(Service, svc.id)) is not None,
             timeout=20, msg="state replicates to the promoted manager")
    finally:
        if w is not None:
            w.stop()
        m0.stop()


def _has_node(api, node_id):
    try:
        api.get_node(node_id)
        return True
    except Exception:
        return False


@requires_crypto
def test_demote_manager_to_worker_under_daemon():
    """A joined manager demoted via the control API leaves raft, tears
    down its manager stack, and keeps serving as a worker (reference:
    integration_test.go demote path)."""
    from swarmkit_tpu.models.types import NodeRole, NodeState

    m0 = _manager_daemon("m0")
    m0.start()
    m1 = None
    try:
        api = m0.manager.control_api
        _speed_up_heartbeats(api)
        token = m0.manager.root_ca.join_token(NodeRole.MANAGER)
        m1 = _manager_daemon("m1", join_addr=m0.server.addr,
                             join_token=token)
        m1.start()
        assert "m-m1" in m0.raft_node.core.peers
        poll(lambda: _has_node(api, "m-m1")
             and api.get_node("m-m1").status.state == NodeState.READY,
             msg="joined manager's agent registers")

        _demote(api, "m-m1")
        poll(lambda: m1.manager is None and m1.raft_node is None,
             timeout=45, msg="demoted manager tears down its stack")
        assert m0.raft_node.core.peers == {"m-m0"}
        assert NodeRole(m1.node.certificate.role) == NodeRole.WORKER
        poll(lambda: api.get_node("m-m1").role == int(NodeRole.WORKER),
             msg="store role reconciled to worker")
        # still a live worker: schedulable
        svc = api.create_service(make_replicated("afterdemote", 4).spec)
        poll(lambda: len([t for t in api.list_tasks(service_id=svc.id)
                          if t.status.state == TaskState.RUNNING]) == 4,
             timeout=30, msg="tasks run, incl. on the demoted node")
        assert {t.node_id for t in api.list_tasks(service_id=svc.id)} \
            == {"m-m0", "m-m1"}
    finally:
        if m1 is not None:
            m1.stop()
        m0.stop()


@requires_crypto
def test_demote_downed_manager_recovers_quorum():
    """Demoting a DEAD manager removes it from raft so the survivors'
    quorum shrinks (reference: integration_test.go:393 demote a downed
    node)."""
    from swarmkit_tpu.models.types import NodeRole

    m0 = _manager_daemon("m0")
    m0.start()
    token = m0.manager.root_ca.join_token(NodeRole.MANAGER)
    joiners = []
    try:
        for h in ("m1", "m2"):
            d = _manager_daemon(h, join_addr=m0.server.addr,
                                join_token=token)
            d.start()
            joiners.append(d)
        api = m0.manager.control_api
        assert m0.raft_node.core.peers == {"m-m0", "m-m1", "m-m2"}
        poll(lambda: _has_node(api, "m-m2"),
             msg="m2's node record registers before we kill it")

        joiners[1].stop()    # kill m2; 2-of-3 quorum survives
        _demote(api, "m-m2")
        poll(lambda: m0.raft_node.core.peers == {"m-m0", "m-m1"},
             timeout=30, msg="dead manager removed from raft")
        poll(lambda: api.get_node("m-m2").role == int(NodeRole.WORKER),
             msg="dead manager's role reconciled")
        # the 2-member group still commits
        svc = api.create_service(make_replicated("post-demote", 1).spec)
        assert svc.id
    finally:
        for d in joiners:
            d.stop()
        m0.stop()


@requires_crypto
def test_worker_rejoin_same_state_dir():
    """A worker stopped and restarted on the same state dir rejoins with
    its persisted identity and turns READY again (reference:
    integration_test.go node rejoin)."""
    from swarmkit_tpu.models.types import NodeState

    m0 = _manager_daemon("m0")
    m0.start()
    w2 = None
    try:
        api = m0.manager.control_api
        state_dir = tempfile.mkdtemp()
        token = m0.manager.root_ca.join_token(0)
        w = _worker_daemon("w0", m0.server.addr, token,
                           state_dir=state_dir)
        w.start()
        wid = w.node.node_id
        poll(lambda: _has_node(api, wid)
             and api.get_node(wid).status.state == NodeState.READY,
             msg="worker READY before restart")
        w.stop()
        poll(lambda: api.get_node(wid).status.state == NodeState.DOWN,
             timeout=45, msg="stopped worker marked DOWN")

        # rejoin with a bogus token: the persisted identity must carry it
        w2 = _worker_daemon("w0", m0.server.addr, "not-a-real-token",
                            state_dir=state_dir)
        w2.start()
        assert w2.node.node_id == wid, "identity persists across rejoin"
        poll(lambda: api.get_node(wid).status.state == NodeState.READY,
             timeout=30, msg="rejoined worker turns READY")
    finally:
        if w2 is not None:
            w2.stop()
        m0.stop()


@requires_crypto
def test_rolling_manager_restart_preserves_cluster():
    """Restart all three managers one at a time; state and membership
    survive throughout (reference: integration_test.go rolling manager
    restarts)."""
    from swarmkit_tpu.models.types import NodeRole

    dirs = {"m0": tempfile.mkdtemp()}
    m0 = _manager_daemon("m0", state_dir=dirs["m0"])
    m0.start()
    token = m0.manager.root_ca.join_token(NodeRole.MANAGER)
    daemons = {"m0": m0}
    try:
        for h in ("m1", "m2"):
            d = _manager_daemon(h, join_addr=m0.server.addr,
                                join_token=token)
            dirs[h] = d.state_dir
            d.start()
            daemons[h] = d
        svc = daemons["m0"].manager.control_api.create_service(
            make_replicated("persistent", 1).spec)

        for h in ("m0", "m1", "m2"):
            old = daemons[h]
            old.stop()
            # survivors (2-of-3) elect a leader if the dead one led
            poll(lambda: any(d.raft_node.is_leader and d.manager.is_leader
                             and d.manager.dispatcher is not None
                             for n, d in daemons.items() if n != h),
                 timeout=45, msg=f"leadership settles without {h}")
            # restarts replay from the WAL; a joiner's bogus join_addr
            # only routes the code path (no RPC is made when persisted
            # state exists)
            fresh = _manager_daemon(h, state_dir=dirs[h],
                                    join_addr=None if h == "m0"
                                    else ("127.0.0.1", 1))
            fresh.start()
            daemons[h] = fresh
            poll(lambda: fresh.manager is not None
                 and svc.id in [s.id for s in _services_of(fresh)],
                 timeout=45,
                 msg=f"restarted {h} recovers replicated state")
        # after the full roll: all three are raft members somewhere
        leader = next(d for d in daemons.values()
                      if d.raft_node is not None and d.raft_node.is_leader)
        assert leader.raft_node.core.peers == {"m-m0", "m-m1", "m-m2"}
    finally:
        for d in daemons.values():
            d.stop()


def _services_of(daemon):
    from swarmkit_tpu.models import Service
    try:
        return daemon.manager.store.view(lambda tx: tx.find(Service))
    except Exception:
        return []


@requires_crypto
def test_promoted_manager_restart_comes_back_as_manager():
    """A runtime-promoted node restarted on its state dir boots straight
    into manager mode (persisted raft id + WAL), like the reference's
    restarted promoted node."""
    from swarmkit_tpu.models.types import NodeRole, NodeState

    m0 = _manager_daemon("m0")
    m0.start()
    w = w2 = None
    try:
        api = m0.manager.control_api
        _speed_up_heartbeats(api)
        state_dir = tempfile.mkdtemp()
        w = _worker_daemon("w0", m0.server.addr,
                           m0.manager.root_ca.join_token(0),
                           state_dir=state_dir)
        w.start()
        wid = w.node.node_id
        poll(lambda: _has_node(api, wid), msg="worker registers")
        _promote(api, wid)
        poll(lambda: w.manager is not None, timeout=45,
             msg="worker promotes")
        w.stop()
        poll(lambda: any(d.raft_node.is_leader for d in (m0,)),
             timeout=30, msg="m0 leads after the promoted node stops")

        from swarmkit_tpu.swarmd import Swarmd
        w2 = Swarmd(state_dir=state_dir, hostname="w0",
                    join_addr=m0.server.addr, join_token="",
                    use_device_scheduler=False)
        w2.start()
        poll(lambda: w2.manager is not None and w2.raft_node is not None,
             timeout=45, msg="restarted promoted node is a manager again")
        assert w2.raft_id == wid
        poll(lambda: wid in m0.raft_node.core.peers, timeout=20,
             msg="rejoined the raft group under its node id")
    finally:
        if w is not None:
            w.stop()
        if w2 is not None:
            w2.stop()
        m0.stop()


@requires_crypto
def test_device_scheduler_inside_live_manager():
    """The TPU planner runs inside a live manager daemon end-to-end:
    service -> orchestrator -> device-planned placement -> dispatcher ->
    agent -> RUNNING (the other daemon tests pin the host path; this one
    proves the device path through the full stack)."""
    from swarmkit_tpu.models.types import NodeState

    m0 = _manager_daemon("m0", use_device_scheduler=True)
    m0.start()
    workers = []
    try:
        api = m0.manager.control_api
        token = m0.manager.root_ca.join_token(0)
        for i in range(3):
            w = _worker_daemon(f"w{i}", m0.server.addr, token)
            w.start()
            workers.append(w)
        poll(lambda: len([n for n in api.list_nodes()
                          if n.status.state == NodeState.READY]) == 4,
             timeout=30, msg="all nodes READY")

        # large enough that the adaptive router sends it to the device
        planner = m0.manager.scheduler.batch_planner
        assert planner is not None, "device planner must be wired"
        planner.enable_small_group_routing = False

        svc = api.create_service(make_replicated("devplanned", 12).spec)
        poll(lambda: len([t for t in api.list_tasks(service_id=svc.id)
                          if t.status.state == TaskState.RUNNING
                          and t.desired_state == TaskState.RUNNING]) == 12,
             timeout=45, msg="12 replicas RUNNING via the device path")
        assert planner.stats["tasks_planned"] >= 12, planner.stats
        # spread across all four agents (manager node + 3 workers)
        per_node = {}
        for t in api.list_tasks(service_id=svc.id):
            per_node[t.node_id] = per_node.get(t.node_id, 0) + 1
        assert sorted(per_node.values()) == [3, 3, 3, 3], per_node
    finally:
        for w in workers:
            w.stop()
        m0.stop()
