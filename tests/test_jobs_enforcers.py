"""Jobs orchestrator + constraint/volume enforcer tests (reference:
manager/orchestrator/jobs/*_test.go, constraintenforcer tests)."""

import time

import pytest

from swarmkit_tpu.models import (
    Annotations, Cluster, Node, Resources, ResourceRequirements, Service,
    ServiceMode, ServiceSpec, Task, TaskSpec, TaskState, TaskStatus, Version,
    Volume,
)
from swarmkit_tpu.models.specs import (
    ClusterSpec, ContainerSpec, GlobalJob, ReplicatedJob, VolumeSpec,
)
from swarmkit_tpu.models.types import (
    Placement, RestartPolicy, RestartCondition, VolumeAvailability,
    VolumeAttachment, now,
)
from swarmkit_tpu.orchestrator import (
    ConstraintEnforcer, JobsOrchestrator, VolumeEnforcer,
)
from swarmkit_tpu.state import ByService, MemoryStore
from swarmkit_tpu.utils import new_id

from test_orchestrator import make_node, poll


@pytest.fixture
def store():
    s = MemoryStore()
    s.update(lambda tx: tx.create(Cluster(
        id=new_id(), spec=ClusterSpec(annotations=Annotations(
            name="default")))))
    yield s
    s.close()


def make_replicated_job(name, total, max_concurrent=0):
    return Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name=name),
            task=TaskSpec(container=ContainerSpec(image="job:1"),
                          restart=RestartPolicy(
                              condition=RestartCondition.ON_FAILURE,
                              delay=0.05)),
            mode=ServiceMode.REPLICATED_JOB,
            replicated_job=ReplicatedJob(total_completions=total,
                                         max_concurrent=max_concurrent),
        ),
        spec_version=Version(index=1))


def tasks_of(store, svc):
    return store.view(lambda tx: tx.find(Task, ByService(svc.id)))


def test_replicated_job_respects_max_concurrent(store):
    orch = JobsOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated_job("batch", total=6, max_concurrent=2)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2,
             msg="only max_concurrent tasks at once")
        time.sleep(0.3)
        assert len(tasks_of(store, svc)) == 2
        got = tasks_of(store, svc)
        assert {t.slot for t in got} == {0, 1}
        assert all(t.desired_state == TaskState.COMPLETE for t in got)

        # complete one: a new slot's task is created
        def complete(tx, tid=got[0].id):
            t = tx.get(Task, tid).copy()
            t.status = TaskStatus(state=TaskState.COMPLETE, timestamp=now())
            tx.update(t)
        store.update(complete)
        poll(lambda: len(tasks_of(store, svc)) == 3,
             msg="a replacement completion should be scheduled")

        # complete everything; no new tasks beyond total
        def complete_all(tx):
            for t in tx.find(Task, ByService(svc.id)):
                if t.status.state != TaskState.COMPLETE:
                    cur = t.copy()
                    cur.status = TaskStatus(state=TaskState.COMPLETE,
                                            timestamp=now())
                    tx.update(cur)
        for _ in range(4):
            store.update(complete_all)
            time.sleep(0.2)
        got = tasks_of(store, svc)
        completed = [t for t in got
                     if t.status.state == TaskState.COMPLETE]
        assert len(completed) == 6, \
            f"6 completions expected, got {len(completed)}"
        assert {t.slot for t in completed} == set(range(6))
    finally:
        orch.stop()


def test_global_job_one_completion_per_node(store):
    n1, n2 = make_node("n1"), make_node("n2")
    store.update(lambda tx: (tx.create(n1), tx.create(n2)))
    orch = JobsOrchestrator(store)
    orch.start()
    try:
        svc = Service(
            id=new_id(),
            spec=ServiceSpec(
                annotations=Annotations(name="gjob"),
                task=TaskSpec(container=ContainerSpec(image="job:1")),
                mode=ServiceMode.GLOBAL_JOB),
            spec_version=Version(index=1))
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2)
        got = tasks_of(store, svc)
        assert {t.node_id for t in got} == {n1.id, n2.id}
        assert all(t.desired_state == TaskState.COMPLETE for t in got)

        # new node -> one more run
        n3 = make_node("n3")
        store.update(lambda tx: tx.create(n3))
        poll(lambda: len(tasks_of(store, svc)) == 3)
    finally:
        orch.stop()


def test_constraint_enforcer_evicts_on_label_change(store):
    node = make_node("n1", labels={"disk": "ssd"})
    svc = Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name="web"),
            task=TaskSpec(
                container=ContainerSpec(image="img"),
                placement=Placement(constraints=["node.labels.disk==ssd"])),
            mode=ServiceMode.REPLICATED),
        spec_version=Version(index=1))
    t = Task(id=new_id(), service_id=svc.id, slot=1, node_id=node.id,
             desired_state=TaskState.RUNNING, spec=svc.spec.task,
             spec_version=Version(index=1),
             status=TaskStatus(state=TaskState.RUNNING))

    def setup(tx):
        tx.create(node)
        tx.create(svc)
        tx.create(t)
    store.update(setup)

    ce = ConstraintEnforcer(store)
    ce.start()
    try:
        time.sleep(0.3)
        assert store.view(lambda tx: tx.get(Task, t.id)).desired_state \
            == TaskState.RUNNING, "compliant task must not be touched"

        def drop_label(tx):
            n = tx.get(Node, node.id).copy()
            n.spec.annotations.labels = {}
            tx.update(n)
        store.update(drop_label)
        poll(lambda: store.view(lambda tx: tx.get(Task, t.id))
             .desired_state == TaskState.SHUTDOWN,
             msg="noncompliant task should be shut down")
    finally:
        ce.stop()


# ---------------------------------------------------------------------------
# reconciler-level BDD cases (reference: manager/orchestrator/jobs/
# replicated/reconciler_test.go + global/reconciler_test.go — the
# fake-reconciler pattern: drive reconcile_service directly, no threads)
# ---------------------------------------------------------------------------

def _reconciler(store):
    from swarmkit_tpu.orchestrator.jobs import ReplicatedJobReconciler
    from swarmkit_tpu.orchestrator.restart import Supervisor
    return ReplicatedJobReconciler(store, Supervisor(store,
                                                     start_worker=False))


def _global_reconciler(store):
    from swarmkit_tpu.orchestrator.jobs import GlobalJobReconciler
    from swarmkit_tpu.orchestrator.restart import Supervisor
    return GlobalJobReconciler(store, Supervisor(store,
                                                 start_worker=False))


def _set_state(store, task_id, state):
    def cb(tx):
        t = tx.get(Task, task_id).copy()
        t.status = TaskStatus(state=state, timestamp=now())
        tx.update(t)
    store.update(cb)


def test_reconciler_max_concurrent_window_refill(store):
    """The in-flight window refills one-for-one as completions land,
    never exceeding max_concurrent, until total_completions slots
    exist (reconciler_test.go 'number of tasks' cases)."""
    svc = make_replicated_job("w", total=5, max_concurrent=2)
    store.update(lambda tx: tx.create(svc))
    r = _reconciler(store)
    r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    assert len(got) == 2 and {t.slot for t in got} == {0, 1}

    # re-reconcile without progress: the window must NOT grow
    r.reconcile_service(svc.id, None)
    assert len(tasks_of(store, svc)) == 2

    # one completion -> exactly one refill, in the next free slot
    _set_state(store, got[0].id, TaskState.COMPLETE)
    r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    assert len(got) == 3 and {t.slot for t in got} == {0, 1, 2}

    # drain to done: 5 completions, no 6th slot ever created
    for _ in range(6):
        for t in tasks_of(store, svc):
            if t.status.state != TaskState.COMPLETE:
                _set_state(store, t.id, TaskState.COMPLETE)
        r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    assert sorted(t.slot for t in got) == [0, 1, 2, 3, 4]
    assert all(t.status.state == TaskState.COMPLETE for t in got)


def test_reconciler_failed_task_restarts_in_window(store):
    """A failed job task routes through the restart supervisor (new
    task, same slot) and still counts against the window."""
    svc = make_replicated_job("f", total=3, max_concurrent=2)
    store.update(lambda tx: tx.create(svc))
    r = _reconciler(store)
    r.reconcile_service(svc.id, None)
    first = tasks_of(store, svc)
    _set_state(store, first[0].id, TaskState.FAILED)
    r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    # the failed task is marked down and a replacement owns its slot;
    # the window stays at 2 live tasks
    live = [t for t in got if t.desired_state <= TaskState.COMPLETE]
    assert len(live) == 2
    assert {t.slot for t in live} == {t.slot for t in first}
    dead = [t for t in got if t.id == first[0].id]
    assert dead and dead[0].desired_state > TaskState.COMPLETE


def test_reconciler_stale_job_iteration_removed(store):
    """Bumping job_status.job_iteration marks every older-iteration
    task REMOVE and refills the window at the new iteration
    (reconciler_test.go 'removes tasks of old iterations')."""
    from swarmkit_tpu.models.objects import JobStatus
    svc = make_replicated_job("it", total=2, max_concurrent=2)
    store.update(lambda tx: tx.create(svc))
    r = _reconciler(store)
    r.reconcile_service(svc.id, None)
    old = tasks_of(store, svc)
    assert all((t.job_iteration.index if t.job_iteration else 0) == 0
               for t in old)

    def bump(tx):
        s = tx.get(Service, svc.id).copy()
        s.job_status = JobStatus(job_iteration=Version(index=1))
        tx.update(s)
    store.update(bump)
    r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    stale = [t for t in got if t.id in {o.id for o in old}]
    fresh = [t for t in got if t.id not in {o.id for o in old}]
    assert stale and all(t.desired_state == TaskState.REMOVE
                         for t in stale)
    assert len(fresh) == 2
    assert all(t.job_iteration.index == 1 for t in fresh)
    # REMOVE is idempotent: a second pass changes nothing
    before = {t.id: t.desired_state for t in tasks_of(store, svc)}
    r.reconcile_service(svc.id, None)
    assert {t.id: t.desired_state
            for t in tasks_of(store, svc)} == before


def test_global_reconciler_node_join_fill_and_filters(store):
    """Global jobs run once per constraint-matching node; joins fill,
    paused/drained/constraint-failing nodes are excluded
    (global/reconciler_test.go node cases)."""
    from swarmkit_tpu.models.types import NodeAvailability
    n1 = make_node("g1", labels={"tier": "batch"})
    n2 = make_node("g2", labels={"tier": "web"})
    store.update(lambda tx: (tx.create(n1), tx.create(n2)))
    svc = Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name="gj"),
            task=TaskSpec(
                container=ContainerSpec(image="job:1"),
                placement=Placement(
                    constraints=["node.labels.tier==batch"])),
            mode=ServiceMode.GLOBAL_JOB),
        spec_version=Version(index=1))
    store.update(lambda tx: tx.create(svc))
    r = _global_reconciler(store)
    r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    assert [t.node_id for t in got] == [n1.id], \
        "constraint must exclude the web node"

    # node join: a new matching node gets its completion; a PAUSED one
    # does not
    n3 = make_node("g3", labels={"tier": "batch"})
    n4 = make_node("g4", labels={"tier": "batch"})
    n4.spec.availability = NodeAvailability.PAUSE
    store.update(lambda tx: (tx.create(n3), tx.create(n4)))
    r.reconcile_service(svc.id, None)
    got = tasks_of(store, svc)
    assert {t.node_id for t in got} == {n1.id, n3.id}
    # idempotent once covered
    r.reconcile_service(svc.id, None)
    assert len(tasks_of(store, svc)) == 2


def test_volume_enforcer_removes_tasks_on_drained_volume(store):
    vol = Volume(id=new_id(),
                 spec=VolumeSpec(annotations=Annotations(name="vol1")))
    t = Task(id=new_id(), service_id=new_id(), slot=1,
             desired_state=TaskState.RUNNING,
             spec=TaskSpec(container=ContainerSpec(image="img")),
             status=TaskStatus(state=TaskState.RUNNING),
             volumes=[VolumeAttachment(id=vol.id, source="v",
                                       target="/data")])

    def setup(tx):
        tx.create(vol)
        tx.create(t)
    store.update(setup)

    ve = VolumeEnforcer(store)
    ve.start()
    try:
        def drain(tx):
            v = tx.get(Volume, vol.id).copy()
            v.spec.availability = VolumeAvailability.DRAIN
            tx.update(v)
        store.update(drain)
        poll(lambda: store.view(lambda tx: tx.get(Task, t.id))
             .desired_state == TaskState.REMOVE,
             msg="task using drained volume should be removed")
    finally:
        ve.stop()
