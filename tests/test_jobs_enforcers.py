"""Jobs orchestrator + constraint/volume enforcer tests (reference:
manager/orchestrator/jobs/*_test.go, constraintenforcer tests)."""

import time

import pytest

from swarmkit_tpu.models import (
    Annotations, Cluster, Node, Resources, ResourceRequirements, Service,
    ServiceMode, ServiceSpec, Task, TaskSpec, TaskState, TaskStatus, Version,
    Volume,
)
from swarmkit_tpu.models.specs import (
    ClusterSpec, ContainerSpec, GlobalJob, ReplicatedJob, VolumeSpec,
)
from swarmkit_tpu.models.types import (
    Placement, RestartPolicy, RestartCondition, VolumeAvailability,
    VolumeAttachment, now,
)
from swarmkit_tpu.orchestrator import (
    ConstraintEnforcer, JobsOrchestrator, VolumeEnforcer,
)
from swarmkit_tpu.state import ByService, MemoryStore
from swarmkit_tpu.utils import new_id

from test_orchestrator import make_node, poll


@pytest.fixture
def store():
    s = MemoryStore()
    s.update(lambda tx: tx.create(Cluster(
        id=new_id(), spec=ClusterSpec(annotations=Annotations(
            name="default")))))
    yield s
    s.close()


def make_replicated_job(name, total, max_concurrent=0):
    return Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name=name),
            task=TaskSpec(container=ContainerSpec(image="job:1"),
                          restart=RestartPolicy(
                              condition=RestartCondition.ON_FAILURE,
                              delay=0.05)),
            mode=ServiceMode.REPLICATED_JOB,
            replicated_job=ReplicatedJob(total_completions=total,
                                         max_concurrent=max_concurrent),
        ),
        spec_version=Version(index=1))


def tasks_of(store, svc):
    return store.view(lambda tx: tx.find(Task, ByService(svc.id)))


def test_replicated_job_respects_max_concurrent(store):
    orch = JobsOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated_job("batch", total=6, max_concurrent=2)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2,
             msg="only max_concurrent tasks at once")
        time.sleep(0.3)
        assert len(tasks_of(store, svc)) == 2
        got = tasks_of(store, svc)
        assert {t.slot for t in got} == {0, 1}
        assert all(t.desired_state == TaskState.COMPLETE for t in got)

        # complete one: a new slot's task is created
        def complete(tx, tid=got[0].id):
            t = tx.get(Task, tid).copy()
            t.status = TaskStatus(state=TaskState.COMPLETE, timestamp=now())
            tx.update(t)
        store.update(complete)
        poll(lambda: len(tasks_of(store, svc)) == 3,
             msg="a replacement completion should be scheduled")

        # complete everything; no new tasks beyond total
        def complete_all(tx):
            for t in tx.find(Task, ByService(svc.id)):
                if t.status.state != TaskState.COMPLETE:
                    cur = t.copy()
                    cur.status = TaskStatus(state=TaskState.COMPLETE,
                                            timestamp=now())
                    tx.update(cur)
        for _ in range(4):
            store.update(complete_all)
            time.sleep(0.2)
        got = tasks_of(store, svc)
        completed = [t for t in got
                     if t.status.state == TaskState.COMPLETE]
        assert len(completed) == 6, \
            f"6 completions expected, got {len(completed)}"
        assert {t.slot for t in completed} == set(range(6))
    finally:
        orch.stop()


def test_global_job_one_completion_per_node(store):
    n1, n2 = make_node("n1"), make_node("n2")
    store.update(lambda tx: (tx.create(n1), tx.create(n2)))
    orch = JobsOrchestrator(store)
    orch.start()
    try:
        svc = Service(
            id=new_id(),
            spec=ServiceSpec(
                annotations=Annotations(name="gjob"),
                task=TaskSpec(container=ContainerSpec(image="job:1")),
                mode=ServiceMode.GLOBAL_JOB),
            spec_version=Version(index=1))
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2)
        got = tasks_of(store, svc)
        assert {t.node_id for t in got} == {n1.id, n2.id}
        assert all(t.desired_state == TaskState.COMPLETE for t in got)

        # new node -> one more run
        n3 = make_node("n3")
        store.update(lambda tx: tx.create(n3))
        poll(lambda: len(tasks_of(store, svc)) == 3)
    finally:
        orch.stop()


def test_constraint_enforcer_evicts_on_label_change(store):
    node = make_node("n1", labels={"disk": "ssd"})
    svc = Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name="web"),
            task=TaskSpec(
                container=ContainerSpec(image="img"),
                placement=Placement(constraints=["node.labels.disk==ssd"])),
            mode=ServiceMode.REPLICATED),
        spec_version=Version(index=1))
    t = Task(id=new_id(), service_id=svc.id, slot=1, node_id=node.id,
             desired_state=TaskState.RUNNING, spec=svc.spec.task,
             spec_version=Version(index=1),
             status=TaskStatus(state=TaskState.RUNNING))

    def setup(tx):
        tx.create(node)
        tx.create(svc)
        tx.create(t)
    store.update(setup)

    ce = ConstraintEnforcer(store)
    ce.start()
    try:
        time.sleep(0.3)
        assert store.view(lambda tx: tx.get(Task, t.id)).desired_state \
            == TaskState.RUNNING, "compliant task must not be touched"

        def drop_label(tx):
            n = tx.get(Node, node.id).copy()
            n.spec.annotations.labels = {}
            tx.update(n)
        store.update(drop_label)
        poll(lambda: store.view(lambda tx: tx.get(Task, t.id))
             .desired_state == TaskState.SHUTDOWN,
             msg="noncompliant task should be shut down")
    finally:
        ce.stop()


def test_volume_enforcer_removes_tasks_on_drained_volume(store):
    vol = Volume(id=new_id(),
                 spec=VolumeSpec(annotations=Annotations(name="vol1")))
    t = Task(id=new_id(), service_id=new_id(), slot=1,
             desired_state=TaskState.RUNNING,
             spec=TaskSpec(container=ContainerSpec(image="img")),
             status=TaskStatus(state=TaskState.RUNNING),
             volumes=[VolumeAttachment(id=vol.id, source="v",
                                       target="/data")])

    def setup(tx):
        tx.create(vol)
        tx.create(t)
    store.update(setup)

    ve = VolumeEnforcer(store)
    ve.start()
    try:
        def drain(tx):
            v = tx.get(Volume, vol.id).copy()
            v.spec.availability = VolumeAvailability.DRAIN
            tx.update(v)
        store.update(drain)
        poll(lambda: store.view(lambda tx: tx.get(Task, t.id))
             .desired_state == TaskState.REMOVE,
             msg="task using drained volume should be removed")
    finally:
        ve.stop()
