"""Manager composition, CA/join tokens, keymanager, logbroker, watch API,
metrics, CLI — the remaining manager-side components."""

import os
import time

import pytest

from swarmkit_tpu.cli import run_command
from swarmkit_tpu.manager import (
    KeyManager, LogBroker, LogSelector, Manager, WatchRequest, WatchServer,
)
from swarmkit_tpu.manager.controlapi import APIError
from swarmkit_tpu.manager.dispatcher import Config_
from swarmkit_tpu.manager.keymanager import Config as KMConfig
from swarmkit_tpu.manager.logbroker import LogMessage
from swarmkit_tpu.models import (
    Annotations, Cluster, Node, Service, Task, TaskState,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import NodeRole
from swarmkit_tpu.node import Node as ClusterNode
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.security import (
    InvalidCertificate, InvalidToken, KeyReadWriter, RootCA,
)
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.utils import new_id

from test_orchestrator import poll

from swarmkit_tpu.security.ca import HAVE_CRYPTOGRAPHY

requires_crypto = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="requires the 'cryptography' package")



def fast_cfg():
    return Config_(heartbeat_period=0.3, heartbeat_epsilon=0.02,
                   process_updates_interval=0.02,
                   assignment_batching_wait=0.02)


# --------------------------------------------------------------- CA / tokens

@requires_crypto
def test_join_tokens_and_certificates():
    ca = RootCA()
    worker_token = ca.join_token(NodeRole.WORKER)
    manager_token = ca.join_token(NodeRole.MANAGER)
    assert worker_token.startswith("SWMTKN-1-")
    assert ca.role_for_token(worker_token) == NodeRole.WORKER
    assert ca.role_for_token(manager_token) == NodeRole.MANAGER
    with pytest.raises(InvalidToken):
        ca.role_for_token("SWMTKN-1-bogus-bogus")
    with pytest.raises(InvalidToken):
        RootCA().role_for_token(worker_token)  # different cluster

    cert = ca.issue("node1", NodeRole.WORKER)
    ca.verify(cert)
    assert cert.node_id == "node1"
    assert cert.role == int(NodeRole.WORKER)
    # a cert from a different CA fails verification (x509 chain check)
    with pytest.raises(InvalidCertificate):
        ca.verify(RootCA().issue("node1", NodeRole.MANAGER))
    # an expired cert fails closed
    with pytest.raises(InvalidCertificate):
        ca.verify(ca.issue("node2", NodeRole.WORKER, expiry=-30))

    # token rotation invalidates old tokens
    old = worker_token
    new = ca.rotate_join_token(NodeRole.WORKER)
    assert new != old
    with pytest.raises(InvalidToken):
        ca.role_for_token(old)
    assert ca.role_for_token(new) == NodeRole.WORKER


@requires_crypto
def test_key_read_writer_kek(tmp_path):
    ca = RootCA()
    cert = ca.issue("n1", NodeRole.WORKER)
    path = os.path.join(tmp_path, "sub", "node.key")
    rw = KeyReadWriter(path, kek=b"passphrase")
    rw.write(cert, b"keydata")
    got, key = rw.read()
    assert got.node_id == "n1" and key == b"keydata"
    # wrong KEK fails
    with pytest.raises(Exception):
        KeyReadWriter(path, kek=b"wrong").read()
    # KEK rotation and unlock
    rw.rotate_kek(None)
    got2, _ = KeyReadWriter(path).read()
    assert got2.node_id == "n1"


# --------------------------------------------------------------- key manager

def test_keymanager_rotation():
    store = MemoryStore()
    store.update(lambda tx: tx.create(Cluster(
        id=new_id(), spec=ClusterSpec(annotations=Annotations(
            name="default")))))
    km = KeyManager(store, KMConfig(rotation_interval=0.2))
    km.start()
    try:
        def keys():
            from swarmkit_tpu.state.store import ByName
            c = store.view(lambda tx: tx.find(Cluster, ByName("default")))[0]
            return c.network_bootstrap_keys, c.encryption_key_lamport_clock

        poll(lambda: len(keys()[0]) >= 2, msg="keys created at startup")
        first_clock = keys()[1]
        poll(lambda: keys()[1] > first_clock, msg="rotation advances clock")
        ks, _ = keys()
        # at most 2 keys per subsystem (current + previous)
        from collections import Counter
        per = Counter(k.subsystem for k in ks)
        assert all(v <= 2 for v in per.values()), per
    finally:
        km.stop()


# ---------------------------------------------------------------- log broker

def test_logbroker_fanout():
    store = MemoryStore()
    t = Task(id=new_id(), service_id="svcA", slot=1, node_id="n1")
    store.update(lambda tx: tx.create(t))
    broker = LogBroker(store)

    listener = broker.listen_subscriptions()
    sub = broker.subscribe_logs(LogSelector(service_ids=["svcA"]))
    msg = listener.get(timeout=2)
    assert msg.id == sub.id and not msg.close

    broker.publish_logs([
        LogMessage(task_id=t.id, node_id="n1", stream="stdout",
                   data=b"hello"),
        LogMessage(task_id="other", node_id="n2", stream="stdout",
                   data=b"not for us"),
    ])
    got = sub.get(timeout=2)
    assert got.data == b"hello"
    import pytest as _p
    with _p.raises(TimeoutError):
        sub.get(timeout=0.1)

    sub.close()
    end = listener.get(timeout=2)
    assert end.close
    broker.close()


def test_logbroker_subscription_options():
    """tail/since/streams/follow (reference: api/logbroker.proto:26
    LogSubscriptionOptions): history replays from the broker's bounded
    per-task ring; follow=False closes after the backlog."""
    import pytest as _p

    from swarmkit_tpu.manager.logbroker import LogSubscriptionOptions
    from swarmkit_tpu.models.types import now
    from swarmkit_tpu.state.watch import Closed

    store = MemoryStore()
    t = Task(id=new_id(), service_id="svcA", slot=1, node_id="n1")
    store.update(lambda tx: tx.create(t))
    broker = LogBroker(store)

    t_mid = None
    for i in range(5):
        if i == 3:
            t_mid = now()
        broker.publish_logs([LogMessage(
            task_id=t.id, node_id="n1",
            stream="stderr" if i == 4 else "stdout",
            data=f"line{i}".encode())])

    def drain(sub):
        out = []
        while True:
            try:
                out.append(sub.get(timeout=0.2))
            except (Closed, TimeoutError):
                return out

    # tail: last 2 history messages, then closed (no follow)
    sub = broker.subscribe_logs(
        LogSelector(service_ids=["svcA"]),
        options=LogSubscriptionOptions(follow=False, tail=2))
    assert [m.data for m in drain(sub)] == [b"line3", b"line4"]

    # since: only messages at/after the stamp
    sub = broker.subscribe_logs(
        LogSelector(service_ids=["svcA"]),
        options=LogSubscriptionOptions(follow=False, since=t_mid))
    assert [m.data for m in drain(sub)] == [b"line3", b"line4"]

    # streams filter applies to history and live alike
    sub = broker.subscribe_logs(
        LogSelector(service_ids=["svcA"]),
        options=LogSubscriptionOptions(streams=["stderr"], tail=-1))
    msgs = []
    while True:
        try:
            msgs.append(sub.get(timeout=0.2))
        except TimeoutError:
            break
    assert [m.data for m in msgs] == [b"line4"]
    broker.publish_logs([LogMessage(task_id=t.id, node_id="n1",
                                    stream="stdout", data=b"ignored"),
                         LogMessage(task_id=t.id, node_id="n1",
                                    stream="stderr", data=b"kept")])
    assert sub.get(timeout=2).data == b"kept"
    with _p.raises(TimeoutError):
        sub.get(timeout=0.1)
    sub.close()

    # tail=0: no history at all, live only
    sub = broker.subscribe_logs(
        LogSelector(service_ids=["svcA"]),
        options=LogSubscriptionOptions(tail=0))
    with _p.raises(TimeoutError):
        sub.get(timeout=0.1)
    sub.close()
    broker.close()


def test_logbroker_history_bounded():
    """Per-task history honors the byte budget (oldest messages fall
    off) and rings for reaped tasks are pruned."""
    store = MemoryStore()
    t = Task(id=new_id(), service_id="svcA", slot=1, node_id="n1")
    store.update(lambda tx: tx.create(t))
    broker = LogBroker(store)
    broker.HISTORY_BYTES_PER_TASK = 64

    for i in range(10):
        broker.publish_logs([LogMessage(
            task_id=t.id, node_id="n1", stream="stdout",
            data=(f"{i}:" + "x" * 14).encode())])   # 16 bytes each
    ring = broker._history[t.id]
    assert 0 < sum(len(m.data) for m in ring) <= 64
    assert ring[-1].data.startswith(b"9:")
    assert ring[0].data.startswith(b"6:")   # oldest evicted
    broker.close()


# ----------------------------------------------------------------- watch api

def test_watch_api_filters():
    store = MemoryStore()
    server = WatchServer(store)
    stream = server.watch(WatchRequest(kinds=[Node], actions=["create"],
                                       include_old_object=True))
    n = Node(id=new_id())
    t = Task(id=new_id())
    store.update(lambda tx: (tx.create(n), tx.create(t)))
    ev = stream.get(timeout=2)
    assert ev.action == "create" and ev.obj.id == n.id
    with pytest.raises(TimeoutError):
        stream.get(timeout=0.1)   # the task event was filtered out
    stream.close()

    # service/node selectors (reference: watch.proto SelectByServiceID/
    # SelectByNodeID)
    stream = server.watch(WatchRequest(kinds=[Task],
                                       service_ids=["svc-a"]))
    ta = Task(id=new_id(), service_id="svc-a", slot=1)
    tb = Task(id=new_id(), service_id="svc-b", slot=1)
    store.update(lambda tx: (tx.create(ta), tx.create(tb)))
    assert stream.get(timeout=2).obj.id == ta.id
    with pytest.raises(TimeoutError):
        stream.get(timeout=0.1)
    stream.close()
    stream = server.watch(WatchRequest(kinds=[Task], node_ids=["n-1"]))
    tc = Task(id=new_id(), service_id="svc-a", slot=2, node_id="n-1")
    store.update(lambda tx: tx.create(tc))
    assert stream.get(timeout=2).obj.id == tc.id
    stream.close()


# ------------------------------------------------- manager composition + CLI

@requires_crypto
def test_manager_standalone_cluster_and_cli():
    manager = Manager(dispatcher_config=fast_cfg(),
                      use_device_scheduler=False)
    manager.run()
    node = None
    try:
        assert manager.is_leader
        # the default cluster exists with join tokens
        from swarmkit_tpu.state.store import ByName
        cluster = manager.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0]
        assert cluster.root_ca.join_tokens.worker.startswith("SWMTKN-1-")

        # join a worker node through the CA with the worker token
        import tempfile
        node = ClusterNode(TestExecutor(hostname="w1"),
                           tempfile.mkdtemp())
        node.load_or_join(manager.ca_server,
                          cluster.root_ca.join_tokens.worker)
        assert node.role == NodeRole.WORKER
        node.start(manager.dispatcher, store=manager.store, hostname="w1")

        api = manager.control_api
        out = run_command(["service", "create", "--name", "web",
                           "--image", "nginx", "--replicas", "2"], api)
        service_id = out.strip()

        def running():
            tasks = api.list_tasks(service_id=service_id)
            return (len([t for t in tasks
                         if t.desired_state == TaskState.RUNNING]) == 2
                    and all(t.status.state == TaskState.RUNNING
                            for t in tasks
                            if t.desired_state == TaskState.RUNNING))
        poll(running, timeout=20,
             msg="service created via CLI should reach RUNNING")

        ls = run_command(["service", "ls"], api)
        assert "web" in ls and "nginx" in ls
        tasks_out = run_command(["task", "ls"], api)
        assert "RUNNING" in tasks_out and "web.1" in tasks_out
        t0 = api.list_tasks(service_id=service_id)[0]
        insp = run_command(["task", "inspect", t0.id[:8]], api)
        assert f"ID: {t0.id}" in insp and "Status: " in insp
        assert "Image: nginx" in insp
        nodes_out = run_command(["node", "ls"], api)
        assert "w1" in nodes_out and "READY" in nodes_out

        # availability verbs (reference: swarmctl node pause/activate)
        run_command(["node", "pause", "w1"], api)
        assert "pause" in run_command(["node", "ls"], api)
        run_command(["node", "activate", "w1"], api)
        assert "active" in run_command(["node", "ls"], api)
        insp = run_command(["node", "inspect", "w1"], api)
        assert "Hostname: w1" in insp and "Availability: active" in insp

        # service create flag surface (reference: swarmctl
        # service/flagparser): env, labels, publish, restart policy,
        # secret/config refs, network attachment, global mode
        run_command(["secret", "create", "db-pass", "hunter2"], api)
        run_command(["config", "create", "app-conf", "x=1"], api)
        nid = run_command(["network", "create", "backend0"], api)
        sid2 = run_command(
            ["service", "create", "--name", "rich", "--image", "api:1",
             "--replicas", "1", "--env", "MODE=prod", "--label", "tier=web",
             "--publish", "8080:80", "--publish", "53:53/udp",
             "--network", "backend0", "--secret", "db-pass",
             "--config", "app-conf:conf/app.ini",
             "--restart-condition", "on-failure",
             "--restart-delay", "0.5"], api)
        rich = api.get_service(sid2)
        assert rich.spec.task.container.env == ["MODE=prod"]
        assert rich.spec.annotations.labels == {"tier": "web"}
        ports = rich.spec.endpoint.ports
        assert [(p.published_port, p.target_port, int(p.protocol))
                for p in ports] == [(8080, 80, 0), (53, 53, 1)]
        assert rich.spec.task.networks[0].target == nid
        assert rich.spec.task.container.secrets[0].secret_name == "db-pass"
        cref = rich.spec.task.container.configs[0]
        assert cref.target == "conf/app.ini"
        assert rich.spec.task.restart.condition.name == "ON_FAILURE"
        assert rich.spec.task.restart.delay == 0.5
        run_command(["service", "rm", "rich"], api)

        gid = run_command(
            ["service", "create", "--name", "everywhere",
             "--image", "agent:1", "--mode", "global"], api)
        assert api.get_service(gid).spec.mode.name == "GLOBAL"
        poll(lambda: [t for t in api.list_tasks(service_id=gid)
                      if t.status.state == TaskState.RUNNING] or None,
             timeout=20, msg="global service should land on the worker")
        with pytest.raises(APIError):
            run_command(["service", "scale", "everywhere=3"], api)
        run_command(["service", "rm", "everywhere"], api)
        with pytest.raises(APIError):
            run_command(["service", "create", "--name", "x", "--image",
                         "i", "--mode", "global", "--replicas", "5"], api)
        with pytest.raises(APIError):
            run_command(["service", "create", "--name", "x", "--image",
                         "i", "--publish", "99999:80"], api)

        # rolling update from the CLI: new image reaches every replica
        # through the update supervisor (reference: swarmctl service
        # update driving updater.go)
        run_command(["service", "update", "web", "--image", "nginx:2",
                     "--update-parallelism", "2"], api)
        def updated():
            tasks = [t for t in api.list_tasks(service_id=service_id)
                     if t.desired_state == TaskState.RUNNING]
            return (len(tasks) == 2 and all(
                t.spec.container.image == "nginx:2"
                and t.status.state == TaskState.RUNNING for t in tasks))
        poll(updated, timeout=30,
             msg="all replicas should roll to the new image")
        assert "nginx:2" in run_command(["service", "ls"], api)

        # in-proc agents follow key-manager rotations through the local
        # heartbeat piggyback (LocalDispatcherClient), like remote workers
        ex = node.executor
        poll(lambda: getattr(ex, "network_keys", None), timeout=10,
             msg="network keys should reach the in-proc agent")
        clock0 = max(k.lamport_time for k in ex.network_keys)
        manager.keymanager.rotate_now()
        poll(lambda: max(k.lamport_time for k in ex.network_keys) > clock0,
             timeout=10, msg="rotated keys should reach the in-proc agent")

        run_command(["service", "scale", "web=4"], api)
        poll(lambda: len([t for t in api.list_tasks(service_id=service_id)
                          if t.desired_state == TaskState.RUNNING]) == 4,
             timeout=20)

        out = run_command(["service", "rm", "web"], api)
        assert out == service_id
        with pytest.raises(APIError):
            run_command(["service", "inspect", "web"], api)

        # metrics exposed
        from swarmkit_tpu.utils.metrics import registry
        text = registry.expose()
        assert "swarm_manager_nodes" in text
        assert "swarm_store_write_tx_latency_seconds_count" in text
    finally:
        if node is not None:
            node.stop()
        manager.stop()


@requires_crypto
def test_manager_leadership_lifecycle():
    """become_leader starts the loops; become_follower stops them."""
    manager = Manager(dispatcher_config=fast_cfg(),
                      use_device_scheduler=False)
    manager.run()
    try:
        assert manager.scheduler is not None
        assert manager.dispatcher is not None
        manager._become_follower()
        assert manager.scheduler is None
        assert manager.dispatcher is None
        assert not manager.is_leader
        manager._become_leader()
        assert manager.scheduler is not None
    finally:
        manager.stop()


def test_role_manager_promote_demote():
    """Promotion joins raft then flips the observed role; demotion leaves
    raft FIRST (reference: role_manager.go, design/raft.md:136-158)."""
    from swarmkit_tpu.manager.rolemanager import RoleManager
    from swarmkit_tpu.models.specs import NodeSpec

    calls = []

    class FakeRaft:
        id = "m0"
        is_leader = True

        def __init__(self):
            class Core:
                peers = {"m0"}
            self.core = Core()

        def step_down(self):
            calls.append(("stepdown",))

        def add_member(self, nid):
            calls.append(("add", nid))
            self.core.peers.add(nid)

        def remove_member(self, nid):
            calls.append(("remove", nid))
            self.core.peers.discard(nid)

    store = MemoryStore()
    raft = FakeRaft()
    rm = RoleManager(store, raft_node=raft)
    n = Node(id=new_id(),
             spec=NodeSpec(annotations=Annotations(name="w1"),
                           desired_role=NodeRole.WORKER),
             role=int(NodeRole.WORKER))
    store.update(lambda tx: tx.create(n))
    rm.start()
    try:
        # promote
        def promote(tx):
            cur = tx.get(Node, n.id).copy()
            cur.spec.desired_role = NodeRole.MANAGER
            tx.update(cur)
        store.update(promote)
        poll(lambda: store.view(lambda tx: tx.get(Node, n.id)).role
             == int(NodeRole.MANAGER))
        # membership is NOT added eagerly: the promoted node's manager
        # process joins raft itself when it starts
        assert ("add", n.id) not in calls
        raft.core.peers.add(n.id)   # simulate its manager joining

        # demote: raft removal precedes the role flip
        def demote(tx):
            cur = tx.get(Node, n.id).copy()
            cur.spec.desired_role = NodeRole.WORKER
            tx.update(cur)
        store.update(demote)
        poll(lambda: store.view(lambda tx: tx.get(Node, n.id)).role
             == int(NodeRole.WORKER))
        assert ("remove", n.id) in calls
        assert n.id not in raft.core.peers
    finally:
        rm.stop()


def test_watch_resume_from_version():
    """WatchFrom parity (reference: watchapi/watch.go:32 backed by
    raft.go:1617 ChangesBetween): a resumed watcher replays exactly the
    missed events, in order, then goes live."""
    from swarmkit_tpu.manager.watchapi import ResumeCompacted
    from swarmkit_tpu.models import Service, TaskState, TaskStatus

    store = MemoryStore()
    server = WatchServer(store)
    n = Node(id=new_id())
    store.update(lambda tx: tx.create(n))
    mark = store.version   # the watcher "disconnects" here

    # three changes while away: create, update, delete
    t1, t2 = Task(id=new_id()), Task(id=new_id())
    store.update(lambda tx: (tx.create(t1), tx.create(t2)))
    t1b = store.raw_get(Task, t1.id).copy()
    t1b.status = TaskStatus(state=TaskState.ASSIGNED)
    store.update(lambda tx: tx.update(t1b))
    store.update(lambda tx: tx.delete(Task, t2.id))

    stream = server.watch(WatchRequest(
        kinds=[Task], resume_from_version=mark,
        include_old_object=True))
    got = [stream.get(timeout=1) for _ in range(4)]
    assert [(e.action, e.obj.id) for e in got] == [
        ("create", t1.id), ("create", t2.id),
        ("update", t1.id), ("delete", t2.id)]
    assert got[2].old is not None \
        and got[2].old.status.state != TaskState.ASSIGNED
    # then live events flow
    t3 = Task(id=new_id())
    store.update(lambda tx: tx.create(t3))
    assert stream.get(timeout=2).obj.id == t3.id
    stream.close()

    # resuming from the current version replays nothing
    stream2 = server.watch(WatchRequest(
        kinds=[Task], resume_from_version=store.version))
    with pytest.raises(TimeoutError):
        stream2.get(timeout=0.1)
    stream2.close()

    # a compacted version fails loudly, like the reference when the raft
    # log no longer covers the range
    store.changelog_limit = 4
    for _ in range(6):
        x = Node(id=new_id())
        store.update(lambda tx, x=x: tx.create(x))
    with pytest.raises(ResumeCompacted):
        server.watch(WatchRequest(resume_from_version=mark))


def test_watch_resume_covers_block_commits():
    """Columnar scheduler commits replay as per-task update events."""
    from swarmkit_tpu.models import TaskState

    from test_scheduler import make_ready_node, make_service_with_tasks

    store = MemoryStore()
    server = WatchServer(store)
    svc, tasks = make_service_with_tasks(4)
    nodes = [make_ready_node(f"n{i}") for i in range(2)]

    def cb(tx):
        tx.create(svc)
        for x in nodes + tasks:
            tx.create(x)
    store.update(cb)
    stored = sorted(store.view(
        lambda tx: tx.find(Task)), key=lambda t: t.slot)
    mark = store.version

    committed, failed = store.commit_task_block(
        stored, [nodes[i % 2].id for i in range(4)],
        int(TaskState.ASSIGNED), "assigned",
        lambda t, nid: None, lambda t, nid: False)
    assert len(committed) == 4 and not failed

    stream = server.watch(WatchRequest(
        kinds=[Task], resume_from_version=mark,
        include_old_object=True))
    got = [stream.get(timeout=1) for _ in range(4)]
    versions = [e.obj.meta.version.index for e in got]
    assert versions == sorted(versions) and versions[0] == mark + 1
    for e, t in zip(got, stored):
        assert e.action == "update"
        assert e.obj.id == t.id
        assert e.obj.status.state == TaskState.ASSIGNED
        assert e.obj.node_id
        assert e.old is not None and not e.old.node_id
    stream.close()


# ------------------------------------------------------------- deallocator

def test_deallocator_waits_for_tasks_then_frees_networks():
    """Services marked pending_delete are removed only once their tasks
    drain, and their pending-delete networks are freed unless another
    service still uses them (reference: manager/deallocator/
    deallocator.go + its test scenarios)."""
    from swarmkit_tpu.manager.deallocator import Deallocator
    from swarmkit_tpu.models import Network, Service, Task, TaskState
    from swarmkit_tpu.models.specs import NetworkSpec
    from swarmkit_tpu.models.types import (
        Annotations, NetworkAttachmentConfig, TaskStatus,
    )
    from swarmkit_tpu.state import MemoryStore
    from swarmkit_tpu.utils import new_id

    from test_orchestrator import make_replicated, poll

    store = MemoryStore()
    net_shared = Network(id=new_id(), spec=NetworkSpec(
        annotations=Annotations(name="shared")), pending_delete=True)
    net_own = Network(id=new_id(), spec=NetworkSpec(
        annotations=Annotations(name="own")), pending_delete=True)
    doomed = make_replicated("doomed", 2)
    doomed.spec.networks = [
        NetworkAttachmentConfig(target=net_shared.id),
        NetworkAttachmentConfig(target=net_own.id)]
    doomed.pending_delete = True
    survivor = make_replicated("survivor", 1)
    survivor.spec.networks = [NetworkAttachmentConfig(
        target=net_shared.id)]
    tasks = [Task(id=new_id(), service_id=doomed.id, slot=i,
                  status=TaskStatus(state=TaskState.RUNNING),
                  desired_state=TaskState.RUNNING) for i in (1, 2)]

    def setup(tx):
        tx.create(net_shared)
        tx.create(net_own)
        tx.create(doomed)
        tx.create(survivor)
        for t in tasks:
            tx.create(t)

    store.update(setup)
    d = Deallocator(store)
    d.start()
    try:
        import time
        time.sleep(0.3)
        assert store.view(lambda tx: tx.get(Service, doomed.id)) \
            is not None, "service with live tasks must not be deleted"

        store.update(lambda tx: tx.delete(Task, tasks[0].id))
        time.sleep(0.2)
        assert store.view(lambda tx: tx.get(Service, doomed.id)) \
            is not None, "one task still remains"

        store.update(lambda tx: tx.delete(Task, tasks[1].id))
        poll(lambda: store.view(
            lambda tx: tx.get(Service, doomed.id)) is None,
            msg="drained pending-delete service removed")
        poll(lambda: store.view(
            lambda tx: tx.get(Network, net_own.id)) is None,
            msg="its exclusive pending-delete network freed")
        assert store.view(lambda tx: tx.get(Network, net_shared.id)) \
            is not None, "network still used by survivor must stay"

        # the survivor releases the shared network: now it frees too
        cur = store.view(lambda tx: tx.get(Service, survivor.id)).copy()
        cur.spec.networks = []
        store.update(lambda tx: tx.update(cur))
        # re-nudge via a network update event (reference: the event path)
        netcur = store.view(
            lambda tx: tx.get(Network, net_shared.id)).copy()
        store.update(lambda tx: tx.update(netcur))
        poll(lambda: store.view(
            lambda tx: tx.get(Network, net_shared.id)) is None,
            msg="unreferenced pending-delete network freed on event")
    finally:
        d.stop()


def test_deallocator_removes_already_drained_service_at_startup():
    from swarmkit_tpu.manager.deallocator import Deallocator
    from swarmkit_tpu.models import Service
    from swarmkit_tpu.state import MemoryStore

    from test_orchestrator import make_replicated, poll

    store = MemoryStore()
    gone = make_replicated("gone", 1)
    gone.pending_delete = True
    store.update(lambda tx: tx.create(gone))
    d = Deallocator(store)
    d.start()
    try:
        poll(lambda: store.view(
            lambda tx: tx.get(Service, gone.id)) is None,
            msg="drained service reaped by the initial scan")
    finally:
        d.stop()
