"""Mesh-native streaming planner differentials (ISSUE 19).

The resident device tier shards over the planner mesh (node-axis
NamedSharding, per-shard donated scatters — parallel/sharded.py
``put_resident``/``scatter_rows_sharded``); fused runs seed their
node-state columns straight from the resident shards; binpack /
weighted / learned groups ride ``ShardedPlanFn.strategy`` and the
strategy-mixed fused kernel instead of falling back to the host.

Every test here is a differential: placements, store state and the
watch-event stream at mesh N must be byte-identical to the N=1 program
(which itself is bit-equal to the numpy host oracles — test_strategy /
test_streaming hold that leg).  conftest.py forces an 8-virtual-device
CPU platform, so the 2- and 4-way meshes run in-process.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swarmkit_tpu.models import (
    Annotations, Node, NodeAvailability, NodeDescription, NodeSpec,
    NodeState, NodeStatus, Placement, PlacementPreference,
    ReplicatedService, Resources, ResourceRequirements, Service,
    ServiceMode, ServiceSpec, SpreadOver, Task, TaskSpec, TaskState,
    TaskStatus, Version,
)
from swarmkit_tpu.models import types as model_types
from swarmkit_tpu.ops import TPUPlanner
from swarmkit_tpu.ops import fusedbatch
from swarmkit_tpu.ops.kernel import (
    GroupInputs, NodeInputs, StrategyInputs, K_CLAMP, plan_strategy_jit,
)
from swarmkit_tpu.parallel.sharded import make_mesh, plan_strategy_sharded
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.state.events import (
    Event, EventCommit, EventSnapshotRestore, EventTaskBlock,
)
from swarmkit_tpu.utils.metrics import registry as _metrics


@pytest.fixture
def frozen_clock():
    model_types.set_time_source(lambda: 1_700_000_000.0)
    try:
        yield
    finally:
        model_types.set_time_source(None)


_RES = ResourceRequirements(
    reservations=Resources(nano_cpus=10 ** 8, memory_bytes=64 << 20))


def _mk_node(i, cpus=8 * 10 ** 9, mem=32 << 30):
    return Node(
        id=f"n{i:04d}",
        spec=NodeSpec(annotations=Annotations(
            name=f"node-{i:04d}",
            labels={"rack": f"r{i % 3}",
                    "tier": "web" if i % 2 else "db"})),
        status=NodeStatus(state=NodeState.READY),
        description=NodeDescription(
            hostname=f"node-{i:04d}",
            resources=Resources(nano_cpus=cpus, memory_bytes=mem)))


def _mk_service(sid, n_tasks, spec):
    svc = Service(
        id=sid,
        spec=ServiceSpec(annotations=Annotations(name=f"svc-{sid}"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=n_tasks),
                         task=spec),
        spec_version=Version(index=1))
    tasks = [Task(id=f"{sid}-t{k:04d}", service_id=sid, slot=k + 1,
                  desired_state=TaskState.RUNNING, spec=spec,
                  spec_version=Version(index=1),
                  status=TaskStatus(state=TaskState.PENDING,
                                    timestamp=model_types.now()))
             for k in range(n_tasks)]
    return svc, tasks


def _build_store(n_nodes=24):
    store = MemoryStore()
    store.update(lambda tx: [tx.create(_mk_node(i))
                             for i in range(n_nodes)])
    specs = {
        "sva": TaskSpec(resources=_RES),
        "svb": TaskSpec(resources=_RES,
                        placement=Placement(
                            constraints=["node.labels.tier==web"])),
        "svc": TaskSpec(resources=_RES,
                        placement=Placement(preferences=[
                            PlacementPreference(spread=SpreadOver(
                                spread_descriptor="node.labels.rack"))])),
    }
    seeded = {"sva": 20, "svb": 12, "svc": 9}

    def mk(tx):
        for sid, spec in specs.items():
            svc, tasks = _mk_service(sid, seeded[sid], spec)
            tx.create(svc)
            for t in tasks:
                tx.create(t)
    store.update(mk)
    return store, specs, dict(seeded)


def _event_key(ev):
    if isinstance(ev, EventTaskBlock):
        return ("block", tuple(o.id for o in ev.olds),
                tuple(ev.node_ids), ev.base_version, ev.state, ev.message)
    if isinstance(ev, EventCommit):
        return ("commit", ev.version)
    if isinstance(ev, Event):
        obj = ev.obj
        return (ev.action, obj.id, getattr(obj, "node_id", None),
                int(obj.status.state) if hasattr(obj, "status") else None,
                obj.meta.version.index)
    return ("other", repr(ev))


def _pump(sched, sub):
    while True:
        ev = sub.poll()
        if ev is None:
            return
        if isinstance(ev, EventSnapshotRestore):
            sched._resync()
        elif isinstance(ev, Event):
            sched._handle_event(ev)


def _churn_run(planner):
    """The test_streaming churn (arrivals, failures, a drain flip, a
    node join, a node leave) driven through the real event feed, with
    an injectable planner — the mesh/no-mesh differential harness."""
    store, specs, seqs = _build_store()
    planner.enable_small_group_routing = False
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    _, sub = store.view_and_watch(
        lambda tx: sched._setup_tasks_list(tx), accepts_blocks=True)
    obs = store.queue.subscribe(accepts_blocks=True)

    def add(sid, n):
        spec = specs[sid]
        base = seqs[sid]

        def cb(tx):
            for k in range(n):
                tx.create(Task(
                    id=f"{sid}-t{base + k:04d}", service_id=sid,
                    slot=base + k + 1, desired_state=TaskState.RUNNING,
                    spec=spec, spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING)))
        store.update(cb)
        seqs[sid] = base + n

    def fail_some(sid, k):
        victims = sorted(
            (t for t in store.view(lambda tx: tx.find(Task))
             if t.service_id == sid and t.node_id), key=lambda t: t.id
        )[:k]

        def cb(tx):
            for v in victims:
                cur = tx.get(Task, v.id)
                if cur is None:
                    continue
                cur = cur.copy()
                cur.status = TaskStatus(
                    state=TaskState.FAILED,
                    timestamp=model_types.now(), message="churn exit")
                tx.update(cur)
        store.update(cb)

    def flip(nid, avail):
        def cb(tx):
            cur = tx.get(Node, nid).copy()
            cur.spec.availability = avail
            tx.update(cur)
        store.update(cb)

    decisions = sched.tick()                       # tick 1: cold build
    add("sva", 5)
    add("svc", 3)
    fail_some("sva", 2)
    _pump(sched, sub)
    decisions += sched.tick()                      # tick 2: incremental
    add("svb", 4)
    flip("n0002", NodeAvailability.DRAIN)
    _pump(sched, sub)
    decisions += sched.tick()                      # tick 3: incremental
    store.update(lambda tx: tx.create(_mk_node(24)))
    add("sva", 4)
    _pump(sched, sub)
    decisions += sched.tick()                      # tick 4: append row
    store.update(lambda tx: tx.delete(Node, "n0005"))
    add("svc", 4)
    _pump(sched, sub)
    decisions += sched.tick()                      # tick 5: node-remove
    add("svb", 3)
    flip("n0002", NodeAvailability.ACTIVE)
    _pump(sched, sub)
    decisions += sched.tick()                      # tick 6: incremental

    events = [_event_key(e) for e in obs.drain()]
    store.queue.unsubscribe(obs)
    store.queue.unsubscribe(sub)
    tasks = store.view(lambda tx: tx.find(Task))
    state = sorted((t.id, t.node_id, int(t.status.state),
                    t.status.message, t.meta.version.index)
                   for t in tasks)
    return decisions, state, events, sched, planner


def _mesh_planner(monkeypatch, d):
    monkeypatch.setenv("SWARM_PLANNER_MESH", str(d))
    p = TPUPlanner()
    monkeypatch.delenv("SWARM_PLANNER_MESH")
    assert p.mesh is not None and p.mesh.shape["nodes"] == d
    return p


# ------------------------------------------------ kernel-level parity

def test_sharded_strategy_kernel_matches_jit_fuzz():
    """plan_strategy_sharded (4-way node-axis shard_map) vs the
    single-device jit, bit-for-bit over random columns for every
    non-spread strategy.  Combined with test_strategy's jit-vs-oracle
    fuzz this closes the sharded-kernel-vs-host-oracle triangle."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices (conftest forces 8 host devices)")
    mesh = make_mesh(jax.devices()[:4])
    rng = np.random.RandomState(19)
    with fusedbatch.x64():
        for trial in range(4):
            nb = 32
            valid = rng.rand(nb) > 0.1
            cpu = rng.randint(0, 200, nb).astype(np.int64)
            mem = rng.randint(0, 200, nb).astype(np.int64)
            cpu_d, mem_d = 7, 5
            res_ok = valid & (cpu >= cpu_d) & (mem >= mem_d)
            res_cap = np.minimum(cpu // cpu_d, mem // mem_d)
            res_cap = res_cap.clip(0, K_CLAMP).astype(np.int32)
            nodes = NodeInputs(
                valid=jnp.asarray(valid),
                ready=jnp.asarray(rng.rand(nb) > 0.05),
                res_ok=jnp.asarray(res_ok),
                res_cap=jnp.asarray(res_cap),
                svc_tasks=jnp.asarray(
                    rng.randint(0, 6, nb).astype(np.int32)),
                total_tasks=jnp.asarray(
                    rng.randint(0, 9, nb).astype(np.int32)),
                failures=jnp.asarray(
                    rng.randint(0, 3, nb).astype(np.int32)),
                leaf=jnp.zeros(nb, jnp.int32),
                os_hash=jnp.zeros((2, nb), jnp.int32),
                arch_hash=jnp.zeros((2, nb), jnp.int32),
                port_conflict=jnp.zeros(nb, bool),
                extra_mask=jnp.ones(nb, bool), quota_ok=None)
            group = GroupInputs(
                k=jnp.asarray(int(rng.randint(1, 40)), jnp.int32),
                con_hash=jnp.zeros((1, 2, nb), jnp.int32),
                con_op=jnp.full((1,), 2, jnp.int32),
                con_exp=jnp.zeros((1, 2), jnp.int32),
                plat=jnp.full((1, 4), -1, jnp.int32),
                maxrep=jnp.asarray(0, jnp.int32),
                port_limited=jnp.asarray(False))
            sin = StrategyInputs(
                hr_cpu=jnp.asarray(
                    np.clip(cpu // cpu_d, 0, 1023).astype(np.int32)),
                hr_mem=jnp.asarray(
                    np.clip(mem // mem_d, 0, 1023).astype(np.int32)),
                hr_gen=jnp.full(nb, 1023, jnp.int32),
                weights=jnp.asarray(
                    rng.randint(0, 8, 4).astype(np.int32)),
                w1=jnp.asarray(rng.randint(-4, 5, (6, 4)).astype(
                    np.int32)),
                b1=jnp.asarray(rng.randint(-4, 5, 4).astype(np.int32)),
                w2=jnp.asarray(rng.randint(-4, 5, 4).astype(np.int32)),
                b2=jnp.asarray(int(rng.randint(-4, 5)), jnp.int32))
            for sid in (1, 2, 3):
                x1, fc1, sp1 = plan_strategy_jit(nodes, group, sin, sid)
                xm, fcm, spm = plan_strategy_sharded(nodes, group, sin,
                                                     sid, mesh)
                np.testing.assert_array_equal(
                    np.asarray(x1), np.asarray(xm),
                    err_msg=f"trial {trial} sid {sid}")
                np.testing.assert_array_equal(
                    np.asarray(fc1), np.asarray(fcm),
                    err_msg=f"trial {trial} sid {sid}")


# ------------------------------------------------- churn differentials

def test_mesh_churn_byte_identical_to_single_device(frozen_clock,
                                                    monkeypatch):
    """The headline differential: the full churn (arrivals, failures,
    drain flip, node join/leave) at mesh N=2 must produce the same
    decisions, final store state and event stream as N=1 — while the
    resident tier actually runs sharded (per-shard scatters counted)."""
    dm, sm, em, _sched, pm = _churn_run(_mesh_planner(monkeypatch, 2))
    d1, s1, e1, _sched1, _p1 = _churn_run(TPUPlanner())
    assert (dm, sm, em) == (d1, s1, e1)
    snap = pm.streaming_snapshot()
    assert snap["mesh_devices"] == 2, snap
    assert snap["shard_syncs"] >= 1, snap
    assert pm.stats.get("groups_fused", 0) >= 2, pm.stats


def test_mesh_resident_shards_match_mirror_and_seed_fused(frozen_clock,
                                                          monkeypatch):
    """Sharded-scatter column equality: after churn the five sharded
    device columns must equal the host mirror row-for-row (the donated
    per-shard scatter applied exactly the dirty rows a rebuild would),
    and the fused run must have seeded from them (device carries
    counted, resident H2D per tick ~ 0)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    _dm, _sm, _em, sched, planner = _churn_run(
        _mesh_planner(monkeypatch, 4))
    st = planner._streaming
    assert st is not None and st._mesh_active
    st.refresh(sched)
    assert st.device_carry() is not None
    d_valid, d_ready, d_cpu, d_mem, d_total = [
        np.asarray(a) for a in st.dev]
    np.testing.assert_array_equal(d_valid, st.valid)
    np.testing.assert_array_equal(d_ready, st.ready)
    np.testing.assert_array_equal(d_cpu, st.cpu)
    np.testing.assert_array_equal(d_mem, st.mem)
    np.testing.assert_array_equal(d_total, st.total)
    assert st.stats["shard_syncs"] >= 2
    assert st.snapshot()["mesh_devices"] == 4
    assert planner.stats.get("streaming_device_carries", 0) >= 1, \
        planner.stats


def _strategy_spec(strategy, cpus=1, weights=None):
    return TaskSpec(
        resources=ResourceRequirements(reservations=Resources(
            nano_cpus=cpus * 10 ** 9, memory_bytes=1 << 30)),
        placement=Placement(strategy=strategy,
                            strategy_weights=weights or {}))


def _strategy_tick(planner):
    """One tick over a mixed-strategy workload (spread + binpack +
    weighted + learned) on heterogeneous nodes; returns placements."""
    store = MemoryStore()
    nodes = [_mk_node(i, cpus=(4 + (i % 5) * 4) * 10 ** 9)
             for i in range(10)]
    batches = [
        _mk_service("pack", 8, _strategy_spec("binpack")),
        _mk_service("wt", 8, _strategy_spec(
            "weighted", weights={"cpu": 3, "spread": 1})),
        _mk_service("ml", 8, _strategy_spec("learned")),
        _mk_service("spr", 8, _strategy_spec("")),
    ]

    def mk(tx):
        for node in nodes:
            tx.create(node)
        for svc, tasks in batches:
            tx.create(svc)
            for t in tasks:
                tx.create(t)
    store.update(mk)
    if planner is not None:
        planner.enable_small_group_routing = False
    sched = Scheduler(store, batch_planner=planner)
    store.view(sched._setup_tasks_list)
    sched.tick()
    placements = {t.id: t.node_id for t in store.view(
        lambda tx: tx.find(Task))}
    return placements, planner


def _strategy_subset(placements):
    """The binpack/weighted/learned tasks — the services whose host
    oracle carries the task-level bit-parity contract (spread's host
    walk assigns the same per-node counts in a different task order,
    so the spread service only participates in device-vs-device
    comparisons)."""
    return {tid: nid for tid, nid in placements.items()
            if not tid.startswith("spr-")}


def test_mesh_fused_strategies_match_host_oracle(frozen_clock,
                                                 monkeypatch):
    """binpack / weighted / learned at mesh N=2, fused: the whole
    mixed-strategy tick must place byte-identically to the N=1 device
    program, the strategy services must match the numpy host oracle
    task-for-task, every strategy group must ride the device route
    (zero ``route=host`` increments), and the groups fuse instead of
    breaking the run."""
    def host_groups(route):
        return sum(_metrics.get_counter(
            f'swarm_strategy_groups{{route="{route}",'
            f'strategy="{s}"}}')
            for s in ("binpack", "weighted", "learned"))

    host, _ = _strategy_tick(None)
    dev1, _ = _strategy_tick(TPUPlanner())
    h_before = host_groups("host")
    d_before = host_groups("device")
    devm, planner = _strategy_tick(_mesh_planner(monkeypatch, 2))
    assert devm == dev1                       # N=2 == N=1, all services
    assert _strategy_subset(devm) == _strategy_subset(host)
    assert all(nid for nid in devm.values())
    assert host_groups("host") == h_before, "strategy group fell host"
    assert host_groups("device") == d_before + 3
    assert planner.stats.get("groups_strategy_host", 0) == 0
    assert planner.stats.get("groups_fused", 0) >= 4, planner.stats


def test_mesh_per_group_strategy_kernel_routes_on_device(frozen_clock,
                                                         monkeypatch):
    """With fusion off, a non-spread group rides ShardedPlanFn.strategy
    (the per-group sharded kernel) — not the host oracle — and places
    exactly as the N=1 kernel and the host oracle would."""
    host, _ = _strategy_tick(None)
    p1 = TPUPlanner()
    p1.fused_enabled = False
    dev1, _ = _strategy_tick(p1)
    planner = _mesh_planner(monkeypatch, 2)
    planner.fused_enabled = False
    devm, planner = _strategy_tick(planner)
    assert devm == dev1
    assert _strategy_subset(devm) == _strategy_subset(host)
    assert planner.stats.get("groups_strategy_host", 0) == 0
    assert planner.stats.get("groups_planned", 0) >= 4, planner.stats


# --------------------------------------------------- fallback matrix

def test_mesh_epoch_resync(frozen_clock, monkeypatch):
    """Leader-handoff discipline with the sharded tier: an epoch bump
    forces the counted resync, after which the device tier is sharded
    again and mirrors the host columns."""
    store, _specs, _seqs = _build_store(n_nodes=8)
    planner = _mesh_planner(monkeypatch, 2)
    planner.enable_small_group_routing = False
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    store.view(sched._setup_tasks_list)
    sched._tick_epoch = 3
    planner.begin_tick(sched)
    planner.end_tick()
    st = planner._streaming
    assert st._mesh_active and st.stats["resyncs"] == 0
    sched._tick_epoch = 4          # the reign changed
    planner.begin_tick(sched)
    planner.end_tick()
    assert st.stats["resyncs"] == 1, st.stats
    st.refresh(sched)
    assert st._mesh_active
    for dev_col, host_col in zip(st.dev, (st.valid, st.ready, st.cpu,
                                          st.mem, st.total)):
        np.testing.assert_array_equal(np.asarray(dev_col), host_col)


def test_mesh_teardown_and_shard_count_resync(frozen_clock,
                                              monkeypatch):
    """The two new fallback-matrix rows: tearing the mesh down demotes
    to single-device residency; a shard-count change re-uploads over
    the new layout.  Both are counted resyncs with their own reason
    labels, and the host mirror survives untouched."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    store, _specs, _seqs = _build_store(n_nodes=8)
    planner = _mesh_planner(monkeypatch, 2)
    planner.enable_small_group_routing = False
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    store.view(sched._setup_tasks_list)
    planner.begin_tick(sched)
    planner.end_tick()
    st = planner._streaming
    assert st._mesh_active and st.snapshot()["mesh_devices"] == 2
    host_cols = [np.array(c) for c in (st.valid, st.ready, st.cpu,
                                       st.mem, st.total)]

    before_td = _metrics.get_counter(
        'swarm_streaming_resyncs{reason="mesh-teardown"}')
    st.set_mesh(None)
    assert st.dev is None and not st._mesh_active
    assert _metrics.get_counter(
        'swarm_streaming_resyncs{reason="mesh-teardown"}') \
        == before_td + 1
    st.refresh(sched)
    assert st.device_carry() is not None
    assert st.snapshot()["mesh_devices"] == 0   # single-device tier
    for host_col, now_col in zip(host_cols,
                                 (st.valid, st.ready, st.cpu, st.mem,
                                  st.total)):
        np.testing.assert_array_equal(host_col, now_col)

    before_sc = _metrics.get_counter(
        'swarm_streaming_resyncs{reason="shard-count"}')
    st.set_mesh(make_mesh(jax.devices()[:4]))
    assert st.dev is None
    assert _metrics.get_counter(
        'swarm_streaming_resyncs{reason="shard-count"}') \
        == before_sc + 1
    st.refresh(sched)
    assert st._mesh_active and st.snapshot()["mesh_devices"] == 4
    for dev_col, host_col in zip(st.dev, host_cols):
        np.testing.assert_array_equal(np.asarray(dev_col), host_col)


def test_mesh_divergence_resync_reshards(frozen_clock, monkeypatch):
    """The divergence sentinel is layout-independent: swap a NodeInfo
    object behind the resident row (the mirror now tracks a dead
    object) and the next refresh must count the divergence fallback,
    rebuild the mirror, and re-upload the SHARDED device tier."""
    store, _specs, _seqs = _build_store(n_nodes=8)
    planner = _mesh_planner(monkeypatch, 2)
    planner.enable_small_group_routing = False
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    store.view(sched._setup_tasks_list)
    planner.begin_tick(sched)
    planner.end_tick()
    st = planner._streaming
    assert st._mesh_active
    import copy
    ns = sched.node_set.nodes
    ns["n0000"] = copy.copy(ns["n0000"])   # object swap, not mutation
    sched.delta.mark("n0000")
    before = _metrics.get_counter(
        'swarm_streaming_resyncs{reason="divergence"}')
    fb_before = st.stats["fallbacks"]
    st.refresh(sched)
    assert _metrics.get_counter(
        'swarm_streaming_resyncs{reason="divergence"}') == before + 1
    assert st.stats["fallbacks"] == fb_before + 1
    assert st._mesh_active and st.dev is not None
    for dev_col, host_col in zip(st.dev, (st.valid, st.ready, st.cpu,
                                          st.mem, st.total)):
        np.testing.assert_array_equal(np.asarray(dev_col), host_col)


# ------------------------------------------------------ sim differential

def test_mesh_steady_state_churn_sim(monkeypatch):
    """The twin-store steady-state-churn differential with the whole
    plane on a 2-way mesh: streaming+mesh placements must equal the
    forced full-replan twin for the same virtual-time churn."""
    monkeypatch.setenv("SWARM_PLANNER_MESH", "2")
    from swarmkit_tpu.sim import run_scenario
    r = run_scenario("steady-state-churn", seed=7)
    assert r.ok, r.violations


# ------------------------------------------------- bench_compare gate

def test_bench_compare_mesh_resident_transfer_gate(tmp_path):
    """bench_compare's mesh-resident-transfer gate: a cfg10 run under a
    planner mesh must keep resident H2D/tick within the dirty-scatter
    budget and route zero strategy groups to the host oracle; judged on
    the NEW run alone, and skipped entirely for single-device runs."""
    import json as _json
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), "..",
                                      "scripts"))
    try:
        import bench_compare
    finally:
        _sys.path.pop(0)

    def record(mesh=2, resident_h2d=512.0, host_groups=0):
        return {"t": 1.0, "value": 250000.0, "unit": "d/s",
                "metric": "m", "health": "pass", "planner_compiles": 0,
                "configs": {"10_steady_state_churn": {
                    "decisions_per_sec": 50000.0, "compiles": 0,
                    "streaming": {"enabled": True,
                                  "incremental_ticks": 5,
                                  "dirty_frac": 0.01,
                                  "resyncs": 1, "fallbacks": 0},
                    "pending_assigned_p99_s": 0.02,
                    "h2d_bytes_per_tick": 1000.0,
                    "planner_mesh": mesh,
                    "resident_h2d_bytes_per_tick": resident_h2d,
                    "strategy_host_groups": host_groups}}}

    hist = tmp_path / "hist.jsonl"

    def run(old, new):
        with open(hist, "w") as f:
            f.write(_json.dumps(old) + "\n")
            f.write(_json.dumps(new) + "\n")
        return bench_compare.main(["--history", str(hist)])

    assert run(record(), record()) == 0
    # a column re-upload per tick blows the dirty-scatter budget
    assert run(record(), record(resident_h2d=5.0e8)) == 1
    # any strategy group on the host oracle under a mesh fails
    assert run(record(), record(host_groups=3)) == 1
    # the gate is the MESH contract: single-device runs skip it
    assert run(record(), record(mesh=1, resident_h2d=5.0e8)) == 0
    # an old run that also blew the budget must not disarm the gate
    assert run(record(resident_h2d=5.0e8),
               record(resident_h2d=5.0e8)) == 1
