"""Network transport tests: remote agents + remote control over TCP, and
raft consensus over the TCP transport (the distributed communication
backend, SURVEY §5.8)."""

import os
import time

import pytest

from swarmkit_tpu.agent import Agent
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.manager import Manager
from swarmkit_tpu.manager.dispatcher import Config_
from swarmkit_tpu.models import (
    Annotations, Cluster, NodeState, ReplicatedService, Task, TaskState,
)
from swarmkit_tpu.models.types import NodeRole
from swarmkit_tpu.net import (
    ManagerServer, RemoteControlClient, RemoteDispatcherClient,
    TCPRaftTransport, issue_certificate,
)
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.state.store import ByName
from swarmkit_tpu.utils import new_id

from test_orchestrator import make_replicated, poll

from swarmkit_tpu.security.ca import HAVE_CRYPTOGRAPHY

requires_crypto = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="requires the 'cryptography' package")



def fast_cfg():
    return Config_(heartbeat_period=0.3, heartbeat_epsilon=0.02,
                   process_updates_interval=0.02,
                   assignment_batching_wait=0.02)


@requires_crypto
def test_remote_agent_and_control_over_tcp():
    """Full E2E over real sockets: join via token -> cert; agent sessions,
    heartbeats, assignment stream, status writeback; control client drives
    service lifecycle."""
    manager = Manager(dispatcher_config=fast_cfg(),
                      use_device_scheduler=False)
    manager.run()
    server = ManagerServer(manager)
    server.start()
    agent = None
    try:
        cluster = manager.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0]
        token = cluster.root_ca.join_tokens.worker

        # join over the network: token -> certificate
        node_id = new_id()
        cert = issue_certificate(server.addr, node_id, token)
        assert cert.node_id == node_id
        assert NodeRole(cert.role) == NodeRole.WORKER

        # bad token rejected
        with pytest.raises(Exception):
            issue_certificate(server.addr, new_id(), "SWMTKN-1-bad-bad")

        client = RemoteDispatcherClient(server.addr, cert)
        agent = Agent(node_id, TestExecutor(hostname="remote1"), client)
        agent.start()

        poll(lambda: manager.store.view(
            lambda tx: tx.get(
                __import__("swarmkit_tpu.models",
                           fromlist=["Node"]).Node, node_id)) is not None,
            msg="remote node should self-register")

        # the control surface is manager-role gated: a worker cert is
        # rejected, an operator needs a manager-token-issued cert
        with pytest.raises(PermissionError):
            RemoteControlClient(server.addr, cert).list_nodes()
        op_cert = issue_certificate(server.addr, new_id(),
                                    cluster.root_ca.join_tokens.manager)
        control = RemoteControlClient(server.addr, op_cert)
        svc = control.create_service(make_replicated("web", 3).spec)

        def running():
            tasks = control.list_tasks(service_id=svc.id)
            live = [t for t in tasks
                    if t.desired_state == TaskState.RUNNING]
            return (len(live) == 3
                    and all(t.status.state == TaskState.RUNNING
                            and t.node_id == node_id for t in live))
        poll(running, timeout=30,
             msg="remote agent should run all replicas via TCP")

        # scale down over the network
        cur = control.get_service(svc.id)
        spec = cur.spec.copy()
        spec.replicated = ReplicatedService(replicas=1)
        control.update_service(svc.id, cur.meta.version.index, spec)
        poll(lambda: len([t for t in control.list_tasks(service_id=svc.id)
                          if t.desired_state == TaskState.RUNNING]) == 1,
             timeout=30)
        control.close()
    finally:
        if agent is not None:
            agent.stop()
        server.stop()
        manager.stop()


@requires_crypto
def test_unauthenticated_connection_rejected():
    manager = Manager(dispatcher_config=fast_cfg(),
                      use_device_scheduler=False)
    manager.run()
    server = ManagerServer(manager)
    server.start()
    try:
        from swarmkit_tpu.security import RootCA
        foreign = RootCA().issue("evil", NodeRole.WORKER)
        with pytest.raises(PermissionError):
            RemoteControlClient(server.addr, foreign).list_nodes()
    finally:
        server.stop()
        manager.stop()


def test_raft_over_tcp(tmp_path):
    """3-member consensus over real TCP links."""
    from swarmkit_tpu.models import Node, NodeSpec
    from swarmkit_tpu.state.raft import RaftLogger, RaftNode

    ids = ["m0", "m1", "m2"]
    transports = {i: TCPRaftTransport(i) for i in ids}
    for i in ids:
        for j in ids:
            if i != j:
                transports[i].set_peer(j, transports[j].addr)
    members = {}
    for i in ids:
        store = MemoryStore()
        rn = RaftNode(i, ids, store,
                      RaftLogger(os.path.join(tmp_path, i)),
                      transports[i])
        store._proposer = rn
        members[i] = rn
        rn.start()
    try:
        # leader_ready: proposals before the election no-op applies are
        # dropped by design; wait for a proposal-ready leader
        leader = poll(
            lambda: next((m for m in members.values()
                          if m.is_leader and m.core.leader_ready), None)
            if sum(1 for m in members.values() if m.is_leader) == 1
            else None,
            timeout=20, msg="leader over TCP")
        for name in ("a", "b"):
            leader.store.update(lambda tx, name=name: tx.create(Node(
                id=new_id(),
                spec=NodeSpec(annotations=Annotations(name=name)))))

        def converged():
            for m in members.values():
                names = {n.spec.annotations.name
                         for n in m.store.view(lambda tx: tx.find(Node))}
                if names != {"a", "b"}:
                    return False
            return True
        poll(converged, timeout=20,
             msg="stores should converge over TCP links")
    finally:
        for m in members.values():
            m.stop()


@requires_crypto
def test_manager_raft_join_rpc(tmp_path):
    """A promoted node's manager joins the raft group over the network:
    manager-cert gated, returns peer addresses, membership grows."""
    import os

    from swarmkit_tpu.models.types import NodeRole
    from swarmkit_tpu.net import join_raft
    from swarmkit_tpu.state.raft import LocalNetwork, RaftLogger, RaftNode

    net = LocalNetwork()
    store = MemoryStore()
    rn = RaftNode("m0", ["m0"], store,
                  RaftLogger(os.path.join(tmp_path, "m0")), net)
    store._proposer = rn
    rn.start()
    poll(lambda: rn.is_leader, timeout=10)

    manager = Manager(store=store, raft_node=rn,
                      dispatcher_config=fast_cfg(),
                      use_device_scheduler=False)
    manager.raft_peer_addrs["m0"] = ("127.0.0.1", 12345)
    manager.run()
    server = ManagerServer(manager)
    server.start()
    try:
        poll(lambda: manager.is_leader, timeout=10)
        worker_cert = manager.root_ca.issue("joiner", NodeRole.WORKER)
        with pytest.raises(Exception):
            join_raft(server.addr, worker_cert, "joiner")

        mgr_cert = manager.root_ca.issue("m1", NodeRole.MANAGER)
        # the join wedges quorum until the member starts, so start it
        # right after the RPC
        import threading

        def start_member():
            store2 = MemoryStore()
            rn2 = RaftNode("m1", ["m0", "m1"], store2,
                           RaftLogger(os.path.join(tmp_path, "m1")), net)
            store2._proposer = rn2
            rn2.start()
            return rn2

        result = join_raft(server.addr, mgr_cert, "m1",
                           raft_addr=("127.0.0.1", 23456))
        assert "m0" in result["members"]
        assert "m1" in rn.core.peers
        rn2 = start_member()
        try:
            poll(lambda: rn2.core.commit_index > 0, timeout=15,
                 msg="joined manager should replicate")
        finally:
            rn2.stop()
    finally:
        server.stop()
        manager.stop()
        rn.stop()


@requires_crypto
def test_collect_logs_over_tcp():
    """service logs work through the remote control client too."""
    import tempfile as _tf

    from swarmkit_tpu.agent import Agent, ProcessExecutor

    manager = Manager(dispatcher_config=fast_cfg(),
                      use_device_scheduler=False)
    manager.run()
    server = ManagerServer(manager)
    server.start()
    agent = None
    try:
        cluster = manager.store.view(
            lambda tx: tx.find(Cluster, ByName("default")))[0]
        node_id = new_id()
        cert = issue_certificate(server.addr, node_id,
                                 cluster.root_ca.join_tokens.worker)
        client = RemoteDispatcherClient(server.addr, cert)
        agent = Agent(node_id, ProcessExecutor(
            hostname="tcp-logger", log_dir=_tf.mkdtemp()), client)
        agent.log_ship_interval = 0.1
        agent.start()

        op_cert = issue_certificate(server.addr, new_id(),
                                    cluster.root_ca.join_tokens.manager)
        control = RemoteControlClient(server.addr, op_cert)
        from swarmkit_tpu.models import (
            Annotations, ContainerSpec, ReplicatedService,
            RestartCondition, RestartPolicy, ServiceMode, ServiceSpec,
            TaskSpec,
        )
        svc = control.create_service(ServiceSpec(
            annotations=Annotations(name="wire-logger"),
            task=TaskSpec(container=ContainerSpec(
                image="process",
                command=["sh", "-c",
                         "for i in 1 2 3; do echo wire-$i; "
                         "sleep 0.4; done"]),
                restart=RestartPolicy(
                    condition=RestartCondition.NONE)),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=1)))
        poll(lambda: [t for t in control.list_tasks(service_id=svc.id)
                      if t.status.state >= TaskState.RUNNING] or None,
             timeout=20)
        msgs = control.collect_logs(svc.id, duration=4.0)
        data = b"".join(m["data"] for m in msgs)
        # live-only stream: lines published before the subscription are
        # not replayed, so any tick from the overlap window suffices
        assert b"wire-" in data, data
        control.close()
    finally:
        if agent is not None:
            agent.stop()
        server.stop()
        manager.stop()
