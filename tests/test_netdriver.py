"""Network-allocator driver seam tests (ROADMAP item 10 / ISSUE 15).

A registered driver — selected per network by
``NetworkSpec.driver_config`` — owns that network's subnet and address
lifecycle; the built-in IPAM stays the default (unchanged behavior),
``inert`` completes allocation without addressing, and release paths
route by network id back to the owning driver.
"""

import time

from swarmkit_tpu.manager.allocator import Allocator
from swarmkit_tpu.manager.controlapi import ControlAPI
from swarmkit_tpu.manager.netdriver import (
    InertNetworkDriver, NetworkDriver, NetworkDriverRegistry,
)
from swarmkit_tpu.models import (
    Annotations, Network, NetworkAttachmentConfig, Task, TaskState,
)
from swarmkit_tpu.models.specs import (
    ContainerSpec, NetworkSpec, ReplicatedService, ServiceMode,
    ServiceSpec, TaskSpec,
)
from swarmkit_tpu.models.types import (
    Driver, IPAMConfig, IPAMOptions, TaskStatus,
)
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.utils import new_id


def poll(fn, timeout=5.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(0.01)
    raise AssertionError(f"poll timed out: {msg}")


class FakeDriver(NetworkDriver):
    """Records every call; hands out predictable addresses."""

    name = "fake"

    def __init__(self):
        self.calls = []
        self._n = 0

    def allocate_network(self, net):
        self.calls.append(("allocate_network", net.id))
        return IPAMOptions(configs=[IPAMConfig(subnet="192.168.0.0/24",
                                               gateway="192.168.0.1")])

    def restore_network(self, net):
        self.calls.append(("restore_network", net.id))

    def release_network(self, network_id):
        self.calls.append(("release_network", network_id))

    def allocate_ip(self, network_id):
        self._n += 1
        addr = f"192.168.0.{self._n + 1}/24"
        self.calls.append(("allocate_ip", network_id, addr))
        return addr

    def restore_ip(self, network_id, addr):
        self.calls.append(("restore_ip", network_id, addr))

    def release_ip(self, network_id, addr):
        self.calls.append(("release_ip", network_id, addr))


def _service_spec(name, network_target):
    return ServiceSpec(
        annotations=Annotations(name=name),
        mode=ServiceMode.REPLICATED,
        replicated=ReplicatedService(replicas=1),
        task=TaskSpec(
            container=ContainerSpec(image="img"),
            networks=[NetworkAttachmentConfig(target=network_target)]))


def _new_task(svc, spec):
    return Task(id=new_id(), service_id=svc.id, slot=1,
                spec=spec.task.copy(),
                status=TaskStatus(state=TaskState.NEW),
                desired_state=TaskState.RUNNING)


def test_fake_driver_observes_allocate_and_free():
    """The seam's acceptance test: a registered fake driver sees the
    allocate/free calls for its networks — network subnet, service VIP
    and per-task address — while release routes back by network id."""
    store = MemoryStore()
    api = ControlAPI(store)
    alloc = Allocator(store)
    fake = FakeDriver()
    alloc.net_drivers.register("fake", fake)
    alloc.start()
    try:
        net = api.create_network(NetworkSpec(
            annotations=Annotations(name="fakenet"),
            driver_config=Driver(name="fake")))
        poll(lambda: store.view(
            lambda tx: tx.get(Network, net.id).ipam is not None),
            msg="fake network allocated")
        assert ("allocate_network", net.id) in fake.calls
        got = store.view(lambda tx: tx.get(Network, net.id))
        assert got.ipam.configs[0].subnet == "192.168.0.0/24"

        spec = _service_spec("fakesvc", "fakenet")
        svc = api.create_service(spec)
        poll(lambda: api.get_service(svc.id).endpoint is not None
             and api.get_service(svc.id).endpoint.virtual_ips,
             msg="VIP allocated")
        vip = api.get_service(svc.id).endpoint.virtual_ips[0]
        assert vip.addr.startswith("192.168.0.")
        assert ("allocate_ip", net.id, vip.addr) in fake.calls

        t = _new_task(svc, spec)
        store.update(lambda tx: tx.create(t))
        poll(lambda: store.view(
            lambda tx: tx.get(Task, t.id).status.state
            == TaskState.PENDING), msg="task allocated")
        task = store.view(lambda tx: tx.get(Task, t.id))
        assert task.networks and task.networks[0].addresses
        task_addr = task.networks[0].addresses[0]
        assert ("allocate_ip", net.id, task_addr) in fake.calls

        # frees route back to the owning driver by network id
        store.update(lambda tx: tx.delete(Task, t.id))
        poll(lambda: ("release_ip", net.id, task_addr) in fake.calls,
             msg="task address released")
        api.remove_service(svc.id)
        poll(lambda: ("release_ip", net.id, vip.addr) in fake.calls,
             msg="vip released")
        store.update(lambda tx: tx.delete(Network, net.id))
        poll(lambda: ("release_network", net.id) in fake.calls,
             msg="network released")
    finally:
        alloc.stop()


def test_inert_driver_allocates_without_addressing():
    """inert networks complete allocation (tasks reach PENDING) with no
    VIP addresses and no per-task addresses."""
    store = MemoryStore()
    api = ControlAPI(store)
    alloc = Allocator(store)
    alloc.start()
    try:
        net = api.create_network(NetworkSpec(
            annotations=Annotations(name="inertnet"),
            driver_config=Driver(name="inert")))
        poll(lambda: store.view(
            lambda tx: tx.get(Network, net.id).ipam is not None),
            msg="inert network allocated")
        assert store.view(
            lambda tx: tx.get(Network, net.id)).ipam.configs == []

        spec = _service_spec("inertsvc", "inertnet")
        svc = api.create_service(spec)
        poll(lambda: api.get_service(svc.id).endpoint is not None
             and api.get_service(svc.id).endpoint.virtual_ips,
             msg="VIP row present")
        vip = api.get_service(svc.id).endpoint.virtual_ips[0]
        assert vip.addr == ""    # row kept (needs-allocation math), no addr

        t = _new_task(svc, spec)
        store.update(lambda tx: tx.create(t))
        poll(lambda: store.view(
            lambda tx: tx.get(Task, t.id).status.state
            == TaskState.PENDING), msg="task allocated")
        task = store.view(lambda tx: tx.get(Task, t.id))
        assert task.networks and task.networks[0].addresses == []
    finally:
        alloc.stop()


def test_default_ipam_unchanged_and_unknown_name_falls_back():
    """Networks without a driver name keep the built-in IPAM exactly;
    an unknown driver name falls back to it (allocation must not wedge
    on a typo'd spec)."""
    store = MemoryStore()
    api = ControlAPI(store)
    alloc = Allocator(store)
    alloc.start()
    try:
        plain = api.create_network(NetworkSpec(
            annotations=Annotations(name="plain")))
        typo = api.create_network(NetworkSpec(
            annotations=Annotations(name="typo"),
            driver_config=Driver(name="no-such-driver")))
        poll(lambda: store.view(
            lambda tx: all(tx.get(Network, i).ipam is not None
                           for i in (plain.id, typo.id))),
            msg="both networks allocated")
        nets = store.view(lambda tx: [tx.get(Network, i)
                                      for i in (plain.id, typo.id)])
        subnets = [n.ipam.configs[0].subnet for n in nets]
        assert all(s.startswith("10.") and s.endswith("/24")
                   for s in subnets), subnets
        assert len(set(subnets)) == 2
    finally:
        alloc.stop()


def test_registry_binding_and_reset():
    reg = NetworkDriverRegistry(lambda: None)
    fake = FakeDriver()
    reg.register("fake", fake)
    net = Network(id="nid", spec=NetworkSpec(
        annotations=Annotations(name="n"),
        driver_config=Driver(name="fake")))
    assert reg.for_network(net) is fake
    assert reg.for_id("nid") is fake
    assert isinstance(reg.for_id("unknown"), NetworkDriver)
    assert reg.release_binding("nid") is fake
    assert reg.for_id("nid") is not fake   # binding gone -> default
    reg.for_network(net)
    reg.reset_bindings()
    assert reg.for_id("nid") is not fake
    assert isinstance(reg._drivers["inert"], InertNetworkDriver)
