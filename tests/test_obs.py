"""Observability layer: tracer, lifecycle tracker, metrics registry,
trace reports, and the tiny-bench smoke test.

Covers the obs PR's acceptance surface:
* exposition-format golden test for the registry (labeled + plain);
* Timer nearest-rank quantiles and in-place reset;
* span-tree well-formedness (parent links, containment);
* virtual-clock determinism: same sim seed ⇒ byte-identical trace;
* lifecycle latency through the real task FSM edge sequence;
* Collector labeled gauges surviving EventSnapshotRestore recounts;
* bench smoke: a tiny config emits a schema-valid Chrome trace whose
  phases appear in the artifact's phase table.
"""

import importlib
import json
import os
import sys

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    Resources, Task, TaskState, TaskStatus, Version,
)
from swarmkit_tpu.obs import (
    LifecycleTracker, Tracer, phase_table, validate_chrome_trace,
)
from swarmkit_tpu.obs.report import x_events
from swarmkit_tpu.sim.clock import VirtualClock
from swarmkit_tpu.state.events import Event, EventSnapshotRestore
from swarmkit_tpu.state.store import MemoryStore
from swarmkit_tpu.utils.metrics import Registry, Timer


# ------------------------------------------------------------------ registry

def test_exposition_golden():
    reg = Registry()
    reg.counter("foo")
    reg.counter('bar{kind="x"}', 2)
    reg.gauge("g", 1.5)
    reg.gauge('h{state="up"}', 3)
    reg.timer("t").observe(0.25)
    reg.timer('lt{edge="a_b"}').observe(0.5)
    expected = "\n".join([
        'bar_total{kind="x"} 2',
        "foo_total 1",
        "g 1.5",
        'h{state="up"} 3',
        'lt_seconds{edge="a_b",quantile="0.5"} 0.500000',
        'lt_seconds{edge="a_b",quantile="0.9"} 0.500000',
        'lt_seconds{edge="a_b",quantile="0.99"} 0.500000',
        'lt_seconds_count{edge="a_b"} 1',
        'lt_seconds_sum{edge="a_b"} 0.500000',
        't_seconds{quantile="0.5"} 0.250000',
        't_seconds{quantile="0.9"} 0.250000',
        't_seconds{quantile="0.99"} 0.250000',
        "t_seconds_count 1",
        "t_seconds_sum 0.250000",
    ]) + "\n"
    assert reg.expose() == expected


def test_timer_nearest_rank_quantiles():
    t = Timer()
    for v in range(1, 11):
        t.observe(float(v))
    q = t.quantiles()
    assert q[0.5] == 5.0          # was 6.0 with the int(q*n) index
    assert q[0.9] == 9.0
    assert q[0.99] == 10.0        # p99 of <100 samples is the max
    t2 = Timer()
    t2.observe(7.0)
    assert t2.quantiles() == {0.5: 7.0, 0.9: 7.0, 0.99: 7.0}


def test_timer_and_registry_reset_in_place():
    reg = Registry()
    held = reg.timer("x")          # component-held reference
    held.observe(1.0)
    reg.counter("c", 5)
    reg.gauge("g", 2)
    reg.reset()
    assert held.count == 0 and held.total == 0.0
    assert reg.get_counter("c") == 0.0
    assert reg.timer("x") is held  # same object after reset
    held.observe(2.0)
    assert held.quantiles()[0.5] == 2.0


# -------------------------------------------------------------------- tracer

def test_span_tree_well_formedness():
    tr = Tracer()
    tr.reset()
    tr.enable()
    with tr.span("a", "t"):
        with tr.span("b", "t"):
            pass
        with tr.span("c", "t", n=3):
            pass
    with tr.span("d", "t"):
        pass
    tr.disable()
    spans = {s.name: s for s in tr.spans()}
    assert spans["b"].parent_id == spans["a"].span_id
    assert spans["c"].parent_id == spans["a"].span_id
    assert spans["a"].parent_id == 0
    assert spans["d"].parent_id == 0
    for child in ("b", "c"):
        assert spans["a"].start <= spans[child].start
        assert spans[child].end <= spans["a"].end
    assert spans["c"].args == {"n": 3}
    doc = tr.to_chrome()
    assert validate_chrome_trace(doc) == []
    # disabled tracer records nothing
    with tr.span("ghost", "t"):
        pass
    assert "ghost" not in {s.name for s in tr.spans()}


def test_live_snapshot_and_reset_mid_span():
    tr = Tracer()
    tr.reset()
    tr.enable()
    outer = tr.start_span("open_outer", "t")
    with tr.span("closed_child", "t"):
        pass
    # live snapshot while outer is still open: the open span is exported
    # as incomplete, so the child's parent_id resolves and the document
    # validates
    doc = tr.to_chrome()
    assert validate_chrome_trace(doc) == []
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e.get("ph") == "X"}
    assert by_name["open_outer"]["args"].get("incomplete") is True
    assert by_name["closed_child"]["args"]["parent_id"] == outer.span_id

    # reset while a span is open: ending it afterwards must not export a
    # pre-epoch (negative-ts) span into the new session
    stale = tr.start_span("stale", "t")
    tr.reset()
    tr.enable()
    tr.end_span(stale)
    assert "stale" not in {s.name for s in tr.spans()}
    assert tr.dropped == 1
    assert validate_chrome_trace(tr.to_chrome()) == []


def test_phase_overlap_merges_concurrent_spans():
    """Concurrent spans of the same phase (the pipelining PR will emit
    them from worker threads) must not double-count: the hidden fraction
    is bounded by 1.0."""
    def ev(name, ts, dur, sid):
        return {"name": name, "cat": "p", "ph": "X", "ts": ts,
                "dur": dur, "pid": 1, "tid": 1,
                "args": {"span_id": sid, "parent_id": 0}}

    doc = {"traceEvents": [
        ev("plan.dispatch", 0, 100, 1),     # two overlapping plan spans
        ev("plan.dispatch", 0, 100, 2),
        ev("sched.commit", 0, 100, 3),
    ]}
    table = phase_table(doc)
    assert table["plan_wall_s"] == 100 / 1e6
    assert table["plan_commit_overlap_s"] == 100 / 1e6
    assert table["plan_hidden_frac"] == 1.0


def test_sim_trace_determinism_and_content():
    from swarmkit_tpu.sim.scenario import run_scenario

    r1 = run_scenario("crash-leader-mid-commit", seed=3)
    r2 = run_scenario("crash-leader-mid-commit", seed=3)
    assert r1.obs_trace == r2.obs_trace          # byte-identical
    assert r1.obs_trace_sha256 == r2.obs_trace_sha256
    # the span trace is a function of the seed where the seed shapes the
    # control-plane workload (random-fuzz draws task counts from it)
    f0 = run_scenario("random-fuzz", seed=0)
    f1 = run_scenario("random-fuzz", seed=1)
    assert f0.obs_trace != f1.obs_trace
    doc = json.loads(r1.obs_trace)
    assert validate_chrome_trace(doc) == []
    names = {e["name"] for e in x_events(doc)}
    # the control plane's phases are in the trace
    assert {"sched.tick", "sched.batch_build", "sched.commit"} <= names
    # every span closed within the run and parents contain children
    by_id = {e["args"]["span_id"]: e for e in x_events(doc)}
    for e in x_events(doc):
        pid = e["args"]["parent_id"]
        if pid:
            p = by_id[pid]
            assert p["ts"] <= e["ts"]
            assert e["ts"] + e["dur"] <= p["ts"] + p["dur"]


# ----------------------------------------------------------------- lifecycle

def _status(state, ts):
    return TaskStatus(state=state, timestamp=ts)


def test_lifecycle_latency_through_real_fsm():
    reg = Registry()
    tracker = LifecycleTracker(registry=reg)
    with VirtualClock(1000.0) as clk:
        store = MemoryStore()
        sub = store.queue.subscribe(accepts_blocks=True)
        t = Task(id="t1", service_id="s1", slot=1,
                 desired_state=TaskState.RUNNING,
                 status=_status(TaskState.PENDING, 1000.0),
                 spec_version=Version(index=1))
        store.update(lambda tx: tx.create(t))

        fsm = [(TaskState.ASSIGNED, 1000.5), (TaskState.ACCEPTED, 1000.6),
               (TaskState.PREPARING, 1000.8), (TaskState.READY, 1001.0),
               (TaskState.STARTING, 1001.1), (TaskState.RUNNING, 1002.1)]
        for state, ts in fsm:
            clk.advance_to(ts)

            def step(tx, state=state, ts=ts):
                cur = tx.get(Task, "t1").copy()
                cur.status = _status(state, ts)
                tx.update(cur)
            store.update(step)

        while True:
            ev = sub.poll()
            if ev is None:
                break
            tracker.handle_event(ev)

    summary = tracker.summary()
    assert summary["pending->assigned"]["count"] == 1
    assert abs(summary["pending->assigned"]["p50"] - 0.5) < 1e-9
    assert abs(summary["assigned->accepted"]["p50"] - 0.1) < 1e-9
    assert abs(summary["starting->running"]["p50"] - 1.0) < 1e-9
    # created->pending edge off meta.created_at (stamped at tx.create)
    assert summary["created->pending"]["count"] == 1

    # snapshot restore clears edge state: next sighting is a fresh task
    tracker.handle_event(EventSnapshotRestore())
    assert tracker._last == {}


def test_lifecycle_ignores_backward_and_terminal():
    reg = Registry()
    tracker = LifecycleTracker(registry=reg)
    t1 = Task(id="x", service_id="s", slot=1,
              status=_status(TaskState.RUNNING, 10.0))
    tracker.observe_task(t1)
    # backward write (never a forward edge)
    t2 = Task(id="x", service_id="s", slot=1,
              status=_status(TaskState.PENDING, 11.0))
    tracker.observe_task(t2)
    assert not any("running->" in k for k in tracker.summary())
    # terminal transition records the edge and forgets the task
    t3 = Task(id="x", service_id="s", slot=1,
              status=_status(TaskState.FAILED, 12.0))
    tracker.observe_task(t3)
    assert "running->failed" in tracker.summary()
    assert "x" not in tracker._last


# ----------------------------------------------------------------- collector

def test_collector_labeled_gauges_survive_restore():
    from swarmkit_tpu.manager.metrics import Collector
    from swarmkit_tpu.utils.metrics import registry as global_reg

    store = MemoryStore()

    def create(tx):
        tx.create(Node(id="n1",
                       spec=NodeSpec(annotations=Annotations(name="n1")),
                       status=NodeStatus(state=NodeState.READY),
                       description=NodeDescription(
                           hostname="n1", resources=Resources())))
        tx.create(Task(id="t1", service_id="s", slot=1,
                       status=_status(TaskState.RUNNING, 1.0)))
        tx.create(Task(id="t2", service_id="s", slot=2,
                       status=_status(TaskState.PENDING, 1.0)))

    store.update(create)
    c = Collector(store)
    c._recount()   # the same full recount EventSnapshotRestore triggers
    assert global_reg.gauges['swarm_manager_tasks{state="running"}'] == 1
    assert global_reg.gauges['swarm_manager_tasks{state="pending"}'] == 1
    assert global_reg.gauges['swarm_manager_nodes{state="ready"}'] == 1

    # a restore that dropped the RUNNING task must zero its label, not
    # leave the stale pre-restore value behind
    store.update(lambda tx: tx.delete(Task, "t1"))
    c._recount()
    assert global_reg.gauges['swarm_manager_tasks{state="running"}'] == 0
    assert global_reg.gauges['swarm_manager_tasks{state="pending"}'] == 1

    # incremental event handling keeps the labels live too
    store.update(lambda tx: tx.create(
        Task(id="t3", service_id="s", slot=3,
             status=_status(TaskState.RUNNING, 2.0))))
    c._handle(Event("create", store.raw_get(Task, "t3"), None))
    assert global_reg.gauges['swarm_manager_tasks{state="running"}'] == 1


# --------------------------------------------------------------- bench smoke

def test_bench_tiny_config_emits_valid_trace(tmp_path, monkeypatch,
                                             capsys):
    """Tier-1 smoke: a tiny bench run writes a schema-valid Chrome trace
    and the artifact's phase table reflects the trace's per-phase spans."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace_out = str(tmp_path / "trace.json")
    history_out = str(tmp_path / "history.jsonl")
    monkeypatch.setenv("BENCH_HISTORY", history_out)
    monkeypatch.setenv("BENCH_FLIGHTREC_OUT",
                       str(tmp_path / "flightrec.json"))
    monkeypatch.setenv("BENCH_NODES", "64")
    # large enough that the adaptive router always amortizes a device
    # round-trip (4096 tasks ≈ 200ms of host-path cost vs a launch
    # overhead of ~10ms even on a loaded CI box) — 512 was marginal and
    # flaked onto the host path under pytest load
    monkeypatch.setenv("BENCH_TASKS", "4096")
    monkeypatch.setenv("BENCH_TRIALS", "1")
    monkeypatch.setenv("BENCH_SKIP_HOST", "1")
    monkeypatch.setenv("BENCH_SKIP_CONFIGS", "1")
    monkeypatch.setenv("BENCH_SKIP_E2E", "1")
    monkeypatch.setenv("BENCH_TRACE_OUT", trace_out)
    monkeypatch.syspath_prepend(repo_root)
    import bench
    bench = importlib.reload(bench)   # re-read env-derived constants
    try:
        bench.main()
    finally:
        # leave the module with default constants for any later importer
        for k in ("BENCH_NODES", "BENCH_TASKS", "BENCH_TRIALS",
                  "BENCH_SKIP_HOST", "BENCH_SKIP_CONFIGS",
                  "BENCH_SKIP_E2E", "BENCH_TRACE_OUT", "BENCH_HISTORY",
                  "BENCH_FLIGHTREC_OUT"):
            monkeypatch.delenv(k, raising=False)
        importlib.reload(bench)

    artifact = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert artifact["trace_file"] == trace_out
    with open(trace_out) as f:
        doc = json.load(f)
    assert validate_chrome_trace(doc) == []

    trace_names = {e["name"] for e in x_events(doc)}
    assert {"sched.tick", "plan.dispatch", "plan.d2h",
            "sched.commit"} <= trace_names

    table = artifact["phase_table"]["headline"]
    # every phase row is backed by spans in the emitted trace, and the
    # device-plan phases made it into the table
    assert set(table["phases"]) <= trace_names
    assert "plan.dispatch" in table["phases"]
    assert "sched.commit" in table["phases"]
    assert table["plan_wall_s"] > 0
    # fresh table from the same file agrees with the embedded one
    recomputed = phase_table(doc, window=None)
    assert set(table["phases"]) <= set(recomputed["phases"])
    # overhead was measured (enabled vs disabled in the same run)
    assert "overhead_pct" in artifact["obs"]
    assert artifact["obs"]["enabled_decisions_per_sec"] > 0
    assert artifact["obs"]["disabled_decisions_per_sec"] > 0

    # compile observability: the artifact names every jit bucket the
    # headline touched, and — warm-up done — none recompiled inside the
    # timed region (a nonzero count here IS the r4/r5 variance story)
    compiles = artifact["planner_compiles"]
    assert isinstance(compiles, dict) and compiles
    assert all(v == 0 for v in compiles.values()), compiles

    # health plane: a clean tiny-bench run reports every check passing
    assert artifact["health"]["status"] == "pass"
    assert artifact["health"]["checks"]
    assert all(s == "pass" for s in artifact["health"]["checks"].values())

    # the run appended one history record bench_compare.py can diff
    with open(history_out) as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == 1
    assert records[0]["value"] == artifact["value"]
    assert records[0]["health"] == "pass"
