"""Orchestrator layer tests: replicated/global reconciliation, restart
policy, rolling updates, task reaper.

Mirrors the reference's test strategy (manager/orchestrator/*/..._test.go):
real MemoryStore with nil proposer, orchestrators running their event loops,
assertions via store polling.  A FakeAgent stands in for the dispatcher+agent
pipeline by advancing task status to follow desired state.
"""

import threading
import time

import pytest

from swarmkit_tpu.models import (
    Annotations, Cluster, GlobalService, Node, NodeAvailability,
    NodeDescription, NodeSpec, NodeState, NodeStatus, ReplicatedService,
    Resources, RestartCondition, RestartPolicy, Service, ServiceMode,
    ServiceSpec, Task, TaskSpec, TaskState, TaskStatus, UpdateConfig,
    UpdateFailureAction, UpdateState, Version,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import now
from swarmkit_tpu.orchestrator import (
    GlobalOrchestrator, ReplicatedOrchestrator, TaskReaper,
)
from swarmkit_tpu.state import ByService, MemoryStore
from swarmkit_tpu.state.events import Event
from swarmkit_tpu.utils import new_id


def poll(cond, timeout=8.0, interval=0.05, msg="condition not met"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(msg)


def make_node(name, availability=NodeAvailability.ACTIVE,
              state=NodeState.READY, labels=None):
    return Node(
        id=new_id(),
        spec=NodeSpec(annotations=Annotations(name=name, labels=labels or {}),
                      availability=availability),
        status=NodeStatus(state=state),
        description=NodeDescription(hostname=name),
    )


def make_replicated(name, replicas, restart=None, update=None, image="img:1"):
    from swarmkit_tpu.models.specs import ContainerSpec
    return Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name=name),
            task=TaskSpec(container=ContainerSpec(image=image),
                          restart=restart or RestartPolicy(delay=0.05)),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=replicas),
            update=update,
        ),
        spec_version=Version(index=1),
    )


def make_global(name, constraints=None):
    from swarmkit_tpu.models.specs import ContainerSpec
    from swarmkit_tpu.models import Placement
    return Service(
        id=new_id(),
        spec=ServiceSpec(
            annotations=Annotations(name=name),
            task=TaskSpec(container=ContainerSpec(image="img:1"),
                          restart=RestartPolicy(delay=0.05),
                          placement=Placement(constraints=constraints or [])),
            mode=ServiceMode.GLOBAL,
        ),
        spec_version=Version(index=1),
    )


class FakeAgent:
    """Advances task status to follow desired state, like a worker would
    (tests/fakes pattern, reference: agent/testutils/fakes.go)."""

    def __init__(self, store):
        self.store = store
        self._stop = threading.Event()
        self._sub = store.queue.subscribe(
            lambda ev: isinstance(ev, Event) and isinstance(ev.obj, Task))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        from swarmkit_tpu.state.watch import Closed
        while not self._stop.is_set():
            try:
                ev = self._sub.get(timeout=0.1)
            except TimeoutError:
                continue
            except Closed:
                return
            if ev.action == "delete":
                continue
            self._advance(ev.obj.id)

    def _advance(self, task_id):
        def cb(tx):
            t = tx.get(Task, task_id)
            if t is None:
                return
            t = t.copy()
            if t.desired_state == TaskState.RUNNING and \
                    t.status.state < TaskState.RUNNING:
                t.status = TaskStatus(state=TaskState.RUNNING,
                                      timestamp=now(), message="started")
            elif t.desired_state >= TaskState.SHUTDOWN and \
                    TaskState.ASSIGNED <= t.status.state <= TaskState.RUNNING:
                t.status = TaskStatus(state=TaskState.SHUTDOWN,
                                      timestamp=now(), message="shutdown")
            else:
                return
            tx.update(t)
        try:
            self.store.update(cb)
        except Exception:
            pass

    def stop(self):
        self._stop.set()
        self.store.queue.unsubscribe(self._sub)
        self._thread.join(timeout=2)


@pytest.fixture
def store():
    s = MemoryStore()
    cluster = Cluster(id=new_id(),
                      spec=ClusterSpec(annotations=Annotations(
                          name="default")))
    s.update(lambda tx: tx.create(cluster))
    yield s
    s.close()


def tasks_of(store, svc):
    return store.view(lambda tx: tx.find(Task, ByService(svc.id)))


def live_tasks(store, svc):
    return [t for t in tasks_of(store, svc)
            if t.desired_state <= TaskState.RUNNING]


# ------------------------------------------------------------------ replicated

def test_replicated_creates_tasks(store):
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated("web", 3)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 3,
             msg="3 tasks should be created")
        got = tasks_of(store, svc)
        assert sorted(t.slot for t in got) == [1, 2, 3]
        assert all(t.desired_state == TaskState.RUNNING for t in got)
        assert all(t.status.state == TaskState.NEW for t in got)
    finally:
        orch.stop()


def test_replicated_scale_up_and_down(store):
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated("web", 2)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(live_tasks(store, svc)) == 2)

        cur = store.view(lambda tx: tx.get(Service, svc.id)).copy()
        cur.spec.replicated = ReplicatedService(replicas=5)
        store.update(lambda tx: tx.update(cur))
        poll(lambda: len(live_tasks(store, svc)) == 5,
             msg="scale up to 5")
        assert sorted(t.slot for t in live_tasks(store, svc)) == \
            [1, 2, 3, 4, 5]

        cur = store.view(lambda tx: tx.get(Service, svc.id)).copy()
        cur.spec.replicated = ReplicatedService(replicas=1)
        store.update(lambda tx: tx.update(cur))
        poll(lambda: len(live_tasks(store, svc)) == 1,
             msg="scale down to 1")
        removed = [t for t in tasks_of(store, svc)
                   if t.desired_state == TaskState.REMOVE]
        assert len(removed) == 4
    finally:
        orch.stop()


def test_replicated_restart_on_failure(store):
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated("web", 1)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 1)
        t0 = tasks_of(store, svc)[0]

        # simulate the agent reporting failure
        def fail(tx):
            t = tx.get(Task, t0.id).copy()
            t.status = TaskStatus(state=TaskState.FAILED, timestamp=now(),
                                  err="boom")
            tx.update(t)
        store.update(fail)

        def replaced():
            got = tasks_of(store, svc)
            news = [t for t in got if t.id != t0.id]
            olds = [t for t in got if t.id == t0.id]
            return (news and olds
                    and olds[0].desired_state == TaskState.SHUTDOWN
                    and news[0].slot == t0.slot)
        poll(replaced, msg="failed task should be replaced in same slot")

        # the replacement moves READY->RUNNING after the restart delay
        def replacement_running():
            news = [t for t in tasks_of(store, svc) if t.id != t0.id]
            return news and news[0].desired_state == TaskState.RUNNING
        poll(replacement_running,
             msg="replacement should reach desired RUNNING after delay")
    finally:
        orch.stop()


def test_replicated_restart_condition_none(store):
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated(
            "web", 1, restart=RestartPolicy(condition=RestartCondition.NONE))
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 1)
        t0 = tasks_of(store, svc)[0]

        def fail(tx):
            t = tx.get(Task, t0.id).copy()
            t.status = TaskStatus(state=TaskState.FAILED, timestamp=now())
            tx.update(t)
        store.update(fail)
        poll(lambda: tasks_of(store, svc)[0].desired_state
             == TaskState.SHUTDOWN)
        time.sleep(0.3)
        assert len(tasks_of(store, svc)) == 1, \
            "no replacement for restart-condition NONE"
    finally:
        orch.stop()


def test_replicated_node_down_restarts_elsewhere(store):
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        node = make_node("n1")
        store.update(lambda tx: tx.create(node))
        svc = make_replicated("web", 1)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 1)
        t0 = tasks_of(store, svc)[0]

        # pretend the scheduler assigned it and it ran on n1
        def assign(tx):
            t = tx.get(Task, t0.id).copy()
            t.node_id = node.id
            t.status = TaskStatus(state=TaskState.RUNNING, timestamp=now())
            tx.update(t)
        store.update(assign)

        def down(tx):
            n = tx.get(Node, node.id).copy()
            n.status = NodeStatus(state=NodeState.DOWN)
            tx.update(n)
        store.update(down)

        def replacement_created():
            got = tasks_of(store, svc)
            news = [t for t in got if t.id != t0.id]
            return news and not news[0].node_id
        poll(replacement_created,
             msg="task on downed node should be replaced with unassigned")
    finally:
        orch.stop()


def test_service_delete_marks_tasks_remove(store):
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated("web", 2)
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2)
        store.update(lambda tx: tx.delete(Service, svc.id))
        poll(lambda: all(t.desired_state == TaskState.REMOVE
                         for t in tasks_of(store, svc)),
             msg="deleted service's tasks should be marked REMOVE")
    finally:
        orch.stop()


# -------------------------------------------------------------------- global

def test_global_one_task_per_node(store):
    orch = GlobalOrchestrator(store)
    orch.start()
    try:
        n1, n2 = make_node("n1"), make_node("n2")
        store.update(lambda tx: (tx.create(n1), tx.create(n2)))
        svc = make_global("agent")
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2)
        got = tasks_of(store, svc)
        assert {t.node_id for t in got} == {n1.id, n2.id}
        assert all(t.slot == 0 for t in got)

        # a new node gets a task too
        n3 = make_node("n3")
        store.update(lambda tx: tx.create(n3))
        poll(lambda: len(tasks_of(store, svc)) == 3)
    finally:
        orch.stop()


def test_global_respects_constraints(store):
    orch = GlobalOrchestrator(store)
    orch.start()
    try:
        n1 = make_node("gpu1", labels={"gpu": "true"})
        n2 = make_node("cpu1")
        store.update(lambda tx: (tx.create(n1), tx.create(n2)))
        svc = make_global("gpu-agent",
                          constraints=["node.labels.gpu==true"])
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 1)
        assert tasks_of(store, svc)[0].node_id == n1.id
        time.sleep(0.3)
        assert len(tasks_of(store, svc)) == 1
    finally:
        orch.stop()


def test_global_drain_shuts_down_tasks(store):
    orch = GlobalOrchestrator(store)
    orch.start()
    try:
        n1, n2 = make_node("n1"), make_node("n2")
        store.update(lambda tx: (tx.create(n1), tx.create(n2)))
        svc = make_global("agent")
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2)

        def drain(tx):
            n = tx.get(Node, n1.id).copy()
            n.spec.availability = NodeAvailability.DRAIN
            tx.update(n)
        store.update(drain)

        def drained():
            got = tasks_of(store, svc)
            on_n1 = [t for t in got if t.node_id == n1.id]
            return on_n1 and all(t.desired_state >= TaskState.SHUTDOWN
                                 for t in on_n1)
        poll(drained, msg="tasks on drained node should be shut down")
    finally:
        orch.stop()


# ------------------------------------------------------------- rolling update

def test_rolling_update_replaces_tasks(store):
    agent = FakeAgent(store)
    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        svc = make_replicated(
            "web", 2, image="img:1",
            update=UpdateConfig(parallelism=1, monitor=0.1))
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(tasks_of(store, svc)) == 2)
        poll(lambda: all(t.status.state == TaskState.RUNNING
                         for t in live_tasks(store, svc)))

        # update the image
        def bump(tx):
            cur = tx.get(Service, svc.id).copy()
            cur.previous_spec = cur.spec
            cur.previous_spec_version = cur.spec_version
            cur.spec = cur.spec.copy()
            cur.spec.task.container.image = "img:2"
            cur.spec_version = Version(index=2)
            tx.update(cur)
        store.update(bump)

        def updated():
            live = live_tasks(store, svc)
            return (len(live) == 2
                    and all(t.spec.container.image == "img:2" for t in live)
                    and all(t.status.state == TaskState.RUNNING
                            for t in live))
        poll(updated, timeout=15, msg="all tasks should run img:2")

        cur = store.view(lambda tx: tx.get(Service, svc.id))
        poll(lambda: (store.view(lambda tx: tx.get(Service, svc.id))
                      .update_status.state == UpdateState.COMPLETED),
             msg="update status should complete")
    finally:
        orch.stop()
        agent.stop()


def test_rolling_update_failure_pauses(store):
    orch = ReplicatedOrchestrator(store)

    # agent that runs img:1 but fails img:2 tasks
    class FailingAgent(FakeAgent):
        def _advance(self, task_id):
            def cb(tx):
                t = tx.get(Task, task_id)
                if t is None:
                    return
                t = t.copy()
                if t.desired_state == TaskState.RUNNING and \
                        t.status.state < TaskState.RUNNING:
                    if t.spec.container.image == "img:2":
                        t.status = TaskStatus(state=TaskState.FAILED,
                                              timestamp=now(), err="crash")
                    else:
                        t.status = TaskStatus(state=TaskState.RUNNING,
                                              timestamp=now())
                elif t.desired_state >= TaskState.SHUTDOWN and \
                        TaskState.ASSIGNED <= t.status.state <= \
                        TaskState.RUNNING:
                    t.status = TaskStatus(state=TaskState.SHUTDOWN,
                                          timestamp=now())
                else:
                    return
                tx.update(t)
            try:
                self.store.update(cb)
            except Exception:
                pass

    agent = FailingAgent(store)
    orch.start()
    try:
        svc = make_replicated(
            "web", 2, image="img:1",
            update=UpdateConfig(parallelism=1, monitor=5.0,
                                failure_action=UpdateFailureAction.PAUSE),
            restart=RestartPolicy(condition=RestartCondition.NONE))
        store.update(lambda tx: tx.create(svc))
        poll(lambda: len(live_tasks(store, svc)) == 2)
        poll(lambda: all(t.status.state == TaskState.RUNNING
                         for t in live_tasks(store, svc)))

        def bump(tx):
            cur = tx.get(Service, svc.id).copy()
            cur.previous_spec = cur.spec
            cur.previous_spec_version = cur.spec_version
            cur.spec = cur.spec.copy()
            cur.spec.task.container.image = "img:2"
            cur.spec_version = Version(index=2)
            tx.update(cur)
        store.update(bump)

        poll(lambda: (store.view(lambda tx: tx.get(Service, svc.id))
                      .update_status is not None
                      and store.view(lambda tx: tx.get(Service, svc.id))
                      .update_status.state == UpdateState.PAUSED),
             timeout=15, msg="update should pause after failure")
    finally:
        orch.stop()
        agent.stop()


# ---------------------------------------------------------------- task reaper

def test_task_reaper_respects_retention_limit(store):
    # set retention limit to 2
    def set_limit(tx):
        from swarmkit_tpu.state import ByName
        c = tx.find(Cluster, ByName("default"))[0].copy()
        c.spec.orchestration.task_history_retention_limit = 2
        tx.update(c)
    store.update(set_limit)

    reaper = TaskReaper(store)
    reaper.start()
    try:
        svc = make_replicated("web", 1)
        store.update(lambda tx: tx.create(svc))

        # simulate a slot with 5 historic (dead) tasks + 1 running
        def add_history(tx):
            for i in range(5):
                t = Task(id=new_id(), service_id=svc.id, slot=1,
                         desired_state=TaskState.SHUTDOWN,
                         spec=svc.spec.task,
                         spec_version=Version(index=1),
                         status=TaskStatus(state=TaskState.SHUTDOWN,
                                           timestamp=now() - 100 + i))
                tx.create(t)
            live = Task(id=new_id(), service_id=svc.id, slot=1,
                        desired_state=TaskState.RUNNING,
                        spec=svc.spec.task, spec_version=Version(index=1),
                        status=TaskStatus(state=TaskState.RUNNING,
                                          timestamp=now()))
            tx.create(live)
        store.update(add_history)

        poll(lambda: len(tasks_of(store, svc)) == 2,
             msg=f"reaper should prune history to limit; have "
                 f"{len(tasks_of(store, svc))}")
    finally:
        reaper.stop()


def test_task_reaper_deletes_removed_tasks(store):
    reaper = TaskReaper(store)
    reaper.start()
    try:
        svc = make_replicated("web", 1)
        store.update(lambda tx: tx.create(svc))
        t = Task(id=new_id(), service_id=svc.id, slot=1,
                 desired_state=TaskState.RUNNING, spec=svc.spec.task,
                 spec_version=Version(index=1),
                 status=TaskStatus(state=TaskState.RUNNING))
        store.update(lambda tx: tx.create(t))

        # scale-down marks it REMOVE; the agent then reports SHUTDOWN
        def mark_remove(tx):
            cur = tx.get(Task, t.id).copy()
            cur.desired_state = TaskState.REMOVE
            tx.update(cur)
        store.update(mark_remove)

        def agent_shutdown(tx):
            cur = tx.get(Task, t.id).copy()
            cur.status = TaskStatus(state=TaskState.SHUTDOWN, timestamp=now())
            tx.update(cur)
        store.update(agent_shutdown)

        poll(lambda: store.view(lambda tx: tx.get(Task, t.id)) is None,
             msg="shut-down REMOVE task should be deleted")
    finally:
        reaper.stop()


def test_orchestrator_startup_fixes_inconsistent_tasks(store):
    """taskinit pass: the previous leader left (a) a task whose service was
    deleted and (b) a READY task whose restart delay already elapsed.
    Startup must fix both without deadlocking the store (regression: the
    check ran inside view_and_watch's critical section)."""
    svc = make_replicated("web", 1)
    orphan = Task(id=new_id(), service_id="gone-service", slot=1,
                  desired_state=TaskState.RUNNING, spec=svc.spec.task,
                  spec_version=Version(index=1),
                  status=TaskStatus(state=TaskState.RUNNING))
    ready = Task(id=new_id(), service_id=svc.id, slot=1,
                 desired_state=TaskState.READY, spec=svc.spec.task,
                 spec_version=Version(index=1),
                 status=TaskStatus(state=TaskState.ASSIGNED,
                                   timestamp=now() - 60))

    def setup(tx):
        tx.create(svc)
        tx.create(orphan)
        tx.create(ready)
    store.update(setup)

    orch = ReplicatedOrchestrator(store)
    orch.start()
    try:
        poll(lambda: store.view(lambda tx: tx.get(Task, orphan.id)) is None,
             msg="orphan task of deleted service should be removed")
        poll(lambda: (store.view(lambda tx: tx.get(Task, ready.id))
                      .desired_state == TaskState.RUNNING),
             msg="stranded READY task should be started")
        # the store must still accept writes (no deadlock)
        probe = make_node("probe")
        store.update(lambda tx: tx.create(probe))
    finally:
        orch.stop()
