"""Overload-protection plane + million-swarm harness (ISSUE 20).

Four claims are under test:

1. bounded admission — the dispatcher's session cap, update-buffer
   bound and terminal-assignment compaction each reject/evict at their
   declared bound, count every shed, and never corrupt an admitted
   session;
2. adaptive stretching — past the session threshold the advertised
   heartbeat period stretches (capped), and the expiry deadline honors
   the stretched promise;
3. graceful degradation end-to-end — the million-swarm scenario stays
   green and byte-identical across seeds with both overload invariants
   live, sheds exactly reconciled, and zero premature expirations;
4. checker sensitivity — flipping each seam (count_sheds,
   stretch_extends_deadline) makes the matching invariant fire, so a
   green sweep reflects checker coverage, not blindness.
"""

import os

import pytest

from swarmkit_tpu.manager.dispatcher import (
    Config_, Dispatcher, ErrOverloaded,
)
from swarmkit_tpu.models import (
    Annotations, Cluster, Task, TaskState, TaskStatus,
)
from swarmkit_tpu.models.specs import ClusterSpec
from swarmkit_tpu.models.types import now
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.utils import new_id

from test_scheduler import make_ready_node, make_service_with_tasks


@pytest.fixture
def store():
    s = MemoryStore()
    cluster = Cluster(id=new_id(), spec=ClusterSpec(
        annotations=Annotations(name="default")))
    s.update(lambda tx: tx.create(cluster))
    yield s
    s.close()


def overload_config(**kw):
    defaults = dict(heartbeat_period=5.0, heartbeat_epsilon=0.0,
                    grace_multiplier=3.0, rate_limit_period=0.0)
    defaults.update(kw)
    return Config_(**defaults)


def _mk_nodes(store, n):
    nodes = [make_ready_node(f"n{i:04d}") for i in range(n)]
    def setup(tx):
        for nd in nodes:
            tx.create(nd)
    store.update(setup)
    return nodes


def _mk_assigned_tasks(store, node_id, n, state=TaskState.ASSIGNED):
    svc, tasks = make_service_with_tasks(n)
    def setup(tx):
        tx.create(svc)
        for t in tasks:
            t.node_id = node_id
            t.status = TaskStatus(state=state, timestamp=now())
            tx.create(t)
    store.update(setup)
    return tasks


# ------------------------------------------------- bounded admission

def test_register_shed_at_session_cap(store):
    """The session bound sheds NEW nodes (counted), while an already-
    registered node's re-registration stays admitted at the cap: the
    bound limits concurrent sessions, it never evicts a live one."""
    nodes = _mk_nodes(store, 5)
    d = Dispatcher(store, overload_config(max_sessions=4))
    d.run(start_worker=False)
    try:
        for nd in nodes[:4]:
            d.register(nd.id)
        with pytest.raises(ErrOverloaded):
            d.register(nodes[4].id)
        assert d.stats["sheds"] == 1
        # the cap bounds sessions, not re-registrations
        sid, _period = d.register(nodes[0].id)
        assert sid
        assert d.stats["sheds"] == 1
    finally:
        d.stop()


def test_update_batch_shed_whole_counted_and_recoverable(store):
    """A status batch that would overflow max_pending_updates is shed
    WHOLE with ErrOverloaded: the shed is counted, already-buffered
    updates survive untouched, the session stays valid, and the same
    batch lands after a flush drains the buffer — degraded, never
    silently lossy."""
    (node,) = _mk_nodes(store, 1)
    tasks = _mk_assigned_tasks(store, node.id, 12)
    d = Dispatcher(store, overload_config(
        max_pending_updates=8, max_batch_items=1000))
    d.run(start_worker=False)
    try:
        sid, _ = d.register(node.id)
        ups = lambda ts: [(t.id, TaskStatus(state=TaskState.RUNNING,
                                            message="started",
                                            timestamp=now()))
                          for t in ts]
        d.update_task_status(node.id, sid, ups(tasks[:6]))
        assert len(d._task_updates) == 6
        with pytest.raises(ErrOverloaded):
            d.update_task_status(node.id, sid, ups(tasks[6:12]))
        assert d.stats["sheds"] == 6
        assert len(d._task_updates) == 6      # admitted work untouched
        # rewrites of already-buffered tasks never grow the buffer and
        # always land, even at the bound
        d.update_task_status(node.id, sid, ups(tasks[:6]))
        # the session survived the shed: heartbeat + retry both work
        d.heartbeat(node.id, sid)
        d._flush_updates()
        d.update_task_status(node.id, sid, ups(tasks[6:12]))
        d._flush_updates()
        running = [t for t in store.view(lambda tx: tx.find(Task))
                   if t.status.state == TaskState.RUNNING]
        assert len(running) == 12             # recovery is total
        assert d.stats["sheds"] == 6          # and exactly counted
    finally:
        d.stop()


def test_heartbeat_stretch_engages_and_extends_promise(store):
    """Past hb_stretch_start sessions the advertised period stretches
    linearly (capped at hb_stretch_max) and every stretched advertisement
    is counted; the expiry deadline extends with the stretched promise
    so slowing down can never expire a compliant agent early."""
    nodes = _mk_nodes(store, 8)
    d = Dispatcher(store, overload_config(
        hb_stretch_start=4, hb_stretch_max=3.0))
    d.run(start_worker=False)
    try:
        sids = {}
        for nd in nodes[:4]:
            sids[nd.id] = d.register(nd.id)[0]
        assert d._stretch_factor() == 1.0
        p0 = d.heartbeat(nodes[0].id, sids[nodes[0].id])
        assert p0 == pytest.approx(5.0)
        for nd in nodes[4:]:
            sids[nd.id] = d.register(nd.id)[0]
        assert d._stretch_factor() == pytest.approx(2.0)
        before = d.stats["hb_stretches"]
        p1 = d.heartbeat(nodes[0].id, sids[nodes[0].id])
        assert p1 == pytest.approx(10.0)      # 5.0 x stretch 2.0
        assert d.stats["hb_stretches"] > before
        # the deadline honors the stretched promise: window = period,
        # not period/stretch
        rn = d._nodes[nodes[0].id]
        assert rn.deadline == pytest.approx(now() + p1 * 3.0, abs=0.2)
        assert rn.promised_until == pytest.approx(rn.deadline, abs=0.2)
        assert d.stats["premature_expirations"] == 0
    finally:
        d.stop()


# -------------------------------------- batched fan-out memory bounds

def test_fanout_terminal_compaction_bounds_memory(store):
    """Terminal tasks beyond max_terminal_tasks are compacted out of the
    per-node assignment set as explicit removes: set memory stays
    O(assigned + bound) under churn instead of O(task history), and
    every eviction lands in the shared compaction counter."""
    (node,) = _mk_nodes(store, 1)
    tasks = _mk_assigned_tasks(store, node.id, 40)
    d = Dispatcher(store, overload_config(max_terminal_tasks=8))
    d.run(start_worker=False)
    fan = d.enable_batched_fanout()
    try:
        sid, _ = d.register(node.id)
        stream = fan.open(node.id, sid)
        first = stream.get(timeout=0)
        assert first.type == first.COMPLETE
        assert len(first.changes) == 40
        # churn: 30 of the 40 finish (terminal > RUNNING)
        def finish(tx):
            for t in tasks[:30]:
                t2 = tx.get(Task, t.id).copy()
                t2.status = TaskStatus(state=TaskState.COMPLETE,
                                       timestamp=now())
                tx.update(t2)
        store.update(finish)
        fan.flush()
        aset = fan._sets[node.id]
        assert fan.stats["compactions"] >= 22     # 30 terminal - bound 8
        assert len(aset._terminal) <= 8
        # O(assigned + bound): 10 live + <= 8 retained terminal
        assert len(aset.tasks) <= 18
    finally:
        d.stop()


def test_fanout_open_after_leader_gap_at_1k_sessions():
    """A re-elected leader's fresh dispatcher rebuilds every assignment
    stream from the store view: after a full leader gap, 1000 re-opened
    sessions each receive a COMPLETE set carrying exactly their node's
    assignments — nothing lost, nothing duplicated, and the rebuilt
    fan-out state stays O(assigned) per node."""
    s = MemoryStore()
    try:
        s.update(lambda tx: tx.create(Cluster(
            id=new_id(),
            spec=ClusterSpec(annotations=Annotations(name="default")))))
        n_nodes = 1000
        nodes = _mk_nodes(s, n_nodes)
        svc, tasks = make_service_with_tasks(2 * n_nodes)
        def setup(tx):
            tx.create(svc)
            for i, t in enumerate(tasks):
                t.node_id = nodes[i % n_nodes].id
                t.status = TaskStatus(state=TaskState.ASSIGNED,
                                      timestamp=now())
                tx.create(t)
        s.update(setup)

        def fleet_register(d, fan):
            sids = {nd.id: d.register(nd.id)[0] for nd in nodes}
            streams = {nid: fan.open(nid, sid)
                       for nid, sid in sids.items()}
            return sids, streams

        d1 = Dispatcher(s, overload_config(max_sessions=n_nodes + 8))
        d1.run(start_worker=False)
        fan1 = d1.enable_batched_fanout()
        _, streams1 = fleet_register(d1, fan1)
        assert fan1.stats["complete_sends"] == n_nodes
        d1.stop()                      # the leader gap

        d2 = Dispatcher(s, overload_config(max_sessions=n_nodes + 8))
        d2.run(start_worker=False)
        fan2 = d2.enable_batched_fanout()
        try:
            _, streams2 = fleet_register(d2, fan2)
            for nid, stream in streams2.items():
                msg = stream.get(timeout=0)
                assert msg.type == msg.COMPLETE
                got = sorted(c[2].id for c in msg.changes)
                want = sorted(t.id for t in tasks if t.node_id == nid)
                assert got == want
                assert len(fan2._sets[nid].tasks) == 2  # O(assigned)
        finally:
            d2.stop()
    finally:
        s.close()


# --------------------------------------- scheduler tick deadline budget

def test_scheduler_partial_tick_commits_cleanly():
    """A tick that overruns tick_budget_s commits the groups it already
    planned, defers the rest intact (counted), and later ticks finish
    the backlog: partial progress, no lost or double-planned task."""
    s = MemoryStore()
    try:
        nodes = [make_ready_node(f"n{i}", cpus=64) for i in range(4)]
        services = [make_service_with_tasks(6) for _ in range(5)]
        def setup(tx):
            for nd in nodes:
                tx.create(nd)
            for svc, tasks in services:
                tx.create(svc)
                for t in tasks:
                    tx.create(t)
        s.update(setup)
        sched = Scheduler(s, tick_budget_s=1e-9)
        s.view(sched._setup_tasks_list)
        n1 = sched.tick()
        assert 0 < n1 < 30          # partial: progress, not the world
        assert sched.stats["partial_ticks"] == 1
        assert sched.stats["deferred_tasks"] == 30 - n1
        total = n1
        for _ in range(10):
            if total >= 30:
                break
            total += sched.tick()
        assert total == 30
        assigned = [t for t in s.view(lambda tx: tx.find(Task))
                    if t.status.state == TaskState.ASSIGNED
                    and t.node_id]
        assert len(assigned) == 30   # nothing lost, nothing doubled
    finally:
        s.close()


# --------------------------------------------------- health conditions

def test_health_dispatcher_overload_condition():
    """warn while sheds are actively counted, fail only on sustained
    strict growth, pass/None before the overload plane exports."""
    from swarmkit_tpu.obs.health import dispatcher_overload_value
    from swarmkit_tpu.utils.metrics import Registry
    reg = Registry()
    get = dispatcher_overload_value(n=3)
    assert get(reg) is None                      # plane not exporting
    reg.counter("swarm_dispatcher_sheds", 5)
    assert get(reg) == 0.0                       # first sample: baseline
    reg.counter("swarm_dispatcher_sheds", 5)
    assert get(reg) == 1.0                       # growing: warn
    assert get(reg) == 0.0                       # flat: recovered
    reg.counter("swarm_dispatcher_sheds", 5)
    assert get(reg) == 1.0
    reg.counter("swarm_dispatcher_sheds", 5)
    assert get(reg) == 2.0     # strict growth across the window: fail


def test_health_heartbeat_stretch_condition():
    """fail the instant a premature expiration is counted; warn while
    the advertised stretch is material; pass otherwise."""
    from swarmkit_tpu.obs.health import heartbeat_stretch_value
    from swarmkit_tpu.utils.metrics import Registry
    reg = Registry()
    get = heartbeat_stretch_value(stretch_warn=2.0)
    assert get(reg) is None
    reg.gauge("swarm_dispatcher_hb_stretch", 1.2)
    assert get(reg) == 0.0
    reg.gauge("swarm_dispatcher_hb_stretch", 2.5)
    assert get(reg) == 1.0
    reg.counter("swarm_dispatcher_premature_expirations")
    assert get(reg) == 2.0     # a broken promise is an instant fail


# ------------------------------------------------ controlapi: resume

def test_resume_pipeline_errors_and_success(store):
    """resume_pipeline's exact error surface, and the success path:
    halted -> waiting with a fresh resumed_at watermark, poison ledger
    cleared on the stage AND its direct upstreams."""
    from swarmkit_tpu.manager.controlapi import (
        ControlAPI, FailedPrecondition, NotFound,
    )
    from swarmkit_tpu.models.objects import PipelineStatus

    api = ControlAPI(store)
    with pytest.raises(NotFound):
        api.resume_pipeline("nope")

    plain, _ = make_service_with_tasks(1)
    up, _ = make_service_with_tasks(1)
    stage, _ = make_service_with_tasks(1)
    stage.spec.depends_on = [up.spec.annotations.name]
    stage.pipeline_status = PipelineStatus(
        state="halted", reason="poisoned", updated_at=now(),
        failed_ids=["t1", "t2"])
    up.pipeline_status = PipelineStatus(
        state="ready", reason="", updated_at=now(), failed_ids=["t0"])
    def setup(tx):
        for svc in (plain, up, stage):
            tx.create(svc)
    store.update(setup)

    with pytest.raises(FailedPrecondition):
        api.resume_pipeline(plain.id)      # not a pipeline stage
    with pytest.raises(FailedPrecondition):
        api.resume_pipeline(up.id)         # upstream isn't halted
    got = api.resume_pipeline(stage.id)
    st = got.pipeline_status
    assert st.state == "waiting"
    assert st.failed_ids == [] and st.resumed_at is not None
    up2 = store.view(lambda tx: tx.get(type(up), up.id))
    assert up2.pipeline_status.state == "ready"     # state untouched
    assert up2.pipeline_status.failed_ids == []     # poison forgiven
    assert up2.pipeline_status.resumed_at == st.resumed_at


# --------------------------------- million-swarm scenario + sensitivity

def _small_swarm_env(monkeypatch, sessions=32, tasks=100):
    monkeypatch.setenv("SWARM_MILLION_SWARM_SESSIONS", str(sessions))
    monkeypatch.setenv("SWARM_MILLION_SWARM_TASKS", str(tasks))


def test_million_swarm_green_and_deterministic(monkeypatch):
    """The flagship overload scenario: full fan-out + leader crash +
    follower crash + drop burst + fleet churn over a mux fleet, green
    with both overload invariants live — sheds exactly reconciled
    against what clients observed, stretching engaged, zero premature
    expirations — and byte-identical on replay."""
    from swarmkit_tpu.sim import run_scenario
    _small_swarm_env(monkeypatch)
    a = run_scenario("million-swarm", seed=3)
    assert a.ok, a.violations
    ovl = a.stats["overload"]
    assert ovl["sheds"] > 0                       # the storm really shed
    assert ovl["sheds"] == ovl["client_sheds"]    # ledger reconciles
    assert ovl["hb_stretches"] > 0                # stretching engaged
    assert ovl["premature_expirations"] == 0      # promises honored
    assert a.stats["fleet"]["sessions"] == 32
    assert a.stats["fleet"]["max_concurrent_registrations"] < 32
    b = run_scenario("million-swarm", seed=3)
    assert (a.trace_hash, a.obs_trace_sha256) \
        == (b.trace_hash, b.obs_trace_sha256)


def _run_seeded_swarm(seed, flip):
    """Run million-swarm manually with a seam flipped pre-attach."""
    from swarmkit_tpu.sim.cluster import Sim
    from swarmkit_tpu.sim.faults import NetConfig
    from swarmkit_tpu.sim.scenario import SCENARIOS
    sim = Sim(seed, net_config=NetConfig(), raft_cp=True)
    with sim:
        flip(sim.cp)
        duration = SCENARIOS["million-swarm"](sim)
        sim.run(duration)
        sim.finish(grace=20.0)
    return sim


def test_checker_fires_when_sheds_go_uncounted(monkeypatch):
    """Seam: shed WITHOUT counting (the silent-loss bug).  The
    overload-sheds-are-counted-and-recovered invariant must flag the
    client-observed sheds the dispatcher ledger never covered —
    proving a green sweep reflects checker sensitivity."""
    _small_swarm_env(monkeypatch)
    def flip(cp):
        cp.count_sheds = False
    sim = _run_seeded_swarm(3, flip)
    assert any("overload-sheds-are-counted-and-recovered" in v
               for v in sim.violations.items), (
        "checker failed to flag uncounted sheds:\n"
        + "\n".join(sim.violations.items[:5]))


def test_checker_fires_on_broken_stretch_promise(monkeypatch):
    """Seam: advertise the stretched period but enforce the UNstretched
    expiry deadline.  A fleet agent that crashes for less than its
    promised window gets expired inside the promise — the
    heartbeat-liveness-under-stretch invariant must fire."""
    _small_swarm_env(monkeypatch)
    def flip(cp):
        cp.stretch_extends_deadline = False
    sim = _run_seeded_swarm(3, flip)
    assert any("heartbeat-liveness-under-stretch" in v
               for v in sim.violations.items), (
        "checker failed to flag the premature expiry:\n"
        + "\n".join(sim.violations.items[:5]))


def test_mux_fleet_thundering_herd_bounded(monkeypatch):
    """Satellite: a leader failover must NOT re-register the whole
    fleet inside one driver tick — each agent's seeded re-registration
    jitter spreads the herd, pinned by the fleet's own peak counter."""
    from swarmkit_tpu.sim.cluster import MuxAgentFleet, Sim
    from swarmkit_tpu.sim.faults import NetConfig
    n = 32
    sim = Sim(19, net_config=NetConfig(), raft_cp=True)
    with sim:
        eng = sim.engine
        fleet = MuxAgentFleet(sim.cp, n, interval=1.0,
                              driver_interval=0.25, rpc_budget=64)
        sim.run(12.0)          # elect, bootstrap, register the fleet
        lead = sim.leader()
        assert lead is not None
        lead.crash()
        eng.after(5.0, "restart ex-leader", lead.restart)
        sim.run(25.0)          # failover + full re-registration wave
        sim.finish(grace=20.0)
    assert sim.violations.items == []
    assert fleet.stats["steps"] > 0
    herd = fleet.stats["max_concurrent_registrations"]
    assert 1 <= herd < n, (
        f"failover re-registered {herd}/{n} sessions in one driver "
        "tick: the jitter spread collapsed")
