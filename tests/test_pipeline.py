"""Differential tests for the pipelined scheduler.

The pipelined tick (SWARM_PIPELINE_DEPTH > 1) overlaps group i+1's
device plan with group i's host commit; the contract is that pipelining
changes ONLY wall-clock interleaving — placements, store state, and the
watch-event stream must be byte-identical to the serial path (depth 1)
for the same workload.  These tests build seeded workloads under a
frozen time source and compare depth 1 vs 2 vs 4 end to end, including
the host-fallback and conflict/rollback routes, standalone and with a
real raft proposer (chunk-pipelined block proposals).
"""

import os
import random
import shutil
import tempfile
import time

import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    Placement, PlacementPreference, Platform, ReplicatedService, Resources,
    ResourceRequirements, Service, ServiceMode, ServiceSpec, SpreadOver,
    Task, TaskSpec, TaskState, TaskStatus, Version,
)
from swarmkit_tpu.models import types as model_types
from swarmkit_tpu.ops import TPUPlanner
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.state import MemoryStore
from swarmkit_tpu.state.events import Event, EventCommit, EventTaskBlock


@pytest.fixture
def frozen_clock():
    """Pin models.types.now() so snapshots/events carry identical
    timestamps across the runs being diffed."""
    model_types.set_time_source(lambda: 1_700_000_000.0)
    try:
        yield
    finally:
        model_types.set_time_source(None)


def _mk_nodes(n):
    return [Node(
        id=f"n{i:04d}",
        spec=NodeSpec(annotations=Annotations(
            name=f"node-{i:04d}", labels={"rack": f"r{i % 5}",
                                          "row": f"w{i % 3}",
                                          "hall": f"h{i % 2}",
                                          "site": f"s{i % 2}",
                                          "zone": f"z{i % 4}"})),
        status=NodeStatus(state=NodeState.READY),
        description=NodeDescription(
            hostname=f"node-{i:04d}",
            platform=Platform(os="linux", architecture="amd64"),
            resources=Resources(nano_cpus=16 * 10**9,
                                memory_bytes=64 << 30)))
        for i in range(n)]


def _mk_service(sid, n_tasks, spec=None, spec_version=1):
    svc = Service(
        id=sid,
        spec=ServiceSpec(annotations=Annotations(name=f"svc-{sid}"),
                         mode=ServiceMode.REPLICATED,
                         replicated=ReplicatedService(replicas=n_tasks),
                         task=spec or TaskSpec()),
        spec_version=Version(index=spec_version))
    tasks = [Task(id=f"{sid}-t{k:04d}", service_id=sid, slot=k + 1,
                  desired_state=TaskState.RUNNING, spec=svc.spec.task,
                  spec_version=Version(index=spec_version),
                  status=TaskStatus(state=TaskState.PENDING))
             for k in range(n_tasks)]
    return svc, tasks


def _build_workload(seed):
    """Seeded multi-group workload covering the device route, the
    host-fallback route (node.ip constraint -> unsupported; 5-level
    spread -> host placement), and one-off (no spec-version) groups."""
    rng = random.Random(seed)
    store = MemoryStore()
    nodes = _mk_nodes(48)

    def mk(tx):
        for n in nodes:
            tx.create(n)

    store.update(mk)

    device_spec = TaskSpec(resources=ResourceRequirements(
        reservations=Resources(nano_cpus=10**8, memory_bytes=64 << 20)))
    spread_spec = TaskSpec(placement=Placement(preferences=[
        PlacementPreference(spread=SpreadOver(
            spread_descriptor=f"node.labels.{k}"))
        for k in ("rack", "row", "hall", "site", "zone")]))  # 5 levels
    ip_spec = TaskSpec(placement=Placement(
        constraints=["node.ip!=192.168.0.1"]))

    batches = [
        _mk_service("svca", 200 + rng.randrange(50), device_spec),
        _mk_service("svcb", 150 + rng.randrange(50), device_spec),
        _mk_service("svcc", 100 + rng.randrange(30), spread_spec),
        _mk_service("svcd", 20, ip_spec),
        _mk_service("svce", 120 + rng.randrange(40), device_spec),
    ]

    def mk2(tx):
        for svc, tasks in batches:
            tx.create(svc)
            for t in tasks:
                tx.create(t)
        # one-off tasks: no spec_version -> scheduled as single groups
        for j in range(3):
            tx.create(Task(id=f"oneoff-{j}", service_id="svca",
                           slot=900 + j, desired_state=TaskState.RUNNING,
                           spec=device_spec,
                           status=TaskStatus(state=TaskState.PENDING)))

    store.update(mk2)
    return store


def _event_key(ev):
    if isinstance(ev, EventTaskBlock):
        return ("block", tuple(o.id for o in ev.olds),
                tuple(ev.node_ids), ev.base_version, ev.state, ev.message)
    if isinstance(ev, EventCommit):
        return ("commit", ev.version)
    if isinstance(ev, Event):
        obj = ev.obj
        return (ev.action, obj.id, getattr(obj, "node_id", None),
                int(obj.status.state) if hasattr(obj, "status") else None,
                obj.meta.version.index)
    return ("other", repr(ev))


def _run_tick(store, depth, pre_tick=None, ticks=1):
    sub = store.queue.subscribe(accepts_blocks=True)
    planner = TPUPlanner()
    planner.enable_small_group_routing = False  # deterministic routing
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=depth)
    store.view(sched._setup_tasks_list)
    if pre_tick is not None:
        pre_tick(store, sched)
    decisions = 0
    for _ in range(ticks):
        decisions += sched.tick()
    events = [_event_key(e) for e in sub.drain()]
    store.queue.unsubscribe(sub)
    tasks = store.view(lambda tx: tx.find(Task))
    state = sorted((t.id, t.node_id, int(t.status.state),
                    t.status.message, t.meta.version.index)
                   for t in tasks)
    return decisions, state, events, sched, planner


@pytest.mark.parametrize("depth", [2, 4])
@pytest.mark.parametrize("seed", [7, 23])
def test_pipelined_tick_byte_identical_to_serial(frozen_clock, depth,
                                                 seed):
    """Depth-N placements, store snapshot bytes, and watch-event streams
    must equal the serial path's, across multi-group workloads that also
    exercise host-fallback routes."""
    d1, s1, e1, sched1, _ = _run_tick(_build_workload(seed), 1)
    dn, sn, en, schedn, _ = _run_tick(_build_workload(seed), depth)
    assert dn == d1
    assert sn == s1
    assert en == e1
    # mirror state converged identically too (requeues, all_tasks)
    assert sorted(schedn.unassigned_tasks) == sorted(
        sched1.unassigned_tasks)
    # snapshot bytes: the strongest store-state equality
    b1 = _run_tick(_build_workload(seed), 1)[3].store.save_bytes()
    bn = _run_tick(_build_workload(seed), depth)[3].store.save_bytes()
    assert b1 == bn


def test_pipelined_conflict_rollback_matches_serial(frozen_clock):
    """A mid-flight concurrent assignment (stale mirror version) must
    fail the block item, roll back mirrors, and requeue — identically in
    serial and pipelined mode, across two ticks."""
    def conflict(store, sched):
        def cb(tx):
            for tid in ("svca-t0000", "svcb-t0001"):
                cur = tx.get(Task, tid).copy()
                cur.node_id = "n0000"
                cur.status = TaskStatus(state=TaskState.ASSIGNED,
                                        timestamp=1.0,
                                        message="concurrent writer")
                tx.update(cur)
        store.update(cb)

    d1, s1, e1, sched1, _ = _run_tick(_build_workload(5), 1,
                                      pre_tick=conflict, ticks=2)
    d2, s2, e2, sched2, _ = _run_tick(_build_workload(5), 2,
                                      pre_tick=conflict, ticks=2)
    assert (d1, s1, e1) == (d2, s2, e2)
    # the conflicting tasks were requeued rather than lost or committed
    assert "svca-t0000" in sched1.unassigned_tasks
    assert sorted(sched2.unassigned_tasks) == sorted(
        sched1.unassigned_tasks)


def test_pipelined_raft_chunked_proposals_match_serial(frozen_clock,
                                                      tmp_path):
    """With a real single-voter raft proposer, chunk-pipelined block
    proposals (depth 4, small chunks) must produce the same task states
    and event stream as serial propose-per-chunk."""
    from swarmkit_tpu.state.raft import LocalNetwork, RaftLogger, RaftNode

    def run(depth, sub_dir):
        store = _build_workload(11)
        rn = RaftNode("b0", ["b0"], store,
                      RaftLogger(str(tmp_path / sub_dir)), LocalNetwork())
        store._proposer = rn
        store.pipeline_depth = depth
        store.BLOCK_PROPOSAL_MAX_ITEMS = 64   # force several chunks
        rn.start()
        deadline = time.time() + 15
        while not (rn.is_leader and rn.core.leader_ready):
            assert time.time() < deadline, "raft leader not ready"
            time.sleep(0.01)
        try:
            return _run_tick(store, depth)
        finally:
            rn.stop()

    d1, s1, e1, *_ = run(1, "d1")
    d4, s4, e4, *_ = run(4, "d4")
    assert d4 == d1
    assert s4 == s1
    assert e4 == e1


def test_propose_async_preserves_order(tmp_path):
    """propose_async submissions from one thread commit and run their
    apply-path callbacks in submission order."""
    from swarmkit_tpu.state.raft import LocalNetwork, RaftLogger, RaftNode
    from swarmkit_tpu.state.store import StoreAction

    store = MemoryStore()
    rn = RaftNode("a0", ["a0"], store, RaftLogger(str(tmp_path / "a0")),
                  LocalNetwork())
    rn.start()
    deadline = time.time() + 15
    while not (rn.is_leader and rn.core.leader_ready):
        assert time.time() < deadline
        time.sleep(0.01)
    try:
        applied = []
        node = _mk_nodes(1)[0]
        waiters = [
            rn.propose_async([StoreAction("create", node)],
                             lambda i=i: applied.append(i))
            for i in range(6)]
        for w in waiters:
            rn.wait_proposal(w)
        assert applied == list(range(6))
    finally:
        rn.stop()


def test_pipeline_depth_escape_hatch(monkeypatch):
    """SWARM_PIPELINE_DEPTH=1 reverts every consumer to serial."""
    from swarmkit_tpu.utils.pipeline import default_pipeline_depth

    monkeypatch.setenv("SWARM_PIPELINE_DEPTH", "1")
    assert default_pipeline_depth() == 1
    assert Scheduler(MemoryStore()).pipeline_depth == 1
    assert MemoryStore().pipeline_depth == 1
    monkeypatch.setenv("SWARM_PIPELINE_DEPTH", "4")
    assert Scheduler(MemoryStore()).pipeline_depth == 4
    monkeypatch.setenv("SWARM_PIPELINE_DEPTH", "bogus")
    assert default_pipeline_depth() == 2
    monkeypatch.delenv("SWARM_PIPELINE_DEPTH")
    assert default_pipeline_depth() == 2
    # explicit constructor depth wins over the env
    monkeypatch.setenv("SWARM_PIPELINE_DEPTH", "8")
    assert Scheduler(MemoryStore(), pipeline_depth=1).pipeline_depth == 1


def test_planner_inflight_queue_discipline(frozen_clock):
    """dispatch/fetch must run FIFO, and dispatching over an unfetched
    plan is rejected (its apply feeds the next group's columns)."""
    store = _build_workload(3)
    planner = TPUPlanner()
    planner.enable_small_group_routing = False
    sched = Scheduler(store, batch_planner=planner, pipeline_depth=1)
    store.view(sched._setup_tasks_list)
    groups = dict(sched.unassigned_groups)
    sched.unassigned_groups = {}
    sched.unassigned_tasks.clear()
    (k1, g1), (k2, g2) = list(groups.items())[:2]
    decisions = {}
    planner.begin_tick(sched)
    h1 = planner.dispatch_group(sched, dict(g1), decisions)
    assert h1 is not None
    with pytest.raises(RuntimeError):
        planner.dispatch_group(sched, dict(g2), decisions)
    assert planner.fetch_group(h1) is True
    planner.discard_inflight()
    planner.end_tick()


def test_bench_compare_overlap_gate(tmp_path, capsys):
    """bench_compare exits nonzero when overlap regresses to 0 while
    the pipeline flag is on, and passes otherwise."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        import bench_compare
    finally:
        sys.path.pop(0)

    def record(hidden, depth, dps=250000.0, src="cfg6"):
        return {"t": 1.0, "value": dps, "unit": "d/s",
                "metric": "m", "health": "pass",
                "configs": {"6_live_manager_2x100k_x_10k":
                            {"decisions_per_sec": dps}},
                "pipeline_depth": depth, "plan_hidden_frac": hidden,
                "plan_commit_overlap_s": hidden * 0.1,
                "plan_overlap_source": src}

    import json
    hist = tmp_path / "hist.jsonl"
    with open(hist, "w") as f:
        for rec in (record(0.5, 2), record(0.0, 2)):
            f.write(json.dumps(rec) + "\n")
    assert bench_compare.main(["--history", str(hist)]) == 1

    with open(hist, "w") as f:
        for rec in (record(0.5, 2), record(0.45, 2)):
            f.write(json.dumps(rec) + "\n")
    assert bench_compare.main(["--history", str(hist)]) == 0

    # the gate must not disarm after one bad run: a zero-overlap
    # baseline followed by another zero-overlap pipelined run still
    # fails (the new run alone is judged)
    with open(hist, "w") as f:
        for rec in (record(0.0, 2), record(0.0, 2)):
            f.write(json.dumps(rec) + "\n")
    assert bench_compare.main(["--history", str(hist)]) == 1

    # serial escape hatch: overlap 0 is expected, not a regression
    with open(hist, "w") as f:
        for rec in (record(0.5, 2), record(0.0, 1)):
            f.write(json.dumps(rec) + "\n")
    assert bench_compare.main(["--history", str(hist)]) == 0

    # headline-window measurement (no cfg6, single group): overlap 0 is
    # structural, not a regression
    with open(hist, "w") as f:
        for rec in (record(0.0, 2, src="headline"),
                    record(0.0, 2, src="headline")):
            f.write(json.dumps(rec) + "\n")
    assert bench_compare.main(["--history", str(hist)]) == 0
    capsys.readouterr()
