"""ISSUE 17: per-plane saturation metrics + causal task journeys.

Covers the PlaneStats window/probe contract, the journey ledger's
milestone grammar and critical-path attribution, checker sensitivity
for the two new saturation SLO checks (a stalled committer and a
saturated scheduler plane MUST fail; their healthy twins MUST stay
green), PYTHONHASHSEED-independence of the ledger and ``/debug/planes``
bytes, journey byte-identity across a raft-attached leader crash
(stitched, not truncated), and the render-on-empty bugfix sweep for
``/debug/health`` + ``/debug/planes``.
"""

import json
import os
import subprocess
import sys

import pytest

from swarmkit_tpu.models import Meta, Task, TaskState, TaskStatus
from swarmkit_tpu.obs import planes as planes_mod
from swarmkit_tpu.obs.health import (
    FAIL, PASS, WARN, Check, HealthEvaluator, apply_lag_value,
    default_checks, plane_saturation_value,
)
from swarmkit_tpu.obs.flightrec import FlightRecorder
from swarmkit_tpu.obs.journey import (
    JOURNEY_CAP, JourneyLedger, journeys,
)
from swarmkit_tpu.obs.planes import PlaneStats
from swarmkit_tpu.sim.clock import VirtualClock
from swarmkit_tpu.utils.metrics import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_planes():
    """Isolate the module plane table (rebound by reset, so the saved
    capture survives) and the shared journey ledger."""
    p_saved = planes_mod.save_state()
    j_saved = journeys.save_state()
    planes_mod.reset()
    journeys.reset(sample_rate=1.0, cap=JOURNEY_CAP)
    yield
    planes_mod.restore_state(p_saved)
    journeys.restore_state(j_saved)


# ------------------------------------------------------------ plane windows

def test_plane_occupancy_window_and_gauges():
    reg = Registry()
    with VirtualClock(1000.0) as clk:
        p = PlaneStats("scheduler", registry=reg)
        # construction consumes no time; the first roll opens the window
        assert p.roll()["occupancy"] == 0.0
        p.note_busy(2.0)
        clk.advance_to(1004.0)        # 2s busy over a 4s window
        snap = p.roll()
        assert snap["occupancy"] == 0.5
        assert reg.get_gauge(
            'swarm_plane_occupancy{plane="scheduler"}') == 0.5
        # the window resets: a fresh roll with no busy time reads 0
        clk.advance_to(1008.0)
        assert p.roll()["occupancy"] == 0.0
        # busy time is clamped: over-reporting never exceeds 1.0
        p.note_busy(100.0)
        clk.advance_to(1009.0)
        assert p.roll()["occupancy"] == 1.0


def test_plane_probe_depth_age_and_drop_counters():
    reg = Registry()
    with VirtualClock(1000.0) as clk:
        p = PlaneStats("raft", registry=reg)
        p.set_probe(lambda: {"depth": 7.0, "oldest_age": 1.5})
        clk.advance_to(1001.0)
        snap = p.roll()
        assert snap["queue_depth"] == 7.0
        assert snap["oldest_age_s"] == 1.5
        assert reg.get_gauge(
            'swarm_plane_queue_depth{plane="raft"}') == 7.0
        p.drop(); p.defer(2)
        assert reg.get_counter(
            'swarm_plane_drops{plane="raft"}') == 1
        assert reg.get_counter(
            'swarm_plane_defers{plane="raft"}') == 2
        rep = p.report()
        assert rep["drops"] == 1 and rep["defers"] == 2


def test_plane_probe_failure_never_raises():
    """A dying component's probe (or a dead weakref target) must not
    take observability down — roll() swallows and reports stale."""
    with VirtualClock(1000.0) as clk:
        p = PlaneStats("device", registry=Registry())

        def boom():
            raise RuntimeError("component mid-teardown")
        p.set_probe(boom)
        clk.advance_to(1001.0)
        p.roll()                      # must not raise
        assert p.report()["queue_depth"] == 0.0


def test_report_all_empty_and_sorted(fresh_planes):
    assert planes_mod.report_all() == {}
    for name in ("watch", "raft", "device"):
        planes_mod.plane(name)
    assert list(planes_mod.report_all()) == ["device", "raft", "watch"]


# ---------------------------------------------------------- journey ledger

def _task(tid, state, ts, created_at=0.0):
    return Task(id=tid, meta=Meta(created_at=created_at),
                status=TaskStatus(state=state, timestamp=ts))


def _feed(ledger, tid, t0=1000.0):
    """One complete created->running journey, milestones 1s apart."""
    ledger.observe_task(_task(tid, TaskState.NEW, t0, created_at=t0),
                        version=1, created=True)
    ledger.observe_task(_task(tid, TaskState.PENDING, t0 + 1.0),
                        version=2)
    ledger.observe_task(_task(tid, TaskState.ASSIGNED, t0 + 3.0),
                        version=3)
    ledger.note_sent(tid, ts=t0 + 4.0)
    ledger.observe_task(_task(tid, TaskState.ACCEPTED, t0 + 5.0),
                        version=4)
    ledger.observe_task(_task(tid, TaskState.RUNNING, t0 + 7.0),
                        version=5)


def test_journey_milestones_dedup_and_edges():
    led = JourneyLedger(sample_rate=1.0)
    led.enabled = True
    _feed(led, "t1")
    # replicated re-sightings (another member, post-failover replay)
    # are idempotent: first stamp wins
    led.observe_task(_task("t1", TaskState.RUNNING, 2000.0), version=9)
    ms = led.journey_of("t1")
    names = [n for n, _ts, _v in ms]
    assert names == ["created", "admitted", "planned", "committed",
                     "assigned_sent", "agent_ack", "running"]
    assert ms[-1][1] == 1007.0        # not the 2000.0 re-sighting
    # the edges partition created->running exactly
    total = sum(dt for _e, dt, _p in led.edges(ms))
    assert total == pytest.approx(7.0)


def test_critical_path_fractions_sum_to_one():
    led = JourneyLedger(sample_rate=1.0)
    led.enabled = True
    for i in range(10):
        _feed(led, f"t{i:03d}", t0=1000.0 + 50.0 * i)
    attr = led.critical_path(0.99)
    assert attr["tasks"] == 10 and attr["cohort"] >= 1
    assert attr["planes"], "attribution must name owning planes"
    frac = sum(p["frac"] for p in attr["planes"].values())
    assert frac == pytest.approx(1.0, abs=0.01)
    secs = sum(p["seconds"] for p in attr["planes"].values())
    assert secs == pytest.approx(attr["total_s"])
    # every edge of a journey is charged to the later milestone's plane
    assert set(attr["planes"]) <= {"api", "orchestrator", "scheduler",
                                   "commit", "dispatcher", "agent"}


def test_journey_cap_and_sampling_are_counted():
    led = JourneyLedger(sample_rate=1.0, cap=2)
    led.enabled = True
    for i in range(4):
        _feed(led, f"t{i}")
    s = led.summary()
    assert s["sampled_tasks"] == 2
    assert s["overflow"] > 0          # refusals are counted, not silent
    led2 = JourneyLedger(sample_rate=0.0)
    led2.enabled = True
    _feed(led2, "tx")
    assert led2.summary()["sampled_tasks"] == 0
    assert led2.summary()["refused"] > 0


def test_disabled_ledger_records_nothing():
    led = JourneyLedger(sample_rate=1.0)
    assert led.enabled is False       # dark by default
    led.handle_event(None)
    led.note_sent("t1")
    assert led.summary()["sampled_tasks"] == 0


# ------------------------------------------- saturation checker sensitivity

def _hev(check):
    return HealthEvaluator(registry=check_reg, recorder=FlightRecorder(),
                           checks=[check])


check_reg = None   # rebound per test


def _sched_check():
    return Check("scheduler_occupancy", plane_saturation_value(
        "scheduler"), 1.0, 2.0, "state")


def _lag_check():
    return Check("apply_lag", apply_lag_value(warn_entries=256.0, n=4),
                 1.0, 2.0, "state")


def test_scheduler_occupancy_check_fires_and_green_twin():
    global check_reg
    check_reg = reg = Registry()
    hev = _hev(_sched_check())
    # no data: a fresh manager is healthy, not unknown-unhealthy
    assert hev.evaluate() == {"scheduler_occupancy": PASS}
    # sustained occupancy at the ceiling -> warn
    reg.gauge('swarm_plane_occupancy{plane="scheduler"}', 0.95)
    assert hev.evaluate() == {"scheduler_occupancy": WARN}
    # unbounded backlog-age growth (strict, over the floor) -> fail
    for age in (1.0, 2.0, 4.0, 8.0):
        reg.gauge('swarm_plane_oldest_age_s{plane="scheduler"}', age)
        states = hev.evaluate()
    assert states == {"scheduler_occupancy": FAIL}
    assert hev.failing()
    # green twin: same shape, healthy numbers — must stay green
    check_reg = reg2 = Registry()
    hev2 = _hev(_sched_check())
    reg2.gauge('swarm_plane_occupancy{plane="scheduler"}', 0.30)
    for _ in range(4):               # flat age: no growth, no fail
        reg2.gauge('swarm_plane_oldest_age_s{plane="scheduler"}', 1.0)
        assert hev2.evaluate() == {"scheduler_occupancy": PASS}


def test_apply_lag_check_stalled_committer_fails_green_twin_passes():
    global check_reg
    check_reg = reg = Registry()
    hev = _hev(_lag_check())
    assert hev.evaluate() == {"apply_lag": PASS}      # no raft plane yet
    # stalled committer: lag over the bar AND strictly growing
    for lag in (300.0, 340.0, 400.0, 500.0):
        reg.gauge('swarm_plane_queue_depth{plane="raft_apply"}', lag)
        states = hev.evaluate()
    assert states == {"apply_lag": FAIL}
    # over the bar but NOT growing: catching up -> warn only
    check_reg = reg2 = Registry()
    hev2 = _hev(_lag_check())
    for lag in (500.0, 400.0, 300.0, 280.0):
        reg2.gauge('swarm_plane_queue_depth{plane="raft_apply"}', lag)
        states = hev2.evaluate()
    assert states == {"apply_lag": WARN}
    # green twin: healthy lag stays green forever
    check_reg = reg3 = Registry()
    hev3 = _hev(_lag_check())
    for lag in (3.0, 5.0, 2.0, 7.0, 4.0):
        reg3.gauge('swarm_plane_queue_depth{plane="raft_apply"}', lag)
        assert hev3.evaluate() == {"apply_lag": PASS}


def test_default_checks_include_saturation_checks():
    names = {c.name for c in default_checks()}
    assert {"scheduler_occupancy", "apply_lag"} <= names


# ----------------------------------------------- hash-seed independence

_HASHSEED_SCRIPT = r"""
import hashlib, json, sys
from swarmkit_tpu.sim.clock import VirtualClock
from swarmkit_tpu.models import Meta, Task, TaskState, TaskStatus
from swarmkit_tpu.obs import planes as planes_mod
from swarmkit_tpu.obs.debugpages import _h_planes
from swarmkit_tpu.obs.journey import journeys

planes_mod.reset()
journeys.reset(sample_rate=0.5)
journeys.enabled = True
with VirtualClock(1000.0) as clk:
    # feed task ids out of a SET: iteration order varies with the hash
    # seed, the ledger's output must not
    ids = {f"task-{i:04d}" for i in range(200)}
    for tid in ids:
        journeys.observe_task(
            Task(id=tid, meta=Meta(created_at=1000.0),
                 status=TaskStatus(state=TaskState.NEW,
                                   timestamp=1000.0)),
            version=1, created=True)
        journeys.observe_task(
            Task(id=tid,
                 status=TaskStatus(state=TaskState.RUNNING,
                                   timestamp=1002.0)),
            version=2)
    for name in {"scheduler", "raft", "watch", "device"}:
        planes_mod.plane(name).note_busy(0.5)
    clk.advance_to(1010.0)
    planes_mod.roll_all()
body, code, _ = _h_planes(None, {})
assert code == 200
print(hashlib.sha256(journeys.dump_bytes()).hexdigest())
print(hashlib.sha256(body).hexdigest())
"""


def test_hashseed_independent_ledger_and_planes_page():
    """The journey ledger bytes and the /debug/planes body are pure
    functions of the fed events — two processes with different
    PYTHONHASHSEED must emit identical hashes (crc32 sampling + sorted
    dumps, never hash())."""
    outs = []
    for seed in ("0", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=seed,
                   JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", _HASHSEED_SCRIPT],
                           capture_output=True, text=True, cwd=REPO,
                           env=env, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1], f"hash-seed dependent output:\n{outs}"


# ------------------------------------- sim determinism: stitched journeys

def _assert_stitched(report):
    """The ledger survived the crash stitched: complete journeys exist
    (created AND running present on one task), and planned/committed
    milestones carry store-version tokens."""
    summary = report.journeys_dump["summary"]
    assert summary["sampled_tasks"] > 0, "ledger is empty"
    assert summary["complete"] > 0, "no complete journey: truncated?"
    versioned = [
        v for ms in report.journeys_dump["journeys"].values()
        for name, _ts, v in ms if name == "committed"]
    assert versioned and all(v > 0 for v in versioned)


@pytest.mark.parametrize("seed", [3, 11])
def test_journey_byte_identity_across_leader_crash(seed):
    """Same scenario + seed twice: the raft-attached leader-crash run
    must produce byte-identical journey ledgers (the acceptance bar:
    milestones ride replicated stamps, so a successor leader's events
    dedup instead of forking the ledger)."""
    from swarmkit_tpu.sim.scenario import run_scenario

    r1 = run_scenario("leader-crash-mid-tick", seed=seed)
    r2 = run_scenario("leader-crash-mid-tick", seed=seed)
    assert r1.ok and r2.ok, (r1.violations, r2.violations)
    assert r1.journeys_sha256 == r2.journeys_sha256
    assert r1.journeys_dump == r2.journeys_dump
    _assert_stitched(r1)


@pytest.mark.slow
def test_journey_byte_identity_twenty_seeds():
    from swarmkit_tpu.sim.scenario import run_scenario

    for seed in range(20):
        r1 = run_scenario("leader-crash-mid-tick", seed=seed)
        r2 = run_scenario("leader-crash-mid-tick", seed=seed)
        assert r1.journeys_sha256 == r2.journeys_sha256, f"seed {seed}"
        _assert_stitched(r1)


# --------------------------------------------- debug pages render-on-empty

def test_debug_pages_render_on_fresh_manager(fresh_planes):
    """Bugfix sweep: /debug/health and /debug/planes must render (not
    500) on a fresh manager with zero observations."""
    import urllib.request

    from swarmkit_tpu.utils.httpdebug import DebugServer

    hev = HealthEvaluator(registry=Registry(),
                          recorder=FlightRecorder(),
                          checks=default_checks())
    srv = DebugServer(health_evaluator=hev)
    srv.start()
    try:
        for path in ("/debug/health", "/debug/planes"):
            url = f"http://{srv.addr[0]}:{srv.addr[1]}{path}"
            with urllib.request.urlopen(url) as resp:
                assert resp.status == 200, path
                doc = json.loads(resp.read().decode())
        # the planes page on an empty process: empty taxonomy + the
        # ledger summary, never a traceback
        assert doc["planes"] == {}
        assert doc["journeys"]["sampled_tasks"] == 0
    finally:
        srv.stop()


def test_debug_pages_render_on_deposed_ex_leader(fresh_planes):
    """Bugfix sweep, second arm: a deposed ex-leader's components are
    torn down (weakref probes dead, probes may raise) — the pages must
    still render from the module-level state that remains."""
    import gc
    import weakref

    from swarmkit_tpu.obs.debugpages import _h_planes

    class Dying:
        def depth(self):
            return {"depth": 1.0}

    comp = Dying()
    ref = weakref.ref(comp)
    planes_mod.plane("scheduler").set_probe(
        lambda: ref().depth() if ref() is not None else {})

    def boom():
        raise RuntimeError("session torn down")
    planes_mod.plane("dispatcher").set_probe(boom)
    del comp
    gc.collect()
    planes_mod.roll_all()            # dead + raising probes: no crash
    body, code, ctype = _h_planes(None, {})
    assert code == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert set(doc["planes"]) == {"dispatcher", "scheduler"}
