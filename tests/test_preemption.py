"""Priority classes & device-batched preemption (ISSUE 10): the
priority model end to end, the host victim oracle vs the device kernel
(differential fuzz across buckets/seeds), the scheduler's atomic
preemption pass, the preemption-storm scenario (green + deterministic),
checker-sensitivity for all three new invariants, jobs-under-churn, and
the priority_inversion health check.
"""

import random

import numpy as np
import pytest

from swarmkit_tpu.models import (
    Annotations, Node, NodeDescription, NodeSpec, NodeState, NodeStatus,
    ReplicatedService, Resources, ResourceRequirements, Service,
    ServiceMode, ServiceSpec, Task, TaskSpec, TaskState, TaskStatus,
    Version,
)
from swarmkit_tpu.models.types import now
from swarmkit_tpu.scheduler import Scheduler
from swarmkit_tpu.scheduler import preempt as hp
from swarmkit_tpu.sim.cluster import Sim
from swarmkit_tpu.sim.faults import NetConfig
from swarmkit_tpu.sim.scenario import run_scenario
from swarmkit_tpu.state.store import MemoryStore

CPU = 2 * 10 ** 9
GB = 1 << 30


# ---------------------------------------------------------------------------
# priority model: spec propagation + queue ordering
# ---------------------------------------------------------------------------

def test_service_priority_propagates_into_task_spec():
    from swarmkit_tpu.orchestrator import common
    svc = Service(
        id="s1",
        spec=ServiceSpec(
            annotations=Annotations(name="s1"),
            mode=ServiceMode.REPLICATED,
            replicated=ReplicatedService(replicas=1),
            task=TaskSpec(),
            priority=7),
        spec_version=Version(index=1))
    t = common.new_task(None, svc, 1, "")
    assert t.spec.priority == 7
    assert common.task_priority(t) == 7
    # the propagated priority is NOT spec drift: the task is clean
    assert not common.is_task_dirty(svc, t, None)
    # a task-level priority wins over the service-level one
    svc2 = svc.copy()
    svc2.spec.task = TaskSpec(priority=3)
    t2 = common.new_task(None, svc2, 1, "")
    assert t2.spec.priority == 3


def _mk_store(n_nodes, bands, node_cpu=4 * 10 ** 9):
    """bands: [(service_id, priority, n_pending, n_running)]; running
    tasks round-robin over the nodes."""
    store = MemoryStore()

    def mk(tx):
        for i in range(n_nodes):
            tx.create(Node(
                id=f"n{i:03d}",
                spec=NodeSpec(annotations=Annotations(name=f"n{i:03d}")),
                status=NodeStatus(state=NodeState.READY),
                description=NodeDescription(
                    hostname=f"n{i:03d}",
                    resources=Resources(nano_cpus=node_cpu,
                                        memory_bytes=16 * GB))))
        for sid, prio, n_pending, n_running in bands:
            spec = TaskSpec(
                priority=prio,
                resources=ResourceRequirements(reservations=Resources(
                    nano_cpus=CPU, memory_bytes=GB)))
            tx.create(Service(
                id=sid,
                spec=ServiceSpec(
                    annotations=Annotations(name=sid),
                    mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(
                        replicas=n_pending + n_running),
                    task=spec),
                spec_version=Version(index=1)))
            for s in range(n_running):
                tx.create(Task(
                    id=f"{sid}-r{s:03d}", service_id=sid, slot=s + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    node_id=f"n{s % n_nodes:03d}",
                    status=TaskStatus(state=TaskState.RUNNING,
                                      timestamp=now())))
            for s in range(n_pending):
                tx.create(Task(
                    id=f"{sid}-p{s:03d}", service_id=sid,
                    slot=n_running + s + 1,
                    desired_state=TaskState.RUNNING, spec=spec,
                    spec_version=Version(index=1),
                    status=TaskStatus(state=TaskState.PENDING,
                                      timestamp=now())))
    store.update(mk)
    return store


def test_priority_ordered_queue_schedules_high_band_first():
    # 2 nodes x 2 slots = 4 slots; lo enqueued BEFORE hi, but hi must
    # win the constrained capacity
    store = _mk_store(2, [("lo", 0, 4, 0), ("hi", 5, 4, 0)])
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = store.view(lambda tx: tx.find(Task))
    hi = [t for t in tasks if t.service_id == "hi"]
    lo = [t for t in tasks if t.service_id == "lo"]
    assert all(t.node_id for t in hi), "high band must place first"
    assert not any(t.node_id for t in lo), "no capacity left for lo"


# ---------------------------------------------------------------------------
# the preemption pass: atomic swap, budget, cooldown, strictly-lower
# ---------------------------------------------------------------------------

def test_preemption_evicts_strictly_lower_and_requeues():
    # full cluster of lo; hi arrives and must preempt exactly its size
    store = _mk_store(3, [("lo", 0, 0, 6), ("hi", 10, 2, 0)])
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    n = sched.tick()
    tasks = store.view(lambda tx: tx.find(Task))
    hi = [t for t in tasks if t.service_id == "hi"]
    victims = [t for t in tasks
               if "swarm.preempted.at" in t.annotations.labels]
    assert all(t.node_id and t.status.state == TaskState.ASSIGNED
               for t in hi)
    assert len(victims) == 2
    assert all(v.desired_state == TaskState.SHUTDOWN for v in victims)
    assert all(v.annotations.labels["swarm.preempted.prio"] == "0"
               and v.annotations.labels["swarm.preempted.by.prio"] == "10"
               for v in victims)
    assert sched.stats["preemptions"] == 2
    assert n >= 2
    # anti-thrash cooldown stamped per victim slot
    assert len(sched.preempt.cooldowns) == 2


def test_preemption_never_touches_equal_or_higher():
    # cluster full of priority-10 work; a priority-10 and a priority-5
    # band arrive: NOTHING may be preempted
    store = _mk_store(3, [("res", 10, 0, 6), ("same", 10, 2, 0),
                          ("below", 5, 2, 0)])
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = store.view(lambda tx: tx.find(Task))
    assert not any("swarm.preempted.at" in t.annotations.labels
                   for t in tasks)
    assert sched.stats["preemptions"] == 0


def test_preemption_budget_bounds_one_tick():
    store = _mk_store(4, [("lo", 0, 0, 8), ("hi", 10, 6, 0)])
    sched = Scheduler(store, preempt_budget=3)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = store.view(lambda tx: tx.find(Task))
    victims = [t for t in tasks
               if "swarm.preempted.at" in t.annotations.labels]
    assert len(victims) == 3, "per-tick budget must cap evictions"
    placed_hi = [t for t in tasks if t.service_id == "hi" and t.node_id]
    assert len(placed_hi) == 3


def test_preemption_cooldown_blocks_rethrash():
    store = _mk_store(2, [("lo", 0, 0, 4), ("hi", 10, 1, 0)])
    sched = Scheduler(store, preempt_cooldown=3600.0)
    store.view(sched._setup_tasks_list)
    sched.tick()
    assert sched.stats["preemptions"] == 1
    victim_slots = set(sched.preempt.cooldowns)
    # a second arrival wanting the SAME slot finds it cooling down; with
    # every other node fully occupied by cooled-down... here remaining
    # nodes still have victims, so it preempts a DIFFERENT slot
    def more(tx):
        svc = tx.get(Service, "hi").copy()
        svc.spec.replicated.replicas += 1
        tx.update(svc)
        tx.create(Task(
            id="hi-p990", service_id="hi", slot=99,
            desired_state=TaskState.RUNNING, spec=svc.spec.task,
            spec_version=Version(index=1),
            status=TaskStatus(state=TaskState.PENDING, timestamp=now())))
    store.update(more)
    sched._resync()
    sched.tick()
    assert sched.stats["preemptions"] == 2
    assert len(sched.preempt.cooldowns) == 2
    assert set(sched.preempt.cooldowns) > victim_slots


def test_unsupported_groups_are_skipped():
    # no resource demand: preemption cannot fix constraint infeasibility
    store = MemoryStore()

    def mk(tx):
        tx.create(Node(
            id="n0", spec=NodeSpec(annotations=Annotations(name="n0")),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname="n0", resources=Resources(
                    nano_cpus=4 * 10 ** 9, memory_bytes=16 * GB))))
        spec = TaskSpec(priority=5)
        tx.create(Service(
            id="c", spec=ServiceSpec(
                annotations=Annotations(name="c"),
                mode=ServiceMode.REPLICATED,
                replicated=ReplicatedService(replicas=1), task=spec),
            spec_version=Version(index=1)))
        tx.create(Task(id="c-p0", service_id="c", slot=1,
                       desired_state=TaskState.RUNNING, spec=spec,
                       spec_version=Version(index=1),
                       status=TaskStatus(state=TaskState.PENDING,
                                         timestamp=now())))
    store.update(mk)
    t = store.view(lambda tx: tx.get(Task, "c-p0"))
    assert not hp.preemptable_group(t)


def test_max_replicas_groups_are_waived():
    from swarmkit_tpu.models.types import Placement
    t = Task(spec=TaskSpec(
        priority=5,
        placement=Placement(max_replicas=2),
        resources=ResourceRequirements(reservations=Resources(
            nano_cpus=CPU, memory_bytes=GB))))
    assert not hp.preemptable_group(t), \
        "max_replicas eligibility cannot be held across stacked picks"


def test_one_off_tasks_preempt_as_singletons():
    """The spec-version-less one-off bucket is heterogeneous: each task
    must be judged at its OWN priority/demand — here the priority-8
    one-off may preempt the priority-5 victim, the priority-3 one
    must not."""
    store = MemoryStore()

    def mk(tx):
        tx.create(Node(
            id="n0", spec=NodeSpec(annotations=Annotations(name="n0")),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname="n0",
                resources=Resources(nano_cpus=CPU, memory_bytes=16 * GB))))
        res = ResourceRequirements(reservations=Resources(
            nano_cpus=CPU, memory_bytes=GB))
        vic_spec = TaskSpec(priority=5, resources=res)
        for sid, spec in (("vic", vic_spec),
                          ("one-hi", TaskSpec(priority=8, resources=res)),
                          ("one-lo", TaskSpec(priority=3, resources=res))):
            tx.create(Service(
                id=sid, spec=ServiceSpec(
                    annotations=Annotations(name=sid),
                    mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=1), task=spec),
                spec_version=Version(index=1)))
        tx.create(Task(id="vic-r0", service_id="vic", slot=1,
                       desired_state=TaskState.RUNNING, spec=vic_spec,
                       spec_version=Version(index=1), node_id="n0",
                       status=TaskStatus(state=TaskState.RUNNING,
                                         timestamp=now())))
        # spec_version=None: both land in the one-off (None) bucket
        for sid in ("one-lo", "one-hi"):
            svc_spec = TaskSpec(priority=8 if sid == "one-hi" else 3,
                                resources=res)
            tx.create(Task(id=f"{sid}-p0", service_id=sid, slot=1,
                           desired_state=TaskState.RUNNING, spec=svc_spec,
                           status=TaskStatus(state=TaskState.PENDING,
                                             timestamp=now())))
    store.update(mk)
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = {t.id: t for t in store.view(lambda tx: tx.find(Task))}
    assert tasks["one-hi-p0"].node_id == "n0"
    assert tasks["one-hi-p0"].status.state == TaskState.ASSIGNED
    assert not tasks["one-lo-p0"].node_id, \
        "a priority-3 one-off must not ride the priority-8 selection"
    assert tasks["vic-r0"].desired_state == TaskState.SHUTDOWN
    assert sched.stats["preemptions"] == 1


# ---------------------------------------------------------------------------
# differential fuzz: device kernel vs host oracle (mirrors the
# fused-differential discipline — byte-identical picks, every bucket)
# ---------------------------------------------------------------------------

def _random_candidates(rng, n, V, with_gen=False):
    kwargs = {}
    if with_gen:
        # generic-resource victim bucket (ISSUE 12 waiver shrink): the
        # third resource column must stay byte-identical across paths
        kwargs["free_gen"] = np.array(
            [rng.randrange(0, 4) for _ in range(n)], np.int64)
        kwargs["vgen"] = np.array(
            [[rng.randrange(0, 3) for _ in range(n)]
             for _ in range(V)], np.int64)
    return hp.CandidateSet(
        infos=None,
        ok=np.array([rng.random() < 0.8 for _ in range(n)]),
        free_cpu=np.array([rng.randrange(-4, 9) * 10 ** 9
                           for _ in range(n)], np.int64),
        free_mem=np.array([rng.randrange(0, 8) * GB
                           for _ in range(n)], np.int64),
        vvalid=np.array([[rng.random() < 0.6 for _ in range(n)]
                         for _ in range(V)]),
        vprio=np.array([[rng.randrange(0, 5) for _ in range(n)]
                        for _ in range(V)], np.int32),
        vcpu=np.array([[rng.randrange(0, 5) * 10 ** 9
                        for _ in range(n)] for _ in range(V)], np.int64),
        vmem=np.array([[rng.randrange(0, 4) * GB
                        for _ in range(n)] for _ in range(V)], np.int64),
        victims=None, vb=V, n_candidates=1, **kwargs)


@pytest.mark.parametrize("n,V,with_gen",
                         [(7, 4, False), (40, 16, False), (17, 4, False),
                          (11, 4, True), (23, 16, True)])
def test_device_selection_matches_host_oracle(n, V, with_gen):
    from swarmkit_tpu.ops import preempt as dp
    for seed in range(25):
        rng = random.Random(seed * 1000 + n * 7 + V)
        cand = _random_candidates(rng, n, V, with_gen=with_gen)
        cpu_d = rng.randrange(1, 5) * 10 ** 9
        mem_d = rng.randrange(0, 3) * GB
        gen_d = rng.randrange(1, 4) if with_gen else 0
        budget = rng.randrange(1, 20)
        n_picks = min(rng.randrange(1, 12), budget)
        host = hp.select_victims_host(cand, cpu_d, mem_d, gen_d,
                                      n_picks, budget)
        dev, _label, _fn = dp.plan_victims(cand, cpu_d, mem_d, gen_d,
                                           n_picks, budget)
        assert host == dev, (seed, n, V, host, dev)


def test_generic_demand_is_preemptable_and_places():
    """The narrowed waiver end-to-end: a priority band demanding ONE
    discrete generic kind evicts a lower-priority holder of that kind
    (victims free generics too, not just cpu/memory)."""
    from swarmkit_tpu.models.types import (
        GenericResource, GenericResourceKind,
    )
    store = MemoryStore()
    gpu = [GenericResource(kind="gpu", value=2,
                           res_type=GenericResourceKind.DISCRETE)]

    def mk(tx):
        tx.create(Node(
            id="n0", spec=NodeSpec(annotations=Annotations(name="n0")),
            status=NodeStatus(state=NodeState.READY),
            description=NodeDescription(
                hostname="n0",
                resources=Resources(nano_cpus=8 * 10 ** 9,
                                    memory_bytes=16 * GB,
                                    generic=list(gpu)))))
        lo_spec = TaskSpec(priority=0, resources=ResourceRequirements(
            reservations=Resources(nano_cpus=CPU, generic=list(gpu))))
        hi_spec = TaskSpec(priority=9, resources=ResourceRequirements(
            reservations=Resources(nano_cpus=CPU, generic=list(gpu))))
        for sid, spec in (("g-lo", lo_spec), ("g-hi", hi_spec)):
            tx.create(Service(
                id=sid, spec=ServiceSpec(
                    annotations=Annotations(name=sid),
                    mode=ServiceMode.REPLICATED,
                    replicated=ReplicatedService(replicas=1), task=spec),
                spec_version=Version(index=1)))
        assert hp.preemptable_group(Task(spec=hi_spec))
        tx.create(Task(id="g-lo-r0", service_id="g-lo", slot=1,
                       desired_state=TaskState.RUNNING, spec=lo_spec,
                       spec_version=Version(index=1), node_id="n0",
                       status=TaskStatus(state=TaskState.RUNNING,
                                         timestamp=now())))
        tx.create(Task(id="g-hi-p0", service_id="g-hi", slot=1,
                       desired_state=TaskState.RUNNING, spec=hi_spec,
                       spec_version=Version(index=1),
                       status=TaskStatus(state=TaskState.PENDING,
                                         timestamp=now())))
    store.update(mk)
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    tasks = {t.id: t for t in store.view(lambda tx: tx.find(Task))}
    assert tasks["g-lo-r0"].desired_state == TaskState.SHUTDOWN
    assert tasks["g-hi-p0"].node_id == "n0"
    assert sched.stats["preemptions"] == 1


def test_multi_kind_generic_demand_still_waived():
    from swarmkit_tpu.models.types import (
        GenericResource, GenericResourceKind,
    )
    t = Task(spec=TaskSpec(
        priority=5,
        resources=ResourceRequirements(reservations=Resources(
            nano_cpus=CPU,
            generic=[GenericResource(kind="gpu", value=1),
                     GenericResource(kind="fpga", value=1)]))))
    assert not hp.preemptable_group(t)
    named = Task(spec=TaskSpec(
        priority=5,
        resources=ResourceRequirements(reservations=Resources(
            generic=[GenericResource(
                kind="gpu", value_str="gpu-0",
                res_type=GenericResourceKind.NAMED)]))))
    assert not hp.preemptable_group(named)


def test_device_and_host_schedulers_place_identically():
    from swarmkit_tpu.ops import TPUPlanner

    def run(planner):
        store = _mk_store(3, [("lo", 0, 0, 6), ("mid", 3, 1, 0),
                              ("hi", 10, 2, 0)])
        sched = Scheduler(store, batch_planner=planner)
        if planner is not None:
            planner.enable_small_group_routing = False
        store.view(sched._setup_tasks_list)
        sched.tick()
        return sorted(
            (t.id, t.node_id, int(t.status.state), int(t.desired_state))
            for t in store.view(lambda tx: tx.find(Task)))

    host = run(None)
    device = run(TPUPlanner())
    assert host == device


def test_breaker_open_routes_selection_to_host():
    from swarmkit_tpu.ops import TPUPlanner
    planner = TPUPlanner()
    for _ in range(planner.breaker.threshold):
        planner.breaker.record_failure()
    assert planner.select_victims(None, CPU, GB, 0, 1, 8) is None
    assert planner.stats.get("preempt_breaker_to_host", 0) >= 1


# ---------------------------------------------------------------------------
# the scenario: green, deterministic, preemptions observed
# ---------------------------------------------------------------------------

def test_preemption_storm_green_and_deterministic():
    # first run warms the victim-kernel jit signatures: its obs trace
    # carries the one-off plan.compile events (zero-duration under the
    # virtual clock, but present), so byte-identity is judged on the
    # warm pair — same discipline as the bench's warm-up windows
    warm = run_scenario("preemption-storm", seed=0)
    assert warm.ok, warm.violations
    r1 = run_scenario("preemption-storm", seed=0)
    assert r1.ok, r1.violations
    r2 = run_scenario("preemption-storm", seed=0)
    assert r2.trace_hash == r1.trace_hash == warm.trace_hash
    assert r2.obs_trace_sha256 == r1.obs_trace_sha256
    # every band converged RUNNING (20 = 12 lo + 4 mid + 4 hi)
    assert r1.stats["tasks"].get("RUNNING", 0) == 20, r1.stats["tasks"]


def test_jobs_survive_failover_churn():
    """Jobs-under-churn: the failover scenario's replicated job must
    show all its completions despite two leadership hand-offs (the
    jobs orchestrator rides the raft-attached control plane now)."""
    r = run_scenario("failover-churn-rollout", seed=0)
    assert r.ok, r.violations
    assert r.stats["tasks"].get("COMPLETE", 0) >= 6, r.stats["tasks"]


# ---------------------------------------------------------------------------
# checker sensitivity: every new invariant must FIRE when its
# enforcement is disabled (house rule since PR 1)
# ---------------------------------------------------------------------------

def _mini_storm(seed, configure=None, duration=45.0):
    """Small contention sim: 16 lo tasks, two workers die (capacity 12),
    a 2-task priority-5 band arrives — preemption must fire; after heal
    the 18 tasks fit the 20 slots again."""
    sim = Sim(seed=seed, n_managers=3, n_agents=5,
              net_config=NetConfig(), raft_cp=True)
    with sim:
        eng = sim.engine
        cp = sim.cp
        if configure is not None:
            configure(cp)
        sim.start_raft_workload(interval=0.8)
        eng.at(eng.clock.start + 5.0, "lo band",
               lambda: cp.add_service("svc-lo", 16, priority=0,
                                      nano_cpus=CPU))
        a = cp.agents
        eng.at(eng.clock.start + 14.0, "node death w0", a[0].crash)
        eng.at(eng.clock.start + 16.0, "node death w1", a[1].crash)
        eng.at(eng.clock.start + 20.0, "hi band",
               lambda: cp.add_service("svc-hi", 2, priority=5,
                                      nano_cpus=CPU))
        eng.at(eng.clock.start + 34.0, "node return w0", a[0].restart)
        eng.at(eng.clock.start + 36.0, "node return w1", a[1].restart)
        sim.run(duration)
        sim.finish(grace=20.0)
    return sim


def test_sensitivity_no_priority_inversion():
    """Disable the preemption pass: the feasible-with-victims high band
    starves past the bound — the checker must catch the inversion."""
    def cfg(cp):
        cp.preemption_enabled = False
        cp.preempt_inversion_bound = 10.0
    sim = _mini_storm(11, cfg)
    assert any("no-priority-inversion" in v
               for v in sim.violations.items), sim.violations.items


def test_sensitivity_preempted_tasks_requeue(monkeypatch):
    """Break the requeue path (the reconciler skips services that show
    a preemption marker, so evicted slots never refill): the checker
    must report the lost work."""
    from swarmkit_tpu.orchestrator import replicated as repl
    from swarmkit_tpu.state.store import ByService
    orig = repl.Orchestrator._reconcile

    def skip_preempted(self, service):
        tasks = self.store.view(
            lambda tx: tx.find(Task, ByService(service.id)))
        if any("swarm.preempted.at" in t.annotations.labels
               for t in tasks):
            return
        orig(self, service)
    monkeypatch.setattr(repl.Orchestrator, "_reconcile", skip_preempted)
    sim = _mini_storm(12)
    assert any("preempted-tasks-requeue" in v
               for v in sim.violations.items), sim.violations.items


def test_sensitivity_preemption_thrash_bound():
    """Tighten the thrash bound below any real preemption (0): a single
    eviction must trip it — proving the rate tracking is live."""
    def cfg(cp):
        cp.preempt_thrash_bound = 0
    sim = _mini_storm(13, cfg)
    assert any("preemption-thrash-bound" in v
               for v in sim.violations.items), sim.violations.items


def test_mini_storm_is_green_by_default():
    """The sensitivity harness itself is green with enforcement on —
    the three tests above fail for the injected reason, nothing else."""
    sim = _mini_storm(14)
    assert not sim.violations.items, sim.violations.items


# ---------------------------------------------------------------------------
# obs: priority_inversion SLO check
# ---------------------------------------------------------------------------

def test_priority_inversion_health_check():
    from swarmkit_tpu.obs.health import HealthEvaluator
    from swarmkit_tpu.utils.metrics import Registry
    reg = Registry()
    ev = HealthEvaluator(registry=reg)
    assert ev.evaluate()["priority_inversion"] == "pass"
    reg.gauge("swarm_priority_inversion", 0.0)
    assert ev.evaluate()["priority_inversion"] == "pass"
    reg.gauge("swarm_priority_inversion", 2.0)
    assert ev.evaluate()["priority_inversion"] == "warn"
    reg.gauge("swarm_priority_inversion", 9.0)
    assert ev.evaluate()["priority_inversion"] == "fail"
    reg.gauge("swarm_priority_inversion", 0.0)
    assert ev.evaluate()["priority_inversion"] == "pass"


def test_preemption_metrics_exported():
    """The pass exports counters + latency edge timers the dashboards
    and the health plane read."""
    from swarmkit_tpu.utils.metrics import registry as reg
    pre0 = reg.get_counter('swarm_preemptions{reason="priority"}')
    store = _mk_store(2, [("lo", 0, 0, 4), ("hi", 10, 1, 0)])
    sched = Scheduler(store)
    store.view(sched._setup_tasks_list)
    sched.tick()
    assert reg.get_counter('swarm_preemptions{reason="priority"}') \
        == pre0 + 1
    commit_t = reg.get_timer('swarm_preempt_latency{edge="commit"}')
    assert commit_t is not None and commit_t.count > 0
    assert reg.get_gauge("swarm_priority_inversion") is not None


# ---------------------------------------------------------------------------
# slow tier: the 20-seed acceptance sweep
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_preemption_storm_wide_sweep():
    """Acceptance: 20 seeds of preemption-storm, all green (which
    includes no-preempt-equal-or-higher holding everywhere), and
    byte-identical reports on re-run for sampled seeds."""
    import sys, os
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import chaos_sweep
    reports = chaos_sweep.sweep(("preemption-storm",), n_seeds=20)
    out = chaos_sweep.verdict(reports, ("preemption-storm",), 20, 0)
    assert out["ok"], out["failures"] or out["coverage"]["uncovered"]
    by_seed = {r.seed: r for r in reports}
    for seed in (0, 7, 13):
        r2 = run_scenario("preemption-storm", seed, keep_trace=True)
        assert r2.trace_hash == by_seed[seed].trace_hash, seed
        assert r2.violations == by_seed[seed].violations, seed
